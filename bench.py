"""Driver perf contract: GPT train-step throughput + MFU on one chip.

Prints exactly ONE JSON line on stdout:
  {"metric": "gpt_train_mfu", "value": <MFU %>, "unit": "%", "vs_baseline":
   <MFU/45%>, "tokens_per_sec_per_chip": ..., "config": ..., ...}
Everything else (progress, the flash-attention microbench in --flash mode)
goes to stderr.

The measured workload is the framework's hot path: SpmdTrainer's single
fused XLA executable (fwd+bwd+Adam update) on a 1-device mesh, bf16 AMP,
activation recompute, flash attention — GPT-3 config at sequence 2048
(BASELINE.json config #4; the 45% MFU north star is the baseline).
Reference role: operators/benchmark/op_tester.cc:1 (in-tree perf harness).
"""
import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _rows_file() -> str:
    path = os.environ.get("BENCH_ROWS_FILE", "").strip()
    if path.lower() in ("0", "off", "none", "false"):
        return ""
    if not path:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_rows.jsonl")
    return path


def _bench_run() -> str:
    """The sweep's run id (BENCH_RUN env).  Rows are tagged with it and
    the resume logic only trusts rows of the SAME run — without an
    explicit id every re-invocation would skip its own measurements."""
    return os.environ.get("BENCH_RUN", "").strip()


def _persist_row(row, kind="train"):
    """Append one measured row to the incremental JSON log AS MEASURED
    (fsync'd append): a transient remote-compile HTTP-500 late in a
    sweep no longer loses the rows already paid for — r04 and half of
    r05 died with every row still in memory.  BENCH_ROWS_FILE names the
    file ('0'/'off' disables; default BENCH_rows.jsonl next to this
    script).  Over-budget files are compacted AFTER the append (the
    new row always lands first, mirroring the metrics-snapshot
    rotation)."""
    path = _rows_file()
    if not path:
        return
    try:
        rec = {"kind": kind, "ts": time.time(), "run": _bench_run(),
               **row}
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        _compact_rows(path)
    except (OSError, TypeError, ValueError) as e:
        log(f"  row persist skipped: {type(e).__name__}: {e}")


def _compaction_key(rec) -> tuple:
    """Compaction identity: (run, candidate key) — the same key the
    resume logic matches on, so keeping the NEWEST row per key provably
    preserves resume semantics (resume reads the last match anyway)."""
    kind = rec.get("kind")
    if kind == "train":
        cand = _train_row_key(rec)
    elif kind == "serve":
        cand = _serve_row_key(rec)
    else:
        # smoke/loadtest/autotune rows: identity is the metric itself
        cand = (str(kind), str(rec.get("metric", "")))
    return (str(rec.get("run", "")), cand)


def _compact_rows(path, max_bytes=None, keep_per_key=None):
    """Size-triggered compaction of the bench-rows log (ISSUE 16): the
    file is fsync-append-only and grows without bound across runs.
    When it exceeds BENCH_ROWS_MAX_MB (default 64), rewrite it keeping
    only the newest BENCH_ROWS_KEEP (default 4) rows per (run,
    candidate key), dropping unparseable lines; if the deduped file
    still busts the budget, the oldest surviving rows go too (the
    newest always stays).  Atomic tmp+rename via framework.fs, exactly
    like the metrics-snapshot rotation it mirrors."""
    if max_bytes is None:
        try:
            max_bytes = int(float(os.environ.get(
                "BENCH_ROWS_MAX_MB", "64")) * 1024 * 1024)
        except ValueError:
            max_bytes = 64 * 1024 * 1024
    if max_bytes <= 0:                  # BENCH_ROWS_MAX_MB=0: never
        return False
    if keep_per_key is None:
        try:
            keep_per_key = max(1, int(os.environ.get(
                "BENCH_ROWS_KEEP", "4")))
        except ValueError:
            keep_per_key = 4
    try:
        if os.path.getsize(path) <= max_bytes:
            return False
        with open(path, errors="replace") as f:
            lines = f.readlines()
        seen: dict = {}
        kept_rev = []
        for line in reversed(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                continue                # garbage lines die in compaction
            if not isinstance(rec, dict):
                continue
            key = _compaction_key(rec)
            n = seen.get(key, 0)
            if n >= keep_per_key:
                continue
            seen[key] = n + 1
            kept_rev.append(line if line.endswith("\n") else line + "\n")
        kept = list(reversed(kept_rev))
        # still over budget after dedup: shed oldest rows, newest stays
        while len(kept) > 1 and sum(map(len, kept)) > max_bytes:
            kept.pop(0)
        from paddle_tpu.framework.fs import open_for_write
        with open_for_write(path, "w") as f:
            f.writelines(kept)
        log(f"  rows: compacted {len(lines)} -> {len(kept)} lines "
            f"(> {max_bytes / 1e6:.0f}MB budget)")
        return True
    except OSError:
        return False


def _train_row_key(row) -> tuple:
    """Identity of a train candidate, shared by the sweep spec and the
    persisted row so resume can match them."""
    q = row.get("quantize")
    pol = row.get("remat_policy") or "off"
    return ("train", str(row.get("config")), int(row.get("batch", 0)),
            int(row.get("seq", 0)), bool(row.get("use_flash")),
            bool(row.get("remat")), str(pol),
            bool(row.get("scan_layers")),
            bool(row.get("overlap", True)),
            str(q).lower() if q else "none")


def _serve_row_key(row) -> tuple:
    return ("serve", str(row.get("config")),
            int(row.get("batch_slots", 0)),
            str(row.get("kv_dtype") or "dense"),
            bool(row.get("decode_megakernel")),
            int(row.get("prompt_len", 0)), int(row.get("gen_tokens", 0)),
            int(row.get("tp", 1) or 1), int(row.get("ep", 1) or 1),
            int(row.get("prefill_chunk", 0) or 0))


def _measured_rows(kind) -> dict:
    """{candidate key: persisted row} for THIS run — the sweep-resume
    satellite: a rerun after a transient late failure (the r04/r05
    mode) consults these and re-measures only the unmeasured tail.
    Active only when BENCH_RUN names the run and BENCH_RESUME != 0."""
    run = _bench_run()
    path = _rows_file()
    if not run or not path or os.environ.get("BENCH_RESUME", "1") == "0":
        return {}
    keyer = _train_row_key if kind == "train" else _serve_row_key
    required = "mfu" if kind == "train" else "value"
    out = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (not isinstance(rec, dict) or rec.get("run") != run
                        or rec.get("kind") != kind
                        or required not in rec):
                    continue
                out[keyer(rec)] = rec
    except OSError:
        return {}
    return out


def peak_flops(device) -> float:
    """Peak dense bf16 FLOP/s for a device.  The per-kind table now
    lives in the executable observatory
    (observability.exec_registry.PEAK_FLOPS_BF16, alongside the HBM
    bandwidth/capacity tables the roofline needs); MFU keeps its old
    contract — 0.0 on unknown/host kinds, never a nominal figure."""
    from paddle_tpu.observability import exec_registry as _er
    kind = getattr(device, "device_kind", "").lower()
    peak, nominal = _er.peak_flops(kind)
    return 0.0 if nominal else peak


def _flash_blocks(seq, head_dim, causal=True):
    from paddle_tpu.ops import get_block_sizes
    return get_block_sizes(seq, head_dim, causal)


def bench_train(config_name, batch, seq, steps, warmup, use_flash=True,
                remat=None, smoke=False, scan=None, overlap=None,
                quantize=None, remat_policy=None):
    """One measured train candidate.  The knob axes of ROADMAP item 1's
    sweep — quantize × flash × scan × overlap × remat(policy) — are
    explicit parameters (None = the documented env default), so
    main()'s candidate enumeration and the row identity the resume
    logic matches on are the same thing."""
    prev = os.environ.get("PADDLE_TPU_OVERLAP")
    if overlap is not None:
        os.environ["PADDLE_TPU_OVERLAP"] = "1" if overlap else "0"
    try:
        return _bench_train_body(config_name, batch, seq, steps, warmup,
                                 use_flash, remat, smoke, scan, overlap,
                                 quantize, remat_policy)
    finally:
        if overlap is not None:
            if prev is None:
                os.environ.pop("PADDLE_TPU_OVERLAP", None)
            else:
                os.environ["PADDLE_TPU_OVERLAP"] = prev


def _bench_train_body(config_name, batch, seq, steps, warmup, use_flash,
                      remat, smoke, scan, overlap, quantize,
                      remat_policy):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed import SpmdTrainer, async_dispatch, \
        create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.io.device_prefetch import DevicePrefetcher
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_configs
    from paddle_tpu.utils.compile_cache import ensure_compile_cache
    from dataclasses import replace
    import jax

    # persistent XLA compile cache: warm bench runs skip the 95s
    # warmup+compile that BENCH_r05 paid on every invocation
    cache_dir = ensure_compile_cache()

    # blocked cross-entropy (no [B,S,V] logits) and scan-over-layers
    # (O(1) traced transformer bodies) are ON by default; env
    # kill-switches for A/B
    fused_ce = os.environ.get("BENCH_FUSED_CE", "1") != "0"
    scan_layers = bool(scan) if scan is not None else \
        os.environ.get("BENCH_SCAN_LAYERS", "1") != "0"
    # AQT fake-quant matmuls (param, else BENCH_QUANTIZE=int8|fp8):
    # quantized forward + straight-through backward — the int8 MXU runs
    # at 2× the bf16 rate, the direct attack on ROADMAP item 1's
    # 35%→45% gap.  MFU stays reported against the bf16 peak so the
    # trajectory rows compare like for like.
    if quantize is None:
        quantize = os.environ.get("BENCH_QUANTIZE", "")
    quantize = str(quantize).strip().lower()
    quantize = None if quantize in ("", "0", "off", "none") else quantize
    overlap_eff = bool(overlap) if overlap is not None else \
        os.environ.get("PADDLE_TPU_OVERLAP", "1") != "0"
    cfg = replace(gpt_configs()[config_name], max_seq_len=seq,
                  use_flash_attention=use_flash, fused_ce=fused_ce,
                  quantize=quantize)
    log(f"bench: {config_name} seq={seq} batch={batch} "
        f"flash={use_flash} fused_ce={fused_ce} scan={scan_layers} "
        f"quantize={quantize} ({cfg.num_params()/1e6:.0f}M params)")

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    st = DistributedStrategy()
    st.amp = True                      # bf16 params + activations
    # remat costs extra FLOPs; models that fit in HBM without it run
    # faster with it off (measured: 125m b8 flash 30.2% MFU remat-off vs
    # 25.4% with dots_no_batch).  Per-candidate setting; BENCH_RECOMPUTE
    # env overrides.
    if os.environ.get("BENCH_RECOMPUTE") is not None:
        remat = os.environ["BENCH_RECOMPUTE"] != "0"
    elif remat is None:
        remat = True
    st.recompute = remat               # remat blocks, selective policy:
    # save matmul outputs ('dots_no_batch'), recompute only the cheap
    # elementwise ops — 'full' remat pays the whole forward twice and
    # caps MFU ~2/3.  The policy is now a sweep axis (and the winner's
    # choice lands in the unified tuning table for SpmdTrainer users
    # that don't pin one).
    if remat_policy is None:
        remat_policy = "dots_no_batch"
    st.recompute_configs = {"policy": remat_policy,
                            "scan_layers": scan_layers}
    mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
    # resilience config rides the perf trajectory: the anomaly policy is
    # part of the measured step (skip compiles an extra finite-check +
    # select into the executable)
    anomaly_policy = os.environ.get("BENCH_ANOMALY_POLICY", "raise")
    # collective breakdown (comm_ms/comm_fraction in the JSON): the AOT
    # analysis re-lowers the step, but its XLA compile hits the
    # persistent cache (identical HLO), so the steady-state cost is a
    # deserialize; BENCH_COMM_STATS=0 drops it entirely
    comm_stats = os.environ.get("BENCH_COMM_STATS", "1") != "0"
    trainer = SpmdTrainer(model, opt, lambda o, l: crit(o, l), mesh=mesh,
                          strategy=st, anomaly_policy=anomaly_policy,
                          comm_stats=comm_stats)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    t0 = time.perf_counter()
    for _ in range(warmup):
        loss = trainer.train_step(ids, labels)
    loss.block_until_ready()
    warmup_s = time.perf_counter() - t0
    log(f"  warmup+compile {warmup_s:.1f}s loss={float(loss):.4f}")

    # evidence the Pallas flash kernel engages in THIS compiled step:
    # pallas kernels lower to tpu custom-calls in the step's HLO
    # (skipped in smoke mode: re-lowering isn't part of that contract)
    flash_in_step = None
    if not smoke:
        try:
            batch_dev = trainer.shard_batch((ids, labels))
            import jax.numpy as jnp
            lowered = trainer.step_executable.lower(
                trainer.params, trainer.opt_state, trainer.buffers,
                jnp.asarray(1e-4, jnp.float32), jnp.asarray(1, jnp.int32),
                *batch_dev)
            txt = lowered.as_text()
            # the Pallas kernel lowers to a tpu_custom_call target; the
            # XLA composite fallback (which also carries 'flash' in op
            # metadata) and @Sharding custom-calls must NOT satisfy this
            flash_in_step = "tpu_custom_call" in txt
            log(f"  flash kernel in step HLO: {flash_in_step}")
        except Exception as e:
            log(f"  flash HLO check skipped: {type(e).__name__}: {e}")

    # measured loop, PIPELINED: a DevicePrefetcher device_puts the next
    # batches with the trainer's sharding on a background thread while
    # the step runs, and nothing reads the loss back until the end —
    # the host only dispatches (this is the tentpole being measured)
    prefetch_depth = int(os.environ.get("PADDLE_TPU_PREFETCH_DEPTH", "2"))
    async_dispatch.reset_host_sync_count()
    if prefetch_depth > 0:
        prefetcher = DevicePrefetcher(
            ((ids, labels) for _ in range(steps)), trainer.shard_batch,
            depth=prefetch_depth, timings=trainer._timings)
        t0 = time.perf_counter()
        for dev_ids, dev_labels in prefetcher:
            loss = trainer.train_step(dev_ids, dev_labels)
    else:
        # PADDLE_TPU_PREFETCH_DEPTH=0: honor the documented kill-switch
        # (A/B the transfer thread out), same as Model.fit
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.train_step(ids, labels)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    # syncs during the measured window: the final barrier only.  A
    # regression that re-introduces a per-step float(loss)/np.asarray
    # shows up here (bench --smoke asserts on it)
    host_syncs_measured = async_dispatch.host_sync_count()

    # async checkpoint cost: what the TRAIN THREAD pays for a save (the
    # device->host snapshot; serialization+commit run in the background)
    ckpt_save_ms = ckpt_async = None
    if not smoke:
        try:
            import tempfile
            from paddle_tpu.distributed.resilience import CheckpointManager
            with tempfile.TemporaryDirectory() as td:
                mgr = CheckpointManager(td, keep_last=1, async_save=True)
                t0 = time.perf_counter()
                mgr.save(trainer, step=trainer._step_count)
                ckpt_save_ms = round((time.perf_counter() - t0) * 1e3, 2)
                mgr.wait()
                ckpt_async = True
                log(f"  ckpt: train-thread blocked {ckpt_save_ms}ms, "
                    f"commit {mgr.last_commit_ms:.0f}ms (background)")
        except Exception as e:
            log(f"  ckpt bench skipped: {type(e).__name__}: {e}")

    # ONE stats read: the property itself syncs the on-device anomaly
    # counters, so re-evaluating it per key would pollute sync_ms
    trainer_stats = trainer.stats

    # executable observatory (ISSUE 15): run the deferred XLA cost/
    # memory analyses for this trainer's executables — an AOT re-lower
    # the persistent cache serves as a deserialize, AFTER the measured
    # window so the compile/sync budgets above are untouched — and
    # attach the roofline digest (flops, bytes, achieved-vs-peak, MFU
    # attribution) to the row.  BENCH_EXEC_PROFILE=0 disables.
    exec_profile = None
    if os.environ.get("BENCH_EXEC_PROFILE", "1") != "0":
        try:
            from paddle_tpu.observability import exec_registry as _er
            _er.analyze_all(trainer._exec_component)
            exec_profile = _er.profile(trainer._exec_component)
        except Exception as e:
            log(f"  exec profile skipped: {type(e).__name__}: {e}")

    step_ms = dt / steps * 1e3
    tokens_per_sec = batch * seq * steps / dt
    flops_tok = cfg.flops_per_token(seq)
    peak = peak_flops(jax.devices()[0])
    mfu = tokens_per_sec * flops_tok / peak if peak else 0.0
    row = {
        "config": config_name, "batch": batch, "seq": seq,
        "steps": steps, "step_ms": round(step_ms, 2),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "flops_per_token": flops_tok,
        "peak_flops": peak, "mfu": mfu,
        "loss": float(loss),
        "use_flash": use_flash,
        "flash_kernel_in_step": flash_in_step,
        "fused_ce": fused_ce,
        "scan_layers": scan_layers,
        # quantized-path knobs (ISSUE 7): the next TPU run must be able
        # to attribute its MFU delta to these
        "quantize": quantize,
        "kv_dtype": os.environ.get("PADDLE_TPU_KV_DTYPE") or None,
        # the autotuned tiles this step's flash kernel ran with
        "flash_blocks": list(_flash_blocks(
            seq, cfg.hidden_size // cfg.num_heads)) if use_flash else None,
        "remat": remat,
        "remat_policy": remat_policy if remat else "off",
        "overlap": overlap_eff,
        "anomaly_policy": anomaly_policy,
        "ckpt_save_ms": ckpt_save_ms,
        "ckpt_async": ckpt_async,
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        # step-time breakdown (trainer.stats): where the wall clock went
        "warmup_s": round(warmup_s, 2),
        "prefetch_depth": prefetch_depth,
        "host_syncs_measured": host_syncs_measured,
        "compile_cache_dir": cache_dir,
        **{k: trainer_stats[k] for k in
           ("data_wait_ms", "h2d_ms", "dispatch_ms", "sync_ms",
            "compile_ms_cold", "steps_timed",
            # per-step wall time (profiler.StepTimer via the trainer)
            "step_time_ms", "step_time_mean_ms",
            # collective breakdown (None when BENCH_COMM_STATS=0 or the
            # AOT analysis failed)
            "comm_ms", "comm_fraction", "comm_bytes",
            "comm_collectives")},
    }
    # per-executable roofline digest (observability.exec_registry): the
    # MFU-attribution evidence ROADMAP item 1's hardware run reads
    row["exec_profile"] = exec_profile
    # perf-doctor verdict over THIS row's window figures (ISSUE 14):
    # the machine-readable "which knob next" the ROADMAP-1 triage wants
    # attached to every measured candidate
    from paddle_tpu.observability import doctor as _doctor
    row["doctor"] = _doctor.diagnose(
        {**trainer_stats, **row, "exec_profile": exec_profile},
        kind="train")
    _persist_row(row, kind="train")
    return row


def _transient_compile_error(e) -> bool:
    """Degraded remote-compile service (not a real OOM / shape error)."""
    s = f"{type(e).__name__}: {e}"
    return any(m in s for m in (
        "remote_compile", "HTTP 500", "HTTP 502", "HTTP 503",
        "tpu_compile_helper", "DEADLINE_EXCEEDED", "UNAVAILABLE",
        "Connection reset", "Connection refused",
        # remote-backend HBM can be held briefly by an expiring lease
        # from a killed client; a genuine OOM just costs one bounded
        # retry
        "RESOURCE_EXHAUSTED", "ResourceExhausted"))


def _backoff_s(attempt, base=15.0, cap=180.0):
    """Exponential backoff with full jitter: a degraded remote-compile
    helper recovers on its own schedule, and N clients hammering it in
    lockstep (the round-4 failure mode: fixed linear waits) just extend
    the brownout.  base·2^attempt capped, scaled by U[0.5, 1.5)."""
    import random
    return min(cap, base * (2 ** attempt)) * (0.5 + random.random())


def _retry_transient(fn, tries=3, label="bench"):
    """Run fn() with bounded exponential-backoff+jitter retries on
    TRANSIENT compile/execute failures (_transient_compile_error); real
    errors propagate immediately.  Shared by the train sweep and the
    serve/loadtest paths — run r04 was lost to a 500ing compile helper
    with no retry around the measured config."""
    for attempt in range(tries):
        try:
            return fn()
        except Exception as e:
            if not (attempt + 1 < tries and _transient_compile_error(e)):
                raise
            msg = f"{type(e).__name__}: {str(e)[:200]}"
        # the except block has exited: the exception + traceback (which
        # pin the dead attempt's device arrays) are freed before the
        # backoff, so HBM is clean for the retry
        import gc
        import jax as _jax
        gc.collect()
        try:
            _jax.clear_caches()
        except Exception:
            pass
        wait = _backoff_s(attempt)
        log(f"  {label}: transient compile failure ({msg}); "
            f"retry {attempt + 2}/{tries} in {wait:.0f}s")
        time.sleep(wait)


def bench_train_retry(config_name, batch, seq, steps, warmup,
                      use_flash=True, remat=None, tries=3, **knobs):
    """bench_train with backoff retries on transient compile failures.

    Round 4's number collapsed because every sweep point died on a
    degraded remote-compile helper (HTTP 500) and there was no retry.
    """
    return _retry_transient(
        lambda: bench_train(config_name, batch, seq, steps, warmup,
                            use_flash=use_flash, remat=remat, **knobs),
        tries=tries, label=f"{config_name} b{batch}")


def _candidate_key(c) -> tuple:
    """Normalize a sweep candidate spec (None = env default) into the
    SAME identity tuple _train_row_key derives from a persisted row, so
    resume can match them."""
    remat = c.get("remat")
    if os.environ.get("BENCH_RECOMPUTE") is not None:
        remat = os.environ["BENCH_RECOMPUTE"] != "0"
    elif remat is None:
        remat = True
    pol = (c.get("remat_policy") or "dots_no_batch") if remat else "off"
    scan = c.get("scan")
    if scan is None:
        scan = os.environ.get("BENCH_SCAN_LAYERS", "1") != "0"
    overlap = c.get("overlap")
    if overlap is None:
        overlap = os.environ.get("PADDLE_TPU_OVERLAP", "1") != "0"
    q = c.get("quantize")
    if q is None:
        q = os.environ.get("BENCH_QUANTIZE", "")
    q = str(q).strip().lower()
    q = "none" if q in ("", "0", "off", "none") else q
    return ("train", str(c["config"]), int(c["batch"]), int(c["seq"]),
            bool(c.get("flash", True)), bool(remat), str(pol),
            bool(scan), bool(overlap), q)


def _train_candidates(on_tpu):
    """The enumerated MFU sweep (ROADMAP item 1): quantize × flash ×
    scan × overlap × remat-policy as first-class candidates.
    BENCH_SWEEP=full crosses every axis on the primary config; the
    default curates the informative subset — the measured-good 125m
    recipe, the int8 attack on the 35→45 gap, the remat-policy A/B,
    single-knob scan/overlap ablations, and the aspirational 350m
    points."""
    if not on_tpu:
        return [dict(config="gpt3-tiny", batch=4, seq=256, steps=5,
                     warmup=2, flash=True)]
    primary = os.environ.get("BENCH_CONFIG", "gpt3-125m")
    batch = int(os.environ.get("BENCH_BATCH", 8))
    seq = int(os.environ.get("BENCH_SEQ", 2048))
    base = dict(config=primary, batch=batch, seq=seq, steps=20, warmup=3,
                flash=True)
    if os.environ.get("BENCH_SWEEP", "").strip().lower() == "full":
        cands = []
        for quantize in (None, "int8"):
            for flash in (True, False):
                for scan in (True, False):
                    for overlap in (True, False):
                        for remat in (False, True):
                            cands.append(dict(
                                base, flash=flash, scan=scan,
                                overlap=overlap, remat=remat,
                                quantize=quantize or "off"))
        return cands
    cands = [
        dict(base, remat=False),                       # r05's best recipe
        dict(base, remat=False, quantize="int8"),      # the int8 attack
        dict(base, remat=True, remat_policy="dots_no_batch",
             quantize="int8"),
        dict(base, remat=True, remat_policy="dots_no_batch"),
        dict(base, remat=True, remat_policy="full"),   # policy A/B
        dict(base, remat=False, scan=False),           # scan ablation
        dict(base, remat=False, overlap=False),        # overlap ablation
    ]
    if not os.environ.get("BENCH_CONFIG"):
        cands += [
            dict(config="gpt3-350m", batch=16, seq=seq, steps=20,
                 warmup=3, flash=True, remat=True),
            dict(config="gpt3-350m", batch=16, seq=seq, steps=20,
                 warmup=3, flash=True, remat=True, quantize="int8"),
        ]
    return cands


def _record_winner_tuning(result):
    """Persist the sweep winner's remat-policy choice into the unified
    tuning table so SpmdTrainer users that don't pin a policy inherit
    the measured one (op "remat_policy", key (device, h, layers,
    seq))."""
    try:
        from paddle_tpu.models.gpt import gpt_configs
        from paddle_tpu.distributed.spmd import remat_policy_key
        from paddle_tpu.utils import tuning as _tuning
        cfg = gpt_configs().get(result["config"])
        if cfg is None:
            return
        from dataclasses import replace as _replace
        key = remat_policy_key(_replace(cfg, max_seq_len=result["seq"]))
        if key is None:
            return
        _tuning.record("remat_policy", key, result["remat_policy"])
        log(f"  tuning: remat_policy{key} = {result['remat_policy']}")
    except Exception as e:
        log(f"  tuning: remat_policy record skipped: "
            f"{type(e).__name__}: {e}")


def _sweep_prefill_buckets(cfg, seq):
    """Measure each default prefill bucket's compiled latency and
    record a merged list (drop a bucket when padding up to the next one
    costs < 1.25×: fewer executables, nearly-free padding) into the
    unified tuning table (op "prefill_buckets")."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from dataclasses import replace as _replace
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.utils import tuning as _tuning

    paddle.seed(0)
    model = GPTForCausalLM(_replace(cfg, fused_ce=False))
    eng = InferenceEngine(model, batch_slots=2)
    times = {}
    for b in eng.buckets:
        ids = jnp.zeros((1, b), jnp.int32)
        fn = lambda: eng._prefill_jit(eng.params, eng.cache, ids,
                                      np.int32(0), np.int32(1))
        _, eng.cache = fn()                       # compile
        t0 = time.perf_counter()
        logits, eng.cache = fn()
        np.asarray(logits)                        # real sync
        times[b] = (time.perf_counter() - t0) * 1e3
    kept = [eng.buckets[-1]]
    for b in reversed(eng.buckets[:-1]):
        if times[b] < times[kept[0]] / 1.25:
            kept.insert(0, b)
    _tuning.record("prefill_buckets",
                   (_tuning.device_kind(), seq), kept)
    ms = {k: round(v, 1) for k, v in times.items()}
    log(f"  tuning: prefill_buckets({seq}) = {kept} (measured {ms})")
    return kept


def run_tuning_sweeps():
    """On-device sweeps persisted into the unified tuning table
    (utils.tuning), armed by PADDLE_TPU_TUNING=sweep on real TPU: int8
    qmm tiles for the bench config's projection shapes, the measured
    prefill-bucket list, and (multi-device) the MoE all-to-all chunk
    count.  Best-effort — a failed sweep leaves defaults in place."""
    import jax
    from paddle_tpu.utils import tuning as _tuning
    if not _tuning.sweep_enabled():
        return
    try:
        if jax.default_backend() != "tpu":
            return
    except Exception:
        return
    from dataclasses import replace as _replace
    from paddle_tpu.models.gpt import gpt_configs
    config_name = os.environ.get("BENCH_CONFIG", "gpt3-125m")
    seq = int(os.environ.get("BENCH_SEQ", 2048))
    batch = int(os.environ.get("BENCH_BATCH", 8))
    cfg = _replace(gpt_configs()[config_name], max_seq_len=seq)
    h, f = cfg.hidden_size, cfg.ffn_hidden_size
    kvd = cfg.num_kv_heads * cfg.head_dim
    try:
        from paddle_tpu.ops.quantized_matmul import get_qmm_tiles
        m = batch * seq
        for (n, k) in ((h + 2 * kvd, h), (h, h), (f, h), (h, f)):
            tiles = get_qmm_tiles(m, n, k)    # sweeps + records if armed
            log(f"  tuning: qmm_tiles(m={m}, n={n}, k={k}) -> {tiles}")
    except Exception as e:
        log(f"  tuning: qmm sweep skipped: {type(e).__name__}: {e}")
    try:
        _sweep_prefill_buckets(cfg, seq)
    except Exception as e:
        log(f"  tuning: prefill bucket sweep skipped: "
            f"{type(e).__name__}: {e}")
    try:
        import jax as _jax
        if len(_jax.devices()) > 1:
            from paddle_tpu.distributed.overlap import autotune_a2a_sweep
            autotune_a2a_sweep(batch * seq)
    except Exception as e:
        log(f"  tuning: a2a sweep skipped: {type(e).__name__}: {e}")


def _serve_sweep():
    """TPU serve bench with megakernel off/on as enumerated candidates
    (ROADMAP item 1's missing serve axis), resume-aware; the winner is
    THE one JSON line."""
    measured = _measured_rows("serve")
    config = os.environ.get("BENCH_CONFIG", "gpt3-125m")
    from paddle_tpu.ops.quantized_matmul import resolve_kv_quant
    kv_dtype = resolve_kv_quant(None) or "dense"
    best, rows, last_err = None, [], None
    for mk in (False, True):
        key = ("serve", config, _serve_slots(), kv_dtype, mk,
               _SERVE_DEFAULTS["prompt_len"],
               _SERVE_DEFAULTS["gen_tokens"],
               int(os.environ.get("PADDLE_TPU_SERVE_TP", "1") or 1))
        if key in measured:
            log(f"  serve resume: skipping measured megakernel={mk}")
            row = dict(measured[key])
        else:
            try:
                row = _retry_transient(
                    lambda mk=mk: bench_serve(smoke=False,
                                              decode_megakernel=mk,
                                              emit=False),
                    tries=3, label=f"serve mk={mk}")
            except Exception as e:
                last_err = f"{type(e).__name__}: {str(e)[:300]}"
                log(f"  serve megakernel={mk} failed: {last_err}")
                continue
        rows.append(row)
        if best is None or (row.get("value") or 0) > \
                (best.get("value") or 0):
            best = row
    if best is None:
        raise SystemExit(f"all serve candidates failed: {last_err}")
    best = dict(best)
    best["candidates"] = [
        {k: r.get(k) for k in ("decode_megakernel", "value",
                               "decode_hbm_bytes_per_tok",
                               "step_ms_p50", "decode_tokens_per_sec")}
        for r in rows]
    print(json.dumps(best))


def bench_flash(seqs=(1024, 2048, 4096), batch=8):
    """Secondary microbench: Pallas flash vs XLA composite, fwd+bwd."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu import ops as _ops
    from paddle_tpu.nn.functional.attention import _sdpa_reference

    rows = []
    for s in seqs:
        q = jnp.asarray(np.random.RandomState(0)
                        .randn(batch, s, 12, 64).astype(np.float32) * 0.1,
                        dtype=jnp.bfloat16)

        def run(fn):
            lfn = jax.jit(jax.grad(
                lambda q_, k_, v_: fn(q_, k_, v_).astype(jnp.float32)
                .sum()))
            # host transfer forces real sync: block_until_ready returns
            # early on the remote backend (measured 0.02ms "timings")
            float(lfn(q, q, q).astype(jnp.float32).sum())
            n, t0 = 10, time.perf_counter()
            g = None
            for _ in range(n):
                g = lfn(q, q, q)
            float(g.astype(jnp.float32).sum())
            return (time.perf_counter() - t0) / n * 1e3

        comp_ms = run(lambda a, b, c: _sdpa_reference(
            a, b, c, is_causal=True))
        row = {"seq": s, "composite_ms": round(comp_ms, 2),
               "flash_blocks": list(_flash_blocks(s, 64))}
        if _ops.flash_attention_available():
            flash_ms = run(lambda a, b, c: _ops.flash_attention(
                a, b, c, causal=True))
            row["flash_ms"] = round(flash_ms, 2)
            row["speedup"] = round(comp_ms / flash_ms, 2)
        rows.append(row)
        log(f"  flash bench {row}")
    return rows


# TPU serve-bench candidate defaults, shared with _serve_sweep's resume
# keys so the two can never drift apart
_SERVE_DEFAULTS = {"prompt_len": 128, "gen_tokens": 64}


def _serve_slots() -> int:
    return int(os.environ.get("PADDLE_TPU_DECODE_SLOTS", 8))


def bench_serve(config_name=None, batch_slots=None, prompt_len=None,
                gen_tokens=None, num_requests=None, smoke=False,
                decode_megakernel=None, emit=True):
    """Serving-path bench (`--serve`): continuous-batching engine
    throughput on the winning train config's model — prefill+decode
    tokens/sec, p50/p95 per-decode-step latency, slot occupancy, and
    the recompile-free-decode proof (compile counter).  `--serve
    --smoke` is the CPU dry run: asserts the decode executable compiles
    ONCE across 8 generated tokens and that host syncs stay at one per
    decode step + one per admission."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from dataclasses import replace
    from paddle_tpu.distributed import async_dispatch
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_configs
    from paddle_tpu.utils import compile_counter
    from paddle_tpu.utils.compile_cache import ensure_compile_cache

    cache_dir = ensure_compile_cache()
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if smoke or not on_tpu:
        config_name = config_name or "gpt3-tiny"
        batch_slots = batch_slots or 2
        prompt_len = prompt_len or 6
        gen_tokens = gen_tokens or 8
        num_requests = num_requests or 3
        seq = 64
    else:
        # the winning train config (BENCH_r05 trajectory: gpt3-125m)
        config_name = config_name or os.environ.get("BENCH_CONFIG",
                                                    "gpt3-125m")
        batch_slots = batch_slots or _serve_slots()
        prompt_len = prompt_len or _SERVE_DEFAULTS["prompt_len"]
        gen_tokens = gen_tokens or _SERVE_DEFAULTS["gen_tokens"]
        num_requests = num_requests or 2 * batch_slots
        seq = int(os.environ.get("BENCH_SEQ", 2048))
    cfg = replace(gpt_configs()[config_name], max_seq_len=seq,
                  fused_ce=False)
    log(f"serve bench: {config_name} slots={batch_slots} "
        f"prompt={prompt_len} gen={gen_tokens} requests={num_requests} "
        f"({cfg.num_params() / 1e6:.0f}M params)")

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if decode_megakernel is not None:
        # candidate axis of the serve sweep; None keeps the config/env
        # default (ops.decode_megakernel.megakernel_enabled)
        model.enable_decode_megakernel(bool(decode_megakernel))
    eng = InferenceEngine(model, batch_slots=batch_slots)
    rng = np.random.RandomState(0)

    bucket = eng._bucket_for(prompt_len)
    t0 = time.perf_counter()
    eng.warmup(buckets=[bucket])
    warmup_s = time.perf_counter() - t0
    log(f"  warmup+compile {warmup_s:.1f}s "
        f"(cold {eng.stats['compile_ms_cold']:.0f}ms)")

    prompts = [rng.randint(1, cfg.vocab_size, (prompt_len,))
               .astype(np.int32) for _ in range(num_requests)]
    snap = compile_counter.snapshot()
    async_dispatch.reset_host_sync_count()
    step_ms, admit_ms = [], []
    t0 = time.perf_counter()
    for p in prompts:
        eng.add_request(p, max_new_tokens=gen_tokens)
    while eng._queue or eng.num_active:
        p0 = eng._timings["prefills"]
        ts = time.perf_counter()
        eng.step()
        dt_ms = (time.perf_counter() - ts) * 1e3
        # p50/p95 must mean DECODE latency: steps that ran a prefill
        # admission are tracked separately (a prefill is orders of
        # magnitude slower and would drown the decode trend line)
        if eng._timings["prefills"] == p0:
            step_ms.append(dt_ms)
        else:
            admit_ms.append(dt_ms)
    dt = time.perf_counter() - t0
    syncs = async_dispatch.host_sync_count()
    stats = eng.stats

    total_tokens = stats["tokens_generated"] + stats["prefills"]
    decode_lat = np.percentile(step_ms, [50, 95]) if step_ms else [0, 0]
    out = {
        "metric": "gpt_serve_tokens_per_sec",
        "value": round(total_tokens / dt, 2),
        "unit": "tok/s",
        "config": config_name,
        "batch_slots": batch_slots,
        "kv_dtype": eng.kv_dtype or "dense",
        "prompt_len": prompt_len,
        "prefill_bucket": bucket,
        "gen_tokens": gen_tokens,
        "num_requests": num_requests,
        "wall_s": round(dt, 3),
        "tokens_generated": total_tokens,
        "step_ms_p50": round(float(decode_lat[0]), 3),
        "step_ms_p95": round(float(decode_lat[1]), 3),
        "admit_step_ms_p50": round(float(np.percentile(admit_ms, 50)), 3)
        if admit_ms else None,
        "admit_steps": len(admit_ms),
        "slot_occupancy": stats["slot_occupancy"],
        "prefill_ms_total": stats["prefill_ms"],
        "decode_ms_total": stats["decode_ms"],
        "decode_tokens_per_sec": stats["decode_tokens_per_sec"],
        # megakernel sweep axis + the decode loop's HBM traffic per
        # token (int8-aware; the fused kernel's saving as a NUMBER)
        "decode_megakernel": stats["decode_megakernel"],
        "decode_hbm_bytes_per_tok": stats["decode_hbm_bytes_per_tok"],
        # pod-scale serving (ISSUE 18/19): the tensor- and
        # expert-parallel sweep axes (both join the resume row key)
        "tp": stats["tp"],
        "ep": stats["ep"],
        # chunked prefill (ISSUE 20): sweep axis (joins the resume row
        # key) + the stall the un-chunked scheduler measures
        "chunked_prefill": stats["chunked_prefill"],
        "prefill_chunk": stats["prefill_chunk"],
        "prefill_stall_ms": stats["prefill_stall_ms"],
        "moe_num_experts": stats.get("moe_num_experts", 0),
        "serving_mesh": stats.get("serving_mesh"),
        "compile_ms_cold": stats["compile_ms_cold"],
        "xla_compiles_measured": snap.new_compiles,
        "host_syncs_measured": syncs,
        "warmup_s": round(warmup_s, 2),
        "compile_cache_dir": cache_dir,
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }
    if stats.get("moe_num_experts"):
        # expert-balance columns (ISSUE 19): the load histogram,
        # overflow rate and skew the expert-imbalance doctor rule reads
        for k in ("moe_expert_load", "moe_dropped_rate",
                  "moe_load_skew", "moe_assigned_tokens"):
            out[k] = stats.get(k)
    # perf-doctor verdict for this row (observability.doctor): the
    # engine's serving signals + this window's measured compile count
    from paddle_tpu.observability import doctor as _doctor
    out["doctor"] = _doctor.diagnose({**stats, **out}, kind="serve")
    log(f"  serve: {out['value']} tok/s, decode p50 "
        f"{out['step_ms_p50']}ms p95 {out['step_ms_p95']}ms, "
        f"occupancy {out['slot_occupancy']}, "
        f"compiles in measured window: {snap.new_compiles}")

    if smoke:
        # the acceptance contract: after warmup, the decode loop (8+
        # generated tokens across several requests) triggers ZERO new
        # XLA compiles — a shape wobble (the old concat cache) would
        # recompile per token and show up here
        if snap.new_compiles != 0:
            raise SystemExit(
                f"serve --smoke: {snap.new_compiles} XLA compiles during "
                f"the measured window (expected 0 after warmup — the "
                f"decode path is not shape-stable)")
        # one sync per decode step (sampled-token readback) + one per
        # admission (first-token sample): anything more means a hidden
        # per-step read-back crept into the scheduler
        budget = stats["decode_steps"] + stats["prefills"]
        if syncs > budget:
            raise SystemExit(
                f"serve --smoke: {syncs} host syncs for "
                f"{stats['decode_steps']} decode steps + "
                f"{stats['prefills']} admissions (budget {budget})")
        if stats["tokens_generated"] < 8:
            raise SystemExit("serve --smoke: fewer than 8 tokens decoded")
        out["metric"] = "serve_smoke"
        out["ok"] = True
        log(f"  serve smoke ok: {total_tokens} tokens, 0 compiles, "
            f"{syncs} syncs/{budget} budget")
        # tp=2 CPU-mesh leg (ISSUE 18): subprocess, because the virtual
        # device count can't change in an already-imported jax
        _smoke_serve_tp()
        out["serve_tp_smoke"] = True
        # ep=2 CPU-mesh leg (ISSUE 19): expert-parallel MoE serving
        # parity on the same 8-virtual-device subprocess pattern
        _smoke_serve_ep()
        out["serve_ep_smoke"] = True
        # tier-1 wall-budget guard (ISSUE 19 satellite): fail the smoke
        # when a test file's fast lane outgrows the per-file budget
        _smoke_tier1_budget()
    # executable observatory (ISSUE 15): analyze AFTER the measured
    # window + smoke assertions (the AOT re-lower is a compile the
    # 0-compile contract must not see) and attach the per-executable
    # roofline digest to the serve row
    out["exec_profile"] = None
    if os.environ.get("BENCH_EXEC_PROFILE", "1") != "0":
        try:
            from paddle_tpu.observability import exec_registry as _er
            _er.analyze_all(eng._exec_component)
            out["exec_profile"] = _er.profile(eng._exec_component)
        except Exception as e:
            log(f"  exec profile skipped: {type(e).__name__}: {e}")
    _persist_row(out, kind="serve")
    if emit:
        print(json.dumps(out))
    return out


def _loadtest_telemetry_smoke(obs):
    """Telemetry columns of the loadtest smoke (ISSUE 13): the Poisson
    window ran with spans armed, so the buffer must render a
    per-request Chrome-trace timeline (queued/prefill/decode spans on
    request tracks) that validates, and the process registry must emit
    a Prometheus exposition a parser round-trips.  The trace lands next
    to BENCH_rows.jsonl as BENCH_serve_trace.json for inspection."""
    doc = obs.tracer().chrome_trace()
    n_events = obs.validate_chrome_trace(doc)
    req_names = {e["name"] for e in doc["traceEvents"]
                 if e.get("pid") == obs.spans.PID_REQUESTS
                 and e["ph"] == "X"}
    for need in ("queued", "prefill", "decode"):
        if need not in req_names:
            raise SystemExit(
                f"loadtest --smoke: per-request timeline is missing "
                f"{need!r} spans (request-track spans: "
                f"{sorted(req_names)})")
    trace_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_serve_trace.json")
    try:
        obs.tracer().export(trace_path)
    except OSError as e:
        log(f"  trace export skipped: {e}")
        trace_path = None
    text = obs.registry().exposition()
    parsed = obs.parse_exposition(text)
    for family in ("serve_decode_ticks_total", "serve_ttft_ms",
                   "kv_blocks_in_use", "host_syncs_total"):
        if family not in parsed:
            raise SystemExit(
                f"loadtest --smoke: {family!r} missing from the "
                f"Prometheus exposition")
    log(f"  telemetry: {n_events} trace events "
        f"({len(req_names)} request span kinds), "
        f"{len(parsed)} exposition families")
    return {"telemetry_trace_events": n_events,
            "telemetry_trace_path": trace_path,
            "telemetry_exposition_families": len(parsed)}


def _smoke_chunked():
    """Chunked-prefill smoke (ISSUE 20, rides --serve --loadtest
    --smoke): PAIRED open-loop runs — identical prompts + identical
    Poisson arrivals — on one paged replica with chunked prefill ON vs
    OFF at a rate calibrated to this machine's capacity.  The contract:

    - ZERO XLA compiles in either measured window (the chunk
      executable is as shape-stable as the decode one — slot churn,
      graduation and preemption resume never retrace);
    - block pool leak-free at drain in both modes, and
      ``prefill_stall_ms`` identically 0 under chunking (the stall the
      un-chunked engine measures is DEFINED away, not just reduced);
    - p99 inter-token latency STRICTLY improves with chunking at equal
      offered load — long prompts stop stalling running decodes —
      with throughput inside the noise floor.  Single-run p99 on a
      busy CI host carries scheduler jitter, so the comparison may
      retry on up to 3 paired arrival seeds; the reported columns are
      the winning pair's.

    Returns the chunked columns merged into the loadtest smoke JSON."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.inference.loadgen import (SharedPrefixWorkload,
                                              run_loadtest)
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.utils import compile_counter

    cfg = GPTConfig(vocab_size=211, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=256,
                    use_flash_attention=False)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    chunk = 16
    # long prompts (~7 chunks) against short decodes: the regime where
    # one monolithic prefill visibly stalls every running decode
    wl_kw = dict(shared_frac=0.5, prefix_len=96, tail_len=(3, 10),
                 max_new=(4, 8))

    def mk_engine(chunked):
        e = InferenceEngine(model, batch_slots=4,
                            prefill_buckets=[16, 128],
                            kv_layout="paged", kv_block_size=16,
                            kv_num_blocks=48,
                            prefill_chunk=chunk if chunked else 0)
        e.warmup(buckets=e.buckets)
        return e

    # calibrate the Poisson rate to THIS machine: a closed-loop burst
    # on the warmed UNCHUNKED engine ~= its service capacity; at that
    # rate prompts and running decodes genuinely contend, which is the
    # regime chunking exists for (the comparison stays paired either
    # way, so a fast/slow host shifts both numbers together)
    calw = SharedPrefixWorkload(cfg.vocab_size, seed=9, **wl_kw)
    cal = mk_engine(False)
    t0 = time.perf_counter()
    for _ in range(12):
        p, mn = calw.sample()
        cal.add_request(p, max_new_tokens=mn)
    while cal._queue or cal.num_active:
        cal.step()
    rate = 12 / max(time.perf_counter() - t0, 1e-3)
    cal.check_leak_free()
    del cal, calw                       # release the calibration pool
    log(f"  chunked smoke: calibrated rate {rate:.1f} rps")

    def run_mode(chunked, seed):
        wl = SharedPrefixWorkload(cfg.vocab_size, seed=3, **wl_kw)
        eng = mk_engine(chunked)
        snap = compile_counter.snapshot()
        rep = run_loadtest(eng, 32, rate, workload=wl, seed=seed)
        if snap.new_compiles:
            raise SystemExit(
                f"chunked smoke: {snap.new_compiles} XLA compiles in "
                f"the measured window (chunked={chunked}) — the "
                f"chunked-prefill path is not shape-stable")
        stall = eng.stats["prefill_stall_ms"]
        if chunked and stall:
            raise SystemExit(
                f"chunked smoke: prefill_stall_ms {stall} != 0 under "
                f"chunking — a monolithic prefill ran anyway")
        try:
            eng.check_leak_free()
        except AssertionError as e:
            raise SystemExit(f"chunked smoke: {e}")
        rep["prefill_stall_ms"] = stall
        return rep

    NOISE = 0.25    # paired tok/s jitter floor on a busy CPU CI host
    win = None
    pairs = 0
    for seed in (0, 1, 2):
        a, b = run_mode(True, seed), run_mode(False, seed)
        pairs += 1
        if a["itl_ms_p99"] is None or b["itl_ms_p99"] is None:
            raise SystemExit("chunked smoke: ITL columns missing from "
                             "the loadtest report")
        log(f"  chunked pair seed={seed}: ITL p99 "
            f"{a['itl_ms_p99']}/{b['itl_ms_p99']}ms, tok/s "
            f"{a['tokens_per_sec']}/{b['tokens_per_sec']}, stall "
            f"{b['prefill_stall_ms']}ms")
        if a["itl_ms_p99"] < b["itl_ms_p99"] and \
                a["tokens_per_sec"] >= b["tokens_per_sec"] * (1 - NOISE):
            win = (a, b)
            break
    if win is None:
        raise SystemExit(
            "chunked smoke: chunked prefill never beat unchunked on "
            "p99 ITL (with tok/s inside the noise floor) across 3 "
            "paired arrival seeds")
    a, b = win
    return {
        "chunked_smoke_pairs_run": pairs,
        "chunked_rate_rps": round(rate, 2),
        "chunked_prefill_chunk": chunk,
        "chunked_itl_ms_p99": a["itl_ms_p99"],
        "unchunked_itl_ms_p99": b["itl_ms_p99"],
        "chunked_itl_ms_p50": a["itl_ms_p50"],
        "unchunked_itl_ms_p50": b["itl_ms_p50"],
        "chunked_tokens_per_sec": a["tokens_per_sec"],
        "unchunked_tokens_per_sec": b["tokens_per_sec"],
        "unchunked_prefill_stall_ms": b["prefill_stall_ms"],
    }


def _fleet_smoke():
    """The serving-FLEET smoke (CPU, rides --serve --loadtest --smoke):
    2 paged replicas + the prefix-aware router + speculative decoding,
    asserting the ISSUE-12 contract end to end:

    - ZERO XLA compiles during every measured window (draft prefill,
      spec tick, both replicas, both policies — the whole fleet is
      shape-stable after warmup);
    - block pools leak-free at drain on every replica;
    - accepted_tokens_per_tick > 1.5 (the spec tick amortizes its one
      host sync over >1.5 committed tokens; the smoke drafts with the
      target itself, the acceptance-rate ceiling — a real deployment
      plugs in a small draft config);
    - cache-aware routing beats round-robin on PREFIX HIT RATE and on
      p99 TTFT under the skewed-tenant workload.  The comparison is
      PAIRED (identical Poisson arrivals + prompts per policy) at a
      rate calibrated to this machine's measured capacity; the hit-rate
      win must hold on EVERY pair, and because single-run p99 on a
      busy CI host carries scheduler jitter, the p99 comparison may be
      retried on up to 3 paired arrival seeds — the reported row is
      the winning pair.

    Returns the fleet columns merged into the loadtest smoke JSON."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.inference.loadgen import (MultiTenantWorkload,
                                              run_fleet_loadtest,
                                              warm_fleet)
    from paddle_tpu.inference.router import Router
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.utils import compile_counter

    cfg = GPTConfig(vocab_size=211, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=256,
                    use_flash_attention=False)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    wl_kw = dict(num_tenants=6, skew=0.5, prefix_len=112,
                 tail_len=(3, 10), max_new=(2, 4))

    def mk_fleet(policy):
        reps = []
        for _ in range(2):
            # pool sized so ONE replica cannot cache every tenant's
            # prefix (6 tenants x 7 blocks > 30): round-robin thrashes,
            # the prefix router's per-replica partition fits — the
            # regime cache-aware routing exists for
            e = InferenceEngine(model, batch_slots=4,
                                prefill_buckets=[16, 128],
                                kv_layout="paged", kv_block_size=16,
                                kv_num_blocks=30, spec_k=2,
                                draft_model=model)
            e.warmup(buckets=e.buckets)
            reps.append(e)
        # gap=1: affinity holds while the replicas stay within one
        # request of each other — tight enough that placement is
        # near-least-loaded (the tail stays healthy), loose enough
        # that tenants keep their home replica (the hit rate stays
        # high); swept in ISSUE-12 bring-up, 3/3 paired wins
        return Router(reps, policy=policy, max_load_gap=1)

    # calibrate the Poisson rate to THIS machine: closed-loop burst on
    # a warmed prefix fleet ~= its service capacity; driving both
    # fleets at that rate puts them at critical load, where routing
    # quality shows in the tail (the comparison stays paired either
    # way, so a fast/slow CI host only shifts both numbers together)
    calw = MultiTenantWorkload(cfg.vocab_size, seed=9, **wl_kw)
    cal = mk_fleet("prefix")
    warm_fleet(cal, calw)
    t0 = time.perf_counter()
    for _ in range(16):
        _t, p, mn = calw.sample()
        cal.add_request(p, max_new_tokens=mn)
    cal.run()
    rate = 16 / max(time.perf_counter() - t0, 1e-3)
    for r in cal.replicas:
        r.check_leak_free()
    del cal, calw          # release the calibration fleet's pools
    log(f"  fleet smoke: calibrated rate {rate:.1f} rps")

    def run_pair(seed):
        reports = {}
        for policy in ("prefix", "round_robin"):
            wl = MultiTenantWorkload(cfg.vocab_size, seed=3, **wl_kw)
            fleet = mk_fleet(policy)
            warm_fleet(fleet, wl)
            snap = compile_counter.snapshot()
            rep = run_fleet_loadtest(fleet, 48, rate, workload=wl,
                                     seed=seed)
            if snap.new_compiles:
                raise SystemExit(
                    f"fleet smoke: {snap.new_compiles} XLA compiles in "
                    f"the measured window (policy={policy}) — the "
                    f"spec-decode/fleet path is not shape-stable")
            for r in fleet.replicas:
                try:
                    r.check_leak_free()
                except AssertionError as e:
                    raise SystemExit(f"fleet smoke: {e}")
            reports[policy] = rep
        return reports["prefix"], reports["round_robin"]

    win = None
    pairs = 0
    for seed in (0, 1, 2):
        a, b = run_pair(seed)
        pairs += 1
        if not a["prefix_hit_rate"] > b["prefix_hit_rate"]:
            raise SystemExit(
                f"fleet smoke: prefix routing did not beat round-robin "
                f"on hit rate ({a['prefix_hit_rate']} vs "
                f"{b['prefix_hit_rate']})")
        log(f"  fleet pair seed={seed}: hit "
            f"{a['prefix_hit_rate']}/{b['prefix_hit_rate']}, p99 "
            f"{a['ttft_ms_p99']}/{b['ttft_ms_p99']}ms, per_tick "
            f"{a.get('accepted_tokens_per_tick')}")
        if a["ttft_ms_p99"] < b["ttft_ms_p99"]:
            win = (a, b)
            break
    if win is None:
        raise SystemExit(
            "fleet smoke: prefix routing never beat round-robin on p99 "
            "TTFT across 3 paired runs")
    a, b = win
    if not (a.get("accepted_tokens_per_tick") or 0) > 1.5:
        raise SystemExit(
            f"fleet smoke: accepted_tokens_per_tick "
            f"{a.get('accepted_tokens_per_tick')} <= 1.5")
    return {
        "fleet_replicas": a["num_replicas"],
        "fleet_rate_rps": round(rate, 2),
        "fleet_pairs_run": pairs,
        "fleet_spec_k": 2,
        "accepted_tokens_per_tick": a["accepted_tokens_per_tick"],
        "fleet_prefix_hit_rate": a["prefix_hit_rate"],
        "fleet_rr_prefix_hit_rate": b["prefix_hit_rate"],
        "fleet_router_hit_rate": a["router_hit_rate"],
        "fleet_ttft_ms_p99": a["ttft_ms_p99"],
        "fleet_rr_ttft_ms_p99": b["ttft_ms_p99"],
        "fleet_ttft_ms_p50": a["ttft_ms_p50"],
        "fleet_rr_ttft_ms_p50": b["ttft_ms_p50"],
        "fleet_replica_occupancy": a["replica_occupancy"],
        "fleet_requests_per_replica": a["requests_per_replica"],
        "fleet_tokens_per_sec": a["tokens_per_sec"],
        # observability tentpole columns (ISSUE 14): per-replica
        # tick-time skew verdict + the fleet doctor's knob ranking
        "fleet_straggler": a["straggler"],
        "fleet_doctor": a["doctor"],
    }


def bench_loadtest(smoke=False):
    """`--serve --loadtest`: open-loop Poisson load test against the
    PAGED engine (block-pool KV + radix prefix cache) — p50/p99
    time-to-first-token, tokens/sec, slot AND block-pool occupancy,
    prefix-cache hit rate, preemptions.  `--serve --loadtest --smoke`
    is the CPU dry run / CI contract: a few dozen Poisson arrivals with
    shared-prefix prompts must run with ZERO XLA compiles after warmup,
    drain the block pool leak-free (free == total), and score a
    prefix-cache hit rate > 0."""
    import jax
    import paddle_tpu as paddle
    from dataclasses import replace
    from paddle_tpu.distributed import async_dispatch
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.inference.loadgen import (SharedPrefixWorkload,
                                              run_loadtest)
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_configs
    from paddle_tpu.utils import compile_counter
    from paddle_tpu.utils.compile_cache import ensure_compile_cache

    cache_dir = ensure_compile_cache()
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if smoke or not on_tpu:
        config_name, seq, slots = "gpt3-tiny", 64, 4
        block_size, num_blocks = 8, 28
        num_requests, rate_rps = 24, 100.0
        # two buckets cover the whole smoke workload (prompts <= 28,
        # roomy 28-block pool => no preemption resumes past 32); fewer
        # buckets = fewer warmup executables = cheaper tier-1 smoke
        buckets = [16, 32]
        wl_kw = dict(shared_frac=0.6, prefix_len=16, tail_len=(3, 12),
                     max_new=(4, 10))
    else:
        buckets = None
        config_name = os.environ.get("BENCH_CONFIG", "gpt3-125m")
        seq = int(os.environ.get("BENCH_SEQ", 2048))
        slots = int(os.environ.get("PADDLE_TPU_DECODE_SLOTS", 8))
        block_size = int(os.environ.get("PADDLE_TPU_KV_BLOCK_SIZE", 128))
        num_blocks = int(os.environ.get("PADDLE_TPU_KV_BLOCKS", 0)) or None
        num_requests = int(os.environ.get("BENCH_LOAD_REQUESTS",
                                          4 * slots))
        rate_rps = float(os.environ.get("BENCH_LOAD_RPS", 4.0))
        wl_kw = dict(shared_frac=0.5, prefix_len=2 * block_size,
                     tail_len=(16, 128), max_new=(32, 96))
    cfg = replace(gpt_configs()[config_name], max_seq_len=seq,
                  fused_ce=False)
    log(f"loadtest: {config_name} slots={slots} block_size={block_size} "
        f"requests={num_requests} rate={rate_rps}/s "
        f"({cfg.num_params() / 1e6:.0f}M params)")

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    eng = InferenceEngine(model, batch_slots=slots, kv_layout="paged",
                          kv_block_size=block_size,
                          kv_num_blocks=num_blocks,
                          prefill_buckets=buckets)
    t0 = time.perf_counter()
    # every bucket's cold AND traced-prefix prefill + decode + sample:
    # Poisson traffic (incl. preemption resumes) may touch any of them,
    # and the measured window must stay compile-free
    eng.warmup(buckets=eng.buckets)
    warmup_s = time.perf_counter() - t0
    log(f"  warmup+compile {warmup_s:.1f}s "
        f"(cold {eng.stats['compile_ms_cold']:.0f}ms)")

    workload = SharedPrefixWorkload(cfg.vocab_size, seed=0, **wl_kw)
    # --smoke: spans ARMED through the measured window (ISSUE 13) — the
    # compile/sync assertions below therefore hold with telemetry ON,
    # and the buffer renders the per-request timeline the smoke
    # validates.  Real measurements keep spans opt-in
    # (PADDLE_TPU_SPANS): an un-consumed 250k-event buffer has no
    # business inside a row that claims steady-state numbers.
    from paddle_tpu import observability as obs
    if smoke:
        obs.tracer().start()
    snap = compile_counter.snapshot()
    async_dispatch.reset_host_sync_count()
    report = run_loadtest(eng, num_requests, rate_rps, workload=workload)
    st = eng.stats
    out = {
        "metric": "gpt_serve_loadtest",
        "value": report["tokens_per_sec"],
        "unit": "tok/s",
        "config": config_name,
        "batch_slots": slots,
        "kv_dtype": eng.kv_dtype or "dense",
        **report,
        "decode_steps": st["decode_steps"],
        "chunked_prefill": st["chunked_prefill"],
        "prefill_chunk": st["prefill_chunk"],
        "prefill_stall_ms": st["prefill_stall_ms"],
        "xla_compiles_measured": snap.new_compiles,
        "jaxpr_traces_measured": snap.new_traces,
        "host_syncs_measured": async_dispatch.host_sync_count(),
        "warmup_s": round(warmup_s, 2),
        "compile_ms_cold": st["compile_ms_cold"],
        "compile_cache_dir": cache_dir,
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }
    log(f"  loadtest: {out['value']} tok/s, TTFT p50 "
        f"{report['ttft_ms_p50']}ms p99 {report['ttft_ms_p99']}ms, "
        f"block occupancy {report.get('block_occupancy')}, prefix hit "
        f"rate {report.get('prefix_hit_rate')}, "
        f"preemptions {report['preemptions']}, compiles in window: "
        f"{snap.new_compiles}")

    if smoke:
        if snap.new_compiles != 0:
            raise SystemExit(
                f"loadtest --smoke: {snap.new_compiles} XLA compiles "
                f"during the Poisson window (expected 0 after warmup — "
                f"the paged decode/prefill path is not shape-stable)")
        # leak check: flush the radix cache, then EVERY pool block must
        # be back on the free list (free == total)
        try:
            eng.check_leak_free()
        except AssertionError as e:
            raise SystemExit(f"loadtest --smoke: {e}")
        if not report.get("prefix_hit_rate"):
            raise SystemExit(
                "loadtest --smoke: prefix-cache hit rate is 0 on a "
                "shared-prefix workload — radix matching is broken")
        if report["num_requests"] < num_requests:
            raise SystemExit(
                f"loadtest --smoke: only {report['num_requests']}/"
                f"{num_requests} requests completed")
        out["metric"] = "loadtest_smoke"
        out["ok"] = True
        out["kv_blocks_free_at_drain"] = eng._alloc.num_free
        out.update(_loadtest_telemetry_smoke(obs))
        log(f"  loadtest smoke ok: {report['tokens_generated']} tokens, "
            f"0 compiles, pool drained "
            f"{eng._alloc.num_free}/{eng._alloc.capacity} free, "
            f"hit rate {report['prefix_hit_rate']}")
        # the serving-FLEET smoke rides along (ISSUE 12): 2 replicas +
        # prefix-aware router + spec decode, its columns merged into
        # this one JSON line
        out.update(_fleet_smoke())
        log(f"  fleet smoke ok: hit {out['fleet_prefix_hit_rate']} vs "
            f"rr {out['fleet_rr_prefix_hit_rate']}, p99 "
            f"{out['fleet_ttft_ms_p99']}ms vs rr "
            f"{out['fleet_rr_ttft_ms_p99']}ms, "
            f"{out['accepted_tokens_per_tick']} accepted tokens/tick")
        # chunked-prefill leg (ISSUE 20): paired chunked-vs-unchunked
        # loadtest at equal offered load — p99 ITL must win, tok/s must
        # stay in the noise, 0 compiles, pools leak-free
        out.update(_smoke_chunked())
        log(f"  chunked smoke ok: ITL p99 "
            f"{out['chunked_itl_ms_p99']}ms vs "
            f"{out['unchunked_itl_ms_p99']}ms unchunked, tok/s "
            f"{out['chunked_tokens_per_sec']} vs "
            f"{out['unchunked_tokens_per_sec']}")
    _persist_row(out, kind="loadtest")
    print(json.dumps(out))


def bench_multichip_child():
    """Child half of --multichip-smoke (runs with JAX_PLATFORMS=cpu and
    8 virtual host devices): executes the shared overlap-parity phases
    and prints ONE JSON line.  Each phase asserts sync-vs-overlap loss
    parity (rtol 1e-5), zero XLA recompiles across steps 2..N, and that
    the new comm_ms/comm_fraction stats fields exist — a phase failure
    exits non-zero.  The elastic phase additionally proves the ISSUE-10
    contract: train on dp=8, checkpoint, restore on dp=4 with loss
    parity and no unexpected recompiles after the restore."""
    import time as _time
    import jax
    from paddle_tpu.testing import multichip

    t0 = _time.perf_counter()
    phases = []
    for fn in (multichip.run_zero3_phase, multichip.run_1f1b_phase,
               multichip.run_moe_a2a_phase,
               multichip.run_elastic_restore_phase,
               multichip.run_dcn_phase, multichip.run_serve_tp_phase,
               multichip.run_serve_ep_phase):
        r = fn()
        phases.append(r)
        log(f"  multichip phase {r['name']} ok t={r['t_s']}s")
    out = {
        "metric": "multichip_smoke", "ok": True,
        "n_devices": len(jax.devices()),
        "wall_s": round(_time.perf_counter() - t0, 1),
        "overlap_env": os.environ.get("PADDLE_TPU_OVERLAP", "1"),
        "parity_rtol": multichip.PARITY_RTOL,
        "phases": phases,
    }
    print(json.dumps(out))


def bench_serve_tp_child():
    """Child half of the --serve --smoke tp leg (runs with
    JAX_PLATFORMS=cpu and 8 virtual host devices): tp=2 serving must be
    token-identical to tp=1 on both KV layouts, recompile-free after
    warmup, with submesh meta on the exec-registry entries.  Prints ONE
    JSON line; any violated contract raises and exits non-zero."""
    from paddle_tpu.testing import multichip
    out = multichip.run_serve_tp_phase()
    out["metric"] = "serve_tp_smoke"
    out["ok"] = True
    print(json.dumps(out))


def bench_serve_ep_child():
    """Child half of the --serve --smoke ep leg (runs with
    JAX_PLATFORMS=cpu and 8 virtual host devices): ep=2 expert-parallel
    MoE serving must be token-identical to the replicated ep=1 engine
    on both KV layouts, recompile-free after warmup, with 'ep' submesh
    meta and a2a bytes attributed to the ep axis.  Prints ONE JSON
    line; any violated contract raises and exits non-zero."""
    from paddle_tpu.testing import multichip
    out = multichip.run_serve_ep_phase()
    out["metric"] = "serve_ep_smoke"
    out["ok"] = True
    print(json.dumps(out))


def _smoke_serve_ep(n_devices=8):
    """ep=2 CPU-mesh leg of --serve --smoke (ISSUE 19): the same
    re-exec pattern as the tp leg — expert-parallel serving needs a
    multi-device mesh jax can no longer grow in this process."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(kept)
    for k in [k for k in env
              if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_"))]:
        env.pop(k, None)
    env.pop("PADDLE_TPU_SERVE_TP", None)   # the child builds its own mesh
    env.pop("PADDLE_TPU_SERVE_EP", None)
    rc = subprocess.call(
        [sys.executable, "-u", os.path.abspath(__file__),
         "--serve-ep-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    if rc != 0:
        raise SystemExit(
            f"serve --smoke: ep=2 CPU-mesh leg failed (exit {rc})")
    log("  serve ep=2 smoke ok (MoE parity + 0 compiles + ep a2a bytes)")


def _smoke_tier1_budget():
    """Tier-1 wall-budget guard (ISSUE 19 satellite): read the recorded
    per-file fast-lane durations and fail the smoke when any
    non-exempt test file exceeds the per-file budget — the 870s tier-1
    wall budget stays honest because an overgrown file must either
    shed tests to @pytest.mark.slow or claim an explicit exemption.
    Graceful no-op when no durations file has been recorded yet."""
    from paddle_tpu.testing import tier1_budget
    verdict = tier1_budget.check_recorded_durations()
    if verdict is None:
        log("  tier1 budget: no durations file recorded — skipped")
        return
    if verdict["over_budget"]:
        raise SystemExit(
            "bench --smoke: tier-1 per-file budget exceeded: "
            + "; ".join(
                f"{f} {s:.1f}s > {verdict['budget_s']:.0f}s"
                for f, s in verdict["over_budget"])
            + " — move tests to @pytest.mark.slow or exempt the file "
              "in PADDLE_TPU_TIER1_EXEMPT")
    log(f"  tier1 budget ok: {verdict['files']} file(s) within "
        f"{verdict['budget_s']:.0f}s each")


def _smoke_serve_tp(n_devices=8):
    """tp=2 CPU-mesh leg of --serve --smoke (ISSUE 18): re-exec on a
    virtual n-device mesh (same subprocess pattern + env scrub as
    --multichip-smoke — jax is already imported here, so the device
    count can only change in a child)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(kept)
    for k in [k for k in env
              if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_"))]:
        env.pop(k, None)
    env.pop("PADDLE_TPU_SERVE_TP", None)   # the child builds its own mesh
    rc = subprocess.call(
        [sys.executable, "-u", os.path.abspath(__file__),
         "--serve-tp-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    if rc != 0:
        raise SystemExit(
            f"serve --smoke: tp=2 CPU-mesh leg failed (exit {rc})")
    log("  serve tp=2 smoke ok (parity + 0 compiles + submesh meta)")


def bench_multichip_smoke(n_devices=8):
    """--multichip-smoke: re-exec this script on a virtual n-device CPU
    mesh (XLA_FLAGS host-platform device count) and run the overlap
    parity phases.  A subprocess is mandatory: jax is already imported
    here, so device-count env flags can no longer take effect, and any
    TPU-tunnel env (AXON vars) must be scrubbed exactly like the driver
    dryrun does (__graft_entry__.dryrun_multichip round-4 root cause)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(kept)
    for k in [k for k in env
              if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_"))]:
        env.pop(k, None)
    rc = subprocess.call(
        [sys.executable, "-u", os.path.abspath(__file__),
         "--multichip-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    if rc != 0:
        raise SystemExit(rc)


def _smoke_quantized_decode():
    """Quantized-path leg of --smoke (ISSUE 7): one int8-KV decode step
    must stay within tolerance of the dense-cache logits, and a warmed
    int8 engine must decode with ZERO new XLA compiles (the int8 cache
    adds scale operands — this proves they are shape-stable)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.utils import compile_counter

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (1, 9)).astype(np.int32)

    # parity leg: prefill + one decode step, int8 cache vs fp cache
    tok = jnp.asarray([ids[0, -1]], jnp.int32)
    act = jnp.ones((1,), jnp.int32)
    cf = m.init_kv_cache(1)
    _, cf = m.prefill(jnp.asarray(ids[:, :-1]), cf, 0, 8)
    lf, _ = m.decode_step(tok, cf, act)
    cq = m.init_kv_cache(1, kv_dtype="int8")
    _, cq = m.prefill(jnp.asarray(ids[:, :-1]), cq, 0, 8)
    lq, _ = m.decode_step(tok, cq, act)
    diff = float(np.max(np.abs(np.asarray(lq) - np.asarray(lf))))
    scale = float(np.max(np.abs(np.asarray(lf)))) or 1.0
    if diff > 0.05 * scale:
        raise SystemExit(
            f"bench --smoke: int8 KV decode diverged from the dense "
            f"cache (max abs diff {diff:.5f} vs logit scale {scale:.4f})")

    # zero-recompile leg: a warmed int8 engine generates compile-free
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16],
                          kv_dtype="int8")
    eng.warmup(buckets=[16])
    with compile_counter.assert_no_recompiles("quantized decode smoke"):
        rid = eng.add_request(ids[0, :7], max_new_tokens=8)
        gen = eng.run()[rid]
    if len(gen) < 8:
        raise SystemExit("bench --smoke: quantized decode produced "
                         f"{len(gen)} tokens (expected 8)")
    log(f"  quantized smoke ok: int8 decode diff {diff:.5f} "
        f"(scale {scale:.3f}), {len(gen)} tokens, 0 compiles")
    return {"quantized_decode_ok": True,
            "quantized_logit_diff": round(diff, 5),
            "quantized_kv_dtype": "int8"}


def _smoke_megakernel():
    """Megakernel leg of --smoke (ISSUE 11): the fused decode step's
    logits must match the composed kernels path at 1e-5 on the CPU
    composite, and a warmed megakernel engine must decode with ZERO new
    XLA compiles — the fused path is exercised in tier-1, not only on
    hardware."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.utils import compile_counter

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (1, 9)).astype(np.int32)
    tok = jnp.asarray([ids[0, -1]], jnp.int32)
    act = jnp.ones((1,), jnp.int32)

    # parity leg: composed path vs fused path, same params, fresh caches
    m.enable_decode_megakernel(False)
    cc = m.init_kv_cache(1)
    _, cc = m.prefill(jnp.asarray(ids[:, :-1]), cc, 0, 8)
    lc, _ = m.decode_step(tok, cc, act)
    m.enable_decode_megakernel(True)
    cm = m.init_kv_cache(1)
    _, cm = m.prefill(jnp.asarray(ids[:, :-1]), cm, 0, 8)
    lm, _ = m.decode_step(tok, cm, act)
    diff = float(np.max(np.abs(np.asarray(lm) - np.asarray(lc))))
    if diff > 1e-5:
        raise SystemExit(
            f"bench --smoke: megakernel decode diverged from the "
            f"composed path (max abs logit diff {diff:.2e} > 1e-5)")

    # zero-recompile leg: a warmed megakernel engine generates
    # compile-free (the fused op must be shape-stable in the decode
    # executable exactly like the composed kernels)
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16])
    eng.warmup(buckets=[16])
    assert eng.stats["decode_megakernel"], \
        "megakernel flag did not reach the engine stats"
    with compile_counter.assert_no_recompiles("megakernel decode smoke"):
        rid = eng.add_request(ids[0, :7], max_new_tokens=8)
        gen = eng.run()[rid]
    if len(gen) < 8:
        raise SystemExit("bench --smoke: megakernel decode produced "
                         f"{len(gen)} tokens (expected 8)")
    hbm = eng.stats["decode_hbm_bytes_per_tok"]
    log(f"  megakernel smoke ok: logit diff {diff:.2e}, {len(gen)} "
        f"tokens, 0 compiles, {hbm} HBM bytes/tok")
    return {"megakernel_decode_ok": True,
            "megakernel_logit_diff": round(diff, 8),
            "decode_hbm_bytes_per_tok": hbm}


def _smoke_telemetry():
    """Telemetry leg of --smoke (ISSUE 13): the unified observability
    layer must actually EXPORT — the Prometheus exposition parses back
    (round-trip), the span buffer renders a structurally-valid
    Chrome-trace JSON containing the train phase spans, and the JSONL
    snapshot writer lands its file atomically (no .tmp orphan, every
    line valid JSON).  Runs against whatever the preceding legs put in
    the process registry/tracer, so it exercises the real wiring, not a
    synthetic fixture."""
    import tempfile
    from paddle_tpu import observability as obs

    # 1) exposition round-trip: the families every --smoke run feeds
    text = obs.registry().exposition()
    parsed = obs.parse_exposition(text)
    for family in ("train_steps_total", "train_step_time_ms",
                   "host_syncs_total"):
        if family not in parsed:
            raise SystemExit(
                f"bench --smoke: metric family {family!r} missing from "
                f"the Prometheus exposition (families: "
                f"{sorted(parsed)[:12]}...)")

    # 2) chrome trace: bench_smoke armed the tracer before the train
    # legs, so the buffer must hold train phase spans and validate
    tr = obs.tracer()
    doc = tr.chrome_trace()
    n_events = obs.validate_chrome_trace(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    if "dispatch" not in names:
        raise SystemExit(
            f"bench --smoke: no 'dispatch' span in the trace "
            f"({n_events} events; names {sorted(names)[:12]})")
    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "trace.json")
        tr.export(trace_path)
        with open(trace_path) as f:
            obs.validate_chrome_trace(json.load(f))

        # 3) atomic JSONL snapshot: two writes -> two parseable lines,
        # no .tmp orphan next to the committed file
        snap_path = os.path.join(td, "metrics.jsonl")
        obs.registry().write_snapshot(snap_path)
        obs.registry().write_snapshot(snap_path, extra={"leg": "smoke"})
        leftovers = [p for p in os.listdir(td) if p.endswith(".tmp")]
        if leftovers:
            raise SystemExit(
                f"bench --smoke: snapshot writer orphaned {leftovers}")
        with open(snap_path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        if len(lines) != 2 or "metrics" not in lines[-1]:
            raise SystemExit(
                f"bench --smoke: snapshot JSONL malformed "
                f"({len(lines)} lines)")
    snap = obs.snapshot()
    log(f"  telemetry smoke ok: {len(parsed)} exposition families, "
        f"{n_events} trace events, snapshot families "
        f"{len(snap['metrics'])}")
    return {"telemetry_ok": True,
            "telemetry_exposition_families": len(parsed),
            "telemetry_trace_events": n_events,
            "telemetry_snapshot_families": len(snap["metrics"])}


def _smoke_doctor():
    """Perf-doctor leg of --smoke (ISSUE 14): the doctor must attribute
    a DELIBERATELY sync-heavy train loop (float(loss) read every step —
    the classic dispatch-pipeline killer) as host-sync-bound with the
    matching knob, and must stay SILENT on the same config driven
    lazily — a doctor that cries wolf is worse than none."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import (SpmdTrainer, async_dispatch,
                                        create_mesh)
    from paddle_tpu.observability import doctor as _doctor

    def build():
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 10))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        return SpmdTrainer(m, opt,
                           lambda o, y: F.cross_entropy(o, y),
                           mesh=create_mesh({"dp": 1}))

    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, size=(8,)).astype(np.int64)
    n = 4

    def run(sync_heavy):
        tr = build()
        tr.train_step(x, y)                  # warmup/compile
        s0 = async_dispatch.host_sync_count()
        for _ in range(n):
            res = tr.train_step(x, y)
            if sync_heavy:
                float(res)                   # per-step blocking readback
        syncs = async_dispatch.host_sync_count() - s0
        return _doctor.diagnose(
            {**tr.stats, "host_syncs_measured": syncs, "steps": n},
            kind="train")

    bad = run(sync_heavy=True)
    hits = [v for v in bad if v["bottleneck"] == "host-sync-bound"]
    if not hits:
        raise SystemExit(
            f"bench --smoke: doctor missed the injected sync-heavy "
            f"config (verdicts: {[v['bottleneck'] for v in bad]})")
    if "lazy" not in hits[0]["knob"]:
        raise SystemExit(
            f"bench --smoke: host-sync-bound verdict carries the wrong "
            f"knob: {hits[0]['knob']!r}")
    clean = run(sync_heavy=False)
    if any(v["bottleneck"] == "host-sync-bound" for v in clean):
        raise SystemExit(
            f"bench --smoke: doctor flagged the CLEAN config as "
            f"host-sync-bound ({clean})")
    log(f"  doctor smoke ok: sync-heavy -> host-sync-bound "
        f"(syncs/step {hits[0]['evidence']['syncs_per_step']}), "
        f"clean -> {[v['bottleneck'] for v in clean] or 'no verdict'}")
    return {"doctor_ok": True,
            "doctor_sync_heavy": [v["bottleneck"] for v in bad],
            "doctor_clean": [v["bottleneck"] for v in clean]}


def _smoke_exec_profile(train_row):
    """Executable-observatory leg of --smoke (ISSUE 15): the train row
    must carry an exec_profile whose train_step digest has flops /
    bytes / roofline fields populated; a serve-side engine must produce
    the same for its decode executable; and the report CLI must exit 0
    rendering a snapshot written by this process — the registry
    round-trips offline."""
    import subprocess
    import tempfile
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import exec_registry as _er

    prof = train_row.get("exec_profile")
    ts = (prof or {}).get("train_step")
    if not ts:
        raise SystemExit(
            "bench --smoke: train row carries no exec_profile."
            "train_step digest")
    for fld in ("flops", "bytes_accessed", "arithmetic_intensity",
                "bound", "mfu", "mean_ms"):
        if ts.get(fld) in (None, ""):
            raise SystemExit(
                f"bench --smoke: train exec_profile missing {fld!r} "
                f"(got {sorted(k for k, v in ts.items() if v is not None)})")

    # serve leg: a tiny engine's decode executable through the same path
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rid = eng.add_request(np.arange(1, 8, dtype=np.int32),
                          max_new_tokens=8)
    eng.run()
    _er.analyze_all(eng._exec_component)
    sprof = _er.profile(eng._exec_component) or {}
    dec = sprof.get("decode") or sprof.get("megakernel_decode")
    if not dec:
        raise SystemExit("bench --smoke: serve exec_profile has no "
                         "decode digest")
    for fld in ("flops", "bytes_accessed", "bound", "hbm_bw_frac"):
        if dec.get(fld) in (None, ""):
            raise SystemExit(
                f"bench --smoke: decode exec_profile missing {fld!r}")

    # snapshot -> report CLI round-trip (offline rendering, exit 0)
    with tempfile.TemporaryDirectory() as td:
        snap_path = os.path.join(td, "snapshot.jsonl")
        obs.write_snapshot(snap_path)
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability.report",
             "--snapshot", snap_path],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
        if proc.returncode != 0:
            raise SystemExit(
                f"bench --smoke: report CLI exited "
                f"{proc.returncode}:\n{proc.stderr[-2000:]}")
        if "decode" not in proc.stdout or "hbm ledger" not in proc.stdout:
            raise SystemExit(
                f"bench --smoke: report CLI output missing the "
                f"registry/ledger tables:\n{proc.stdout[:2000]}")
    n_exec = len(_er.registry().entries())
    log(f"  exec-profile smoke ok: train_step {ts['bound']}-bound "
        f"mfu={ts['mfu']}, decode {dec['bound']}-bound "
        f"bw_frac={dec['hbm_bw_frac']}, report CLI rendered "
        f"{n_exec} executables")
    return {"exec_profile_ok": True,
            "exec_profile_train_bound": ts["bound"],
            "exec_profile_decode_bound": dec["bound"],
            "exec_profile_registered": n_exec}


def _env_overrides(pairs):
    """Context manager: set/unset env knobs for one trial, restoring
    the previous values on exit (None value = unset)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        saved = {k: os.environ.get(k) for k in pairs}
        try:
            for k, v in pairs.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = str(v)
            yield
        finally:
            for k, prev in saved.items():
                if prev is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = prev
    return _cm()


def bench_autotune(smoke=False):
    """`bench.py --autotune` (ISSUE 16 tentpole): doctor-driven greedy
    coordinate descent over the train knob space instead of the
    enumerated sweep — measure the incumbent, follow the ranked
    verdict's structured action to ONE axis, trial its candidates,
    accept only beyond the noise floor, commit winners to the tuning
    table with provenance.  Reuses the bench harness whole: every
    measurement is bench_train under _retry_transient, every row lands
    in BENCH_rows.jsonl, and BENCH_RUN-keyed resume means a crashed
    tune continues from the rows already paid for.  Prints ONE JSON
    line (metric autotune_train_mfu)."""
    import jax
    from paddle_tpu.autotune import AutotuneController
    from paddle_tpu.utils import tuning as _tuning

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    if smoke or not on_tpu:
        config_name, batch, seq, steps, warmup = \
            "gpt3-tiny", 2, 64, 2, 1
    else:
        config_name = os.environ.get("BENCH_CONFIG", "gpt3-125m")
        batch = int(os.environ.get("BENCH_BATCH", 8))
        seq = int(os.environ.get("BENCH_SEQ", 2048))
        steps, warmup = 20, 3
    base = {
        "use_flash": bool(on_tpu),
        "remat_policy": "dots_no_batch" if on_tpu else "off",
        "quantize": None,
        "scan": os.environ.get("BENCH_SCAN_LAYERS", "1") != "0",
        "overlap": os.environ.get("PADDLE_TPU_OVERLAP", "1") != "0",
        "prefetch_depth": int(os.environ.get(
            "PADDLE_TPU_PREFETCH_DEPTH", "2")),
    }
    measured = _measured_rows("train")
    if measured:
        log(f"  autotune resume: {len(measured)} measured row(s) for "
            f"run '{_bench_run()}' on file")

    def measure(cfg):
        pol = cfg.get("remat_policy") or "off"
        remat = pol != "off"
        spec = dict(config=config_name, batch=batch, seq=seq,
                    flash=cfg.get("use_flash", True), remat=remat,
                    remat_policy=pol if remat else None,
                    scan=cfg.get("scan"), overlap=cfg.get("overlap"),
                    quantize=cfg.get("quantize"))
        # a persisted row is only trusted when the axes OUTSIDE the row
        # key (env-carried knobs) sit at this trial's values
        if cfg.get("prefetch_depth") == base["prefetch_depth"] and \
                cfg.get("moe_a2a_chunks") is None:
            row = measured.get(_candidate_key(spec))
            if row is not None:
                log(f"  autotune resume: reusing measured row for "
                    f"{_candidate_key(spec)}")
                return dict(row)
        env = {"PADDLE_TPU_PREFETCH_DEPTH": cfg.get("prefetch_depth")}
        if cfg.get("moe_a2a_chunks") is not None:
            env["PADDLE_TPU_MOE_A2A_CHUNKS"] = cfg["moe_a2a_chunks"]
        with _env_overrides(env):
            return bench_train_retry(
                config_name, batch, seq, steps, warmup,
                use_flash=cfg.get("use_flash", True), remat=remat,
                tries=3, scan=cfg.get("scan"),
                overlap=cfg.get("overlap"),
                quantize=cfg.get("quantize"),
                remat_policy=pol if remat else None)

    # where accepted winners persist (the embedder knows the identity
    # keys; the controller stamps provenance)
    commit_keys = {}
    try:
        from dataclasses import replace as _replace
        from paddle_tpu.distributed.spmd import remat_policy_key
        from paddle_tpu.models.gpt import gpt_configs
        cfg0 = gpt_configs().get(config_name)
        if cfg0 is not None:
            key = remat_policy_key(_replace(cfg0, max_seq_len=seq))
            if key is not None:
                commit_keys["remat_policy"] = ("remat_policy", key)
    except Exception as e:
        log(f"  autotune: remat commit key skipped: "
            f"{type(e).__name__}: {e}")
    commit_keys["moe_a2a_chunks"] = (
        "moe_a2a_chunks", (_tuning.device_kind(), batch * seq))

    ctl = AutotuneController(
        measure, kind="train", objective_key="mfu",
        run_id=_bench_run() or "autotune",
        commit_keys=commit_keys,
        axes=["remat_policy", "quantize", "use_flash", "scan",
              "overlap", "prefetch_depth", "moe_a2a_chunks"],
        log=log)
    summary = ctl.run(base)
    out = {"metric": "autotune_train_mfu",
           "value": round((summary.get("best") or 0.0) * 100, 2),
           "unit": "%", **summary}
    _persist_row(out, kind="autotune")
    print(json.dumps(out, default=str))
    return out


def _smoke_autotune():
    """Autotune leg of --smoke (ISSUE 16): on a deliberately mistuned
    5-knob config with a planted best, the controller must (a) converge
    to the planted best in <= K+2 measured trials (vs a 96-point full
    grid), (b) accept only improvements beyond the noise floor, (c)
    never revisit a trialed (axis, value), (d) roll back BOTH a planted
    regression and a planted recompile-storm trial with an
    autotune-rollback flightrec bundle each, (e) commit the winner to
    the tuning table stamped with autotune provenance that survives a
    table reload from disk, and (f) report zero compiles outside trial
    windows."""
    import tempfile
    from paddle_tpu.autotune import AutotuneController
    from paddle_tpu.observability import flightrec as _fr
    from paddle_tpu.utils import tuning as _tuning

    BEST = {"quantize": "int8", "remat_policy": "off", "overlap": True,
            "prefetch_depth": 4, "scan": True}
    START = {"quantize": None, "remat_policy": "dots_no_batch",
             "overlap": False, "prefetch_depth": 2, "scan": True}
    K = len(START)
    GRID = 2 * 4 * 2 * 3 * 2            # the full-sweep cost it replaces

    def objective(cfg):
        mfu = 0.30
        mfu += 0.05 if cfg["quantize"] == "int8" else 0.0
        mfu += 0.04 if cfg["remat_policy"] == "off" else 0.0
        mfu += 0.03 if cfg["overlap"] else 0.0
        if cfg["prefetch_depth"] == 4:
            mfu += 0.02
        elif cfg["prefetch_depth"] == 0:
            mfu -= 0.20                 # the planted regression trial
        return round(mfu, 6)

    def verdicts(cfg):
        v = []
        if cfg["quantize"] != "int8":
            v.append({"bottleneck": "mfu-below-target", "score": 0.9,
                      "knob": "quantize=int8 (BENCH_QUANTIZE)",
                      "action": {"op": "qmm_tiles", "param": "quantize",
                                 "env": "BENCH_QUANTIZE",
                                 "candidates": ["int8"]}})
        if cfg["remat_policy"] != "off":
            v.append({"bottleneck": "mfu-below-target", "score": 0.8,
                      "knob": "remat off",
                      "action": {"op": "remat_policy",
                                 "param": "remat_policy", "env": None,
                                 "candidates": ["off"]}})
        if not cfg["overlap"]:
            v.append({"bottleneck": "comm-bound", "score": 0.7,
                      "knob": "PADDLE_TPU_OVERLAP=1",
                      "action": {"op": None, "param": "overlap",
                                 "env": "PADDLE_TPU_OVERLAP",
                                 "candidates": [True]}})
        if cfg["prefetch_depth"] != 4:
            v.append({"bottleneck": "data-starved", "score": 0.6,
                      "knob": "raise prefetch_depth",
                      "action": {"op": None, "param": "prefetch_depth",
                                 "env": "PADDLE_TPU_PREFETCH_DEPTH",
                                 "candidates": [0, 4]}})
        # always-on bait: trialing scan=False recompile-storms below
        v.append({"bottleneck": "mfu-below-target", "score": 0.5,
                  "knob": "scan_layers off",
                  "action": {"op": None, "param": "scan", "env": None,
                             "candidates": [False]}})
        return v

    def measure(cfg):
        return {"mfu": objective(cfg), "doctor": verdicts(cfg),
                "xla_compiles_measured":
                    7 if cfg["scan"] is False else 0}

    with tempfile.TemporaryDirectory() as td:
        frdir = os.path.join(td, "flightrec")
        with _env_overrides({
                "PADDLE_TPU_TUNING_CACHE": os.path.join(td, "t.json"),
                "PADDLE_TPU_FLIGHTREC_DIR": frdir}):
            _tuning.reset_for_tests()
            key = ("smoke", "64", "2", "32")
            ctl = AutotuneController(
                measure, kind="train", objective_key="mfu",
                noise_floor=0.02, run_id="smoke-autotune",
                commit_keys={"remat_policy": ("remat_policy", key)},
                axes=["quantize", "remat_policy", "overlap",
                      "prefetch_depth", "scan"], log=log)
            summary = ctl.run(dict(START))

            final = {k: summary["config"][k] for k in BEST}
            if final != BEST:
                raise SystemExit(f"bench --smoke: autotune missed the "
                                 f"planted best: {final} != {BEST}")
            n = summary["measured_trials"]
            if n > K + 2 or n >= GRID:
                raise SystemExit(
                    f"bench --smoke: autotune took {n} trials "
                    f"(bound {K + 2}, grid {GRID})")
            pairs = [(t["axis"], repr(t["value"]))
                     for t in summary["trials"]]
            if len(pairs) != len(set(pairs)):
                raise SystemExit("bench --smoke: autotune revisited a "
                                 "trialed (axis, value) pair")
            for t in summary["trials"]:
                if t.get("outcome") == "accept" and \
                        t["improvement"] <= ctl.noise_floor:
                    raise SystemExit(
                        f"bench --smoke: accepted within noise: {t}")
            reasons = sorted(t["reason"] for t in summary["trials"]
                             if t.get("outcome") == "rollback")
            if reasons != ["recompile-storm", "regression"]:
                raise SystemExit(f"bench --smoke: autotune rollbacks "
                                 f"wrong: {reasons}")
            if summary["compiles_outside_trials"] != 0:
                raise SystemExit(
                    f"bench --smoke: {summary['compiles_outside_trials']}"
                    f" compiles outside autotune trial windows")
            # winner round-trips from DISK with provenance intact
            _tuning.reset_for_tests()
            if _tuning.lookup("remat_policy", key) != "off":
                raise SystemExit("bench --smoke: autotune winner did "
                                 "not round-trip the tuning table")
            prov = _tuning.provenance("remat_policy", key)
            if not prov or prov.get("source") != "autotune" or \
                    prov.get("run") != "smoke-autotune" or \
                    not prov.get("improvement", 0) > 0:
                raise SystemExit(f"bench --smoke: autotune provenance "
                                 f"missing/wrong: {prov}")
            bundles = _fr.find_bundles(frdir)
            rb = [b for b in bundles if b.endswith("autotune-rollback")]
            if len(rb) != 2:
                raise SystemExit(
                    f"bench --smoke: expected 2 autotune-rollback "
                    f"bundles, found {len(rb)} in {bundles}")
            with open(os.path.join(rb[0], "bundle.json")) as f:
                if "autotune" not in f.read():
                    raise SystemExit("bench --smoke: rollback bundle "
                                     "lacks the autotune evidence")
            _tuning.reset_for_tests()   # drop the tmp-table cache
    log(f"  autotune smoke ok: {n} trials (grid {GRID}), "
        f"improvement +{summary['improvement'] * 100:.1f}%, "
        f"2 rollbacks bundled, provenance stamped")
    return {"autotune_ok": True, "autotune_trials": n,
            "autotune_improvement": summary["improvement"],
            "autotune_rollbacks": 2,
            "autotune_compiles_outside_trials":
                summary["compiles_outside_trials"]}


def bench_smoke():
    """2-step CPU-friendly dry run guarding the dispatch path (tier-1,
    `python bench.py --smoke`): asserts the step-time breakdown fields
    exist and that the measured loop performed NO per-step host sync
    (the one allowed sync is the final barrier), then re-runs the same
    tiny config to measure the persistent-cache warm start, and finally
    runs the quantized-decode leg (_smoke_quantized_decode: int8 KV
    parity within tolerance + zero recompiles after warmup) plus the
    telemetry leg (_smoke_telemetry: exposition round-trip, valid
    chrome trace, atomic snapshot — with the span tracer ARMED through
    all of it, so 'telemetry on' is what the other invariants are
    proven under).  Exits non-zero on any violated invariant, so CI
    catches dispatch-path regressions before a TPU bench ever runs."""
    from paddle_tpu import observability as obs
    obs.tracer().start()       # spans active through every leg
    required = ("data_wait_ms", "h2d_ms", "dispatch_ms", "sync_ms",
                "compile_ms_cold", "steps_timed", "host_syncs_measured",
                "prefetch_depth", "comm_ms", "comm_fraction",
                "step_time_ms")
    cold = bench_train("gpt3-tiny", 2, 64, steps=2, warmup=1,
                       use_flash=False, remat=False, smoke=True)
    missing = [k for k in required if k not in cold]
    if missing:
        raise SystemExit(f"bench --smoke: stats fields missing: {missing}")
    if cold["host_syncs_measured"] > 1:
        raise SystemExit(
            f"bench --smoke: {cold['host_syncs_measured']} host syncs in "
            f"a {cold['steps']}-step window (max 1: the final barrier) — "
            f"a per-step sync crept back into the dispatch path")
    # second identical run in the same process: fresh trainer, fresh jit
    # objects, so its first-call cost shows the compile-cache warm path
    warm = bench_train("gpt3-tiny", 2, 64, steps=2, warmup=1,
                       use_flash=False, remat=False, smoke=True)
    # bench rows now carry the doctor field (ISSUE 14): the smoke train
    # row must have it, even when the verdict list is empty
    if "doctor" not in cold:
        raise SystemExit("bench --smoke: train row lost the 'doctor' "
                         "field")
    qrow = _smoke_quantized_decode()
    mkrow = _smoke_megakernel()
    trow = _smoke_telemetry()
    drow = _smoke_doctor()
    erow = _smoke_exec_profile(cold)
    arow = _smoke_autotune()
    out = {
        "metric": "bench_smoke", "ok": True,
        "compile_ms_cold": cold["compile_ms_cold"],
        "compile_ms_warm": warm["compile_ms_cold"],
        "compile_cache_dir": cold["compile_cache_dir"],
        "doctor": cold["doctor"],
        "exec_profile": cold["exec_profile"],
        **{k: cold[k] for k in required},
        **qrow,
        **mkrow,
        **trow,
        **drow,
        **erow,
        **arow,
    }
    log(f"  smoke ok: cold compile {cold['compile_ms_cold']:.0f}ms, "
        f"warm {warm['compile_ms_cold']:.0f}ms, "
        f"syncs {cold['host_syncs_measured']}")
    _persist_row(out, kind="smoke")
    print(json.dumps(out))


def main():
    import jax
    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    log(f"bench: platform={dev.platform} "
        f"kind={getattr(dev, 'device_kind', '?')}")

    if "--serve" in sys.argv:
        smoke = "--smoke" in sys.argv
        if "--loadtest" in sys.argv:
            if smoke or not on_tpu:
                bench_loadtest(smoke=smoke)
            else:
                # the measured config rides the same transient-failure
                # retry as the train sweep (ROADMAP item 1)
                _retry_transient(lambda: bench_loadtest(smoke=False),
                                 tries=3, label="loadtest")
        elif smoke or not on_tpu:
            bench_serve(smoke=smoke)
        else:
            # megakernel off/on enumerated (resume-aware), winner wins
            _serve_sweep()
        return

    if "--serve-tp-child" in sys.argv:
        bench_serve_tp_child()
        return

    if "--serve-ep-child" in sys.argv:
        bench_serve_ep_child()
        return

    if "--multichip-child" in sys.argv:
        bench_multichip_child()
        return

    if "--multichip-smoke" in sys.argv:
        bench_multichip_smoke()
        return

    if "--autotune" in sys.argv:
        # doctor-driven coordinate descent (ISSUE 16); checked before
        # --smoke so `--autotune --smoke` means "autotune, tiny config"
        bench_autotune(smoke="--smoke" in sys.argv or not on_tpu)
        return

    if "--smoke" in sys.argv:
        bench_smoke()
        return

    if "--flash" in sys.argv:
        rows = bench_flash()
        print(json.dumps({"metric": "flash_attention_bench", "rows": rows}))
        return

    if on_tpu:
        run_tuning_sweeps()
    sweep = _train_candidates(on_tpu)
    fallbacks = [dict(config="gpt3-125m", batch=8, seq=2048, steps=20,
                      warmup=3, remat=True)] if on_tpu else []
    # an explicit BENCH_CONFIG pins the primary measurement
    # (_train_candidates honors it); the stock fallbacks still catch a
    # failing request so the bench always emits a number.  BENCH_ONLY=1
    # drops even the fallbacks (probe mode).
    if os.environ.get("BENCH_ONLY") == "1":
        sweep = sweep[:1]
        fallbacks = []
    measured = _measured_rows("train")
    if measured:
        log(f"  resume: {len(measured)} measured row(s) for run "
            f"'{_bench_run()}' on file")

    # MFU below this on real TPU means something is pathological
    # (degraded compile service / host transfer stall): r4 published
    # 1.23% without flagging it.  Retry such points and prefer any
    # healthy result over a pathological one.
    sanity_floor = 0.08 if on_tpu else 0.0

    result, last_err, candidates = None, None, []

    def consider(r):
        nonlocal result
        r["pathological"] = bool(sanity_floor and r["mfu"] < sanity_floor)
        candidates.append({k: r[k] for k in
                           ("config", "batch", "use_flash", "mfu",
                            "step_ms", "pathological")})
        log(f"  candidate {r['config']} b{r['batch']} "
            f"flash={r['use_flash']}: MFU {r['mfu'] * 100:.2f}%"
            + (" [PATHOLOGICAL]" if r["pathological"] else ""))
        if result is None:
            result = r
        elif result["pathological"] and not r["pathological"]:
            result = r
        elif r["mfu"] > result["mfu"] and not r["pathological"]:
            result = r

    def release_device_memory(force_clear=False):
        """Failed candidates must not poison later ones: drop compiled
        executables and force-collect so the dead trainer's params/opt
        state leave HBM (keeping the raised exception object alive would
        pin its traceback frames -> the arrays; that leak produced
        ResourceExhausted on configs that fit fine in a fresh process).

        With the persistent compile cache ON, the unconditional
        jax.clear_caches() between candidates is gone: in-memory
        executables are cheap to keep and expensive to rebuild when the
        remote-compile service is degraded.  Failure paths still clear
        (force_clear=True) — a dead trainer's executables are pure HBM
        ballast."""
        import gc
        import jax as _jax
        from paddle_tpu.utils.compile_cache import compile_cache_enabled
        gc.collect()
        if force_clear or not compile_cache_enabled():
            try:
                _jax.clear_caches()
            except Exception:
                pass
        gc.collect()

    sweep_flash = os.environ.get("BENCH_FLASH", "1") != "0"

    def run_candidate(c, tries=2, force_flash=None):
        """One sweep point: consult the resume log first (same run +
        same candidate identity => reuse the paid-for row), else
        measure; False = the point failed (device memory released)."""
        kw = dict(c)
        if force_flash is not None:
            kw["flash"] = force_flash
        if not sweep_flash:
            kw["flash"] = False
        key = _candidate_key(kw)
        if key in measured:
            row = dict(measured[key])
            if sanity_floor and row.get("mfu", 0.0) < sanity_floor:
                # a row measured during a degraded-service window (the
                # r4 1.23%-MFU mode) must be RE-measured, not trusted —
                # resume exists to skip valid work, not to pin bad rows
                log(f"  resume: re-measuring pathological row "
                    f"(mfu {row.get('mfu', 0.0) * 100:.2f}%) for "
                    f"{kw.get('config')} b{kw.get('batch')}")
            else:
                log(f"  resume: skipping measured candidate "
                    f"{kw.get('config')} b{kw.get('batch')} "
                    f"(quantize={kw.get('quantize')}, "
                    f"flash={key[4]}, remat={key[5]}/{key[6]}, "
                    f"scan={key[7]}, overlap={key[8]})")
                consider(row)
                return True
        try:
            consider(bench_train_retry(
                kw["config"], kw["batch"], kw["seq"], kw["steps"],
                kw["warmup"], use_flash=kw.get("flash", True),
                remat=kw.get("remat"), tries=tries,
                scan=kw.get("scan"), overlap=kw.get("overlap"),
                quantize=kw.get("quantize"),
                remat_policy=kw.get("remat_policy")))
            release_device_memory()
            return True
        except Exception as e:  # OOM etc: skip this point
            nonlocal last_err
            last_err = f"{type(e).__name__}: {str(e)[:300]}"
            log(f"  {kw['config']} b{kw['batch']} failed: {last_err}")
            release_device_memory(force_clear=True)
            return False

    for c in sweep:
        run_candidate(c)
    if result is None or result["pathological"]:
        # flash kernel itself may be the pathology: try composite path
        for c in sweep[:1] + fallbacks:
            run_candidate(c, tries=3, force_flash=False)
            if result is not None and not result["pathological"]:
                break
    if result is None:
        raise SystemExit(f"all bench configs failed: {last_err}")

    # flash A/B on the winning config: prove the Pallas kernel's value
    # (or catch it being slower than the composite) with a real number
    flash_speedup = None
    winner_knobs = dict(
        scan=result.get("scan_layers"), overlap=result.get("overlap"),
        quantize=result.get("quantize") or "off",
        remat_policy=result.get("remat_policy")
        if result.get("remat_policy") not in (None, "off") else None)
    if on_tpu and result["use_flash"] and not result["pathological"]:
        try:
            off = bench_train_retry(result["config"], result["batch"],
                                    result["seq"], max(result["steps"] // 2,
                                                       5), 2,
                                    use_flash=False,
                                    remat=result["remat"], tries=3,
                                    **winner_knobs)
            flash_speedup = round(off["step_ms"] / result["step_ms"], 3)
            log(f"  flash A/B: on {result['step_ms']}ms "
                f"off {off['step_ms']}ms speedup {flash_speedup}x")
            if off["mfu"] > result["mfu"]:
                log("  NOTE: composite beat flash; keeping faster path")
            consider(off)  # audit trail: the A/B row joins candidates
        except Exception as e:
            log(f"  flash A/B skipped: {type(e).__name__}: {str(e)[:200]}")
    if on_tpu and result["use_flash"] and flash_speedup is None \
            and not result["pathological"]:
        # full-step composite compile flaked: the attention-only
        # microbench is a tiny program the degraded compile helper still
        # accepts — kernel-vs-composite evidence, honestly labeled
        try:
            rows = bench_flash(seqs=(result["seq"],),
                               batch=result["batch"])
            if rows and "speedup" in rows[0]:
                flash_speedup = rows[0]["speedup"]
                log(f"  flash A/B fallback (attention microbench): "
                    f"{flash_speedup}x")
        except Exception as e:
            log(f"  flash microbench fallback failed: "
                f"{type(e).__name__}: {str(e)[:200]}")

    # warm-start proof on the winning config: a fresh trainer's first
    # step should deserialize from the persistent cache instead of
    # recompiling (the 95s-every-run tax BENCH_r05 paid).  2 steps, and
    # the transient-compile retry covers a flaky cache-miss recompile.
    compile_ms_warm = None
    from paddle_tpu.utils.compile_cache import compile_cache_enabled
    if compile_cache_enabled() and not result["pathological"] and \
            os.environ.get("BENCH_WARM", "1") != "0":
        try:
            warm = bench_train_retry(
                result["config"], result["batch"], result["seq"], 2, 1,
                use_flash=result["use_flash"], remat=result["remat"],
                tries=2, **winner_knobs)
            compile_ms_warm = warm["compile_ms_cold"]
            log(f"  compile: cold {result['compile_ms_cold']:.0f}ms -> "
                f"warm {compile_ms_warm:.0f}ms (persistent cache)")
        except Exception as e:
            log(f"  warm-compile check skipped: "
                f"{type(e).__name__}: {str(e)[:200]}")
        release_device_memory()

    if on_tpu and not result["pathological"]:
        # the sweep's measured remat-policy winner feeds the tuning
        # table so un-pinned SpmdTrainer users inherit it
        _record_winner_tuning(result)

    out = {
        "metric": "gpt_train_mfu",
        "value": round(result["mfu"] * 100, 2),
        "unit": "%",
        # BASELINE.json north star: >=45% MFU
        "vs_baseline": round(result["mfu"] / 0.45, 4) if result["mfu"]
        else 0.0,
    }
    out.update(result)
    out["compile_ms_warm"] = compile_ms_warm
    out["flash_speedup"] = flash_speedup
    out["candidates"] = candidates
    print(json.dumps(out))


if __name__ == "__main__":
    main()
