"""Slice-loss kill-and-resume, end to end (ISSUE 17 acceptance run).

A fresh process trains a 2-slice hierarchical-dp GPT; the fault
harness silences slice 1 mid-run (PADDLE_FAULT_SLICE_DOWN); the
membership layer detects the stale heartbeat and the trainer re-forms
the mesh IN MEMORY onto the surviving slice — no checkpoint directory,
no process restart — and keeps training.  The parent asserts:

- the full loss curve matches an uninterrupted 2-slice reference run
  (rtol 1e-5);
- zero XLA compiles after the first (expected, new-topology) post-
  reform step;
- the flight-recorder bundle the child dumps carries both the
  ``membership_change`` and the ``mesh_reform`` events — the black box
  a real slice loss must leave behind.

Mirrors tests/test_elastic.py's subprocess pattern (same env scrub,
same 8-virtual-device CPU topology).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SLICE_TRAIN = """
import json
import os
import sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.membership import (SliceMembership,
                                               CallbackTransport,
                                               DcnCollectiveGuard)
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)
from paddle_tpu.utils import compile_counter
from paddle_tpu.observability import flightrec

mode = sys.argv[1]
N = 7

paddle.seed(3)
cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                num_heads=2, max_seq_len=16, use_flash_attention=False)
model = GPTForCausalLM(cfg)
opt = paddle.optimizer.Adam(learning_rate=1e-3,
                            parameters=model.parameters())
crit = GPTPretrainingCriterion()
tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                 mesh=create_mesh({"dp": 4}, dcn_slices=2))
print("DCN", tr.dcn_size, flush=True)

t = {"now": 0.0}
m = SliceMembership(2, transport=CallbackTransport(), timeout_s=1.0,
                    clock=lambda: t["now"])
tr.attach_membership(m, guard=DcnCollectiveGuard(retries=2))

rng = np.random.RandomState(0)
data = []
for _ in range(N):
    b = rng.randint(0, 64, (8, 16)).astype(np.int32)
    data.append((b, np.roll(b, -1, 1).astype(np.int64)))

snap = None
for i, (b, l) in enumerate(data):
    print("LOSS", repr(float(tr.train_step(b, l))), flush=True)
    if mode == "faulted" and i == 2:
        t["now"] += 5.0   # slice 1 is armed silent: its age now grows
    if i == 4:
        # faulted: the reform ran at the end of step 3 and step 4 paid
        # the one new-topology compile; everything after must not
        snap = compile_counter.snapshot()
print("COMPILES_AFTER", snap.new_compiles, flush=True)
if mode == "faulted":
    path = flightrec.dump("slice-loss-test")
    print("BUNDLE", path, flush=True)
print("STATS", json.dumps({
    "mesh_reforms": tr.stats["mesh_reforms"],
    "lost_slices": tr.stats["lost_slices"],
    "dcn_slices": tr.stats["dcn_slices"],
    "devices": int(tr.mesh.devices.size)}), flush=True)
print("DONE", tr._step_count, flush=True)
"""


def _run_child(script, mode, extra_env, timeout=300):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    for k in ("PADDLE_FAULT_SIGTERM_STEP", "PADDLE_FAULT_MESH_SHRINK",
              "PADDLE_FAULT_NAN_STEP", "PADDLE_FAULT_CKPT_TRUNCATE",
              "PADDLE_FAULT_SLICE_DOWN", "PADDLE_FAULT_DCN_DELAY_MS",
              "PADDLE_TPU_DCN_SLICES", "PADDLE_TPU_SLICE_HB_DIR",
              "PADDLE_TPU_FLIGHTREC_DIR"):
        env.pop(k, None)
    env.update(extra_env)
    return subprocess.run([sys.executable, str(script), mode],
                          env=env, capture_output=True, text=True,
                          timeout=timeout)


def _losses(stdout):
    return [float(ln.split(" ", 1)[1]) for ln in stdout.splitlines()
            if ln.startswith("LOSS")]


def _field(stdout, tag):
    for ln in stdout.splitlines():
        if ln.startswith(tag + " "):
            return ln.split(" ", 1)[1].strip()
    raise AssertionError(f"{tag} line missing from child stdout")


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_subprocess_slice_loss_reforms_and_resumes(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(_SLICE_TRAIN)
    frdir = str(tmp_path / "flightrec")

    p_ref = _run_child(script, "ref", {})
    assert p_ref.returncode == 0, p_ref.stderr
    ref = _losses(p_ref.stdout)
    assert len(ref) == 7 and "DCN 2" in p_ref.stdout
    ref_stats = json.loads(_field(p_ref.stdout, "STATS"))
    assert ref_stats["mesh_reforms"] == 0 and ref_stats["devices"] == 8

    p = _run_child(script, "faulted",
                   {"PADDLE_FAULT_SLICE_DOWN": "1:3",
                    "PADDLE_TPU_FLIGHTREC_DIR": frdir})
    assert p.returncode == 0, p.stderr
    assert "DONE 7" in p.stdout

    # the in-memory reform resumed with the uninterrupted loss curve
    np.testing.assert_allclose(_losses(p.stdout), ref, rtol=1e-5)

    # zero-recompile contract on the survivor topology
    assert _field(p.stdout, "COMPILES_AFTER") == "0"

    stats = json.loads(_field(p.stdout, "STATS"))
    assert stats["mesh_reforms"] == 1 and stats["lost_slices"] == [1]
    assert stats["dcn_slices"] == 1 and stats["devices"] == 4

    # the black box: one bundle, carrying both event kinds
    from paddle_tpu.observability import flightrec
    bundle_path = _field(p.stdout, "BUNDLE")
    assert bundle_path != "None", "flightrec bundle was not written"
    doc = flightrec.load_bundle(bundle_path)
    kinds = [e["kind"] for e in doc["bundle"]["events"]]
    assert "membership_change" in kinds, kinds
    assert "mesh_reform" in kinds, kinds
    reform = [e for e in doc["bundle"]["events"]
              if e["kind"] == "mesh_reform"][0]
    assert reform["lost_slices"] == [1] and reform["dcn_size"] == 1
