"""GPipe with buffer-carrying stages (BatchNorm) via buffer_mode='frozen'.

Reference: the SectionWorker forbids cross-microbatch state; frozen mode
runs buffered layers with read-only buffers (train-mode BN normalizes
with batch stats, so the forward math is unchanged — only running-stat
tracking is off).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import create_mesh
from paddle_tpu.distributed.pipeline import GPipeTrainer


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 8)
        self.bn = nn.BatchNorm1D(8)

    def forward(self, x):
        return F.relu(self.bn(self.fc(x)))


def build(seed=0):
    paddle.seed(seed)
    pre = nn.Linear(4, 8)
    blocks = [Block() for _ in range(4)]
    post = nn.Linear(8, 2)
    return pre, blocks, post


def mse(out, y):
    return F.mse_loss(out, y)


def batch(n=8):
    rng = np.random.RandomState(0)
    return (rng.randn(n, 4).astype(np.float32),
            rng.randn(n, 2).astype(np.float32))


def test_buffers_forbidden_by_default():
    pre, blocks, post = build()
    params = [p for l in (pre, post, *blocks) for p in l.parameters()]
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    with pytest.raises(NotImplementedError, match="frozen"):
        GPipeTrainer(pre, blocks, post, opt, mse,
                     mesh=create_mesh({"pp": 2}), num_microbatches=2)


def test_frozen_buffers_pipeline_matches_single_device():
    """First-step loss of the pp=2 frozen-buffer pipeline equals the
    eager PER-MICROBATCH forward loss: BatchNorm uses batch statistics,
    and a pipeline normalizes each microbatch separately (inherent to
    microbatching, reference included)."""
    x, y = batch()

    pre, blocks, post = build()
    losses = []
    for lo in (0, 4):  # the two microbatches of 4
        out = post(blocks[3](blocks[2](blocks[1](blocks[0](
            pre(paddle.to_tensor(x[lo:lo + 4])))))))
        losses.append(float(mse(out, paddle.to_tensor(y[lo:lo + 4]))))
    eager_loss = float(np.mean(losses))

    pre, blocks, post = build()
    params = [p for l in (pre, post, *blocks) for p in l.parameters()]
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    pipe = GPipeTrainer(pre, blocks, post, opt, mse,
                        mesh=create_mesh({"pp": 2}), num_microbatches=2,
                        buffer_mode="frozen")
    pipe_loss = float(pipe.train_step(x, y))
    assert pipe_loss == pytest.approx(eager_loss, rel=1e-4)


def test_frozen_buffers_pipeline_trains():
    pre, blocks, post = build()
    params = [p for l in (pre, post, *blocks) for p in l.parameters()]
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    pipe = GPipeTrainer(pre, blocks, post, opt, mse,
                        mesh=create_mesh({"pp": 2, "dp": 2}),
                        num_microbatches=2, buffer_mode="frozen")
    x, y = batch(16)
    losses = [float(pipe.train_step(x, y)) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.9, losses


def test_frozen_buffers_stay_frozen():
    pre, blocks, post = build()
    bn_mean_before = np.asarray(blocks[0].bn._mean.data).copy()
    params = [p for l in (pre, post, *blocks) for p in l.parameters()]
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    pipe = GPipeTrainer(pre, blocks, post, opt, mse,
                        mesh=create_mesh({"pp": 2}), num_microbatches=2,
                        buffer_mode="frozen")
    x, y = batch()
    pipe.train_step(x, y)
    np.testing.assert_array_equal(
        np.asarray(blocks[0].bn._mean.data), bn_mean_before)
