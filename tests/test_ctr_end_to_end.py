"""CTR end-to-end: InMemoryDataset -> sparse Embedding -> trained model,
and the same pipeline against the parameter server.

Ties together the round-5 subsystems the reference uses for
click-through-rate training: slot dataset (data_set.h), SelectedRows
sparse gradients (selected_rows.h), lazy sparse Adam (adam_op.h), and
the host-side PS (distributed/service/).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import InMemoryDataset


def make_ctr_dataset(n=256, vocab=1000, slots=3, seed=0):
    """Synthetic CTR data: click prob driven by a hidden per-id weight."""
    rng = np.random.RandomState(seed)
    hidden = rng.randn(vocab) * 1.5
    records = []
    for _ in range(n):
        ids = rng.randint(0, vocab, (slots,))
        logit = hidden[ids].sum()
        label = float(rng.rand() < 1 / (1 + np.exp(-logit)))
        records.append({"label": [label], "slot": ids.tolist()})
    ds = InMemoryDataset(use_slots=["slot"], batch_size=32)
    ds.set_records(records)
    return ds, hidden


class CTRModel(nn.Layer):
    def __init__(self, vocab, dim=8):
        super().__init__()
        self.emb = nn.Embedding(vocab, dim, sparse=True)
        self.fc = nn.Linear(dim, 1)

    def forward(self, ids, lengths):
        e = self.emb(ids)                            # [B, T, D]
        # padded slots are id -1 -> mask them out of the mean
        mask = (ids >= 0).astype("float32")
        e = e * mask.unsqueeze(-1)
        pooled = e.sum(axis=1) / paddle.clip(
            mask.sum(axis=1, keepdim=True), min=1.0)
        return self.fc(pooled)


def test_inmemory_to_sparse_embedding_training():
    vocab = 1000
    ds, _ = make_ctr_dataset(vocab=vocab)
    ds.local_shuffle(seed=0)
    paddle.seed(0)
    model = CTRModel(vocab)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters(),
                                lazy_mode=True)
    losses = []
    for epoch in range(6):
        ep = []
        for batch in ds.batch_generator():
            ids = paddle.to_tensor(batch["slot"])
            lengths = paddle.to_tensor(batch["slot@len"])
            labels = paddle.to_tensor(batch["label"][:, :1])
            logits = model(ids, lengths)
            loss = F.binary_cross_entropy_with_logits(logits, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ep.append(float(loss))
        losses.append(float(np.mean(ep)))
    assert losses[-1] < losses[0] * 0.85, losses
    # the sparse grad path really ran: embedding grads were SelectedRows
    from paddle_tpu.core.selected_rows import SelectedRows
    loss = F.binary_cross_entropy_with_logits(
        model(ids, lengths), labels)
    loss.backward()
    assert isinstance(model.emb.weight.grad, SelectedRows)


@pytest.mark.slow
def test_ctr_against_parameter_server():
    """Same workload with the embedding table living on a 2-shard PS:
    pull rows, compute grads locally, push sparse updates."""
    from paddle_tpu.distributed.ps import PSClient, PSServer

    vocab, dim = 500, 8
    ds, _ = make_ctr_dataset(n=192, vocab=vocab, seed=1)
    servers = [PSServer("127.0.0.1:0", n_workers=1) for _ in range(2)]
    eps = []
    for s in servers:
        s.start()
        eps.append(f"127.0.0.1:{s.port}")
    try:
        cli = PSClient(eps)
        cli.ensure_sparse_table("emb", dim=dim, rule="adagrad",
                                init_scale=0.01, seed=0)
        paddle.seed(0)
        fc = nn.Linear(dim, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=fc.parameters())
        losses = []
        for epoch in range(6):
            ep = []
            for batch in ds.batch_generator():
                ids = batch["slot"]                  # [B, T] (>=0 here)
                flat = ids.reshape(-1)
                rows = cli.pull_sparse("emb", flat)   # [B*T, D]
                e = paddle.to_tensor(
                    rows.reshape(ids.shape[0], ids.shape[1], dim),
                    stop_gradient=False)
                labels = paddle.to_tensor(batch["label"][:, :1])
                pooled = e.mean(axis=1)
                loss = F.binary_cross_entropy_with_logits(fc(pooled),
                                                          labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                g = np.asarray(e.grad.data).reshape(len(flat), dim)
                cli.push_sparse("emb", flat, g, lr=0.3)
                e.clear_grad()
                ep.append(float(loss))
            losses.append(float(np.mean(ep)))
        assert losses[-1] < losses[0] * 0.9, losses
        assert cli.sparse_table_size("emb") > 0
        cli.close()
    finally:
        for s in servers:
            s.stop()
