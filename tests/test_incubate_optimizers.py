"""LookAhead / ModelAverage / ExponentialMovingAverage.

Reference semantics:
- LookAhead: /root/reference/python/paddle/incubate/optimizer/lookahead.py
- ModelAverage window rule:
  /root/reference/paddle/fluid/operators/average_accumulates_op.h:80
- EMA: /root/reference/python/paddle/fluid/optimizer.py:3466
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate import LookAhead, ModelAverage
from paddle_tpu.optimizer import ExponentialMovingAverage


def make_data(seed=0, n=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 4).astype(np.float32),
            rng.randn(n, 2).astype(np.float32))


def mse(out, y):
    return F.mse_loss(out, y)


def train_eager(opt_factory, steps=10, seed=0):
    paddle.seed(seed)
    model = nn.Linear(4, 2)
    opt = opt_factory(model)
    x, y = make_data()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    for _ in range(steps):
        loss = mse(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return model, opt


def test_lookahead_matches_hand_rolled():
    """LookAhead(SGD) == manual fast/slow bookkeeping."""
    k, alpha, lr, steps = 3, 0.4, 0.1, 7
    model, _ = train_eager(
        lambda m: LookAhead(paddle.optimizer.SGD(
            learning_rate=lr, parameters=m.parameters()),
            alpha=alpha, k=k),
        steps=steps)

    # manual replica
    paddle.seed(0)
    ref = nn.Linear(4, 2)
    x, y = make_data()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    fast = {n: np.asarray(p.data, np.float64)
            for n, p in ref.named_parameters()}
    slow = {n: v.copy() for n, v in fast.items()}
    for step in range(1, steps + 1):
        loss = mse(ref(xt), yt)
        loss.backward()
        grads = {n: np.asarray(p.grad.data, np.float64)
                 for n, p in ref.named_parameters()}
        for n in fast:
            fast[n] = fast[n] - lr * grads[n]
            if step % k == 0:
                slow[n] = slow[n] + alpha * (fast[n] - slow[n])
                fast[n] = slow[n]
        # write back so the next forward uses the updated fast weights
        for n, p in ref.named_parameters():
            p._data = paddle.to_tensor(
                fast[n].astype(np.float32)).data
            p.clear_grad()

    for n, p in model.named_parameters():
        np.testing.assert_allclose(np.asarray(p.data), fast[n],
                                   rtol=1e-5, atol=1e-6)


def test_lookahead_wraps_adam_and_converges():
    model, _ = train_eager(
        lambda m: LookAhead(paddle.optimizer.Adam(
            learning_rate=0.05, parameters=m.parameters())),
        steps=60)
    x, y = make_data()
    loss = float(mse(model(paddle.to_tensor(x)), paddle.to_tensor(y)))
    assert loss < 1.0


def test_lookahead_inside_compiled_trainer():
    """The slow weights are plain optimizer state, so LookAhead runs
    inside the compiled SpmdTrainer step unchanged."""
    from paddle_tpu.distributed import SpmdTrainer, create_mesh

    k, alpha, lr, steps = 3, 0.4, 0.1, 7
    paddle.seed(0)
    model = nn.Linear(4, 2)
    inner = paddle.optimizer.SGD(learning_rate=lr,
                                 parameters=model.parameters())
    la = LookAhead(inner, alpha=alpha, k=k)
    tr = SpmdTrainer(model, la, mse, mesh=create_mesh({"dp": 1}))
    x, y = make_data()
    for _ in range(steps):
        tr.train_step(x, y)

    eager_model, _ = train_eager(
        lambda m: LookAhead(paddle.optimizer.SGD(
            learning_rate=lr, parameters=m.parameters()),
            alpha=alpha, k=k),
        steps=steps)
    for (n, p), (_, q) in zip(sorted(tr.params.items()),
                              sorted({n: p.data for n, p in
                                      eager_model.named_parameters()}
                                     .items())):
        np.testing.assert_allclose(np.asarray(p), np.asarray(q),
                                   rtol=1e-5, atol=1e-6)


def test_model_average_window_and_apply():
    rate, min_w, max_w = 0.5, 2, 4
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    ma = ModelAverage(rate, parameters=model.parameters(),
                      min_average_window=min_w, max_average_window=max_w)
    x, y = make_data()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)

    # hand-rolled replica of average_accumulates_op.h
    names = [n for n, _ in model.named_parameters()]
    s1 = {n: 0.0 for n in names}
    s2 = {n: 0.0 for n in names}
    s3 = {n: 0.0 for n in names}
    na = ona = nu = 0
    history = {n: [] for n in names}
    for _ in range(6):
        loss = mse(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        ma.step()
        nu += 1
        na += 1
        for n, p in model.named_parameters():
            s1[n] = s1[n] + np.asarray(p.data, np.float64)
        if na >= min_w and na >= min(max_w, int(nu * rate)):
            for n in names:
                s3[n] = s1[n] + s2[n]
                s1[n], s2[n] = 0.0, 0.0
            ona, na = na, 0
    expect = {n: (s1[n] + s2[n] + s3[n]) / max(na + ona, 1)
              for n in names}

    live = {n: np.asarray(p.data).copy()
            for n, p in model.named_parameters()}
    with ma.apply():
        for n, p in model.named_parameters():
            np.testing.assert_allclose(np.asarray(p.data), expect[n],
                                       rtol=1e-5, atol=1e-6)
    for n, p in model.named_parameters():  # restored after the context
        np.testing.assert_array_equal(np.asarray(p.data), live[n])


def test_ema_bias_corrected():
    decay = 0.9
    paddle.seed(0)
    model = nn.Linear(4, 2)
    ema = ExponentialMovingAverage(decay, parameters=model.parameters())
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x, y = make_data()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)

    shadow = {n: 0.0 for n, _ in model.named_parameters()}
    t = 0
    for _ in range(5):
        loss = mse(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        ema.update()
        t += 1
        for n, p in model.named_parameters():
            shadow[n] = decay * shadow[n] + \
                (1 - decay) * np.asarray(p.data, np.float64)

    live = {n: np.asarray(p.data).copy()
            for n, p in model.named_parameters()}
    with ema.apply():
        for n, p in model.named_parameters():
            np.testing.assert_allclose(
                np.asarray(p.data), shadow[n] / (1 - decay ** t),
                rtol=1e-5, atol=1e-6)
    for n, p in model.named_parameters():
        np.testing.assert_array_equal(np.asarray(p.data), live[n])


def test_ema_functional_form_matches_eager():
    import jax
    decay = 0.95
    paddle.seed(1)
    model = nn.Linear(4, 2)
    params = {n: p.data for n, p in model.named_parameters()}
    ema = ExponentialMovingAverage(decay, parameters=model.parameters())
    state = ema.init_state(params)

    step = jax.jit(ema.update_state)
    for i in range(4):
        bumped = {n: a + 0.1 * (i + 1) for n, a in params.items()}
        state = step(bumped, state)
        for n, p in model.named_parameters():
            p._data = bumped[n]
        ema.update()

    avg = ema.averaged(params, state)
    with ema.apply():
        for n, p in model.named_parameters():
            np.testing.assert_allclose(np.asarray(p.data),
                                       np.asarray(avg[n]),
                                       rtol=1e-5, atol=1e-6)


def test_ema_thres_steps_schedule():
    ema = ExponentialMovingAverage(0.999, thres_steps=True,
                                   parameters=[])
    # early steps use (1+t)/(10+t) < 0.999
    assert float(ema._current_decay(1.0)) == pytest.approx(2 / 11)
    assert float(ema._current_decay(1e6)) == pytest.approx(0.999)
