"""C inference API: build the shared lib, export a model, serve it from C.

Reference: paddle/fluid/inference/capi/pd_predictor.cc + its C tests.
Two layers of proof: the ctypes test exercises the exact C ABI in-
process; the subprocess test runs a REAL standalone C executable with
no Python on its command line (marked slow — it builds a binary and
cold-starts an embedded interpreter + XLA).
"""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path_factory.mktemp("export") / "lin")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([1, 4], "float32")])
    x = np.arange(4, dtype=np.float32).reshape(1, 4) * 0.1
    expect = np.asarray(model(paddle.to_tensor(x)).data)
    return path, x, expect


@pytest.fixture(scope="module")
def capi_lib(tmp_path_factory):
    from paddle_tpu.inference.capi.build import build_library
    out = str(tmp_path_factory.mktemp("capi") / "libpd_inference.so")
    try:
        return build_library(out)
    except Exception as e:  # no compiler in exotic envs: skip, not fail
        pytest.skip(f"cannot build C library: {e}")


class PDTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("shape", ctypes.c_int64 * 8),
                ("ndim", ctypes.c_int32),
                ("dtype", ctypes.c_char * 16)]


def test_capi_run_matches_python(exported_model, capi_lib):
    path, x, expect = exported_model
    lib = ctypes.CDLL(capi_lib)
    lib.PD_NewPredictor.restype = ctypes.c_void_p
    lib.PD_NewPredictor.argtypes = [ctypes.c_char_p]
    lib.PD_PredictorRun.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(PDTensor), ctypes.c_int32,
        ctypes.POINTER(ctypes.POINTER(PDTensor)),
        ctypes.POINTER(ctypes.c_int32)]
    lib.PD_GetLastError.restype = ctypes.c_char_p

    pred = lib.PD_NewPredictor(path.encode())
    assert pred, lib.PD_GetLastError()

    xin = np.ascontiguousarray(x)
    t = PDTensor()
    t.data = xin.ctypes.data_as(ctypes.c_void_p)
    t.ndim = 2
    t.shape[0], t.shape[1] = 1, 4
    t.dtype = b"float32"

    outs = ctypes.POINTER(PDTensor)()
    n_outs = ctypes.c_int32()
    rc = lib.PD_PredictorRun(pred, ctypes.byref(t), 1,
                             ctypes.byref(outs), ctypes.byref(n_outs))
    assert rc == 0, lib.PD_GetLastError()
    assert n_outs.value == 1
    out_t = outs[0]
    assert out_t.dtype.decode().startswith("float32")
    shape = tuple(out_t.shape[i] for i in range(out_t.ndim))
    assert shape == (1, 2)
    vals = np.ctypeslib.as_array(
        ctypes.cast(out_t.data, ctypes.POINTER(ctypes.c_float)),
        shape=shape).copy()
    np.testing.assert_allclose(vals, expect, rtol=1e-5, atol=1e-6)

    lib.PD_TensorsFree(outs, n_outs)
    lib.PD_DeletePredictor(ctypes.c_void_p(pred))


def test_capi_error_reporting(capi_lib):
    lib = ctypes.CDLL(capi_lib)
    lib.PD_NewPredictor.restype = ctypes.c_void_p
    lib.PD_NewPredictor.argtypes = [ctypes.c_char_p]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    pred = lib.PD_NewPredictor(b"/nonexistent/model")
    assert not pred
    assert b"PD_NewPredictor" in lib.PD_GetLastError()


@pytest.mark.slow
def test_standalone_c_binary_serves_export(exported_model,
                                           tmp_path_factory):
    from paddle_tpu.inference.capi.build import build_demo
    path, x, expect = exported_model
    try:
        exe = build_demo(str(tmp_path_factory.mktemp("demo") /
                             "pd_capi_demo"))
    except Exception as e:
        pytest.skip(f"cannot build demo: {e}")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):  # no TPU plugin inside the embedded interpreter
        if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_")):
            del env[k]
    proc = subprocess.run([exe, path, "4"], env=env, capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout={proc.stdout}\nstderr={proc.stderr}"
    assert "CAPI-DEMO-OK" in proc.stdout
    # the demo feeds the same ramp input the fixture used
    first = float(proc.stdout.split("OUT 0")[1].split(":")[1].split()[0])
    assert first == pytest.approx(float(expect[0, 0]), rel=1e-4)
