"""Unified telemetry layer (ISSUE 13): registry, spans, capture, SLO.

The two invariants that make telemetry shippable on a serving hot path:

1. **Telemetry-on is free of syncs and recompiles**: with the span
   tracer armed and metrics flowing, a warmed engine's decode loop
   performs EXACTLY one host sync per tick (PR-3's counter proves it —
   zero added) and zero new XLA compiles/traces.
2. **Telemetry-off allocates nothing per step**: an inactive tracer
   buffers nothing, and a disabled registry (PADDLE_TPU_METRICS=0)
   hands every caller the same shared no-op child.

Plus the export contracts the bench smoke rides: Prometheus exposition
round-trips through the parser, Chrome-trace JSON validates and holds
the per-request lifecycle, snapshot files land atomically.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import observability as obs
from paddle_tpu.distributed import async_dispatch
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability.capture import (ProfileWindow,
                                              parse_profile_spec)
from paddle_tpu.observability.metrics import Registry
from paddle_tpu.observability.slo import (FleetAggregator, SLOMonitor,
                                          load_bench_baseline)
from paddle_tpu.utils import compile_counter


@pytest.fixture
def tracer():
    """Armed span tracer, always disarmed + cleared afterwards (the
    tracer is process-global; other test files must not inherit it)."""
    tr = obs.tracer()
    tr.clear()
    tr.start()
    yield tr
    tr.stop()
    tr.clear()


def tiny_model(seed=0):
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(seed)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
def test_registry_counter_gauge_histogram():
    r = Registry()
    c = r.counter("reqs_total", "requests", labels=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc()
    assert c.labels(kind="a").value == 3
    assert c.labels(kind="b").value == 1
    g = r.gauge("depth")
    g.set(7)
    g.inc()
    assert g.value == 8
    h = r.histogram("lat_ms", buckets=(10.0, 100.0))
    for v in (1, 5, 50, 500):
        h.observe(v)
    child = h.labels()
    assert child.count == 4 and child.sum == 556
    assert child.counts == [2, 1, 1]          # <=10, <=100, +Inf
    assert child.percentile(50) == 10.0


def test_registry_kind_conflict_raises():
    r = Registry()
    r.counter("x_total")
    with pytest.raises(ValueError):
        r.gauge("x_total")


def test_registry_label_child_is_cached():
    r = Registry()
    c = r.counter("y_total", labels=("k",))
    assert c.labels(k="v") is c.labels(k="v")   # lock-free after first


def test_exposition_round_trips_through_parser():
    r = Registry()
    r.counter("a_total", "with \"quotes\"",
              labels=("k",)).labels(k='va"l\nue').inc(4)
    r.gauge("b").set(2.5)
    r.histogram("h_ms", buckets=(1.0, 10.0)).observe(3.0)
    text = r.exposition()
    parsed = obs.parse_exposition(text)
    assert parsed["a_total"]["type"] == "counter"
    name, labels, value = parsed["a_total"]["samples"][0]
    assert labels == {"k": 'va"l\nue'} and value == 4
    assert parsed["b"]["samples"][0][2] == 2.5
    hist = parsed["h_ms"]
    assert hist["type"] == "histogram"
    by_name = {}
    for name, labels, value in hist["samples"]:
        by_name.setdefault(name, []).append((labels, value))
    # cumulative buckets: 0 at le=1, 1 at le=10 and +Inf; sum/count ride
    assert [v for _, v in by_name["h_ms_bucket"]] == [0, 1, 1]
    assert by_name["h_ms_sum"][0][1] == 3.0
    assert by_name["h_ms_count"][0][1] == 1


def test_exposition_escapes_hostile_label_values_and_help():
    """ISSUE 14 satellite: backslashes, quotes, and newlines in label
    values AND in metric help text must render escaped and round-trip
    through the parser — a raw newline in a HELP line used to split
    into a garbage sample line and break the whole scrape."""
    hostiles = ['back\\slash', 'a"b', 'nl\nx', 'end\\', 'mix\\"q\n,=}{',
                'tab\tv', '{br}ace']
    for h in hostiles:
        r = Registry()
        r.counter("t_total", 'help with\nnewline, \\ and "quotes"',
                  labels=("k",)).labels(k=h).inc(2)
        r.histogram("h_ms", "hist\nhelp", labels=("k",),
                    buckets=(1.0, 10.0)).labels(k=h).observe(3.0)
        text = r.exposition()
        # the exposition itself must not contain a raw-newline-split
        # garbage line (every line is a comment or parses as a sample)
        parsed = obs.parse_exposition(text)
        name, labels, value = parsed["t_total"]["samples"][0]
        assert labels == {"k": h} and value == 2
        hist = {n: v for n, lbl, v in parsed["h_ms"]["samples"]
                if lbl.get("k") == h and n == "h_ms_count"}
        assert hist["h_ms_count"] == 1


def test_load_bench_baseline_missing_empty_corrupt(tmp_path):
    """ISSUE 14 satellite: a missing, empty, or corrupt (binary
    garbage) BENCH_rows.jsonl yields a clean no-baseline verdict —
    never an exception out of a serving loop."""
    # missing
    assert load_bench_baseline(str(tmp_path / "nope.jsonl")) is None
    # empty
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert load_bench_baseline(str(empty)) is None
    # corrupt: binary garbage raises UnicodeDecodeError during line
    # iteration without the hardening
    corrupt = tmp_path / "corrupt.jsonl"
    corrupt.write_bytes(b"\xff\xfe\x00garbage\x80\x81\nmore\xff\n")
    assert load_bench_baseline(str(corrupt)) is None
    # half-corrupt: the valid row is still found past garbage lines
    mixed = tmp_path / "mixed.jsonl"
    mixed.write_bytes(
        b"\x80bad\n" +
        json.dumps({"kind": "loadtest", "metric": "gpt_serve_loadtest",
                    "ttft_ms_p99": 12.5}).encode() + b"\n{half")
    assert load_bench_baseline(str(mixed)) == 12.5
    # SLOMonitor built over each of them: clean "no baseline" verdict
    for path in (tmp_path / "nope.jsonl", empty, corrupt):
        mon = SLOMonitor(rows_path=str(path))
        assert mon.baseline_ttft_p99_ms is None
        mon.observe(50.0)
        v = mon.check()
        assert v["regressed"] is False
        assert v["baseline_ttft_p99_ms"] is None


def test_snapshot_jsonl_is_atomic(tmp_path):
    r = Registry()
    r.counter("c_total").inc(5)
    path = str(tmp_path / "m.jsonl")
    r.write_snapshot(path)
    r.counter("c_total").inc()
    r.write_snapshot(path, extra={"step": 2})
    # no temp orphan, every line parses, history preserved
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert len(lines) == 2
    assert lines[0]["metrics"]["c_total"]["series"][0]["value"] == 5
    assert lines[1]["metrics"]["c_total"]["series"][0]["value"] == 6
    assert lines[1]["step"] == 2


def test_disabled_registry_is_shared_noop(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS", "0")
    c1 = obs_metrics.counter("never_registered_total")
    c2 = obs_metrics.gauge("never_registered_gauge")
    # every disabled factory hands back the SAME null metric whose
    # children are the SAME null child: no per-call-site state at all
    assert c1 is c2
    assert c1.labels(any="x") is c2.labels(other="y")
    c1.inc()
    c2.labels(a="b").observe(3.0)
    assert "never_registered_total" not in obs_metrics.snapshot()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def test_span_records_only_when_active():
    tr = obs.tracer()
    tr.clear()
    assert not tr.active
    with obs.span("idle"):
        pass
    assert len(tr) == 0          # off = nothing buffered
    tr.start()
    try:
        with obs.span("busy", args={"n": 1}):
            pass
    finally:
        tr.stop()
    assert len(tr) == 1
    ev = tr.chrome_trace()["traceEvents"][-1]
    assert ev["name"] == "busy" and ev["ph"] == "X"
    assert ev["args"] == {"n": 1}
    tr.clear()


def test_tracer_capacity_drops_not_grows():
    from paddle_tpu.observability.spans import SpanTracer
    tr = SpanTracer(capacity=3)
    tr.start()
    for i in range(5):
        tr.complete(f"e{i}", 0.0, 1.0)
    assert len(tr) == 3 and tr.dropped == 2
    assert tr.chrome_trace()["otherData"]["dropped_events"] == 2


def test_chrome_trace_validates_and_labels_request_tracks(tracer):
    from paddle_tpu.observability.spans import PID_REQUESTS
    tracer.complete("queued", 0.0, 5.0, pid=PID_REQUESTS, tid=42,
                    cat="request")
    tracer.instant("preempt", pid=PID_REQUESTS, tid=42)
    doc = tracer.chrome_trace()
    assert obs.validate_chrome_trace(doc) == len(doc["traceEvents"])
    names = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "thread_name" and e["tid"] == 42
               and e["args"]["name"] == "request 42" for e in names)


def test_validate_chrome_trace_rejects_malformed():
    with pytest.raises(ValueError):
        obs.validate_chrome_trace({"traceEvents": [{"name": "x"}]})
    with pytest.raises(ValueError):
        obs.validate_chrome_trace(
            {"traceEvents": [{"name": "x", "ph": "X", "pid": 1,
                              "tid": 1, "ts": "soon", "dur": 1}]})


def test_record_event_feeds_span_buffer(tracer):
    from paddle_tpu.profiler import RecordEvent
    with RecordEvent("phase_x"):
        pass
    assert any(e["name"] == "phase_x"
               for e in tracer.chrome_trace()["traceEvents"])


# ---------------------------------------------------------------------------
# capture control
# ---------------------------------------------------------------------------
def test_parse_profile_spec():
    assert parse_profile_spec("2:5") == (2, 5, "/tmp/paddle_tpu_profile")
    assert parse_profile_spec("0:3:/x/y") == (0, 3, "/x/y")
    for bad in ("5", "5:2", "-1:3", "a:b"):
        with pytest.raises(ValueError):
            parse_profile_spec(bad)


def test_profile_window_start_stop(monkeypatch):
    calls = []
    import paddle_tpu.profiler as prof
    monkeypatch.setattr(prof, "start_profiler",
                        lambda d: calls.append(("start", d)) or d)
    monkeypatch.setattr(prof, "stop_profiler",
                        lambda **kw: calls.append(("stop", None)))
    w = ProfileWindow(2, 4, log_dir="/tmp/cap", kind="train")
    for step in range(6):
        w.on_step(step)
    assert calls == [("start", "/tmp/cap"), ("stop", None)]
    assert w.done and not w.active
    # window entirely in the past: never starts
    calls.clear()
    w2 = ProfileWindow(1, 2)
    w2.on_step(10)
    assert calls == [] and w2.done


def test_profile_window_from_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_PROFILE", raising=False)
    assert ProfileWindow.from_env() is None
    monkeypatch.setenv("PADDLE_TPU_PROFILE", "3:7")
    w = ProfileWindow.from_env(kind="serve")
    assert (w.start, w.stop) == (3, 7) and w.log_dir.endswith("serve")


# ---------------------------------------------------------------------------
# trainer wiring (StepTimer satellite)
# ---------------------------------------------------------------------------
def test_spmd_trainer_step_timer_and_registry(tracer):
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt,
                     lambda out, y: F.cross_entropy(out, y),
                     mesh=create_mesh({"dp": 1}))
    c0 = tr._m_steps.value
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, size=(8,)).astype(np.int64)
    for _ in range(3):
        tr.train_step(x, y)
    st = tr.stats
    # the once-orphaned profiler.StepTimer is live: wall time in stats…
    assert st["step_time_ms"] is not None and st["step_time_ms"] > 0
    assert st["step_time_mean_ms"] > 0
    # …and mirrored into the registry
    assert tr._m_steps.value == c0 + 3
    assert tr._m_step_ms.value == pytest.approx(st["step_time_ms"],
                                                abs=1e-3)
    # train phase spans landed while the tracer was armed
    names = {e["name"] for e in obs.tracer().chrome_trace()["traceEvents"]}
    assert "dispatch" in names


# ---------------------------------------------------------------------------
# comm_stats graceful degradation (satellite)
# ---------------------------------------------------------------------------
def test_comm_stats_degrades_instead_of_raising():
    from paddle_tpu.utils import comm_stats

    class BrokenCompiled:
        def as_text(self):
            raise RuntimeError("no HLO text on this backend")

    before = obs_metrics.counter(
        "comm_stats_failures_total", labels=("stage",)).labels(
        stage="analyze_compiled").value
    out = comm_stats.analyze_compiled(BrokenCompiled())
    assert out["unavailable"] and out["count"] == 0 and out["bytes"] == 0
    assert out["by_op"] == {} and out["comm_ms"] == 0.0
    assert "no HLO text" in out["error"]
    after = obs_metrics.counter(
        "comm_stats_failures_total", labels=("stage",)).labels(
        stage="analyze_compiled").value
    assert after == before + 1
    # a trainer storing this breakdown reports zeros, not a crash
    assert comm_stats.empty_breakdown()["unavailable"]


def test_comm_stats_analyze_jit_failure_returns_none():
    import jax
    from paddle_tpu.utils import comm_stats

    def f(a, b):
        return a @ b

    # mismatched shapes: lowering raises inside, caller gets None
    bad = (jax.ShapeDtypeStruct((3, 4), np.float32),
           jax.ShapeDtypeStruct((3, 4), np.float32))
    assert comm_stats.analyze_jit(jax.jit(f), *bad) is None


# ---------------------------------------------------------------------------
# overhead suite (the tentpole invariants)
# ---------------------------------------------------------------------------
def _decode_n(eng, prompt, n):
    """Admit one request and decode it to completion, returning the
    (sync delta, tick delta) the run cost."""
    s0 = async_dispatch.host_sync_count()
    t0 = eng._timings["decode_steps"]
    rid = eng.add_request(prompt, max_new_tokens=n)
    out = eng.run()[rid]
    assert len(out) == n
    return (async_dispatch.host_sync_count() - s0,
            eng._timings["decode_steps"] - t0)


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_telemetry_on_adds_zero_syncs_and_zero_recompiles(layout, tracer):
    """THE overhead contract: spans armed + metrics flowing, a warmed
    engine decodes with exactly 1 sync per tick + 1 per admission
    (telemetry adds ZERO) and zero new XLA compiles or traces."""
    m = tiny_model()
    kw = dict(kv_block_size=8) if layout == "paged" else {}
    eng = InferenceEngine(m, batch_slots=2, kv_layout=layout,
                          prefill_buckets=[16], **kw)
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 97, (7,)).astype(np.int32)
    # the executable observatory is ARMED (entries registered at
    # warmup) — the sync/recompile budget below therefore proves the
    # registry + HBM ledger add nothing to the hot path (ISSUE 15)
    from paddle_tpu.observability import exec_registry as er
    kinds = {e.kind for e in er.registry().entries(eng._exec_component)}
    assert {"prefill", "decode", "sample"} <= kinds
    with compile_counter.assert_no_recompiles(
            f"{layout} decode with telemetry on"):
        syncs, ticks = _decode_n(eng, prompt, 8)
    # 1 admission sample + 1 per decode tick — nothing else
    assert syncs == ticks + 1, \
        f"telemetry added host syncs: {syncs} for {ticks} ticks"
    # runtime pairing happened (registry saw every tick) without a
    # single extra sync or compile
    dec = [e for e in er.registry().entries(eng._exec_component)
           if e.kind == "decode"][0]
    assert dec.calls >= ticks
    # reading the ledger + stats (exec_profile/hbm/doctor) is dict math
    s0 = async_dispatch.host_sync_count()
    with compile_counter.assert_no_recompiles("stats read"):
        st = eng.stats
        er.ledger().snapshot()
    assert async_dispatch.host_sync_count() == s0
    assert "exec_profile" in st and "hbm" in st
    # the request left a full lifecycle on its track
    from paddle_tpu.observability.spans import PID_REQUESTS
    req_spans = {e["name"] for e in tracer.chrome_trace()["traceEvents"]
                 if e.get("pid") == PID_REQUESTS and e["ph"] == "X"}
    assert {"queued", "prefill", "decode"} <= req_spans


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_telemetry_on_spec_decode_zero_recompiles(tracer):
    """Spec engine (target-as-draft harness): spans on, one sync per
    spec tick, zero recompiles, accept counts in the tick args."""
    m = tiny_model()
    eng = InferenceEngine(m, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, prefill_buckets=[16],
                          spec_k=2, draft_model=m)
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, 97, (6,)).astype(np.int32)
    with compile_counter.assert_no_recompiles(
            "spec decode with telemetry on"):
        syncs, ticks = _decode_n(eng, prompt, 6)
    assert syncs == ticks + 1
    spec_ticks = [e for e in tracer.chrome_trace()["traceEvents"]
                  if e["name"] == "spec_tick"]
    assert spec_ticks and all("committed" in e["args"]
                              for e in spec_ticks)
    # the spec tick joined the observatory as its own kind (ISSUE 15)
    from paddle_tpu.observability import exec_registry as er
    kinds = {e.kind for e in er.registry().entries(eng._exec_component)}
    assert "spec_verify" in kinds


def test_exec_registry_armed_trainer_step_budget():
    """SpmdTrainer half of the ISSUE-15 overhead contract: with the
    registry + ledger armed (always), a warmed trainer's steps stay
    recompile-free and the lazy loop performs zero per-step syncs —
    registration/pairing is pure host dict work."""
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.observability import exec_registry as er
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 10))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, lambda o, y: F.cross_entropy(o, y),
                     mesh=create_mesh({"dp": 1}))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, size=(8,)).astype(np.int64)
    tr.train_step(x, y)                        # warmup/compile
    assert [e.kind for e in er.registry().entries(tr._exec_component)] \
        == ["train_step"]
    s0 = async_dispatch.host_sync_count()
    with compile_counter.assert_no_recompiles("registry-armed steps"):
        for _ in range(4):
            tr.train_step(x, y)                # lazy: no readbacks
    assert async_dispatch.host_sync_count() == s0
    e = er.registry().entries(tr._exec_component)[0]
    assert e.calls >= 4
    # ledger tracked the trainer state without touching the device
    cats = {t["category"] for t in er.ledger().snapshot()["tracked"]
            if t["name"] == tr.telemetry_label}
    assert "params" in cats
    assert async_dispatch.host_sync_count() == s0


def test_exec_registry_snapshot_to_report_round_trip(tmp_path):
    """Registry round-trip through observability.snapshot() → the
    report CLI renderer: what a warmed engine registered must come back
    out of the offline snapshot file."""
    from paddle_tpu.observability import exec_registry as er
    from paddle_tpu.observability import report
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(5)
    rid = eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                          max_new_tokens=4)
    eng.run()
    er.analyze_all(eng._exec_component)
    snap = obs.snapshot()
    mine = [r for r in snap["executables"]["executables"]
            if r["component"] == eng._exec_component]
    assert {"prefill", "decode", "sample"} <= {r["kind"] for r in mine}
    path = str(tmp_path / "snap.jsonl")
    obs.write_snapshot(path)
    rec = report.load_snapshot_file(path)
    text = report.render_snapshot(rec)
    assert eng._exec_component in text and "hbm ledger" in text
    assert report.main(["--snapshot", path]) == 0


def test_telemetry_off_buffers_nothing():
    """Disabled path: tracer inactive -> the decode loop appends no
    events (no per-step span allocation at all)."""
    tr = obs.tracer()
    assert not tr.active
    tr.clear()
    m = tiny_model()
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(2)
    rid = eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                          max_new_tokens=4)
    eng.run()
    assert len(tr) == 0


# ---------------------------------------------------------------------------
# fleet aggregation + SLO
# ---------------------------------------------------------------------------
class _FakeReplica:
    def __init__(self, recs):
        self.request_stats = recs
        self._queue = []
        self.num_active = 0
        self.blocks_in_use = 3
        self._request_stats_cap = 4096


def test_fleet_aggregator_scrapes_new_records_once():
    recs = {1: {"ttft_ms": 10.0, "tokens": 5, "timed_out": False},
            2: {"ttft_ms": 99.0, "tokens": 2, "timed_out": True}}
    agg = FleetAggregator([_FakeReplica(recs)])
    assert agg.scrape()["new_requests"] == 2
    assert agg.scrape()["new_requests"] == 0     # seen-set dedupes
    snap = obs_metrics.snapshot()
    series = {tuple(sorted(s["labels"].items())): s
              for s in snap["fleet_requests_total"]["series"]}
    assert series[(("outcome", "ok"), ("replica", "0"))]["value"] >= 1
    assert series[(("outcome", "timed_out"),
                   ("replica", "0"))]["value"] >= 1


def test_slo_monitor_threshold_and_regression(tmp_path):
    rows = tmp_path / "rows.jsonl"
    rows.write_text(
        json.dumps({"kind": "loadtest", "metric": "gpt_serve_loadtest",
                    "ttft_ms_p99": 20.0}) + "\n" +
        json.dumps({"kind": "loadtest", "metric": "loadtest_smoke",
                    "ttft_ms_p99": 1.0}) + "\n")
    # smoke rows are excluded from the baseline
    assert load_bench_baseline(str(rows)) == 20.0
    mon = SLOMonitor(ttft_p99_ms=50.0, baseline_ttft_p99_ms=20.0,
                     regression_factor=2.0)
    for _ in range(20):
        mon.observe(10.0)
    v = mon.check()
    assert not v["breached"] and not v["regressed"]
    for _ in range(50):
        mon.observe(120.0)               # way past threshold + 2x20
    v = mon.check()
    assert v["breached"] and v["regressed"]
    assert mon.breaches >= 1 and mon.regressions >= 1


def test_router_scrape_metrics_and_counters():
    from paddle_tpu.inference.router import Router
    ra, rb = _FakeReplica({}), _FakeReplica({})
    r = Router([ra, rb], policy="round_robin")
    r.route(np.asarray([1, 2, 3], np.int32))
    r.route(np.asarray([4, 5], np.int32))
    assert r._m_routed.value >= 2
    assert r.scrape_metrics()["new_requests"] == 0
    ra.request_stats[7] = {"ttft_ms": 5.0, "tokens": 3,
                           "timed_out": False}
    assert r.scrape_metrics()["new_requests"] == 1


# ---------------------------------------------------------------------------
# the acceptance shot: one snapshot, three tiers
# ---------------------------------------------------------------------------
def test_one_snapshot_returns_train_serve_and_fleet_metrics():
    """ISSUE 13 acceptance: a live run touching trainer + engine +
    fleet aggregation answers from ONE metrics.snapshot() call."""
    # train tier (SpmdTrainer ran in this process in the test above;
    # run one more step to be order-independent)
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt,
                     lambda out, y: F.cross_entropy(out, y),
                     mesh=create_mesh({"dp": 1}))
    rng = np.random.RandomState(0)
    tr.train_step(rng.randn(4, 8).astype(np.float32),
                  rng.randint(0, 4, size=(4,)).astype(np.int64))
    # serve tier
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rid = eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                          max_new_tokens=3)
    eng.run()
    # fleet tier
    FleetAggregator([eng]).scrape()

    snap = obs.snapshot()["metrics"]
    for family in ("train_steps_total", "train_step_ms",     # train
                   "serve_decode_ticks_total", "serve_ttft_ms",  # serve
                   "fleet_ttft_ms", "fleet_tokens_total",    # fleet
                   "host_syncs_total", "xla_compiles_total"):
        assert family in snap, f"{family} missing from snapshot()"
