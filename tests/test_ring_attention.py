"""Ring attention / sequence parallelism tests (virtual 8-device mesh).

SURVEY.md §5 marks context parallelism ABSENT in the reference ("design
fresh: ring attention over ICI neighbor exchange"); ground truth is the
framework's own composite attention on the unsharded arrays.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import (SpmdTrainer, create_mesh,
                                    ring_attention)
from paddle_tpu.distributed.mesh import set_mesh
from paddle_tpu.nn.functional.attention import _sdpa_reference


def qkv(b=2, s=32, h=4, d=8, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d).astype(dtype) * 0.3)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [4, 8])
def test_ring_matches_reference(causal, sp):
    q, k, v = qkv()
    ref = _sdpa_reference(q, k, v, is_causal=causal)
    mesh = create_mesh({"sp": sp})
    out = ring_attention(q, k, v, mesh=mesh, causal=causal,
                         batch_axis=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_ring_composes_with_dp():
    q, k, v = qkv(b=4)
    ref = _sdpa_reference(q, k, v, is_causal=True)
    mesh = create_mesh({"dp": 2, "sp": 4})
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_ring_backward_matches_reference():
    """jax.grad through the ring (scan + ppermute transpose) equals the
    composite's gradients.  ~30s of grad-of-scan-of-shard_map compile —
    slow-marked under the tight tier-1 budget; forward ring parity
    (both mesh sizes, causal on/off) stays tier-1."""
    q, k, v = qkv(s=16)
    mesh = create_mesh({"sp": 4})

    def loss_ring(q_, k_, v_):
        return (ring_attention(q_, k_, v_, mesh=mesh, causal=True,
                               batch_axis=None) ** 2).sum()

    def loss_ref(q_, k_, v_):
        return (_sdpa_reference(q_, k_, v_, is_causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_sequence_parallel_training_parity():
    """GPT with sequence_parallel=True on a dp2 x sp4 mesh: compiled
    train-step losses match the single-device dense run (the sp layout
    changes placement, not math)."""
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(3):
        ids = rng.randint(0, 64, (4, 32)).astype(np.int32)
        batches.append((ids, np.roll(ids, -1, 1).astype(np.int64)))

    losses = {}
    for name, axes, sp_flag in [("single", {"dp": 1}, False),
                                ("sp", {"dp": 2, "sp": 4}, True)]:
        paddle.seed(31)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False,
                        sequence_parallel=sp_flag)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=create_mesh(axes))
        losses[name] = [float(tr.train_step(x, y)) for x, y in batches]
        # batch actually sharded over sp on the seq dim
        if sp_flag:
            sh = tr.shard_batch(batches[0][0])
            assert "sp" in str(sh.sharding.spec)
    np.testing.assert_allclose(losses["sp"], losses["single"], rtol=2e-4,
                               atol=2e-5)


def test_gpt_sp_flag_without_mesh_falls_back():
    """sequence_parallel=True but no sp axis in the ambient mesh: the
    model silently uses the dense path (same losses as dense config)."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=4, max_seq_len=16, use_flash_attention=False,
                    sequence_parallel=True)
    model = GPTForCausalLM(cfg)
    set_mesh(None)
    ids = np.random.RandomState(0).randint(0, 64, (2, 16)).astype(np.int32)
    out = model(paddle.to_tensor(ids))
    assert np.all(np.isfinite(np.asarray(out.data)))


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_ring_gqa_unexpanded_kv_matches_repeated():
    """GQA: k/v enter the ring with Hkv heads and rotate un-expanded;
    result equals dense attention on repeat_interleaved k/v."""
    rng = np.random.RandomState(7)
    b, s, h, hkv, d = 2, 32, 8, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
    kr = jnp.repeat(k, h // hkv, axis=2)
    vr = jnp.repeat(v, h // hkv, axis=2)
    ref = _sdpa_reference(q, kr, vr, is_causal=True)
    mesh = create_mesh({"sp": 4})
    out = ring_attention(q, k, v, mesh=mesh, causal=True, batch_axis=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_sp_ragged_batch_falls_back_to_dense():
    """Review regression: a batch whose seq/batch doesn't divide the mesh
    must not crash the shard_map — it silently uses dense attention."""
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    crit = GPTPretrainingCriterion()
    paddle.seed(41)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=4, max_seq_len=32, use_flash_attention=False,
                    sequence_parallel=True)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                     mesh=create_mesh({"dp": 2, "sp": 4}))
    rng = np.random.RandomState(0)
    # seq 30 % sp 4 != 0 and batch 3 % dp 2 != 0: both must still train
    for shape in [(4, 30), (3, 32)]:
        ids = rng.randint(0, 64, shape).astype(np.int32)
        loss = float(tr.train_step(ids, np.roll(ids, -1, 1)
                                   .astype(np.int64)))
        assert np.isfinite(loss)


def test_ring_attention_raises_on_bad_shapes():
    q, k, v = qkv(s=30)
    mesh = create_mesh({"sp": 4})
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh, batch_axis=None)


def test_ring_on_mesh_without_sp_axis_degenerates():
    """Review regression: a mesh without an sp axis (or sp=1) must fall
    back to dense attention instead of crashing shard_map."""
    q, k, v = qkv()
    ref = _sdpa_reference(q, k, v, is_causal=True)
    out = ring_attention(q, k, v, mesh=create_mesh({"dp": 2}),
                         causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
