"""Optimizer + LR scheduler tests (reference unittests test_sgd_op.py,
test_adam_op.py, test_lr_scheduler.py — numeric update-rule checks vs
hand-rolled numpy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def make_param(arr):
    p = paddle.Parameter(np.asarray(arr, np.float32))
    p.optimize_attr = {"learning_rate": 1.0}
    p.regularizer = None
    p.need_clip = True
    return p


def set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, np.float32))


class TestUpdateRules:
    def test_sgd(self):
        p = make_param([1.0, 2.0])
        set_grad(p, [0.5, 0.5])
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        o.step()
        np.testing.assert_allclose(p.numpy(), [0.95, 1.95], rtol=1e-6)

    def test_momentum(self):
        p = make_param([1.0])
        o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        set_grad(p, [1.0])
        o.step()  # v=1, p=1-0.1
        np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)
        set_grad(p, [1.0])
        o.step()  # v=1.9, p=0.9-0.19
        np.testing.assert_allclose(p.numpy(), [0.71], rtol=1e-6)

    def test_adam_matches_numpy(self):
        rng = np.random.RandomState(0)
        w = rng.randn(4).astype(np.float32)
        p = make_param(w)
        o = opt.Adam(learning_rate=0.01, parameters=[p])
        m = np.zeros(4)
        v = np.zeros(4)
        cur = w.astype(np.float64)
        for step in range(1, 4):
            g = rng.randn(4).astype(np.float32)
            set_grad(p, g)
            o.step()
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * g * g
            lr_t = 0.01 * np.sqrt(1 - 0.999 ** step) / (1 - 0.9 ** step)
            cur = cur - lr_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(p.numpy(), cur, rtol=1e-5, atol=1e-6)

    def test_adamw_decoupled_decay(self):
        p1 = make_param([1.0])
        o1 = opt.Adam(learning_rate=0.1, parameters=[p1])
        p2 = make_param([1.0])
        o2 = opt.AdamW(learning_rate=0.1, weight_decay=0.1, parameters=[p2])
        set_grad(p1, [0.0])
        set_grad(p2, [0.0])
        o1.step()
        o2.step()
        # zero grad: Adam leaves param, AdamW decays it by lr*wd*p
        np.testing.assert_allclose(p1.numpy(), [1.0], atol=1e-6)
        np.testing.assert_allclose(p2.numpy(), [1.0 - 0.1 * 0.1 * 1.0],
                                   rtol=1e-5)

    def test_lamb_trust_ratio(self):
        p = make_param(np.full(3, 2.0))
        o = opt.Lamb(learning_rate=0.1, lamb_weight_decay=0.0,
                     parameters=[p])
        set_grad(p, np.full(3, 1.0))
        o.step()
        # m1h=1, m2h=1 -> r=1/ (1+eps) ~1; trust = |p|/|r| = 2
        expect = 2.0 - 0.1 * 2.0 * (1.0 / (1.0 + 1e-6))
        np.testing.assert_allclose(p.numpy(), np.full(3, expect), rtol=1e-4)

    def test_weight_decay_l2(self):
        p = make_param([1.0])
        o = opt.SGD(learning_rate=0.1, parameters=[p],
                    weight_decay=paddle.regularizer.L2Decay(0.5))
        set_grad(p, [0.0])
        o.step()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.5], rtol=1e-6)

    def test_grad_clip_global_norm(self):
        p1 = make_param([3.0])
        p2 = make_param([4.0])
        clip = nn.ClipGradByGlobalNorm(1.0)
        o = opt.SGD(learning_rate=1.0, parameters=[p1, p2], grad_clip=clip)
        set_grad(p1, [3.0])
        set_grad(p2, [4.0])
        o.step()  # global norm 5 -> scale 0.2
        np.testing.assert_allclose(p1.numpy(), [3.0 - 0.6], rtol=1e-5)
        np.testing.assert_allclose(p2.numpy(), [4.0 - 0.8], rtol=1e-5)


class TestFunctionalPath:
    def test_apply_gradients_matches_step(self):
        import jax.numpy as jnp
        w = np.random.randn(3, 2).astype(np.float32)
        g = np.random.randn(3, 2).astype(np.float32)
        # eager
        p = make_param(w)
        o1 = opt.Adam(learning_rate=0.01, parameters=[p])
        set_grad(p, g)
        o1.step()
        # functional
        o2 = opt.Adam(learning_rate=0.01)
        params = {"w": jnp.asarray(w)}
        state = o2.init_state(params)
        new_params, _ = o2.apply_gradients(params, {"w": jnp.asarray(g)},
                                           state, lr=0.01, step=1)
        np.testing.assert_allclose(p.numpy(), np.asarray(new_params["w"]),
                                   rtol=1e-5, atol=1e-6)

    def test_state_dict_roundtrip(self):
        p = make_param([1.0, 2.0])
        o = opt.Adam(learning_rate=0.01, parameters=[p])
        set_grad(p, [0.1, 0.1])
        o.step()
        sd = o.state_dict()
        p2 = make_param([1.0, 2.0])
        p2.name = p.name
        o2 = opt.Adam(learning_rate=0.01, parameters=[p2])
        o2.set_state_dict(sd)
        assert o2._step_count == 1
        np.testing.assert_allclose(
            o2._accumulators[p.name]["moment1"],
            o._accumulators[p.name]["moment1"])


class TestTraining:
    def test_linear_regression_converges(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 3).astype(np.float32)
        true_w = np.array([[1.5], [-2.0], [0.5]], np.float32)
        Y = X @ true_w
        lin = nn.Linear(3, 1)
        o = opt.Adam(learning_rate=0.1, parameters=lin.parameters())
        for _ in range(150):
            pred = lin(paddle.to_tensor(X))
            loss = nn.functional.mse_loss(pred, paddle.to_tensor(Y))
            loss.backward()
            o.step()
            o.clear_grad()
        np.testing.assert_allclose(lin.weight.numpy(), true_w, atol=0.05)


class TestLRSchedulers:
    def test_step_decay(self):
        s = opt.lr.StepDecay(1.0, step_size=2, gamma=0.5)
        lrs = [s()]
        for _ in range(4):
            s.step()
            lrs.append(s())
        np.testing.assert_allclose(lrs, [1.0, 1.0, 0.5, 0.5, 0.25])

    def test_cosine(self):
        s = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert s() == pytest.approx(1.0)
        s.step(10)
        assert s() == pytest.approx(0.0, abs=1e-8)
        s.step(5)
        assert s() == pytest.approx(0.5, abs=1e-8)

    def test_linear_warmup(self):
        s = opt.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0,
                                end_lr=0.1)
        assert s() == pytest.approx(0.0)
        s.step(5)
        assert s() == pytest.approx(0.05)
        s.step(15)
        assert s() == pytest.approx(0.1)

    def test_noam(self):
        s = opt.lr.NoamDecay(d_model=512, warmup_steps=4000)
        s.step(4000)
        peak = s()
        s.step(100)
        assert s() < peak
        s.step(8000)
        assert s() < peak

    def test_piecewise(self):
        s = opt.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
        vals = []
        for e in [0, 2, 3, 5, 6, 10]:
            s.step(e)
            vals.append(s())
        np.testing.assert_allclose(
            vals, [0.1, 0.1, 0.01, 0.01, 0.001, 0.001])

    def test_reduce_on_plateau(self):
        s = opt.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)  # 2 bad epochs > patience -> reduce
        assert s() == pytest.approx(0.5)

    def test_scheduler_with_optimizer(self):
        p = make_param([1.0])
        sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        o = opt.SGD(learning_rate=sched, parameters=[p])
        assert o.get_lr() == pytest.approx(0.1)
        sched.step()
        assert o.get_lr() == pytest.approx(0.01)

    def test_one_cycle(self):
        s = opt.lr.OneCycleLR(max_learning_rate=1.0, total_steps=100)
        s.step(30)
        assert s() == pytest.approx(1.0, abs=1e-6)
        s.step(100)
        assert s() == pytest.approx(0.0001, abs=1e-3)
