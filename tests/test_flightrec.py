"""Flight recorder + stall watchdog + perf doctor (ISSUE 14).

Done criteria exercised here:
- a subprocess killed mid-train (SIGTERM fault) and a NAN-rollback run
  both leave a VALID flight-recorder bundle whose Chrome trace
  validates;
- the ring is bounded: memory does not grow with step count;
- a deterministically injected stall (PADDLE_FAULT_HANG) is detected
  by the watchdog within the configured window and the bundle carries
  all-thread stacks;
- the perf doctor emits the expected knob verdict on synthetic
  comm-bound / host-sync-bound / data-starved fixtures, stays silent
  on a clean one, and its field rides trainer/engine stats and the
  loadgen reports;
- straggler detection flags tick-time skew vs the fleet median.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import observability as obs
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import doctor, flightrec, watchdog
from paddle_tpu.observability.flightrec import (FlightRecorder,
                                                find_bundles,
                                                load_bundle)
from paddle_tpu.observability.watchdog import Watchdog, detect_stragglers
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    # the per-process dump cap is shared with every other test file
    # (in-process SIGTERM tests dump too); these tests assert on dumps,
    # so they start from a clean budget
    flightrec.recorder().dumps = 0
    yield
    faults.reset()


def _linear_trainer(seed=0, **kw):
    paddle.seed(seed)
    m = nn.Linear(6, 3)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    return SpmdTrainer(m, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": 1}), **kw)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(4, 6).astype(np.float32),
            rng.randn(4, 3).astype(np.float32))


def tiny_model(seed=0):
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(seed)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# ---------------------------------------------------------------------------
# ring + bundle mechanics
# ---------------------------------------------------------------------------
def test_ring_is_bounded_memory_does_not_grow_with_steps():
    rec = FlightRecorder(ring=32, events=8)
    for i in range(10_000):
        rec.record("step", dur_ms=1.0, step=i)
        if i % 100 == 0:
            rec.note_event("mark", i=i)
    assert len(rec.ring) == 32
    assert len(rec.events) == 8
    # the ring holds the TAIL (the last steps before death)
    assert rec.ring[-1]["step"] == 9_999
    assert rec.ring[0]["step"] == 9_968


def test_dump_is_atomic_and_loads_back(tmp_path):
    rec = FlightRecorder(ring=16)
    for i in range(20):
        rec.record("tick", dur_ms=0.5, tick=i)
    rec.note_event("checkpoint_save", path="/x")
    path = rec.dump("unittest", directory=str(tmp_path))
    assert path is not None and os.path.isdir(path)
    # no .tmp staging orphan survives the rename
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []
    doc = load_bundle(path)
    assert doc["bundle"]["reason"] == "unittest"
    assert len(doc["bundle"]["ring"]) == 16
    assert any(e["kind"] == "checkpoint_save"
               for e in doc["bundle"]["events"])
    # every live thread left a stack in the bundle
    assert doc["bundle"]["stacks"]
    # the chrome trace validates and carries the ring-synthesized spans
    n = obs.validate_chrome_trace(doc["trace"])
    assert n > 0
    names = {e["name"] for e in doc["trace"]["traceEvents"]}
    assert "tick" in names
    assert find_bundles(str(tmp_path)) == [path]


def test_dump_cap_bounds_bundle_count(tmp_path):
    rec = FlightRecorder(ring=4)
    paths = [rec.dump("spam", directory=str(tmp_path))
             for _ in range(flightrec._MAX_DUMPS + 5)]
    written = [p for p in paths if p]
    assert len(written) == flightrec._MAX_DUMPS


def test_disabled_recorder_records_and_dumps_nothing(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC", "0")
    before = len(flightrec.recorder().ring)
    flightrec.record("tick", tick=1)
    assert len(flightrec.recorder().ring) == before
    assert flightrec.dump("off", directory=str(tmp_path)) is None
    assert os.listdir(tmp_path) == []


def test_trainer_and_engine_feed_the_ring():
    rec = flightrec.recorder()
    tr = _linear_trainer()
    x, y = _batch()
    for _ in range(3):
        tr.train_step(x, y)
    kinds = [e["kind"] for e in rec.ring]
    assert kinds.count("train_step") >= 3
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(0)
    eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                    max_new_tokens=4)
    eng.run()
    kinds = [e["kind"] for e in rec.ring]
    assert "decode_tick" in kinds


class _BombNet(nn.Layer):
    """Loss explodes when an input row carries the sentinel value — a
    DATA-keyed anomaly (rollback rewinds the step counter, so a
    step-keyed injection would re-arm forever; same construction as
    test_resilience)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        out = self.fc(x)
        mask = (x > 900.0).astype("float32").max()
        return out * (1.0 + mask * 3.0e38)


def test_rollback_leaves_a_bundle(tmp_path, monkeypatch):
    """anomaly_policy='rollback' on a poisoned batch: the rollback dump
    trigger fires IN-PROCESS with the pre-rewind state."""
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_DIR", str(tmp_path))
    paddle.seed(13)
    model = _BombNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    tr = SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                     mesh=create_mesh({"dp": 1}),
                     anomaly_policy="rollback")
    rng = np.random.RandomState(9)
    bomb = np.full((4, 4), 1000.0, np.float32)
    for i in range(3):
        x = bomb if i == 1 else rng.randn(4, 4).astype(np.float32)
        tr.train_step(x, rng.randn(4, 2).astype(np.float32))
    assert tr.stats["rollback_steps"] == 1
    bundles = find_bundles(str(tmp_path), reason="rollback")
    assert len(bundles) == 1
    doc = load_bundle(bundles[0])
    assert any(e["kind"] == "anomaly_rollback"
               for e in doc["bundle"]["events"])
    obs.validate_chrome_trace(doc["trace"])


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_detects_injected_train_stall(tmp_path, monkeypatch):
    """PADDLE_FAULT_HANG stalls the train thread; the watchdog fires
    within the configured window and the bundle carries every thread's
    stack (the stalled one shows the injected sleep)."""
    monkeypatch.setenv("PADDLE_TPU_WATCHDOG_S", "0.25")
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_FAULT_HANG", "2:1.2")
    tr = _linear_trainer()
    x, y = _batch()
    for _ in range(3):
        tr.train_step(x, y)
    wd = tr.watchdog
    assert wd is not None
    try:
        # >= 1: a slow first-step compile on a loaded CI host may trip
        # the 0.25s window once on its own; the LAST stall is the hang
        assert wd.stalls >= 1
        assert wd.last_stall["label"] == "spmd_train"
        # detection happened within ~1.25x the window, i.e. DURING the
        # 1.2s hang, not after it (age at detection < hang length)
        assert wd.last_stall["age_s"] < 1.2
        stacks = "".join(s for frames in wd.last_stall["stacks"].values()
                         for s in frames)
        assert "maybe_hang" in stacks
        bundles = find_bundles(str(tmp_path), reason="stall")
        assert bundles
        doc = load_bundle(bundles[-1])
        assert doc["bundle"]["stall"]["label"] == "spmd_train"
        assert doc["bundle"]["stacks"]
        obs.validate_chrome_trace(doc["trace"])
    finally:
        wd.disarm()


def test_watchdog_detects_decode_tick_stall(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_WATCHDOG_S", "0.25")
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_FAULT_HANG", "3:1.0")
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(0)
    eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                    max_new_tokens=8)
    eng.run()
    wd = eng.watchdog
    assert wd is not None
    try:
        assert wd.stalls >= 1
        assert find_bundles(str(tmp_path), reason="stall")
    finally:
        wd.disarm()


def test_watchdog_idle_engine_is_not_a_stall(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_WATCHDOG_S", "0.4")
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rng = np.random.RandomState(0)
    eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                    max_new_tokens=4)
    eng.run()
    wd = eng.watchdog
    assert wd is not None
    try:
        # the run's last tick left the engine empty -> watchdog parked:
        # sitting idle for > timeout must NOT count as a stall
        time.sleep(1.0)
        assert wd.stalls == 0
        # traffic re-arms it
        eng.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                        max_new_tokens=2)
        eng.run()
        assert wd.stalls == 0
    finally:
        wd.disarm()


def test_watchdog_parked_by_save_and_beaten_by_eval(tmp_path,
                                                    monkeypatch):
    """A finished training loop must not read as a stall: the final
    checkpoint save parks the trainer's watchdog, and eval steps
    heartbeat it — a train -> save -> (slow tail) sequence stays
    clean."""
    monkeypatch.setenv("PADDLE_TPU_WATCHDOG_S", "0.3")
    tr = _linear_trainer()
    x, y = _batch()
    for _ in range(2):
        tr.train_step(x, y)
    wd = tr.watchdog
    assert wd is not None
    try:
        tr.save(str(tmp_path / "ck"))      # snapshot parks the watchdog
        time.sleep(0.8)                    # post-training tail > window
        assert wd.stalls == 0
        tr.eval_step(x)                    # eval heartbeats, no false arm
        time.sleep(0.1)
        assert wd.stalls == 0
    finally:
        wd.disarm()


def test_watchdog_custom_callback_and_rearm():
    fired = []
    wd = Watchdog(0.1, label="t", on_stall=fired.append,
                  poll_s=0.02).arm()
    try:
        wd.beat()
        time.sleep(0.3)
        assert len(fired) == 1          # once per episode, not per poll
        assert fired[0]["label"] == "t"
        wd.beat()                       # new episode
        time.sleep(0.3)
        assert len(fired) == 2
    finally:
        wd.disarm()


def test_watchdog_validates_args():
    with pytest.raises(ValueError):
        Watchdog(0)
    with pytest.raises(ValueError):
        Watchdog(1.0, on_stall="explode")
    assert watchdog.watchdog_seconds() is None


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------
def test_detect_stragglers_flags_skew_vs_median():
    v = detect_stragglers([10.0, 11.0, 10.5, 40.0], factor=1.75)
    assert v["stragglers"] == [3]
    assert v["median_ms"] == pytest.approx(10.75)
    assert v["ratio"][3] == pytest.approx(40.0 / 10.75, abs=1e-3)
    # healthy fleet: nobody flagged
    assert detect_stragglers([10.0, 11.0, 12.0])["stragglers"] == []
    # None (no ticks) replicas are skipped, indexes stay aligned;
    # flagging is leave-one-out, so a 2-valid-replica fleet still
    # catches its slow member (overall median would hide it)
    v = detect_stragglers([None, 10.0, 50.0])
    assert v["stragglers"] == [2] and v["per_replica_ms"][0] is None
    # empty / all-None / single-replica input: empty verdict, no crash
    assert detect_stragglers([])["stragglers"] == []
    assert detect_stragglers([None, None])["median_ms"] is None
    assert detect_stragglers([99.0])["stragglers"] == []


def test_fleet_aggregator_surfaces_stragglers():
    class _R:
        def __init__(self, ms):
            self.request_stats = {}
            self._queue = []
            self.num_active = 0
            self._request_stats_cap = 16
            self._timings = {"decode_ms": ms * 10, "decode_steps": 10}

    agg = obs.FleetAggregator([_R(10.0), _R(11.0), _R(60.0)])
    out = agg.scrape()
    assert out["straggler"]["stragglers"] == [2]
    assert agg.stragglers()["stragglers"] == [2]
    snap = obs.metrics.snapshot()
    series = {s["labels"]["replica"]: s["value"]
              for s in snap["fleet_tick_ms"]["series"]}
    assert series["2"] == pytest.approx(60.0)
    # a replica with a PARTIAL timing surface (decode_steps but no
    # decode_ms) reads as None, not a KeyError out of scrape()
    broken = _R(10.0)
    del broken._timings["decode_ms"]
    agg2 = obs.FleetAggregator([broken, _R(12.0)])
    assert agg2.scrape()["straggler"]["per_replica_ms"][0] is None


# ---------------------------------------------------------------------------
# perf doctor
# ---------------------------------------------------------------------------
def test_doctor_comm_bound_fixture():
    v = doctor.diagnose(
        {"comm_fraction": 0.41,
         "comm_by_op": {"all-reduce": {"count": 4, "bytes": 1 << 20},
                        "all-gather": {"count": 2, "bytes": 1 << 10}}},
        kind="train")
    assert v and v[0]["bottleneck"] == "comm-bound"
    assert v[0]["evidence"]["comm_fraction"] == 0.41
    assert v[0]["evidence"]["top_op"] == "all-reduce"
    assert "PADDLE_TPU_OVERLAP" in v[0]["knob"]
    assert "a2a_chunks" in v[0]["knob"]


def test_doctor_host_sync_bound_fixture():
    v = doctor.diagnose({"host_syncs_measured": 20, "steps": 10},
                        kind="train")
    assert v and v[0]["bottleneck"] == "host-sync-bound"
    assert v[0]["evidence"]["syncs_per_step"] == 2.0
    assert "lazy" in v[0]["knob"]


def test_doctor_data_starved_fixture():
    v = doctor.diagnose({"data_wait_ms": 600.0, "dispatch_ms": 400.0},
                        kind="train")
    assert v and v[0]["bottleneck"] == "data-starved"
    assert "PADDLE_TPU_PREFETCH_DEPTH" in v[0]["knob"]


def test_doctor_clean_run_yields_no_verdict():
    assert doctor.diagnose(
        {"comm_fraction": 0.03, "data_wait_ms": 5.0,
         "dispatch_ms": 5000.0, "sync_ms": 2.0,
         "host_syncs_measured": 1, "steps": 20,
         "h2d_ms": 10.0}, kind="train") == []


def test_doctor_ranks_multiple_verdicts_by_score():
    v = doctor.diagnose(
        {"comm_fraction": 0.3, "data_wait_ms": 900.0,
         "dispatch_ms": 100.0}, kind="train")
    assert [x["bottleneck"] for x in v] == ["data-starved", "comm-bound"]
    assert v[0]["score"] >= v[1]["score"]


def test_doctor_serve_rules_kv_pressure_and_spec():
    v = doctor.diagnose(
        {"block_occupancy": 0.95, "preemptions": 7,
         "spec_acceptance_rate": 0.1, "prefix_hit_rate": 0.02,
         "prefix_queries": 100}, kind="serve")
    names = [x["bottleneck"] for x in v]
    assert "kv-pressure" in names
    assert "low-spec-acceptance" in names
    assert "prefix-cold" in names
    kv = v[names.index("kv-pressure")]
    assert "PADDLE_TPU_KV_BLOCKS" in kv["knob"]


def test_doctor_tolerates_garbage_and_missing_keys():
    assert doctor.diagnose({}) == []
    assert doctor.diagnose({"comm_fraction": None,
                            "data_wait_ms": "nan?"}) == []


def test_doctor_field_rides_trainer_and_engine_stats():
    tr = _linear_trainer()
    x, y = _batch()
    tr.train_step(x, y)
    assert isinstance(tr.stats["doctor"], list)
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    assert isinstance(eng.stats["doctor"], list)
    # JSON-safe: the stats consumer (bench row persist) dumps it
    json.dumps(tr.stats["doctor"])
    json.dumps(eng.stats["doctor"])


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_doctor_and_straggler_in_loadgen_reports():
    from paddle_tpu.inference.loadgen import (MultiTenantWorkload,
                                              SharedPrefixWorkload,
                                              run_fleet_loadtest,
                                              run_loadtest)
    from paddle_tpu.inference.router import Router
    m = tiny_model()
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16])
    eng.warmup(buckets=[16])
    wl = SharedPrefixWorkload(97, seed=0, prefix_len=4, tail_len=(2, 4),
                              max_new=(2, 3))
    rep = run_loadtest(eng, num_requests=4, rate_rps=200.0, workload=wl)
    assert isinstance(rep["doctor"], list)
    # fleet twin
    reps = []
    for _ in range(2):
        e = InferenceEngine(m, batch_slots=2, prefill_buckets=[16],
                            kv_layout="paged", kv_block_size=8)
        e.warmup(buckets=[16])
        reps.append(e)
    router = Router(reps, policy="round_robin")
    wl2 = MultiTenantWorkload(97, seed=0, num_tenants=2, prefix_len=4,
                              tail_len=(2, 4), max_new=(2, 3))
    frep = run_fleet_loadtest(router, num_requests=6, rate_rps=200.0,
                              workload=wl2)
    assert isinstance(frep["doctor"], list)
    assert "stragglers" in frep["straggler"]
    assert len(frep["straggler"]["per_replica_ms"]) == 2
    json.dumps(frep["doctor"])
    json.dumps(frep["straggler"])


# ---------------------------------------------------------------------------
# subprocess kill-and-dump e2e (the tentpole's black-box acceptance)
# ---------------------------------------------------------------------------
_SUBPROC = """
import sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import SpmdTrainer, create_mesh, \
    PreemptionGuard

mode = sys.argv[1]


class BombNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        out = self.fc(x)
        mask = (x > 900.0).astype("float32").max()
        return out * (1.0 + mask * 3.0e38)


paddle.seed(7)
model = BombNet()
opt = paddle.optimizer.Adam(learning_rate=1e-2,
                            parameters=model.parameters())
tr = SpmdTrainer(
    model, opt, lambda o, y: F.mse_loss(o, y),
    mesh=create_mesh({"dp": 1}),
    anomaly_policy="rollback" if mode == "rollback" else "raise")
rng = np.random.RandomState(0)
bomb = np.full((4, 4), 1000.0, np.float32)
with PreemptionGuard() as g:
    for i in range(6):
        x = bomb if (mode == "rollback" and i == 2) \\
            else rng.randn(4, 4).astype(np.float32)
        tr.train_step(x, rng.randn(4, 2).astype(np.float32))
        if g.preempted:
            print("PREEMPTED", tr._step_count, flush=True)
            sys.exit(0)
print("DONE", tr._step_count, "ROLLBACKS",
      tr.stats["rollback_steps"] if mode == "rollback" else 0,
      flush=True)
"""


def _run_child(tmp_path, mode, extra_env):
    script = tmp_path / "child.py"
    script.write_text(_SUBPROC)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_FLIGHTREC_DIR"] = str(tmp_path / "black_box")
    for k in ("PADDLE_FAULT_NAN_STEP", "PADDLE_FAULT_SIGTERM_STEP",
              "PADDLE_FAULT_HANG", "PADDLE_TPU_WATCHDOG_S"):
        env.pop(k, None)
    env.update(extra_env)
    p = subprocess.run([sys.executable, str(script), mode], env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stderr
    return p, str(tmp_path / "black_box")


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_subprocess_sigterm_leaves_valid_bundle(tmp_path):
    """A trainer killed mid-run by the fault harness's SIGTERM leaves
    an explainable black box: valid bundle JSON, validating Chrome
    trace, the preemption event, and the last steps in the ring."""
    p, bb = _run_child(tmp_path, "sigterm",
                       {"PADDLE_FAULT_SIGTERM_STEP": "3"})
    assert "PREEMPTED 3" in p.stdout
    bundles = find_bundles(bb, reason="sigterm")
    assert len(bundles) == 1, os.listdir(bb)
    doc = load_bundle(bundles[0])
    assert doc["bundle"]["reason"] == "sigterm"
    assert any(e["kind"] == "preemption" for e in doc["bundle"]["events"])
    # the dump runs INSIDE the signal handler, mid-step-3: the ring
    # holds the completed steps (1, 2) — the in-flight one records only
    # at its end, after the handler returned
    steps = [e["step"] for e in doc["bundle"]["ring"]
             if e["kind"] == "train_step"]
    assert steps and steps[-1] == 2
    assert obs.validate_chrome_trace(doc["trace"]) > 0
    # no half-written staging dirs
    assert [n for n in os.listdir(bb) if n.endswith(".tmp")] == []


def test_subprocess_nan_rollback_leaves_valid_bundle(tmp_path):
    p, bb = _run_child(tmp_path, "rollback", {})
    assert "DONE" in p.stdout and "ROLLBACKS 1" in p.stdout
    bundles = find_bundles(bb, reason="rollback")
    assert len(bundles) == 1, os.listdir(bb)
    doc = load_bundle(bundles[0])
    ev = [e for e in doc["bundle"]["events"]
          if e["kind"] == "anomaly_rollback"]
    assert ev and ev[0]["step"] == 3
    assert obs.validate_chrome_trace(doc["trace"]) > 0
