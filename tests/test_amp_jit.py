"""AMP + jit.to_static tests (reference unittests test_amp_*.py,
dygraph_to_static/ suite)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.amp as amp
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.static import InputSpec


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


class TestAutoCast:
    def test_white_op_casts(self):
        lin = nn.Linear(4, 4)
        x = t(np.random.randn(2, 4))
        with amp.auto_cast():
            y = lin(x)
        assert str(y.dtype) == "bfloat16"
        y2 = lin(x)
        assert str(y2.dtype) == "float32"

    def test_black_op_stays_fp32(self):
        x = t(np.random.randn(2, 4))
        with amp.auto_cast():
            h = F.relu(x)  # not in either list: passthrough fp32
            s = F.softmax(h)
        assert str(s.dtype) == "float32"

    def test_custom_lists(self):
        x = t(np.random.randn(2, 4))
        with amp.auto_cast(custom_black_list={"matmul"}):
            y = paddle.matmul(x, x.T)
        assert str(y.dtype) == "float32"

    def test_o2_casts_everything(self):
        x = t(np.random.randn(2, 4))
        with amp.auto_cast(level="O2"):
            y = x + 1.0
        assert str(y.dtype) == "bfloat16"

    def test_fp16_dtype(self):
        lin = nn.Linear(4, 4)
        x = t(np.random.randn(2, 4))
        with amp.auto_cast(dtype="float16"):
            y = lin(x)
        assert str(y.dtype) == "float16"

    def test_grads_flow_through_amp(self):
        lin = nn.Linear(4, 1)
        x = t(np.random.randn(8, 4))
        with amp.auto_cast():
            loss = lin(x).sum()
        loss.backward()
        assert lin.weight.grad is not None
        assert str(lin.weight.grad.dtype) == "float32"  # param grad fp32


class TestGradScaler:
    def test_scale_and_unscale(self):
        p = paddle.Parameter(np.ones(2, np.float32))
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        o = opt.SGD(0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=4.0)
        x = t([1.0, 2.0])
        loss = (paddle.multiply(p, x)).sum()
        scaler.scale(loss).backward()
        # raw grad is scaled by 4
        np.testing.assert_allclose(p.grad.numpy(), [4.0, 8.0])
        scaler.step(o)
        scaler.update()
        # after unscale, sgd applied true grad [1,2]
        np.testing.assert_allclose(p.numpy(), [1 - 0.1, 1 - 0.2],
                                   rtol=1e-6)

    def test_inf_skips_step_and_decays_scale(self):
        p = paddle.Parameter(np.ones(1, np.float32))
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        o = opt.SGD(0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=1024,
                                decr_every_n_nan_or_inf=1)
        p.grad = paddle.to_tensor(np.array([np.inf], np.float32))
        scaler.step(o)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
        assert scaler.get_loss_scaling() == 512.0

    def test_dynamic_growth(self):
        scaler = amp.GradScaler(init_loss_scaling=8.0,
                                incr_every_n_steps=2)
        p = paddle.Parameter(np.ones(1, np.float32))
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        o = opt.SGD(0.0, parameters=[p])
        for _ in range(2):
            p.grad = paddle.to_tensor(np.array([8.0], np.float32))
            scaler.step(o)
            scaler.update()
        assert scaler.get_loss_scaling() == 16.0


class TestToStatic:
    def test_matches_eager_and_trains(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        paddle.seed(1)
        net = Net()
        static_net = paddle.jit.to_static(net)
        x = t(np.random.randn(4, 4))
        net.eval()
        np.testing.assert_allclose(static_net(x).numpy(), net(x).numpy(),
                                   rtol=1e-5, atol=1e-6)
        # gradients flow through compiled call
        net.train()
        o = opt.SGD(0.5, parameters=net.parameters())
        y = paddle.to_tensor(np.array([0, 1, 0, 1]))
        losses = []
        for _ in range(20):
            loss = F.cross_entropy(static_net(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5

    def test_buffer_writeback(self):
        bn_net = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4, momentum=0.0,
                                                               data_format="NC"))
        static = paddle.jit.to_static(bn_net)
        x = t(np.random.randn(16, 4) * 3 + 5)
        static(x)
        # running stats updated through the compiled call
        assert abs(float(bn_net[1]._mean.numpy().mean())) > 0.01

    def test_dropout_fresh_randomness(self):
        net = nn.Sequential(nn.Dropout(0.5))
        static = paddle.jit.to_static(net)
        net.train()
        x = t(np.ones((100,)))
        y1 = static(x).numpy()
        y2 = static(x).numpy()
        assert not np.allclose(y1, y2)

    def test_plain_function(self):
        @paddle.jit.to_static
        def f(a, b):
            return paddle.matmul(a, b) + 1.0

        a = t(np.random.randn(3, 4))
        b = t(np.random.randn(4, 2))
        np.testing.assert_allclose(
            f(a, b).numpy(), np.asarray(a.numpy() @ b.numpy() + 1.0),
            rtol=1e-5)


class TestJitSaveLoad:
    def test_roundtrip(self, tmp_path):
        from paddle_tpu.vision.models import LeNet
        paddle.seed(3)
        net = LeNet()
        net.eval()
        x = t(np.random.randn(2, 1, 28, 28))
        ref = net(x).numpy()
        path = str(tmp_path / "export" / "model")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 1, 28, 28])])
        loaded = paddle.jit.load(path)
        got = loaded(x).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
