"""2-rank payload driven by paddle_tpu.distributed.launch (the
reference's dist_mnist.py-style separate-script pattern,
test_dist_base.py:668). Each rank computes a gradient on its own data,
allreduces it through the eager DataParallel path, and prints the
result for the parent test to compare."""
import jax

# host-CPU backend: two processes must not both grab the TPU, and the
# env var alone loses to an installed TPU plugin
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu.distributed import DataParallel, env  # noqa: E402


def main():
    env.init_parallel_env()
    rank, world = env.get_rank(), env.get_world_size()
    assert world == 2, f"expected 2 ranks, got {world}"
    assert jax.process_count() == 2, "jax.distributed did not initialize"

    paddle.seed(0)                      # identical init on every rank
    model = nn.Linear(4, 2, bias_attr=False)
    dp = DataParallel(model)

    rng = np.random.RandomState(rank)   # different data per rank
    x = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
    loss = dp(x).sum()
    loss.backward()
    dp.apply_collective_grads()
    g = np.asarray(model.weight.grad.data)
    print(f"GRADSUM {rank} {float(g.sum()):.6f}", flush=True)


if __name__ == "__main__":
    main()
