"""Regression tests for review findings + extra op coverage."""
import jax
import numpy as np

import paddle_tpu as paddle


class TestPytreeStability:
    def test_same_shape_tensors_share_treedef(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([3.0, 4.0])
        assert (jax.tree_util.tree_structure(a)
                == jax.tree_util.tree_structure(b))
        out = jax.tree.map(lambda x, y: x + y, a, b)
        np.testing.assert_allclose(np.asarray(out.data), [4.0, 6.0])

    def test_jit_no_retrace(self):
        traces = []

        @jax.jit
        def f(t):
            traces.append(1)
            return t.data * 2

        f(paddle.to_tensor([1.0]))
        f(paddle.to_tensor([2.0]))
        f(paddle.to_tensor([3.0]))
        assert len(traces) == 1


class TestFixedOps:
    def test_mode(self):
        vals, idx = paddle.mode(paddle.to_tensor(
            np.array([[1.0, 1.0, 2.0], [3.0, 4.0, 4.0]])), axis=1)
        np.testing.assert_allclose(vals.numpy(), [1.0, 4.0])
        np.testing.assert_array_equal(idx.numpy(), [1, 2])

    def test_pad(self):
        x = paddle.ones([1, 1, 2, 2])
        out = paddle.pad(x, [1, 1, 0, 0])  # pad W by 1 each side (NCHW)
        assert out.shape == [1, 1, 2, 4]
        out = paddle.pad(paddle.ones([2, 2]), [0, 1, 1, 0], value=5.0)
        assert out.shape == [3, 3]
        assert out.numpy()[0, 0] == 5.0

    def test_masked_select_grad(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32),
                             stop_gradient=False)
        mask = paddle.to_tensor(np.array([True, False, True, False]))
        paddle.masked_select(x, mask).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0, 0.0])

    def test_cummax_cummin(self):
        x = paddle.to_tensor(np.array([[1.0, 3.0, 2.0], [4.0, 0.0, 5.0]]))
        vals, idx = paddle.cummax(x, axis=1)
        np.testing.assert_allclose(vals.numpy(), [[1, 3, 3], [4, 4, 5]])
        np.testing.assert_array_equal(idx.numpy(), [[0, 1, 1], [0, 0, 2]])
        vals, idx = paddle.cummin(x, axis=1)
        np.testing.assert_allclose(vals.numpy(), [[1, 1, 1], [4, 0, 0]])

    def test_multinomial_batched(self):
        probs = paddle.to_tensor(np.eye(4, dtype=np.float32) + 1e-9)
        out = paddle.multinomial(probs, 2, replacement=True)
        assert out.shape == [4, 2]
        np.testing.assert_array_equal(out.numpy()[:, 0], [0, 1, 2, 3])

    def test_householder_product_batched(self):
        a = np.random.rand(2, 4, 3).astype(np.float32)
        tau = np.random.rand(2, 3).astype(np.float32)
        out = paddle.linalg.householder_product(
            paddle.to_tensor(a), paddle.to_tensor(tau))
        assert out.shape == [2, 4, 3]

    def test_shard_index(self):
        x = paddle.to_tensor(np.array([1, 5, 9, 3]))
        out = paddle.shard_index(x, index_num=10, nshards=2, shard_id=0)
        np.testing.assert_array_equal(out.numpy(), [1, -1, -1, 3])
        out = paddle.shard_index(x, index_num=10, nshards=2, shard_id=1)
        np.testing.assert_array_equal(out.numpy(), [-1, 0, 4, -1])


class TestReviewRegressionsRound1b:
    def test_single_element_tuple_backward(self):
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.arange(4, dtype=np.float32),
                             stop_gradient=False)
        y = paddle.split(x, 1)[0]
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(4))

    def test_bool_flag_string_false(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": "false"})
        assert paddle.get_flags("FLAGS_check_nan_inf")[
            "FLAGS_check_nan_inf"] is False
        paddle.set_flags({"FLAGS_check_nan_inf": "true"})
        assert paddle.get_flags("FLAGS_check_nan_inf")[
            "FLAGS_check_nan_inf"] is True
        paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_slice_clamps(self):
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        y = paddle.slice(x, axes=[1], starts=[-10], ends=[2])
        np.testing.assert_allclose(y.numpy(), x.numpy()[:, 0:2])

    def test_stable_descending_argsort(self):
        import paddle_tpu as paddle
        idx = paddle.argsort(
            paddle.to_tensor(np.array([1.0, 1.0, 2.0], np.float32)),
            descending=True, stable=True)
        np.testing.assert_array_equal(idx.numpy(), [2, 0, 1])

    def test_no_helper_pollution(self):
        from paddle_tpu.core.tensor import Tensor
        for bad in ("apply", "convert_dtype", "next_key",
                    "default_float_dtype"):
            assert not hasattr(Tensor, bad), bad

    def test_place_hashable(self):
        import paddle_tpu as paddle
        d = {paddle.CPUPlace(): 1, paddle.TPUPlace(0): 2}
        assert d[paddle.CPUPlace()] == 1


def test_logcumsumexp_trapezoid_renorm():
    """Round-5 math stragglers (logcumsumexp_op, trapezoid, renorm_op)."""
    import numpy as np
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    out = paddle.logcumsumexp(x)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.log(np.cumsum(np.exp([1, 2, 3]))),
                               rtol=1e-5)
    out.sum().backward()
    assert np.isfinite(np.asarray(x.grad.data)).all()

    y = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    assert float(paddle.trapezoid(y)) == 4.0
    xs = paddle.to_tensor(np.array([0.0, 2.0, 4.0], np.float32))
    assert float(paddle.trapezoid(y, x=xs)) == 8.0

    m = paddle.to_tensor(np.eye(2, dtype=np.float32) * 3)
    r = np.asarray(paddle.renorm(m, 2.0, 0, 1.0).data)
    np.testing.assert_allclose(np.linalg.norm(r, axis=1), [1.0, 1.0],
                               rtol=1e-5)
    # slices under the cap are untouched
    small = paddle.to_tensor(np.eye(2, dtype=np.float32) * 0.5)
    np.testing.assert_allclose(
        np.asarray(paddle.renorm(small, 2.0, 0, 1.0).data),
        np.asarray(small.data))
