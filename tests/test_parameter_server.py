"""Minimal host-side parameter server.

Reference: paddle/fluid/distributed/service/brpc_ps_server.h (server),
ps_client.h (client), table/common_dense_table.h + common_sparse_table.cc
(tables + per-table optimizer rules), and the a_sync training mode
(AsyncCommunicator): trainers push grads / pull params with no
cross-trainer synchronization on the hot path.
"""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (DenseTable, PSClient, PSServer,
                                       SparseTable)


def start_servers(n=2, n_workers=1):
    servers = [PSServer("127.0.0.1:0", n_workers=n_workers) for _ in
               range(n)]
    eps = []
    for s in servers:
        s.start()
        eps.append(f"127.0.0.1:{s.port}")
    return servers, eps


def test_dense_table_rules():
    t = DenseTable((2, 3), rule="sgd", init=np.ones((2, 3)))
    t.push(np.full((2, 3), 0.5), lr=0.1)
    np.testing.assert_allclose(t.pull(), 0.95)
    a = DenseTable((4,), rule="adagrad", init=np.zeros(4))
    a.push(np.ones(4), lr=1.0)
    # adagrad first step: -lr * g / (sqrt(g^2) + eps) ~ -1
    np.testing.assert_allclose(a.pull(), -1.0, atol=1e-4)


def test_sparse_table_lazy_rows_and_merge():
    t = SparseTable(dim=4, rule="sgd", init_scale=0.0)
    rows = t.pull([5, 9])
    np.testing.assert_array_equal(rows, np.zeros((2, 4)))
    # duplicate ids in one push aggregate before the rule applies
    t.push([5, 5], np.ones((2, 4)), lr=0.1)
    np.testing.assert_allclose(t.pull([5])[0], -0.2, atol=1e-6)
    assert t.size() == 2


def test_client_server_dense_and_sparse_roundtrip():
    servers, eps = start_servers(2)
    try:
        cli = PSClient(eps)
        cli.ensure_dense_table("w", (3, 2), rule="sgd",
                               init=np.zeros((3, 2)))
        cli.push_dense("w", np.ones((3, 2)), lr=0.5)
        np.testing.assert_allclose(cli.pull_dense("w"), -0.5)

        cli.ensure_sparse_table("emb", dim=3, rule="sgd", init_scale=0.0)
        ids = np.array([0, 1, 2, 3, 7, 8], np.int64)  # spans both shards
        np.testing.assert_array_equal(cli.pull_sparse("emb", ids),
                                      np.zeros((6, 3)))
        g = np.arange(18, dtype=np.float32).reshape(6, 3)
        cli.push_sparse("emb", ids, g, lr=1.0)
        np.testing.assert_allclose(cli.pull_sparse("emb", ids), -g)
        # rows landed on the right shards: total row count adds up
        assert cli.sparse_table_size("emb") == 6
        # empty pull keeps the row width (0, dim), not (0, 0)
        assert cli.pull_sparse("emb", np.empty(0, np.int64)).shape == (0, 3)
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_server_error_propagates_to_client():
    servers, eps = start_servers(1)
    try:
        cli = PSClient(eps)
        with pytest.raises(RuntimeError, match="KeyError"):
            cli.pull_dense("never_created")
        cli.close()
    finally:
        for s in servers:
            s.stop()


def test_two_async_trainers_converge():
    """The a_sync workload: two trainer threads fit a shared linear
    model (dense weights + sparse embedding) against their own data
    with NO synchronization between them — the PS serializes updates
    per table and the average loss must fall."""
    servers, eps = start_servers(2, n_workers=2)
    losses = {0: [], 1: []}
    try:
        boot = PSClient(eps)
        rng0 = np.random.RandomState(42)
        w_true = rng0.randn(4, 1).astype(np.float32)
        emb_true = rng0.randn(10, 4).astype(np.float32)
        # nonzero init: an all-zero bilinear model sits on a saddle
        # where both gradients vanish
        boot.ensure_dense_table("w", (4, 1), rule="sgd",
                                init=rng0.randn(4, 1) * 0.5)
        boot.ensure_sparse_table("emb", dim=4, rule="adagrad",
                                 init_scale=0.1)
        boot.close()

        def trainer(rank):
            cli = PSClient(eps)
            rng = np.random.RandomState(rank)
            for step in range(150):
                ids = rng.randint(0, 10, (16,)).astype(np.int64)
                x = emb_true[ids]                 # features via lookup
                y = x @ w_true
                # forward with the CURRENT server params
                w = cli.pull_dense("w")
                e = cli.pull_sparse("emb", ids)
                pred = e @ w
                err = pred - y                    # [16, 1]
                losses[rank].append(float((err ** 2).mean()))
                # backward: dL/dw = e^T err / n; dL/de = err w^T / n
                n = len(ids)
                cli.push_dense("w", e.T @ err / n, lr=0.05)
                cli.push_sparse("emb", ids, err @ w.T / n, lr=0.3)
            cli.barrier()
            cli.close()

        ts = [threading.Thread(target=trainer, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
            assert not t.is_alive(), "trainer hung"
        for rank in (0, 1):
            first = np.mean(losses[rank][:10])
            last = np.mean(losses[rank][-10:])
            assert last < first * 0.5, \
                f"rank {rank}: {first:.4f} -> {last:.4f}"
    finally:
        for s in servers:
            s.stop()


def test_fleet_init_server_from_env(monkeypatch):
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed.fleet import role_maker as rm_mod

    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:0")
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PORT", "0")
    monkeypatch.setenv("POD_IP", "127.0.0.1")
    # fresh role maker picking up the env
    fleet.base._role_maker = rm_mod.PaddleCloudRoleMaker()
    srv = fleet.init_server()
    try:
        srv.start()
        cli = PSClient([f"127.0.0.1:{srv.port}"])
        cli.ensure_dense_table("t", (2,), init=np.zeros(2))
        np.testing.assert_array_equal(cli.pull_dense("t"), np.zeros(2))
        cli.close()
    finally:
        srv.stop()
        fleet.base._role_maker = None
        fleet.base._ps_server = None
