"""Launcher + spawn integration: REAL 2-process runs on localhost
(reference test_dist_base.py:668 / test_launch.sh strategy — no fake
backend; the JAX coordinator rendezvous runs for real)."""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The baked-in jaxlib 0.4.x CPU backend cannot run multiprocess
# collectives at all — both 2-process tests die in the child with
# "XlaRuntimeError: Multiprocess computations aren't implemented on the
# CPU backend" (verified identical on the untouched seed tree), burning
# ~20s of the tight tier-1 budget on a known-impossible environment.
# Opt back in where a real multi-host backend exists.
_needs_multiproc_backend = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_TEST_MULTIPROC", "") != "1",
    reason="jaxlib CPU backend lacks multiprocess collectives; set "
           "PADDLE_TPU_TEST_MULTIPROC=1 on a multi-host-capable backend")


def _expected_gradsum():
    # payload math: L = sum(X @ W) => dW = X^T @ 1, summed over 2 ranks
    tot = 0.0
    for rank in range(2):
        x = np.random.RandomState(rank).randn(8, 4).astype(np.float32)
        tot += x.sum() * 2  # out_features = 2
    return tot


@_needs_multiproc_backend
def test_launch_two_process_allreduce(tmp_path):
    log_dir = str(tmp_path / "logs")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # children: plain 1-device CPU
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir,
         os.path.join(REPO, "tests", "dist_payload_allreduce.py")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240)
    logs = ""
    for rank in range(2):
        p = os.path.join(log_dir, f"workerlog.{rank}")
        if os.path.exists(p):
            logs += open(p).read()
    assert proc.returncode == 0, \
        f"launcher rc={proc.returncode}\nstdout={proc.stdout}\n" \
        f"stderr={proc.stderr}\nlogs={logs}"
    sums = dict(
        (int(m.group(1)), float(m.group(2)))
        for m in re.finditer(r"GRADSUM (\d+) (-?\d+\.\d+)", logs))
    assert set(sums) == {0, 1}, f"missing rank output; logs:\n{logs}"
    # both ranks agree and equal the cross-rank sum
    assert abs(sums[0] - sums[1]) < 1e-4
    np.testing.assert_allclose(sums[0], _expected_gradsum(), rtol=1e-4)


def test_launch_propagates_child_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(bad)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 3


# ---- elastic membership (ISSUE 10): hosts file + shrink relaunch ------

def test_read_hosts_file_and_nproc_map(tmp_path):
    from paddle_tpu.distributed.launch import (get_cluster,
                                               read_hosts_file)
    hf = tmp_path / "hosts"
    hf.write_text("# survivors after the preemption\n"
                  "10.0.0.1:4\n"
                  "10.0.0.2\n"
                  "\n")
    hosts = read_hosts_file(str(hf), default_nproc=2)
    assert hosts == [("10.0.0.1", 4), ("10.0.0.2", 2)]
    eps, pods = get_cluster([ip for ip, _ in hosts], 2, start_port=7000,
                            nproc_map=dict(hosts))
    assert len(eps) == 6                   # 4 + 2 ranks
    assert pods[0].ranks == [0, 1, 2, 3] and pods[1].ranks == [4, 5]
    # missing file -> None (caller falls back to --ips); an EMPTY file
    # is an explicit zero-survivor signal ([]), not a fallback
    assert read_hosts_file(str(tmp_path / "nope"), 2) is None
    empty = tmp_path / "empty"
    empty.write_text("# nothing\n")
    assert read_hosts_file(str(empty), 2) == []


def test_launch_elastic_shrink_relaunch(tmp_path):
    """Crash at world=2 -> the relaunch attempt re-reads the hosts file
    (which the dying rank shrank to 1 proc, playing the scheduler) and
    the pod completes at the SMALLER world size instead of demanding
    the original one back."""
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1:2\n")
    script = tmp_path / "train.py"
    script.write_text(f"""
import os, sys
world = int(os.environ["PADDLE_TRAINERS_NUM"])
rank = int(os.environ["PADDLE_TRAINER_ID"])
# ONE pre-joined write: both ranks share the launcher's stdout pipe,
# and multi-arg print becomes several write()s when unbuffered -- the
# interleaved "WORLDWORLD  22" flake the assertion below trips on
print(f"WORLD {{world}} RANK {{rank}}", flush=True)
if world == 2:
    if rank == 0:
        with open({str(hosts)!r}, "w") as f:
            f.write("127.0.0.1:1\\n")   # the surviving set
    sys.exit(9)
sys.exit(0)
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--elastic_retries", "1",
         "--elastic_hosts_file", str(hosts), str(script)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "WORLD 2" in proc.stdout and "WORLD 1" in proc.stdout
    assert "elastic restart" in proc.stderr


def test_launch_preemption_reforms_from_survivors(tmp_path):
    """SIGTERM on the launcher: the drain completes, and with an
    elastic hosts file + retries left the pod RE-FORMS over the current
    survivor set instead of exiting at the original world size."""
    import signal
    import time

    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1:2\n")
    marker = tmp_path / "attempt2"
    started = tmp_path / "started"
    script = tmp_path / "serve.py"
    script.write_text(f"""
import os, sys, time
world = int(os.environ["PADDLE_TRAINERS_NUM"])
print("WORLD", world, flush=True)
open({str(started)!r}, "a").write(str(world))
if os.path.exists({str(marker)!r}):
    sys.exit(0)                        # resumed attempt finishes
time.sleep(60)                         # "training" until preempted
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--elastic_retries", "1",
         "--elastic_hosts_file", str(hosts), str(script)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        # wait until attempt 1's ranks are actually up
        deadline = time.time() + 30
        while time.time() < deadline and not started.exists():
            time.sleep(0.1)
        assert started.exists(), "attempt 1 never started"
        # the operator shrinks the membership, then preempts the pod
        hosts.write_text("127.0.0.1:1\n")
        marker.write_text("")
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, (out, err)
    assert "re-forming from the surviving host set" in err
    assert "WORLD 1" in out


@_needs_multiproc_backend
def test_spawn_two_process(tmp_path):
    """paddle.distributed.spawn parity (spawn.py:276) — run via a child
    interpreter so the spawned workers don't inherit this process's
    already-initialized JAX."""
    script = tmp_path / "spawn_main.py"
    script.write_text("""
import numpy as np

def work(rank, base):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.distributed import env
    env.init_parallel_env()
    assert jax.process_count() == 2
    from paddle_tpu.distributed.collective import all_reduce
    t = paddle.to_tensor(np.full((4,), float(rank + base), np.float32))
    all_reduce(t)
    got = float(np.asarray(t.data)[0])
    assert got == 2 * base + 1, got   # (base+0) + (base+1)
    print("SPAWN_OK", rank, flush=True)

if __name__ == "__main__":
    from paddle_tpu.distributed.spawn import spawn
    spawn(work, args=(5.0,), nprocs=2)
    print("PARENT_OK", flush=True)
""")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout={proc.stdout}\nstderr={proc.stderr}"
    assert "PARENT_OK" in proc.stdout


def test_import_does_not_initialize_backend(tmp_path):
    """init_parallel_env must work AFTER `import paddle_tpu` — so the
    package import must not touch the XLA backend (jax.distributed
    refuses to initialize afterwards)."""
    script = tmp_path / "probe.py"
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import paddle_tpu\n"
        "import paddle_tpu.distributed\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb._backends, 'import initialized the backend'\n"
        "print('LAZY_OK')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LAZY_OK" in proc.stdout


def test_spawn_failed_rank_terminates_survivors(tmp_path):
    """Review regression: one rank dying must not deadlock join() while
    the surviving rank waits in a collective."""
    script = tmp_path / "fail_main.py"
    script.write_text("""
import time

def work(rank):
    if rank == 0:
        raise RuntimeError("boom rank0")
    time.sleep(600)   # would deadlock join() without teardown

if __name__ == "__main__":
    from paddle_tpu.distributed.spawn import spawn
    try:
        spawn(work, nprocs=2)
    except RuntimeError as e:
        assert "boom rank0" in str(e), e
        print("FAIL_PROPAGATED", flush=True)
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "FAIL_PROPAGATED" in proc.stdout
