"""InMemoryDataset (CTR slot dataset) + elastic/heartbeat launcher.

Reference: paddle/fluid/framework/data_set.h:157 (InMemoryDataset with
local/global shuffle over slot records) and the fleet elastic manager's
crash-restart + heartbeat failure detection.
"""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.io import InMemoryDataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- InMemoryDataset ------------------------------------------------------
def write_slot_file(path, lines):
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_inmemory_parse_and_batches(tmp_path):
    p = str(tmp_path / "part-0")
    write_slot_file(p, [
        "1 click:3 click:7 q:11 dense:0.5 dense:1.5",
        "0 click:3 dense:2.5 dense:3.5",
        "1 q:4 q:5 q:6 dense:4.5 dense:5.5",
    ])
    ds = InMemoryDataset(dense_slots={"dense": 2}, batch_size=2)
    ds.load_into_memory([p])
    assert ds.get_memory_data_size() == 3
    batches = list(ds.batch_generator())
    assert len(batches) == 2
    b0 = batches[0]
    np.testing.assert_array_equal(b0["label"].reshape(-1), [1, 0])
    np.testing.assert_array_equal(b0["dense"],
                                  [[0.5, 1.5], [2.5, 3.5]])
    np.testing.assert_array_equal(b0["click"], [[3, 7], [3, -1]])
    np.testing.assert_array_equal(b0["click@len"], [2, 1])
    np.testing.assert_array_equal(b0["q"], [[11], [-1]])


def test_inmemory_local_shuffle_deterministic():
    recs = [{"label": [i], "s": [i]} for i in range(20)]
    a = InMemoryDataset()
    a.set_records(list(recs))
    a.local_shuffle(seed=7)
    b = InMemoryDataset()
    b.set_records(list(recs))
    b.local_shuffle(seed=7)
    assert [r["s"] for r in a._records] == [r["s"] for r in b._records]
    assert [r["s"] for r in a._records] != [r["s"] for r in recs]


def test_inmemory_global_shuffle_partitions_exactly():
    recs = [{"label": [i], "s": [i]} for i in range(50)]
    shards = []
    for rank in range(3):
        ds = InMemoryDataset()
        ds.set_records(list(recs))  # every trainer loads the full set
        ds.global_shuffle(rank=rank, world=3, seed=1)
        shards.append(sorted(r["s"][0] for r in ds._records))
    all_ids = sorted(i for s in shards for i in s)
    assert all_ids == list(range(50))          # exact partition
    assert all(len(s) > 0 for s in shards)     # roughly spread


def test_inmemory_use_slots_filter_and_release():
    ds = InMemoryDataset(use_slots=["a"])
    rec = ds.parse_line("1 a:5 b:9")
    assert rec == {"label": [1.0], "a": [5]}
    ds.set_records([rec])
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


# ---- elastic launcher -----------------------------------------------------
CRASH_ONCE = textwrap.dedent("""\
    import os, sys
    # crash on the first pod attempt, succeed on the second: the marker
    # file records that attempt 1 happened
    marker = os.environ["CRASH_MARKER"]
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    if not os.path.exists(marker):
        if rank == "0":
            open(marker, "w").write("died")
            sys.exit(3)
    print(f"RANK {rank} OK", flush=True)
""")

HANG = textwrap.dedent("""\
    import os, time
    from paddle_tpu.distributed import env
    env.heartbeat()          # one beat...
    time.sleep(3600)         # ...then silence (simulated dead collective)
""")


@pytest.mark.slow
def test_elastic_restart_after_crash(tmp_path):
    script = tmp_path / "payload.py"
    script.write_text(CRASH_ONCE)
    marker = str(tmp_path / "crashed")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CRASH_MARKER"] = marker
    log_dir = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--elastic_retries", "2",
         "--log_dir", log_dir, str(script)],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, \
        f"rc={proc.returncode} stderr={proc.stderr}"
    assert "elastic restart" in proc.stderr
    assert os.path.exists(marker)  # first attempt really crashed


@pytest.mark.slow
def test_heartbeat_timeout_detects_hang(tmp_path):
    script = tmp_path / "payload.py"
    script.write_text(HANG)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = str(tmp_path / "logs")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--heartbeat_timeout", "5",
         "--log_dir", log_dir, str(script)],
        env=env, capture_output=True, text=True, timeout=240)
    from paddle_tpu.distributed.launch import RC_HEARTBEAT_LOST
    assert proc.returncode == RC_HEARTBEAT_LOST, \
        f"rc={proc.returncode} stderr={proc.stderr}"
    assert "heartbeat lost" in proc.stderr
    assert time.time() - t0 < 120  # detected the hang, not the timeout


def test_heartbeat_noop_without_env(monkeypatch):
    from paddle_tpu.distributed import env as denv
    monkeypatch.delenv("PADDLE_HEARTBEAT_DIR", raising=False)
    assert denv.heartbeat() is False


def test_heartbeat_touches_file(tmp_path, monkeypatch):
    from paddle_tpu.distributed import env as denv
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setattr(denv, "_last_beat", 0.0)
    assert denv.heartbeat() is True
    assert os.path.exists(str(tmp_path / "hb.0"))
