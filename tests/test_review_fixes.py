"""Regression tests for round-2 inline review findings (spmd/recompute/
optimizer-hook issues)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import SpmdTrainer, create_mesh, recompute
from paddle_tpu.distributed.fleet import DistributedStrategy


def test_recompute_with_batchnorm_buffers():
    # buffers mutated inside the checkpointed region must come out as
    # REAL arrays (round-2 finding: inner tracers leaked into ._mean)
    paddle.seed(0)
    blk = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4))
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32),
                         stop_gradient=False)
    y = recompute(blk, x)
    y.sum().backward()
    bn = blk[1]
    mean = np.asarray(bn._mean.data)  # must not raise TracerError
    assert np.all(np.isfinite(mean))
    # eval-mode forward right after recompute training step
    blk.eval()
    out = blk(paddle.to_tensor(np.random.randn(2, 4).astype(np.float32)))
    assert np.all(np.isfinite(out.numpy()))


def test_minimize_only_loop_trains():
    # round-2 finding: minimize-per-iteration without clear_grad froze on
    # the first batch's gradients
    paddle.seed(0)
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=lin.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)
    losses = []
    for _ in range(5):
        out = lin(paddle.to_tensor(X))
        loss = F.mse_loss(out, paddle.to_tensor(Y))
        opt.minimize(loss)
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_minimize_no_double_backward_still():
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = lin(x).sum()
    loss.backward()
    opt.minimize(loss)  # must not re-run backward (graph is freed)


def test_adamw_decay_fun_matches_eager_in_compiled_path():
    # hook must receive Parameter.name under SpmdTrainer as well
    seen = []

    def decay_fun(name):
        seen.append(name)
        return ".b_" not in name

    paddle.seed(0)
    model = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.9,
                                 parameters=model.parameters(),
                                 apply_decay_param_fun=decay_fun)
    tr = SpmdTrainer(model, opt, lambda o, l: F.mse_loss(o, l),
                     mesh=create_mesh({"dp": 4}))
    x = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 8), np.float32)
    b_before = np.asarray(tr.params["bias"])
    tr.train_step(x, y)
    assert any(".b_" in n for n in seen), seen  # Parameter.name style
    # zero grads (x=0,y=0 -> dL/db nonzero actually; just check hook names)


def test_amp_casts_inputs_bf16():
    captured = {}

    class Probe(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            captured["dtype"] = x.dtype
            return self.fc(x)

    paddle.seed(0)
    model = Probe()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    st = DistributedStrategy()
    st.amp = True
    tr = SpmdTrainer(model, opt, lambda o, l: F.mse_loss(o, l),
                     mesh=create_mesh({"dp": 4}), strategy=st)
    tr.train_step(np.random.randn(4, 8).astype(np.float32),
                  np.random.randn(4, 4).astype(np.float32))
    assert captured["dtype"] == jnp.bfloat16


def test_fp16_amp_builds_scaled_trainer():
    """Round 2 asserted fp16 raised; round 5 implemented dynamic loss
    scaling (tests/test_fp16_scaling.py), so the flag now builds a
    scaled fp16 trainer instead of failing."""
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    st = DistributedStrategy()
    st.amp = True
    st.amp_configs = {"use_bf16": False}
    tr = SpmdTrainer(model, opt, lambda o, l: F.mse_loss(o, l),
                     mesh=create_mesh({"dp": 4}), strategy=st)
    assert tr.fp16_scaling and tr.amp_dtype == jnp.float16


@pytest.mark.parametrize("flag", ["lars", "lamb", "localsgd", "dgc",
                                  "elastic", "fp16_allreduce"])
def test_every_unsupported_flag_raises(flag):
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    st = DistributedStrategy()
    setattr(st, flag, True)
    with pytest.raises(NotImplementedError):
        SpmdTrainer(model, opt, lambda o, l: F.mse_loss(o, l),
                    mesh=create_mesh({"dp": 4}), strategy=st)
