"""nn.Layer system + layers tests (reference test strategy: unittests
test_layers.py, test_conv2d_op.py, test_batch_norm_op.py ... — here
numeric checks are against numpy/torch-free references)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(arr, sg=True):
    return paddle.to_tensor(np.asarray(arr, dtype=np.float32),
                            stop_gradient=sg)


class TestLayerBase:
    def test_parameters_and_state_dict(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
        sd = net.state_dict()
        assert set(sd) == set(names)

        net2 = Net()
        net2.set_state_dict(sd)
        for (n1, p1), (n2, p2) in zip(net.named_parameters(),
                                      net2.named_parameters()):
            np.testing.assert_array_equal(p1.numpy(), p2.numpy())

    def test_buffers(self):
        bn = nn.BatchNorm2D(3)
        assert "_mean" in bn.state_dict()
        assert len(bn.buffers()) == 2

    def test_train_eval(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        net.eval()
        assert not net[1].training
        net.train()
        assert net[1].training

    def test_hooks(self):
        lin = nn.Linear(3, 3)
        calls = []
        h = lin.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        lin(t(np.ones((2, 3))))
        assert calls == [1]
        h.remove()
        lin(t(np.ones((2, 3))))
        assert calls == [1]

    def test_apply_and_sublayers(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(net.sublayers()) == 3
        seen = []
        net.apply(lambda l: seen.append(type(l).__name__))
        assert "Sequential" in seen and "Linear" in seen


class TestCommonLayers:
    def test_linear_matches_numpy(self):
        lin = nn.Linear(4, 3)
        x = np.random.randn(5, 4).astype(np.float32)
        got = lin(t(x)).numpy()
        want = x @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([[0, 3], [5, 0]], dtype=np.int32))
        out = emb(ids).numpy()
        assert np.all(out[0, 0] == 0) and np.all(out[1, 1] == 0)
        assert not np.all(out[0, 1] == 0)

    def test_embedding_grad(self):
        emb = nn.Embedding(6, 3)
        ids = paddle.to_tensor(np.array([1, 1, 2], dtype=np.int32))
        out = emb(ids)
        out.sum().backward()
        g = emb.weight.grad.numpy()
        assert g[1].sum() == pytest.approx(6.0)  # row 1 hit twice
        assert g[0].sum() == 0

    def test_dropout_modes(self):
        x = t(np.ones((100, 100)))
        d = nn.Dropout(0.5)
        y = d(x).numpy()
        # upscale_in_train: surviving values are 2.0
        vals = np.unique(y)
        assert set(np.round(vals, 5)).issubset({0.0, 2.0})
        d.eval()
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())

    def test_flatten(self):
        x = t(np.zeros((2, 3, 4, 5)))
        assert nn.Flatten()(x).shape == [2, 60]
        assert nn.Flatten(0, 1)(x).shape == [6, 4, 5]

    def test_pad2d(self):
        x = t(np.ones((1, 1, 2, 2)))
        y = F.pad(x, [1, 1, 0, 0])  # left/right
        assert y.shape == [1, 1, 2, 4]

    def test_upsample(self):
        x = t(np.arange(4).reshape(1, 1, 2, 2))
        y = F.interpolate(x, scale_factor=2, mode="nearest")
        assert y.shape == [1, 1, 4, 4]


class TestConv:
    def test_conv2d_identity_kernel(self):
        conv = nn.Conv2D(1, 1, 3, padding=1,
                         weight_attr=nn.initializer.Constant(0.0),
                         bias_attr=False)
        w = np.zeros((1, 1, 3, 3), np.float32)
        w[0, 0, 1, 1] = 1.0
        conv.weight.set_value(w)
        x = np.random.randn(2, 1, 5, 5).astype(np.float32)
        np.testing.assert_allclose(conv(t(x)).numpy(), x, rtol=1e-5,
                                   atol=1e-6)

    def test_conv2d_matches_manual(self):
        conv = nn.Conv2D(2, 3, 2, stride=2, bias_attr=False)
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        got = conv(t(x)).numpy()
        w = conv.weight.numpy()
        want = np.zeros((1, 3, 2, 2), np.float32)
        for o in range(3):
            for i_ in range(2):
                for r in range(2):
                    for c in range(2):
                        want[0, o, r, c] += np.sum(
                            x[0, i_, r * 2:r * 2 + 2, c * 2:c * 2 + 2] *
                            w[o, i_])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv2d_groups(self):
        conv = nn.Conv2D(4, 4, 3, padding=1, groups=2)
        x = t(np.random.randn(1, 4, 6, 6))
        assert conv(x).shape == [1, 4, 6, 6]

    def test_conv2d_transpose_shape(self):
        convt = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
        x = t(np.random.randn(2, 3, 8, 8))
        assert convt(x).shape == [2, 6, 16, 16]

    def test_conv_transpose_inverts_stride(self):
        # transpose of all-ones kernel, stride 2: each input pixel spreads
        convt = nn.Conv2DTranspose(1, 1, 2, stride=2, bias_attr=False)
        convt.weight.set_value(np.ones((1, 1, 2, 2), np.float32))
        x = np.array([[[[1.0, 2.0], [3.0, 4.0]]]], np.float32)
        got = convt(t(x)).numpy()
        want = np.array([[[[1, 1, 2, 2], [1, 1, 2, 2],
                           [3, 3, 4, 4], [3, 3, 4, 4]]]], np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_conv1d(self):
        conv = nn.Conv1D(2, 4, 3, padding=1)
        x = t(np.random.randn(2, 2, 10))
        assert conv(x).shape == [2, 4, 10]


class TestNorm:
    def test_batchnorm_train_normalizes(self):
        bn = nn.BatchNorm1D(8, data_format="NC")
        x = np.random.randn(64, 8).astype(np.float32) * 5 + 3
        y = bn(t(x)).numpy()
        np.testing.assert_allclose(y.mean(0), 0, atol=1e-4)
        np.testing.assert_allclose(y.std(0), 1, atol=1e-2)

    def test_batchnorm_updates_running_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.0)  # momentum 0 -> running=batch
        x = np.random.randn(4, 3, 5, 5).astype(np.float32) + 7
        bn(t(x))
        np.testing.assert_allclose(bn._mean.numpy(),
                                   x.mean(axis=(0, 2, 3)), rtol=1e-3)

    def test_batchnorm_eval_uses_running(self):
        bn = nn.BatchNorm2D(2)
        bn.eval()
        x = np.random.randn(3, 2, 4, 4).astype(np.float32)
        y = bn(t(x)).numpy()
        np.testing.assert_allclose(y, x, rtol=1e-3, atol=1e-3)

    def test_layernorm(self):
        ln = nn.LayerNorm(16)
        x = np.random.randn(4, 6, 16).astype(np.float32) * 3 + 1
        y = ln(t(x)).numpy()
        np.testing.assert_allclose(y.mean(-1), 0, atol=1e-4)
        np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = np.random.randn(2, 4, 5, 5).astype(np.float32)
        y = gn(t(x)).numpy()
        grp = y.reshape(2, 2, 2 * 5 * 5)
        np.testing.assert_allclose(grp.mean(-1), 0, atol=1e-4)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = np.random.randn(3, 8).astype(np.float32)
        y = rn(t(x)).numpy()
        want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-5)


class TestPooling:
    def test_max_pool2d(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = F.max_pool2d(t(x), 2).numpy()
        np.testing.assert_array_equal(y[0, 0], [[5, 7], [13, 15]])

    def test_avg_pool2d(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = F.avg_pool2d(t(x), 2).numpy()
        np.testing.assert_allclose(y[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_adaptive_avg_pool2d(self):
        x = t(np.random.randn(2, 3, 8, 8))
        y = F.adaptive_avg_pool2d(x, 1)
        np.testing.assert_allclose(y.numpy()[..., 0, 0],
                                   x.numpy().mean(axis=(2, 3)), rtol=1e-5)

    def test_adaptive_nondivisible(self):
        x = t(np.random.randn(1, 2, 7, 7))
        assert F.adaptive_avg_pool2d(x, 3).shape == [1, 2, 3, 3]


class TestLosses:
    def test_cross_entropy_matches_numpy(self):
        logits = np.random.randn(6, 5).astype(np.float32)
        labels = np.random.randint(0, 5, (6,))
        got = float(F.cross_entropy(t(logits),
                                    paddle.to_tensor(labels)))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(6), labels]).mean()
        assert got == pytest.approx(want, rel=1e-4)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 3).astype(np.float32)
        labels = np.array([0, -100, 2, -100])
        got = float(F.cross_entropy(t(logits), paddle.to_tensor(labels),
                                    ignore_index=-100))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[[0, 2], [0, 2]]).mean()
        assert got == pytest.approx(want, rel=1e-4)

    def test_soft_label(self):
        logits = np.random.randn(3, 4).astype(np.float32)
        soft = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
        got = float(F.cross_entropy(t(logits), t(soft), soft_label=True))
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        want = (-(soft * logp).sum(-1)).mean()
        assert got == pytest.approx(want, rel=1e-4)

    def test_mse_and_l1(self):
        a, b = np.random.randn(5), np.random.randn(5)
        assert float(F.mse_loss(t(a), t(b))) == pytest.approx(
            ((a - b) ** 2).mean(), rel=1e-5)
        assert float(F.l1_loss(t(a), t(b))) == pytest.approx(
            np.abs(a - b).mean(), rel=1e-5)

    def test_bce_with_logits(self):
        z = np.random.randn(8).astype(np.float32)
        y = np.random.randint(0, 2, 8).astype(np.float32)
        got = float(F.binary_cross_entropy_with_logits(t(z), t(y)))
        p = 1 / (1 + np.exp(-z))
        want = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        assert got == pytest.approx(want, rel=1e-4)

    def test_kl_smooth_nll(self):
        logp = np.log(np.random.dirichlet(np.ones(4), 3)).astype(np.float32)
        tgt = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
        got = float(F.kl_div(t(logp), t(tgt), reduction="sum"))
        want = (tgt * (np.log(tgt) - logp)).sum()
        assert got == pytest.approx(want, rel=1e-3)

    def test_ctc_loss_simple(self):
        # single batch, trivially checkable: T=2, labels=[a]
        logp = np.log(np.full((2, 1, 3), 1 / 3, np.float32))
        labels = np.array([[1]], np.int32)
        got = F.ctc_loss(t(logp), paddle.to_tensor(labels),
                         paddle.to_tensor(np.array([2])),
                         paddle.to_tensor(np.array([1])),
                         reduction="none").numpy()[0]
        # paths: (blank,a),(a,blank),(a,a) = 3 paths * (1/9)
        want = -np.log(3 / 9)
        assert got == pytest.approx(want, rel=1e-4)


class TestActivationsGrad:
    @pytest.mark.parametrize("fn,npfn", [
        (F.relu, lambda a: np.maximum(a, 0)),
        (F.sigmoid, lambda a: 1 / (1 + np.exp(-a))),
        (F.tanh, np.tanh),
        (F.softplus, lambda a: np.log1p(np.exp(a))),
        (F.silu, lambda a: a / (1 + np.exp(-a))),
    ])
    def test_forward(self, fn, npfn):
        x = np.random.randn(4, 5).astype(np.float32)
        np.testing.assert_allclose(fn(t(x)).numpy(), npfn(x), rtol=1e-4,
                                   atol=1e-5)

    def test_grad_check(self):
        import math
        from op_test import check_grad
        x = np.random.randn(3, 4)
        check_grad(F.gelu, lambda a: 0.5 * a * (
            1 + np.vectorize(math.erf)(a / np.sqrt(2))), [x])

    def test_softmax(self):
        x = np.random.randn(3, 5).astype(np.float32)
        y = F.softmax(t(x)).numpy()
        np.testing.assert_allclose(y.sum(-1), 1, rtol=1e-5)


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = t(np.random.randn(3, 6, 4))
        y, (h, c) = lstm(x)
        assert y.shape == [3, 6, 8]
        assert h.shape == [2, 3, 8] and c.shape == [2, 3, 8]

    def test_gru_matches_cell(self):
        gru = nn.GRU(3, 5)
        x = np.random.randn(2, 4, 3).astype(np.float32)
        y, h = gru(t(x))
        # final hidden equals last output
        np.testing.assert_allclose(h.numpy()[0], y.numpy()[:, -1],
                                   rtol=1e-5)

    def test_lstmcell_step(self):
        cell = nn.LSTMCell(4, 6)
        x = t(np.random.randn(2, 4))
        out, (h, c) = cell(x)
        assert out.shape == [2, 6]
        np.testing.assert_allclose(out.numpy(), h.numpy())

    def test_bidirect_concat(self):
        rnn = nn.SimpleRNN(4, 6, direction="bidirectional")
        x = t(np.random.randn(2, 5, 4))
        y, h = rnn(x)
        assert y.shape == [2, 5, 12]


class TestTransformer:
    def test_encoder_layer(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        layer.eval()
        x = t(np.random.randn(2, 6, 16))
        assert layer(x).shape == [2, 6, 16]

    def test_full_transformer(self):
        m = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32,
                           dropout=0.0)
        m.eval()
        src = t(np.random.randn(2, 5, 16))
        tgt = t(np.random.randn(2, 3, 16))
        assert m(src, tgt).shape == [2, 3, 16]

    def test_mha_cache_incremental_decode(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = t(np.random.randn(1, 4, 8))
        full = mha(x, x, x,
                   attn_mask=paddle.to_tensor(
                       np.tril(np.ones((4, 4), bool))))
        cache = mha.gen_cache(t(np.zeros((1, 0, 8))))
        outs = []
        for i in range(4):
            step = x[:, i:i + 1]
            o, cache = mha(step, step, step, None, cache)
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(inc, full.numpy(), rtol=1e-3, atol=1e-4)

    def test_sdpa_causal_matches_mask(self):
        q = np.random.randn(1, 5, 2, 4).astype(np.float32)
        got = F.scaled_dot_product_attention(t(q), t(q), t(q),
                                             is_causal=True).numpy()
        mask = np.tril(np.ones((5, 5), bool))
        got2 = F.scaled_dot_product_attention(
            t(q), t(q), t(q),
            attn_mask=paddle.to_tensor(mask)).numpy()
        np.testing.assert_allclose(got, got2, rtol=1e-4, atol=1e-5)


class TestFlashAttentionKernel:
    def test_pallas_matches_composite(self):
        from paddle_tpu.ops import flash_attention as fa
        import jax.numpy as jnp
        fa_mod = __import__("paddle_tpu.ops.flash_attention",
                            fromlist=["*"])
        q = jnp.asarray(np.random.randn(1, 128, 2, 64), jnp.float32)
        k = jnp.asarray(np.random.randn(1, 128, 2, 64), jnp.float32)
        v = jnp.asarray(np.random.randn(1, 128, 2, 64), jnp.float32)
        ref = fa_mod._composite(q, k, v, True)
        fa_mod.set_interpret_mode(True)
        try:
            got = fa_mod.flash_attention(q, k, v, True)
        finally:
            fa_mod.set_interpret_mode(False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-2, atol=2e-3)


class TestReviewRegressions:
    """Regressions for code-review findings (round 1)."""

    def test_inplace_relu_grad(self):
        x = t(np.array([-2.0, 3.0]), sg=False)
        y = x * 1.0
        F.relu_(y)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0])

    def test_batch_norm_bias_without_weight(self):
        import paddle_tpu.nn.functional as F_
        x = t(np.random.randn(8, 3))
        mean = t(np.zeros(3))
        var = t(np.ones(3))
        bias = t(np.full(3, 7.0))
        y = F_.batch_norm(x, mean, var, weight=None, bias=bias)
        np.testing.assert_allclose(y.numpy(), x.numpy() + 7.0, rtol=1e-4)

    def test_layer_norm_bias_without_weight(self):
        x = np.random.randn(4, 8).astype(np.float32)
        y = F.layer_norm(t(x), 8, weight=None, bias=t(np.full(8, 2.0)))
        assert y.numpy().mean() == pytest.approx(2.0, abs=1e-4)

    def test_lstm_initial_state_used(self):
        lstm = nn.LSTM(4, 6)
        x = t(np.random.randn(2, 5, 4))
        h0 = t(np.full((1, 2, 6), 0.5))
        c0 = t(np.full((1, 2, 6), 0.5))
        y1, _ = lstm(x)
        y2, _ = lstm(x, (h0, c0))
        assert not np.allclose(y1.numpy(), y2.numpy())

    def test_rnn_interlayer_dropout_active(self):
        rnn = nn.SimpleRNN(4, 8, num_layers=2, dropout=0.9)
        rnn.train()
        x = t(np.random.randn(2, 5, 4))
        y1, _ = rnn(x)
        y2, _ = rnn(x)
        assert not np.allclose(y1.numpy(), y2.numpy())
        rnn.eval()
        y3, _ = rnn(x)
        y4, _ = rnn(x)
        np.testing.assert_allclose(y3.numpy(), y4.numpy())

    def test_align_corners_bilinear(self):
        # align_corners: corners map exactly
        x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
        y = F.interpolate(t(x), size=[3, 3], mode="bilinear",
                          align_corners=True).numpy()[0, 0]
        np.testing.assert_allclose(
            y, [[0, 0.5, 1], [1, 1.5, 2], [2, 2.5, 3]], rtol=1e-5)

    def test_flash_attention_nonpow2_blocks(self):
        import importlib
        fa_mod = importlib.import_module("paddle_tpu.ops.flash_attention")
        import jax.numpy as jnp
        q = jnp.asarray(np.random.randn(1, 384, 1, 64), jnp.float32)
        ref = fa_mod._composite(q, q, q, True)
        fa_mod.set_interpret_mode(True)
        try:
            got = fa_mod.flash_attention(q, q, q, True)
        finally:
            fa_mod.set_interpret_mode(False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-2, atol=2e-3)

    def test_spectral_norm_persists_uv(self):
        sn = nn.SpectralNorm([4, 3], power_iters=1)
        w = t(np.random.randn(4, 3))
        u_before = sn.weight_u.numpy().copy()
        sn(w)
        u_after1 = sn.weight_u.numpy().copy()
        sn(w)
        u_after2 = sn.weight_u.numpy().copy()
        assert not np.allclose(u_before, u_after1)
        # converging: consecutive iterates get closer
        assert np.linalg.norm(u_after2 - u_after1) < \
            np.linalg.norm(u_after1 - u_before) + 1e-3
