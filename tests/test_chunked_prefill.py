"""Chunked-prefill tests: the ISSUE-20 stall-free-batching contract.

Chunked prefill is a pure SCHEDULING change — admission binds a slot
without running prefill, each tick advances every still-prefilling slot
by up to ``prefill_chunk`` prompt tokens through ONE fixed-shape chunk
executable alongside the decode batch, and a slot graduates to decode
when its prompt completes.  The value proposition collapses unless the
emitted stream stays bit-identical to the monolithic engine's, so this
file pins token identity across the serving matrix (dense AND paged,
fp AND int8 KV, GQA, chunk ∈ {1, 4, ≥prompt}), the zero-recompile
churn contract for the chunk executable, preempt-resume under pool
pressure (with the progressive radix adoption re-hit), speculative
composition, the ``set_prefill_chunk`` hot-apply, the HOL-admission
probe memo, and the ITL / ``prefill_stall_ms`` observability columns
the loadgen + doctor satellites consume.
"""
import importlib

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.func import functional_apply, functional_state
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.utils import compile_counter

da = importlib.import_module("paddle_tpu.ops.decode_attention")

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(seed=0, **over):
    paddle.seed(seed)
    cfg = GPTConfig(**{**TINY, **over})
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def target():
    return tiny_model(0)


@pytest.fixture(scope="module")
def draft():
    return tiny_model(1, num_layers=1)


@pytest.fixture(scope="module")
def prompts():
    # lengths straddle every chunk-4 phase (1, 1, 3, 0 mod 4) and the
    # 16 one ends EXACTLY on both a chunk and a bucket boundary
    rng = np.random.RandomState(0)
    return [rng.randint(1, 97, (n,)).astype(np.int32)
            for n in (5, 9, 3, 16)]


@pytest.fixture(scope="module")
def reference(target, prompts):
    """The monolithic dense engine's greedy output — the ground truth
    every chunked configuration must reproduce exactly."""
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16])
    for p in prompts:
        eng.add_request(p, max_new_tokens=10)
    return eng.run()


# ---- op level: the chunk window IS the verify window --------------------

def test_chunk_attention_is_window_attention():
    """Chunked prefill adds NO new kernels: the chunk-attention exports
    are the PR-10 windowed verify ops themselves (scatter-then-attend
    over the staircase mask is the same computation either way)."""
    from paddle_tpu import ops
    assert ops.chunk_prefill_attention is da.decode_attention_window
    assert ops.paged_chunk_prefill_attention is \
        da.paged_decode_attention_window


def test_chunk_window_from_empty_matches_sequential():
    """The window op at the chunk-edge prefix lengths {0, 1, C-1, C} —
    including the cold start lens=0 a monolithic-verify user never hits
    — must equal a sequential chain of single-token decode calls."""
    rng = np.random.RandomState(0)
    B, S, H, Hkv, D, W = 4, 16, 4, 2, 8, 4
    k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    lens = jnp.asarray(np.array([0, 1, 3, 4], np.int32))
    out = da.decode_attention_window(q, k, v, lens)
    for i in range(W):
        ref = da.decode_attention(q[:, i], k, v, lens + i + 1)
        np.testing.assert_allclose(np.asarray(out[:, i]),
                                   np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_chunk_window_kernel_interpret_edges(quantized):
    """Interpret-mode Pallas window kernel ≡ the XLA composite at the
    chunk-edge prefix lengths (GQA, fp and int8, kernel-eligible
    shapes) — the kernel the chunk executable actually dispatches."""
    if not da._fa._HAS_PLTPU:
        pytest.skip("pallas TPU surface unavailable")
    rng = np.random.RandomState(2)
    B, S, H, Hkv, D, W = 4, 128, 4, 2, 64, 8
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    lens = jnp.asarray(np.array([0, 1, 7, 8], np.int32))
    if quantized:
        k = jnp.asarray(rng.randint(-127, 128, (B, S, Hkv, D))
                        .astype(np.int8))
        v = jnp.asarray(rng.randint(-127, 128, (B, S, Hkv, D))
                        .astype(np.int8))
        ks = jnp.asarray(rng.rand(B, S, Hkv).astype(np.float32) * 0.02)
        vs = jnp.asarray(rng.rand(B, S, Hkv).astype(np.float32) * 0.02)
        args = (q, k, v, lens, ks, vs)
        ref = da._window_composite(q, k, v, lens, ks, vs)
    else:
        k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        args = (q, k, v, lens)
        ref = da._window_composite(q, k, v, lens)
    da.set_interpret_mode(True)
    try:
        out = da.decode_attention_window(*args)
    finally:
        da.set_interpret_mode(None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- model level: chunk ticks ≡ monolithic prefill ----------------------

def test_prefill_chunk_matches_monolithic_prefill(target):
    """Driving prefill_chunk to completion reproduces the monolithic
    prefill — graduation logits AND cache contents — including a
    non-participating row (advance 0) whose garbage writes must stay
    above its valid length."""
    m = target
    params, _ = functional_state(m)
    rng = np.random.RandomState(0)
    lens = [7, 2]                       # row 1 sits idle in tick 2
    prompts = [rng.randint(1, 97, (n,)).astype(np.int32) for n in lens]
    C = 4

    mono = m.init_kv_cache(2, 64)
    logits_mono = []
    for s, p in enumerate(prompts):
        lg, mono = functional_apply(
            m, "prefill", params, jnp.asarray(p[None, :]), mono,
            np.int32(s), np.int32(len(p)))
        logits_mono.append(np.asarray(lg)[0])

    chunked = m.init_kv_cache(2, 64)
    pos = [0, 0]
    done_logits = [None, None]
    while any(pos[b] < lens[b] for b in range(2)):
        toks = np.zeros((2, C), np.int32)
        adv = np.zeros((2,), np.int32)
        for b in range(2):
            a = min(C, lens[b] - pos[b])
            if a > 0:
                toks[b, :a] = prompts[b][pos[b]:pos[b] + a]
            adv[b] = a
        lg, chunked = functional_apply(
            m, "prefill_chunk", params, jnp.asarray(toks), chunked,
            jnp.asarray(np.asarray(pos, np.int32)), jnp.asarray(adv))
        lg = np.asarray(lg)
        for b in range(2):
            if adv[b] and pos[b] + adv[b] == lens[b]:
                done_logits[b] = lg[b]
            pos[b] += int(adv[b])

    np.testing.assert_array_equal(np.asarray(chunked.lengths),
                                  np.asarray(lens, np.int32))
    for b in range(2):
        np.testing.assert_allclose(done_logits[b], logits_mono[b],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(chunked.k).astype(np.float32)[:, b, :lens[b]],
            np.asarray(mono.k).astype(np.float32)[:, b, :lens[b]],
            rtol=1e-5, atol=1e-5)


# ---- engine level: the token-identity matrix ----------------------------

# tier-1 wall budget: the fast lane keeps the 4 corners (chunk extremes
# × dtype × layout, every axis value covered; chunk=4 rides every other
# fast test in this file); the interior combos take the slow lane
_MATRIX_CORNERS = {(1, None, "dense"), (1, "int8", "paged"),
                   (64, None, "paged"), (64, "int8", "dense")}
_MATRIX = [
    pytest.param(c, kv, lay, id=f"{c}-{kv}-{lay}",
                 marks=() if (c, kv, lay) in _MATRIX_CORNERS
                 else pytest.mark.slow)
    for c in (1, 4, 64) for kv in (None, "int8")
    for lay in ("dense", "paged")]


@pytest.mark.parametrize("chunk,kv_dtype,layout", _MATRIX)
def test_chunked_token_identity_matrix(target, prompts, reference,
                                       layout, kv_dtype, chunk):
    """Chunked greedy output ≡ the monolithic rollout across the
    serving matrix — chunk=1 (a tick per token), chunk=64 (every prompt
    completes in one tick) and the interior — with ZERO XLA compiles
    after warmup under slot churn (4 requests over 2 slots).  int8
    engines compare against an int8 MONOLITHIC engine: quantization
    changes logits, never the chunked/monolithic equivalence."""
    kw = dict(kv_layout=layout)
    if layout == "paged":
        kw.update(kv_block_size=8)
    if kv_dtype is None:
        ref = reference
    else:
        ref_eng = InferenceEngine(target, batch_slots=2,
                                  prefill_buckets=[16],
                                  kv_dtype=kv_dtype, **kw)
        for p in prompts:
            ref_eng.add_request(p, max_new_tokens=10)
        ref = ref_eng.run()
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16],
                          prefill_chunk=chunk, kv_dtype=kv_dtype, **kw)
    eng.warmup()
    with compile_counter.assert_no_recompiles(
            f"chunk churn {layout}/{kv_dtype}/C={chunk}"):
        for p in prompts:
            eng.add_request(p, max_new_tokens=10)
        out = eng.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    st = eng.stats
    assert st["chunked_prefill"] and st["prefill_chunk"] == chunk
    assert st["prefill_stall_ms"] == 0
    assert st["prefill_tokens"] == sum(p.size for p in prompts)
    if layout == "paged":
        eng.check_leak_free()


def test_chunked_token_identity_gqa(prompts):
    """The matrix's GQA leg: grouped-query KV through the chunk
    executable, both layouts."""
    tgt = tiny_model(0, num_kv_heads=2)
    ref_eng = InferenceEngine(tgt, batch_slots=2, prefill_buckets=[16])
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=10)
    ref = ref_eng.run()
    for layout in ("dense", "paged"):
        kw = {"kv_block_size": 8} if layout == "paged" else {}
        eng = InferenceEngine(tgt, batch_slots=2, prefill_chunk=4,
                              kv_layout=layout, **kw)
        for p in prompts:
            eng.add_request(p, max_new_tokens=10)
        out = eng.run()
        for rr, ss in zip(sorted(ref), sorted(out)):
            np.testing.assert_array_equal(ref[rr], out[ss])


def test_chunked_with_spec_decode_token_identity(target, draft, prompts,
                                                 reference):
    """Chunked prefill composes with speculative decoding: prefilling
    slots are excluded from the spec set, the draft catches up at
    graduation, and the stream still matches the plain monolithic
    non-spec rollout — with zero compiles under churn."""
    for layout in ("dense", "paged"):
        kw = {"kv_block_size": 8} if layout == "paged" else {}
        eng = InferenceEngine(target, batch_slots=2,
                              prefill_buckets=[16], prefill_chunk=4,
                              spec_k=2, draft_model=draft,
                              kv_layout=layout, **kw)
        eng.warmup(buckets=eng.buckets)
        with compile_counter.assert_no_recompiles(
                f"chunk+spec churn {layout}"):
            for p in prompts:
                eng.add_request(p, max_new_tokens=10)
            out = eng.run()
        for rr, ss in zip(sorted(reference), sorted(out)):
            np.testing.assert_array_equal(reference[rr], out[ss])
        assert eng.stats["spec_ticks"] > 0
        if layout == "paged":
            eng.check_leak_free()


def test_chunked_preempt_resume_radix_rehit(target):
    """Pool pressure mid-stream preempts a chunked slot; the resume
    goes back through chunked admission, re-hits the progressively
    adopted radix blocks, and the output still matches the roomy
    monolithic reference.  The pool is sized so two full-length slots
    CANNOT coexist (2 + 2×3 shared/distinct blocks > 7), forcing at
    least one preemption."""
    rng = np.random.RandomState(11)
    prefix = rng.randint(1, 97, (16,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.randint(1, 97, (4,)).astype(np.int32)])
               for _ in range(4)]
    ref_eng = InferenceEngine(target, batch_slots=2,
                              prefill_buckets=[32])
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=20)
    ref = ref_eng.run()
    eng = InferenceEngine(target, batch_slots=2, prefill_chunk=4,
                          kv_layout="paged", kv_block_size=8,
                          kv_num_blocks=8)
    for p in prompts:
        eng.add_request(p, max_new_tokens=20)
    out = eng.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    assert eng.stats["preemptions"] >= 1
    # progressive adoption made the shared prefix (and any resumed
    # request's own prompt blocks) radix hits
    assert eng._prefix.hit_blocks > 0
    eng.check_leak_free()


# ---- scheduler: HOL admission memo --------------------------------------

@pytest.mark.parametrize("chunk", [0, 4])
def test_hol_blocked_head_not_reprobed(target, chunk):
    """A head-of-line request refused for lack of blocks must NOT be
    re-probed every tick: the allocator's probe counter stays flat
    until a release actually frees something, then the head admits."""
    eng = InferenceEngine(target, batch_slots=2,
                          prefill_buckets=[16, 40], kv_layout="paged",
                          kv_block_size=8, kv_num_blocks=5,
                          prefill_chunk=chunk)
    rng = np.random.RandomState(5)
    pa = rng.randint(1, 97, (35,)).astype(np.int32)
    pb = rng.randint(1, 97, (5,)).astype(np.int32)
    # A fills the ENTIRE pool: 35 + 5 = 40 tokens = all 5 usable blocks,
    # and the final sampled token is returned without a cache write, so
    # decode never extends — the blocked window below sees no legitimate
    # allocator traffic.  Drive A through its whole prefill first.
    ra = eng.add_request(pa, max_new_tokens=5)
    for _ in range(1 if chunk == 0 else -(-35 // chunk)):
        eng.step()
    rb = eng.add_request(pb, max_new_tokens=4)
    eng.step()                          # ONE probe: refused, memoized
    p0 = eng._alloc.probes
    for _ in range(2):
        eng.step()                      # A decodes; head stays gated
    assert eng._alloc.probes == p0, \
        "blocked head-of-line request was re-probed with nothing freed"
    out = eng.run()                     # A retires -> freed blocks wake B
    assert eng._alloc.probes > p0
    assert len(out[ra]) == 5 and len(out[rb]) == 4
    eng.check_leak_free()


# ---- hot-apply + observability ------------------------------------------

def test_set_prefill_chunk_hot_apply(target, prompts, reference):
    """The autotune axis's hot-apply: flipping a warmed monolithic
    engine into chunked mode is a host-side switch whose one-time chunk
    compile lands at apply time — the traffic window after it stays
    compile-free and token-identical."""
    from paddle_tpu.autotune.knobs import axis_for
    ax = axis_for("prefill_chunk")
    assert ax is not None and ax.hot_apply
    assert ax.env == "PADDLE_TPU_CHUNKED_PREFILL"

    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16])
    eng.warmup(buckets=eng.buckets)
    assert eng.set_prefill_chunk(4)
    assert eng.stats["chunked_prefill"] is True
    with compile_counter.assert_no_recompiles("hot-applied chunk"):
        for p in prompts:
            eng.add_request(p, max_new_tokens=10)
        out = eng.run()
    for rr, ss in zip(sorted(reference), sorted(out)):
        np.testing.assert_array_equal(reference[rr], out[ss])
    assert eng.set_prefill_chunk(0)     # and back off again
    assert eng.stats["chunked_prefill"] is False


def test_itl_columns_and_stall_counter(target):
    """Per-request ITL gap percentiles + the pooled engine columns, and
    the prefill_stall_ms counter: positive for a monolithic engine
    whose staggered admissions stall live decodes, identically zero
    under chunking on the same workload."""
    rng = np.random.RandomState(3)
    work = [(rng.randint(1, 97, (n,)).astype(np.int32), mn)
            for n, mn in zip((5, 9, 7, 11, 6), (6, 8, 10, 7, 9))]

    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16])
    rids = [eng.add_request(p, max_new_tokens=mn) for p, mn in work]
    eng.run()
    st = eng.stats
    assert st["prefill_stall_ms"] > 0
    for rid, (_, mn) in zip(rids, work):
        rec = st["per_request"][rid]
        assert len(rec["itl_gaps_ms"]) == mn - 1
        assert rec["itl_ms_p99"] >= rec["itl_ms_p50"] >= 0
    assert st["itl_ms_p99"] >= st["itl_ms_p50"] >= 0

    eng2 = InferenceEngine(target, batch_slots=2, prefill_chunk=4)
    for p, mn in work:
        eng2.add_request(p, max_new_tokens=mn)
    eng2.run()
    st2 = eng2.stats
    assert st2["prefill_stall_ms"] == 0
    assert st2["itl_ms_p99"] >= st2["itl_ms_p50"] >= 0


def test_loadtest_report_itl_columns(target):
    """The loadgen report carries the CO-corrected ITL percentiles next
    to the TTFT ones (satellite a)."""
    from paddle_tpu.inference.loadgen import (SharedPrefixWorkload,
                                              run_loadtest)
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16],
                          prefill_chunk=4)
    eng.warmup()
    wl = SharedPrefixWorkload(97, seed=0, shared_frac=0.0,
                              prefix_len=8, tail_len=(3, 10),
                              max_new=(4, 8))
    rep = run_loadtest(eng, 8, 200.0, workload=wl)
    assert rep["itl_ms_p50"] is not None
    assert rep["itl_ms_p99"] >= rep["itl_ms_p50"] >= 0
    assert rep["num_requests"] == 8


def test_prefill_stall_doctor_rule():
    """The 'prefill-stall' rule: fires on a real stall share with the
    chunked-prefill knob as its machine action, stays silent when
    chunking is already on (its own advice taken), below the window,
    or with the signal absent."""
    from paddle_tpu.observability.doctor import diagnose

    def hits(stats):
        return [v for v in diagnose(stats, "serve")
                if v["bottleneck"] == "prefill-stall"]

    hit = hits({"prefill_stall_ms": 40.0, "decode_ms": 60.0})
    assert hit, "rule did not fire on a 40% stall share"
    act = hit[0]["action"]
    assert act["param"] == "prefill_chunk"
    assert act["env"] == "PADDLE_TPU_CHUNKED_PREFILL"
    assert act["candidates"]
    assert not hits({"prefill_stall_ms": 40.0, "decode_ms": 60.0,
                     "chunked_prefill": True})
    assert not hits({"prefill_stall_ms": 2.0, "decode_ms": 3.0})
    assert not hits({"prefill_stall_ms": 5.0, "decode_ms": 95.0})
    assert not hits({"decode_ms": 95.0})
