"""Native shared-memory ring + multiprocess DataLoader tests.

Reference role: operators/reader/buffered_reader.cc +
fluid/dataloader/dataloader_iter.py:230-378 (multiprocess workers over
shared memory) + mmap_allocator.cc — here a C11-atomics SPSC ring
(io/native/shm_ring.c, compiled on demand) under fork workers.
"""
import multiprocessing as mp
import os

import numpy as np
import pytest

from paddle_tpu.io import DataLoader
from paddle_tpu.io.shm_ring import (RingClosed, RingTimeout, ShmRing,
                                    available)

pytestmark = pytest.mark.skipif(
    not available(), reason="no C compiler for the native ring")


def test_ring_roundtrip_and_order():
    r = ShmRing.create(1 << 16)
    try:
        msgs = [os.urandom(n) for n in (1, 7, 8, 100, 4096)]
        for m in msgs:
            r.push(m)
        for m in msgs:
            assert r.pop() == m
    finally:
        r.destroy()


def test_ring_wraparound_small_capacity():
    """Capacity forces many wraps; every frame must survive intact."""
    r = ShmRing.create(1 << 10)  # 1 KiB
    try:
        rng = np.random.RandomState(0)
        produced = []
        for i in range(200):
            m = bytes(rng.bytes(int(rng.randint(1, 200))))
            produced.append(m)
        # interleave: keep at most 3 in flight
        got = []
        k = 0
        for m in produced:
            r.push(m, timeout_ms=2000)
            if len(produced) - len(got) > 3:
                got.append(r.pop(timeout_ms=2000))
        while len(got) < len(produced):
            got.append(r.pop(timeout_ms=2000))
        assert got == produced
    finally:
        r.destroy()


def test_ring_close_semantics():
    r = ShmRing.create(1 << 12)
    try:
        r.push(b"last")
        r.close_writer()
        assert r.pop() == b"last"       # drain after close
        with pytest.raises(RingClosed):
            r.pop()
        with pytest.raises(RingClosed):
            r.push(b"nope")
    finally:
        r.destroy()


def test_ring_pop_timeout():
    r = ShmRing.create(1 << 12)
    try:
        with pytest.raises(RingTimeout):
            r.pop(timeout_ms=50)
    finally:
        r.destroy()


def test_ring_cross_process():
    """Producer in a real child process, consumer here."""
    r = ShmRing.create(1 << 20)

    def produce(name):
        w = ShmRing.attach(name)
        for i in range(50):
            w.push(bytes([i]) * (i + 1))
        w.close_writer()

    p = mp.get_context("fork").Process(target=produce, args=(r.name,))
    p.start()
    try:
        for i in range(50):
            assert r.pop(timeout_ms=10000) == bytes([i]) * (i + 1)
        with pytest.raises(RingClosed):
            r.pop(timeout_ms=10000)
    finally:
        p.join(10)
        r.destroy()


class _SquareDS:
    def __len__(self):
        return 37

    def __getitem__(self, i):
        return (np.full((3, 4), i, np.float32),
                np.array([i * i], np.int64))


def _collect(loader):
    out = []
    for x, y in loader:
        out.append((np.asarray(x.data), np.asarray(y.data)))
    return out


def test_multiprocess_loader_matches_single():
    ds = _SquareDS()
    ref = _collect(DataLoader(ds, batch_size=5, shuffle=False,
                              num_workers=0))
    got = _collect(DataLoader(ds, batch_size=5, shuffle=False,
                              num_workers=3, use_shared_memory=True))
    assert len(got) == len(ref)
    for (xa, ya), (xb, yb) in zip(ref, got):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_multiprocess_loader_worker_init_and_error():
    class Bad:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("sample 5 corrupt")
            return np.zeros(2, np.float32)

    loader = DataLoader(Bad(), batch_size=2, shuffle=False, num_workers=2,
                        use_shared_memory=True)
    with pytest.raises(RuntimeError, match="sample 5 corrupt"):
        _collect_plain(loader)


def _collect_plain(loader):
    return [np.asarray(b.data) for b in loader]


@pytest.mark.slow
def test_multiprocess_loader_transform_heavy():
    """Transforms run in the worker PROCESS (CPU parallel, no GIL).
    Throughput-flavored soak (heavy per-sample matmuls across worker
    restarts); slow-marked — multiprocess CORRECTNESS stays tier-1 via
    test_multiprocess_loader_matches_single / dead-worker tests."""
    class Heavy:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            a = rng.randn(64, 64).astype(np.float32)
            return (a @ a.T).astype(np.float32)

    ref = _collect_plain(DataLoader(Heavy(), batch_size=4, shuffle=False,
                                    num_workers=0))
    got = _collect_plain(DataLoader(Heavy(), batch_size=4, shuffle=False,
                                    num_workers=4,
                                    use_shared_memory=True))
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)


def test_ring_rejects_oversized_frame():
    """Frames > capacity/2 can starve the wrap; must raise, not spin."""
    r = ShmRing.create(1 << 10)
    try:
        with pytest.raises(ValueError, match="half the ring"):
            r.push(b"x" * 600)
    finally:
        r.destroy()


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_dead_worker_detected_not_hang():
    """SIGKILLed worker (no close_writer) surfaces as RuntimeError via
    liveness polling instead of hanging the trainer."""
    import signal
    import time

    class Slow:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            time.sleep(0.3)
            return np.zeros(2, np.float32)

    from paddle_tpu.io.dataloader import _MultiprocessIter

    class KillingIter(_MultiprocessIter):
        pass

    loader = DataLoader(Slow(), batch_size=2, shuffle=False,
                        num_workers=2, use_shared_memory=True)
    # drive the internals directly so we can SIGKILL a worker
    import multiprocessing as mp
    import pickle
    mp_iter = _MultiprocessIter(loader, list(loader.batch_sampler), 2,
                                loader.shm_ring_capacity, -1, None)
    gen = iter(mp_iter)
    first = next(gen)          # workers are up and producing
    import os as _os
    # kill every child the fork context knows about
    import multiprocessing.process as _mpp
    for c in mp.active_children():
        _os.kill(c.pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="died|exited"):
        for _ in range(8):
            next(gen)
