"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor


def test_batch_norm_grad_includes_stat_terms():
    # Scale invariance: y = BN(x) is invariant to scaling x, so
    # d/dx sum(BN(x)^2) must be ~0 when grads flow through batch stats.
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32),
                         stop_gradient=False)
    rm = Tensor(np.zeros(4, np.float32))
    rv = Tensor(np.ones(4, np.float32))
    y = F.batch_norm(x, rm, rv, training=True)
    loss = (y * y).sum()
    loss.backward()
    assert np.abs(x.grad.numpy()).max() < 1e-4


def test_batch_norm_running_stats_still_update():
    x = paddle.to_tensor(np.random.randn(16, 4).astype(np.float32) * 3 + 1)
    rm = Tensor(np.zeros(4, np.float32))
    rv = Tensor(np.ones(4, np.float32))
    F.batch_norm(x, rm, rv, training=True, momentum=0.5)
    assert np.abs(rm.numpy()).sum() > 0.01
    assert np.abs(rv.numpy() - 1.0).sum() > 0.01


def test_batch_norm_layer_trains_sane():
    # end-to-end: BN layer gradient vs numeric finite difference on weight
    bn = paddle.nn.BatchNorm1D(3)
    x = paddle.to_tensor(np.random.randn(6, 3).astype(np.float32),
                         stop_gradient=False)
    y = bn(x)
    loss = (y * y).mean()
    loss.backward()
    assert bn.weight.grad is not None
    assert np.all(np.isfinite(bn.weight.grad.numpy()))


def test_minimize_after_backward_no_double_backward():
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = lin(x).sum()
    loss.backward()
    opt.minimize(loss)  # must not raise / re-run backward


def test_minimize_alone_still_runs_backward():
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    w0 = lin.weight.numpy().copy()
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = lin(x).sum()
    opt.minimize(loss)
    assert not np.allclose(lin.weight.numpy(), w0)


def test_scaler_minimize_after_backward():
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = lin(x).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.minimize(opt, scaled)  # must not raise


def test_adamw_apply_decay_param_fun():
    lin = paddle.nn.Linear(4, 4)
    lin2 = paddle.nn.Linear(4, 4)
    lin2.bias.set_value(np.full(4, 10.0, np.float32))
    opt3 = paddle.optimizer.AdamW(
        learning_rate=0.1, parameters=lin2.parameters(), weight_decay=0.9,
        apply_decay_param_fun=lambda n: ".b_" not in n)
    opt4 = paddle.optimizer.AdamW(
        learning_rate=0.1, parameters=lin2.parameters(), weight_decay=0.9)
    # grads of zero: only decay acts
    for p in lin2.parameters():
        p.grad = Tensor(np.zeros(p.shape, np.float32))
    b_before = lin2.bias.numpy().copy()
    opt3.step()
    b_excluded = lin2.bias.numpy().copy()
    # bias excluded from decay AND zero grad -> unchanged
    np.testing.assert_allclose(b_excluded, b_before, atol=1e-6)
    for p in lin2.parameters():
        p.grad = Tensor(np.zeros(p.shape, np.float32))
    opt4.step()
    b_decayed = lin2.bias.numpy().copy()
    assert np.abs(b_decayed - b_excluded).max() > 0.01  # decay applied


def test_adamw_honors_regularizer_weight_decay():
    from paddle_tpu.regularizer import L2Decay
    opt = paddle.optimizer.AdamW(
        learning_rate=0.1, parameters=[],
        weight_decay=L2Decay(0.25))
    assert opt._wd_coeff == 0.25
    with pytest.raises(TypeError):
        paddle.optimizer.AdamW(learning_rate=0.1, parameters=[],
                               weight_decay="bogus")


def test_lamb_exclude_from_weight_decay_fn():
    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Lamb(
        learning_rate=0.1, lamb_weight_decay=0.9,
        parameters=lin.parameters(),
        exclude_from_weight_decay_fn=lambda p: ".b_" in getattr(
            p, "name", str(p)))
    for p in lin.parameters():
        p.grad = Tensor(np.zeros(p.shape, np.float32))
    b0 = lin.bias.numpy().copy()
    opt.step()
    # zero grad + excluded decay -> trust ratio * (0 + 0) = no movement
    np.testing.assert_allclose(lin.bias.numpy(), b0, atol=1e-6)
