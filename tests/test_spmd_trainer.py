"""Compiled SPMD trainer tests on the virtual 8-device CPU mesh.

Reference analogue: test_dist_base.py:668's loss-parity strategy (N-rank
run must match the single-process run) applied to the GSPMD executor.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.mesh import mesh_guard
from paddle_tpu.distributed.fleet import DistributedStrategy


def make_mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))


def ce_loss(out, label):
    return F.cross_entropy(out, label)


def make_batches(n=4, bs=16, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(bs, 16).astype(np.float32),
             rng.randint(0, 10, size=(bs,)).astype(np.int64))
            for _ in range(n)]


def eager_losses(batches, lr=0.1, seed=0):
    model = make_mlp(seed)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=model.parameters())
    losses = []
    for x, y in batches:
        out = model(paddle.to_tensor(x))
        loss = ce_loss(out, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses, model


def test_fused_step_matches_eager_dp8():
    batches = make_batches()
    ref_losses, _ = eager_losses(batches)

    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh)
    losses = [float(tr.train_step(x, y)) for x, y in batches]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_step_is_single_executable():
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh)
    x, y = make_batches(1)[0]
    tr.train_step(x, y)
    assert tr.step_executable is not None
    # one compiled fused executable, params live sharded on the mesh
    leaf = next(iter(tr.params.values()))
    assert len(leaf.sharding.device_set) == 8


def test_adam_parity_dp():
    batches = make_batches(3)
    model_e = make_mlp(0)
    opt_e = paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=model_e.parameters())
    ref = []
    for x, y in batches:
        loss = ce_loss(model_e(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt_e.step()
        opt_e.clear_grad()
        ref.append(float(loss))

    mesh = create_mesh({"dp": 4})
    model = make_mlp(0)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh)
    got = [float(tr.train_step(x, y)) for x, y in batches]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_gradient_merge_matches_large_batch():
    # k accumulation steps with avg == one step on the k-times batch
    rng = np.random.RandomState(7)
    xs = rng.randn(4, 8, 16).astype(np.float32)
    ys = rng.randint(0, 10, size=(4, 8)).astype(np.int64)

    big_model = make_mlp(3)
    opt_b = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=big_model.parameters())
    big_loss = ce_loss(big_model(paddle.to_tensor(xs.reshape(32, 16))),
                       paddle.to_tensor(ys.reshape(32)))
    big_loss.backward()
    opt_b.step()
    ref_w = big_model[0].weight.numpy()

    mesh = create_mesh({"dp": 4})
    model = make_mlp(3)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh, strategy=strategy)
    for i in range(4):
        tr.train_step(xs[i], ys[i])
    tr.sync_to_model()
    np.testing.assert_allclose(model[0].weight.numpy(), ref_w,
                               rtol=2e-4, atol=2e-5)


def test_zero_stage2_shards_opt_state():
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 2}
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh, strategy=strategy)
    x, y = make_batches(1)[0]
    tr.train_step(x, y)
    # moment arrays for the big weight must be sharded 8-ways over dp:
    # per-device bytes == total/8
    for name, tree in tr.opt_state.items():
        for aname, arr in tree.items():
            if arr.size >= 8 and any(d % 8 == 0 for d in arr.shape):
                shard_bytes = arr.addressable_shards[0].data.size
                assert shard_bytes == arr.size // 8, (name, aname)


def test_zero_stage3_shards_params_loss_parity():
    batches = make_batches(3)
    ref_losses, _ = eager_losses(batches)
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 3}
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh, strategy=strategy)
    losses = [float(tr.train_step(x, y)) for x, y in batches]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    w = tr.params["0.weight"]
    assert w.addressable_shards[0].data.size == w.size // 8


def test_amp_bf16_trains():
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.amp = True
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh, strategy=strategy)
    batches = make_batches(2)
    l0 = float(tr.train_step(*batches[0]))
    l1 = float(tr.train_step(*batches[1]))
    assert np.isfinite(l0) and np.isfinite(l1)
    # master params stay fp32
    assert tr.params["0.weight"].dtype == jnp.float32


def test_unimplemented_strategy_raises():
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.dgc = True
    with pytest.raises(NotImplementedError):
        SpmdTrainer(model, opt, ce_loss, mesh=mesh, strategy=strategy)


def test_eval_step():
    mesh = create_mesh({"dp": 8})
    model = make_mlp(0)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, ce_loss, mesh=mesh)
    x, _ = make_batches(1)[0]
    out = tr.eval_step(x)
    assert out.shape == (16, 10)
