"""Serving-fleet tests: prefix summaries, the router, disaggregated
prefill/decode, and the fleet load harness (ISSUE 12 tentpole pieces 2
and 3 + the summary() satellite).

The heavyweight end-to-end fleet comparison (prefix routing beats
round-robin on hit rate and p99 TTFT at calibrated load) lives in the
bench fleet smoke (`bench.py --serve --loadtest --smoke`, exercised by
test_paged_kv.test_bench_loadtest_smoke_contract); this file covers the
mechanisms deterministically — summary/fingerprint scoring equals the
real radix match, routing policy decisions, handoff block accounting,
and decode-path purity under disaggregation.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.inference import (DisaggServingEngine, InferenceEngine,
                                  Router, score_overlap)
from paddle_tpu.inference.loadgen import (MultiTenantWorkload,
                                          run_fleet_loadtest, warm_fleet)
from paddle_tpu.utils import compile_counter

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(seed=0, **over):
    paddle.seed(seed)
    cfg = GPTConfig(**{**TINY, **over})
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return tiny_model()


def paged_engine(model, **over):
    kw = dict(batch_slots=2, prefill_buckets=[16, 32],
              kv_layout="paged", kv_block_size=8)
    kw.update(over)
    return InferenceEngine(model, **kw)


# ---- prefix summary / fingerprint scoring -------------------------------

def test_summary_score_matches_real_match(model):
    """score_overlap over a replica summary() must equal what the radix
    tree's match() would find — the router's cheap probe is exact, and
    it must not touch the tree's hit counters."""
    eng = paged_engine(model)
    rng = np.random.RandomState(0)
    prefix = rng.randint(1, 97, (16,)).astype(np.int32)
    tail = rng.randint(1, 97, (5,)).astype(np.int32)
    eng.add_request(np.concatenate([prefix, tail]), max_new_tokens=4)
    eng.run()
    summ = eng.prefix_summary()
    assert summ["cached_blocks"] > 0
    q0 = eng._prefix.queries
    probe = np.concatenate([prefix, rng.randint(1, 97, (4,))
                            .astype(np.int32)])
    score = score_overlap(probe, summ)
    assert eng._prefix.queries == q0          # probe left no footprint
    blocks, matched = eng._prefix.match(probe)
    assert score == len(blocks) == matched // 8 == 2
    # a cold prompt scores zero
    assert score_overlap(rng.randint(1, 97, (20,)).astype(np.int32),
                         summ) == 0
    # summary survives eviction bookkeeping: flush drops everything
    eng.flush_prefix_cache()
    assert score_overlap(probe, eng.prefix_summary()) == 0


def test_engine_stats_expose_prefix_cache(model):
    eng = paged_engine(model)
    eng.add_request(np.arange(1, 20, dtype=np.int32), max_new_tokens=2)
    eng.run()
    pc = eng.stats["prefix_cache"]
    assert pc["block_size"] == 8
    assert isinstance(pc["fingerprints"], int)   # JSON-safe count
    assert pc["fingerprints"] == pc["cached_blocks"] > 0


# ---- router policy ------------------------------------------------------

def test_router_prefers_cached_replica(model):
    """A prompt whose prefix lives on replica 1 routes there; a cold
    prompt falls back to least-loaded; round_robin ignores both."""
    a, b = paged_engine(model), paged_engine(model)
    rng = np.random.RandomState(1)
    prefix = rng.randint(1, 97, (16,)).astype(np.int32)
    # seed replica B with the prefix directly
    b.add_request(np.concatenate([prefix, rng.randint(1, 97, (3,))
                                  .astype(np.int32)]), max_new_tokens=2)
    b.run()
    router = Router([a, b], policy="prefix")
    probe = np.concatenate([prefix,
                            rng.randint(1, 97, (4,)).astype(np.int32)])
    assert router.route(probe) == 1
    assert router.prefix_routed == 1
    assert router.prefix_blocks_routed == 2
    # cold prompt: least-loaded fallback — both idle, index 0 wins
    assert router.route(rng.randint(1, 97, (10,)).astype(np.int32)) == 0
    st = router.stats
    assert st["requests_routed"] == 2
    assert st["router_hit_rate"] == 0.5
    rr = Router([a, b], policy="round_robin")
    assert [rr.route(probe) for _ in range(4)] == [0, 1, 0, 1]


def test_router_load_gap_bounds_affinity(model):
    """Cache affinity must not chase a prefix onto a backed-up replica:
    past max_load_gap the router balances instead."""
    a, b = paged_engine(model), paged_engine(model)
    rng = np.random.RandomState(2)
    prefix = rng.randint(1, 97, (16,)).astype(np.int32)
    b.add_request(np.concatenate([prefix, rng.randint(1, 97, (3,))
                                  .astype(np.int32)]), max_new_tokens=2)
    b.run()
    # pile queued work onto B without stepping it
    for _ in range(4):
        b.add_request(rng.randint(1, 97, (6,)).astype(np.int32),
                      max_new_tokens=2)
    router = Router([a, b], policy="prefix", max_load_gap=2)
    probe = np.concatenate([prefix,
                            rng.randint(1, 97, (4,)).astype(np.int32)])
    assert router.route(probe) == 0          # balance beat affinity
    assert router.prefix_routed == 0
    relaxed = Router([a, b], policy="prefix", max_load_gap=100)
    assert relaxed.route(probe) == 1         # affinity wins when allowed
    b.run()


def test_router_end_to_end_results(model):
    """Router.run() drives every replica to completion and namespaces
    results by replica index."""
    fleet = Router([paged_engine(model), paged_engine(model)],
                   policy="least_loaded")
    rng = np.random.RandomState(3)
    keys = [fleet.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                              max_new_tokens=4) for _ in range(6)]
    out = fleet.run()
    assert set(keys) == set(out.keys())
    assert all(len(v) > 0 for v in out.values())
    for r in fleet.replicas:
        r.check_leak_free()


# ---- fleet load harness -------------------------------------------------

@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_fleet_loadtest_report_columns(model):
    """run_fleet_loadtest on a 2-replica fleet: per-replica columns,
    router hit rate, aggregate prefix hit rate, and zero recompiles in
    the measured window with spec decoding on."""
    def mk(policy):
        reps = []
        for _ in range(2):
            e = paged_engine(model, spec_k=2, draft_model=model)
            e.warmup(buckets=e.buckets)
            reps.append(e)
        return Router(reps, policy=policy)

    wl = MultiTenantWorkload(97, seed=5, num_tenants=4, skew=1.0,
                             prefix_len=16, tail_len=(3, 8),
                             max_new=(2, 4))
    fleet = mk("prefix")
    warm_fleet(fleet, wl)
    snap = compile_counter.snapshot()
    rep = run_fleet_loadtest(fleet, 16, 100.0, workload=wl, seed=0)
    assert snap.new_compiles == 0
    assert rep["num_requests"] == 16
    assert rep["num_replicas"] == 2
    assert len(rep["replica_occupancy"]) == 2
    # router counters are snapshotted: warm_fleet traffic excluded
    assert sum(rep["requests_per_replica"]) == 16
    assert rep["prefix_hit_rate"] > 0
    assert rep["accepted_tokens_per_tick"] > 1.5
    assert rep["ttft_ms_p99"] >= rep["ttft_ms_p50"] > 0
    assert rep["tenants_seen"] <= 4
    for r in fleet.replicas:
        r.check_leak_free()


def test_multitenant_workload_skew():
    wl = MultiTenantWorkload(97, seed=0, num_tenants=4, skew=1.5)
    counts = np.zeros(4)
    for _ in range(400):
        t, prompt, mn = wl.sample()
        counts[t] += 1
        assert prompt.size > wl.prefixes[t].size
        np.testing.assert_array_equal(prompt[:16], wl.prefixes[t])
    assert counts[0] > counts[-1] * 2        # hot head, cold tail


# ---- disaggregated prefill/decode ---------------------------------------

def test_disagg_token_identity_and_leakfree(model):
    """Disaggregated engine ≡ the plain paged engine token for token;
    pools drain leak-free; zero recompiles after warmup (the worker's
    own prefill executables included)."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 97, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12, 7)]
    ref_eng = paged_engine(model)
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=10)
    ref = ref_eng.run()
    dis = DisaggServingEngine(model, batch_slots=2,
                              prefill_buckets=[16, 32], kv_block_size=8)
    dis.warmup()
    with compile_counter.assert_no_recompiles("disagg churn"):
        for p in prompts:
            dis.add_request(p, max_new_tokens=10)
        out = dis.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    assert dis.stats["handoffs"] == len(prompts)
    assert dis.stats["prefill_worker_prefills"] == len(prompts)
    dis.drain()
    dis.check_leak_free()


def test_disagg_decode_steps_run_no_prefill(model):
    """The POINT of disaggregation: the decode engine's own prefill
    executables never run — admissions come exclusively through the
    worker's handoff records."""
    dis = DisaggServingEngine(model, batch_slots=2,
                              prefill_buckets=[16], kv_block_size=8)
    dis.warmup()
    rng = np.random.RandomState(4)
    for _ in range(3):
        dis.add_request(rng.randint(1, 97, (6,)).astype(np.int32),
                        max_new_tokens=6)
    dis.run()
    # every prefill was timed under a worker key, none under the decode
    # engine's own ("prefill_paged*") keys
    keys = dis.decode._first_call_keys
    assert any(k[0].startswith("disagg") for k in keys)
    assert dis.stats["prefill_worker_prefills"] == 3


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_disagg_spec_and_prefix_cache_compose(model):
    """Disagg + spec decode + radix prefix cache all stack: shared
    prefixes hit across handoffs, spec ticks commit >1 token, output
    stays greedy-identical."""
    rng = np.random.RandomState(6)
    prefix = rng.randint(1, 97, (16,)).astype(np.int32)
    prompts = [np.concatenate([prefix, rng.randint(1, 97, (3,))
                               .astype(np.int32)]) for _ in range(4)]
    ref_eng = paged_engine(model)
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=8)
    ref = ref_eng.run()
    dis = DisaggServingEngine(model, batch_slots=2,
                              prefill_buckets=[16, 32], kv_block_size=8,
                              spec_k=2, draft_model=model)
    dis.warmup()
    for p in prompts:
        dis.add_request(p, max_new_tokens=8)
    out = dis.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    st = dis.stats
    assert st["prefix_hit_queries"] >= 3
    assert st["accepted_tokens_per_tick"] > 1.5
    dis.drain()
    dis.check_leak_free()


def test_disagg_deadline_and_drain(model):
    """Wrapper-queue deadlines expire without a prefill; drain returns
    queued + parked work and leaves the pool clean."""
    dis = DisaggServingEngine(model, batch_slots=1,
                              prefill_buckets=[16], kv_block_size=8,
                              prefills_per_step=1, handoff_depth=1)
    dis.warmup()
    rng = np.random.RandomState(8)
    rid = dis.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                          max_new_tokens=4, deadline_s=0.0)
    import time
    time.sleep(0.01)
    dis.step()
    assert dis.request_stats[rid]["timed_out"]
    # now park work and drain
    for _ in range(3):
        dis.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                        max_new_tokens=4)
    dis.step()
    leftover = dis.drain()
    dis.check_leak_free()
    assert not dis.has_work
    assert all(r.slot is None for r in leftover)
