"""Op unit tests in the reference's OpTest style (numeric grad checks).

Reference model: unittests/test_activation_op.py, test_elementwise_*_op.py,
test_matmul_v2_op.py, test_reduce_op.py, ...
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


class TestElementwise:
    def test_add_broadcast(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4).astype(np.float32)
        check_output(paddle.add, np.add, [a, b])
        check_grad(paddle.add, np.add, [a, b])

    def test_sub_mul_div(self):
        a = np.random.rand(2, 3).astype(np.float32) + 0.5
        b = np.random.rand(2, 3).astype(np.float32) + 0.5
        check_grad(paddle.subtract, np.subtract, [a, b])
        check_grad(paddle.multiply, np.multiply, [a, b])
        check_grad(paddle.divide, np.true_divide, [a, b])

    def test_pow_scalar_ops(self):
        a = np.random.rand(3, 3).astype(np.float32) + 0.5
        x = paddle.to_tensor(a, stop_gradient=False)
        y = (x ** 2 + 3 * x - 1) / 2
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), (2 * a + 3) / 2, rtol=1e-5)

    def test_maximum_minimum(self):
        a = np.random.rand(5).astype(np.float32)
        b = np.random.rand(5).astype(np.float32)
        check_output(paddle.maximum, np.maximum, [a, b])
        check_output(paddle.minimum, np.minimum, [a, b])


class TestActivationsMath:
    @pytest.mark.parametrize("pfn,nfn", [
        (paddle.exp, np.exp), (paddle.tanh, np.tanh),
        (paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x))),
        (paddle.sqrt, np.sqrt), (paddle.log, np.log),
        (paddle.sin, np.sin), (paddle.cos, np.cos),
    ])
    def test_unary_grad(self, pfn, nfn):
        a = np.random.rand(3, 4).astype(np.float32) + 0.5
        check_grad(pfn, nfn, [a])

    def test_clip(self):
        a = np.linspace(-2, 2, 10).astype(np.float32)
        check_output(lambda x: paddle.clip(x, -1, 1),
                     lambda x: np.clip(x, -1, 1), [a])


class TestReduce:
    def test_sum_axis(self):
        a = np.random.rand(2, 3, 4).astype(np.float32)
        check_output(lambda x: paddle.sum(x, axis=1),
                     lambda x: np.sum(x, axis=1), [a])
        check_grad(lambda x: paddle.sum(x, axis=[0, 2]),
                   lambda x: np.sum(x, axis=(0, 2)), [a])

    def test_mean_keepdim(self):
        a = np.random.rand(2, 5).astype(np.float32)
        check_output(lambda x: paddle.mean(x, axis=1, keepdim=True),
                     lambda x: np.mean(x, axis=1, keepdims=True), [a])
        check_grad(paddle.mean, np.mean, [a])

    def test_max_min_prod(self):
        a = np.random.rand(3, 4).astype(np.float32)
        check_output(lambda x: paddle.max(x, axis=0),
                     lambda x: np.max(x, axis=0), [a])
        check_output(lambda x: paddle.prod(x, axis=1),
                     lambda x: np.prod(x, axis=1), [a])

    def test_cumsum(self):
        a = np.random.rand(3, 4).astype(np.float32)
        check_output(lambda x: paddle.cumsum(x, axis=1),
                     lambda x: np.cumsum(x, axis=1), [a])
        check_grad(lambda x: paddle.cumsum(x, axis=0),
                   lambda x: np.cumsum(x, axis=0), [a])

    def test_logsumexp_std_var(self):
        a = np.random.rand(4, 4).astype(np.float32)
        from scipy.special import logsumexp as np_lse
        check_output(lambda x: paddle.logsumexp(x, axis=1),
                     lambda x: np_lse(x, axis=1), [a], rtol=1e-4, atol=1e-4)
        check_output(lambda x: paddle.std(x),
                     lambda x: np.std(x, ddof=1), [a], rtol=1e-4, atol=1e-5)


class TestMatmul:
    def test_matmul_grad(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 5).astype(np.float32)
        check_output(paddle.matmul, np.matmul, [a, b])
        check_grad(paddle.matmul, np.matmul, [a, b])

    def test_matmul_transpose_flags(self):
        a = np.random.rand(4, 3).astype(np.float32)
        b = np.random.rand(5, 4).astype(np.float32)
        check_output(lambda x, y: paddle.matmul(x, y, True, True),
                     lambda x, y: x.T @ y.T, [a, b])

    def test_bmm(self):
        a = np.random.rand(2, 3, 4).astype(np.float32)
        b = np.random.rand(2, 4, 5).astype(np.float32)
        check_output(paddle.bmm, np.matmul, [a, b])
        check_grad(paddle.bmm, np.matmul, [a, b], rtol=2e-2)

    def test_einsum(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 5).astype(np.float32)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


class TestManipulation:
    def test_reshape_transpose_grad(self):
        a = np.random.rand(2, 6).astype(np.float32)
        check_grad(lambda x: paddle.reshape(x, [3, 4]),
                   lambda x: np.reshape(x, [3, 4]), [a])
        check_output(lambda x: paddle.transpose(x, [1, 0]),
                     lambda x: x.T, [a])

    def test_concat_split(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(2, 3).astype(np.float32)
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
        parts = paddle.split(out, 2, axis=0)
        np.testing.assert_allclose(parts[0].numpy(), a)
        parts = paddle.split(out, [1, -1], axis=0)
        assert parts[1].shape == [3, 3]

    def test_concat_grad_flows_to_all(self):
        a = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
        paddle.concat([a, b], axis=1).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), np.ones((2, 2)))
        np.testing.assert_allclose(b.grad.numpy(), np.ones((2, 2)))

    def test_squeeze_unsqueeze_stack(self):
        a = np.random.rand(1, 3, 1).astype(np.float32)
        assert paddle.squeeze(paddle.to_tensor(a)).shape == [3]
        assert paddle.unsqueeze(paddle.to_tensor(a), [0]).shape == [1, 1, 3, 1]
        s = paddle.stack([paddle.ones([2]), paddle.zeros([2])], axis=0)
        assert s.shape == [2, 2]

    def test_gather_scatter(self):
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        idx = np.array([0, 2])
        check_output(lambda x: paddle.gather(x, paddle.to_tensor(idx)),
                     lambda x: x[idx], [a])
        upd = np.ones((2, 3), np.float32) * 9
        out = paddle.scatter(paddle.to_tensor(a), paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        ref = a.copy()
        ref[idx] = upd
        np.testing.assert_allclose(out.numpy(), ref)

    def test_gather_nd(self):
        a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        idx = np.array([[0, 1], [1, 2]])
        out = paddle.gather_nd(paddle.to_tensor(a), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), a[[0, 1], [1, 2]])

    def test_tile_expand_flip(self):
        a = np.random.rand(1, 3).astype(np.float32)
        assert paddle.tile(paddle.to_tensor(a), [2, 2]).shape == [2, 6]
        assert paddle.expand(paddle.to_tensor(a), [4, 3]).shape == [4, 3]
        check_output(lambda x: paddle.flip(x, [1]),
                     lambda x: np.flip(x, 1), [a])

    def test_getitem_grad(self):
        a = paddle.to_tensor(np.arange(9, np.float32).reshape(3, 3)
                             if False else np.arange(9, dtype=np.float32).reshape(3, 3),
                             stop_gradient=False)
        a[1:, :2].sum().backward()
        ref = np.zeros((3, 3))
        ref[1:, :2] = 1
        np.testing.assert_allclose(a.grad.numpy(), ref)


class TestSearchLogic:
    def test_argmax_sort_topk(self):
        a = np.random.rand(3, 5).astype(np.float32)
        check_output(lambda x: paddle.argmax(x, axis=1),
                     lambda x: np.argmax(x, axis=1), [a])
        check_output(lambda x: paddle.sort(x, axis=1),
                     lambda x: np.sort(x, axis=1), [a])
        vals, idx = paddle.topk(paddle.to_tensor(a), 2, axis=1)
        np.testing.assert_allclose(vals.numpy(),
                                   -np.sort(-a, axis=1)[:, :2], rtol=1e-6)

    def test_where_nonzero(self):
        a = np.array([[1.0, 0.0], [0.0, 2.0]], np.float32)
        out = paddle.where(paddle.to_tensor(a > 0), paddle.to_tensor(a),
                           paddle.to_tensor(-a))
        np.testing.assert_allclose(out.numpy(), np.abs(a))
        nz = paddle.nonzero(paddle.to_tensor(a))
        assert nz.shape == [2, 2]

    def test_comparisons(self):
        a = paddle.to_tensor([1.0, 2.0, 3.0])
        b = paddle.to_tensor([3.0, 2.0, 1.0])
        np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
        np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
        assert bool(paddle.allclose(a, a))


class TestLinalg:
    def test_norm(self):
        a = np.random.rand(3, 4).astype(np.float32)
        check_output(lambda x: paddle.norm(x),
                     lambda x: np.linalg.norm(x), [a], rtol=1e-5)
        check_output(lambda x: paddle.norm(x, p=1, axis=1),
                     lambda x: np.abs(x).sum(1), [a], rtol=1e-5)

    def test_cholesky_inv_solve(self):
        a = np.random.rand(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        L = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd, rtol=1e-4,
                                   atol=1e-4)
        inv = paddle.linalg.inv(paddle.to_tensor(spd))
        np.testing.assert_allclose(inv.numpy() @ spd, np.eye(4), atol=1e-4)
        b = np.random.rand(4, 2).astype(np.float32)
        x = paddle.linalg.solve(paddle.to_tensor(spd), paddle.to_tensor(b))
        np.testing.assert_allclose(spd @ x.numpy(), b, atol=1e-4)


class TestCreationRandom:
    def test_creation(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3], "int32").dtype == np.int32
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        assert paddle.full([2], 7.0).numpy().tolist() == [7.0, 7.0]
        assert paddle.eye(3).numpy().trace() == 3
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)

    def test_random_reproducible(self):
        paddle.seed(42)
        a = paddle.rand([4])
        paddle.seed(42)
        b = paddle.rand([4])
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        u = paddle.uniform([1000], min=-2, max=2)
        assert -2 <= float(u.min()) and float(u.max()) <= 2
        r = paddle.randint(0, 10, [100])
        assert 0 <= int(r.min()) and int(r.max()) < 10
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))


class TestAutogradEngine:
    def test_shared_subexpression(self):
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        y = x * x          # used twice below
        z = y + y * 3.0
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [16.0])  # d/dx 4x^2

    def test_grad_accumulation_across_backwards(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0] * 3)
        x.clear_grad()
        assert x.grad is None

    def test_no_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        with paddle.no_grad():
            y = (x * 2).sum()
        assert y.stop_gradient
        assert y._creator is None

    def test_detach(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * 2).detach()
        (y * 3).sum()
        assert y.stop_gradient

    def test_retain_graph_false_frees(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * 2).sum()
        y.backward()
        import pytest as _pytest
        with _pytest.raises(Exception):
            y.backward()

    def test_double_backward_with_retain(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * 2).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0] * 3)

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        w = paddle.to_tensor(np.ones(3, np.float32))  # stop_gradient=True
        (x * w).sum().backward()
        assert x.grad is not None and w.grad is None

    def test_nan_check_flag(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            with pytest.raises(Exception):
                paddle.log(x * 0 - 1)  # log(-1) -> nan
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})


class TestTensorMethods:
    def test_methods_and_repr(self):
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert x.shape == [2, 2] and x.ndim == 2 and x.numel() == 4
        assert abs(float(x.mean()) - 2.5) < 1e-6
        assert x.astype("int32").dtype == np.int32
        assert "Tensor" in repr(x)
        assert x.T.shape == [2, 2]
        np.testing.assert_allclose(x.t().numpy(), x.numpy().T)

    def test_item_and_setitem(self):
        x = paddle.to_tensor([[1.0, 2.0]])
        assert x[0, 1].item() == 2.0
        x[0, 0] = 9.0
        assert x[0, 0].item() == 9.0

    def test_cast_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = x.astype("bfloat16").astype("float32").sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3))
