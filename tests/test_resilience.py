"""Fault-tolerant training (ISSUE 2): async integrity-checked
checkpoints, preemption handling, anomaly policies, retry/backoff, and
the deterministic fault-injection harness driving them end to end.

Done criteria exercised here:
- a SIGTERM mid-train (in-process and true subprocess) drains the step,
  commits a verified checkpoint, and the next run resumes at that step
  with losses matching an uninterrupted run;
- a deliberately truncated newest checkpoint is skipped in favor of the
  previous valid one;
- async checkpointing blocks the train thread only for the host
  snapshot (commit happens in the background);
- anomaly policies skip/rollback reproduce a clean run that never saw
  the poisoned batch;
- HDFS ops retry through transient hadoop-CLI failures.
"""
import errno
import json
import os
import signal
import stat
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (CheckpointManager, PreemptionGuard,
                                    SpmdTrainer, create_mesh,
                                    latest_checkpoint)
from paddle_tpu.distributed.checkpoint import (read_checkpoint,
                                               validate_checkpoint)
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.framework.fs import (LocalFS, open_for_write,
                                     retry_with_backoff)
from paddle_tpu.io import DataLoader
from paddle_tpu.testing import InjectedFault, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _linear_trainer(seed=0, anomaly_policy=None, strategy=None):
    paddle.seed(seed)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    return SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": 1}), strategy=strategy,
                       anomaly_policy=anomaly_policy)


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(4, 4).astype(np.float32),
             rng.randn(4, 2).astype(np.float32)) for _ in range(n)]


# ---------------------------------------------------------------------------
# fs hardening
# ---------------------------------------------------------------------------
def test_localfs_put_exdev_fallback(tmp_path, monkeypatch):
    from paddle_tpu.framework import fs as fsmod
    dest = tmp_path / "sub" / "dest.bin"
    real_replace = os.replace

    def fake_replace(src, dst):
        # only the first-hop rename to THIS dest crosses filesystems;
        # the fallback's same-directory rename must go through
        if dst == str(dest) and not str(src).endswith(".xdev.tmp"):
            raise OSError(errno.EXDEV, "cross-device link")
        return real_replace(src, dst)

    monkeypatch.setattr(fsmod.os, "replace", fake_replace)
    src = tmp_path / "src.bin"
    src.write_bytes(b"payload")
    LocalFS().put(str(src), str(dest))
    assert dest.read_bytes() == b"payload"
    assert not src.exists()
    assert not (tmp_path / "sub" / "dest.bin.xdev.tmp").exists()


def test_open_for_write_crash_leaves_no_partial(tmp_path):
    p = str(tmp_path / "ck.bin")
    with pytest.raises(RuntimeError, match="boom"):
        with open_for_write(p) as f:
            f.write(b"half-written")
            raise RuntimeError("boom")
    assert not os.path.exists(p)          # nothing committed
    assert not os.path.exists(p + ".tmp")  # no orphaned temp


def test_retry_with_backoff_recovers_and_exhausts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_with_backoff(flaky, tries=3, base_ms=1,
                              sleep=lambda s: None) == "ok"
    with pytest.raises(OSError):
        retry_with_backoff(lambda: (_ for _ in ()).throw(OSError("x")),
                           tries=2, base_ms=1, sleep=lambda s: None)


def test_fs_fault_injection_windows(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_FAULT_FS", "put:2:2")
    fs = LocalFS()

    def do_put(i):
        src = tmp_path / f"s{i}"
        src.write_bytes(b"x")
        fs.put(str(src), str(tmp_path / f"d{i}"))

    do_put(0)                      # call 1: ok
    with pytest.raises(InjectedFault):
        do_put(1)                  # call 2: armed
    with pytest.raises(InjectedFault):
        do_put(2)                  # call 3: armed
    do_put(3)                      # call 4: ok again


def test_hdfs_retry_through_flaky_hadoop(tmp_path, monkeypatch):
    """A hadoop CLI that fails its first N invocations then recovers:
    the fs layer's backoff absorbs the outage."""
    flaky = tmp_path / "hadoop"
    flaky.write_text(r"""#!/bin/bash
ROOT="$FAKE_HDFS_ROOT"
COUNT="$FAKE_HDFS_COUNT"
n=$(cat "$COUNT" 2>/dev/null || echo 0); n=$((n+1)); echo $n > "$COUNT"
if [ "$n" -le "$FAKE_HDFS_FAILS" ]; then echo "transient" >&2; exit 1; fi
[ "$1" = fs ] || exit 2
shift
op=$1; shift
map() { echo "$ROOT/$(echo "$1" | sed 's|^[a-z]*://||')"; }
case $op in
  -test) shift; p=$(map "$1"); [ -e "$p" ] ;;
  -mkdir) [ "$1" = -p ] && shift; mkdir -p "$(map "$1")" ;;
  -put) [ "$1" = -f ] && shift; src=$1; dst=$(map "$2")
        mkdir -p "$(dirname "$dst")"; cp "$src" "$dst" ;;
  -get) src=$(map "$1"); cp "$src" "$2" ;;
  *) exit 2 ;;
esac
""")
    flaky.chmod(flaky.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "hdfs_root"
    root.mkdir()
    count = tmp_path / "count"
    monkeypatch.setenv("PADDLE_HADOOP_BIN", str(flaky))
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    monkeypatch.setenv("FAKE_HDFS_COUNT", str(count))
    monkeypatch.setenv("FAKE_HDFS_FAILS", "2")

    with open_for_write("hdfs://ns/ck/model.bin") as f:
        f.write(b"abc123")
    assert (root / "ns/ck/model.bin").read_bytes() == b"abc123"

    # a hard outage (always failing) exhausts the retries and raises
    count.write_text("0")
    monkeypatch.setenv("FAKE_HDFS_FAILS", "999")
    monkeypatch.setenv("PADDLE_TPU_FS_RETRIES", "2")
    with pytest.raises(subprocess.CalledProcessError):
        with open_for_write("hdfs://ns/ck/other.bin") as f:
            f.write(b"nope")


# ---------------------------------------------------------------------------
# manifest checkpoints + CheckpointManager
# ---------------------------------------------------------------------------
def test_manifest_checkpoint_roundtrip(tmp_path):
    tr = _linear_trainer(0)
    for x, y in _batches(3):
        tr.train_step(x, y)
    p = str(tmp_path / "ck-m")
    tr.save(p, extra={"note": "mid"}, manifest=True)
    assert os.path.isdir(p)
    assert validate_checkpoint(p)
    tr2 = _linear_trainer(9)
    extra = tr2.load(p)
    assert extra == {"note": "mid"}
    assert tr2._step_count == 3
    for n in tr.params:
        np.testing.assert_array_equal(np.asarray(tr.params[n]),
                                      np.asarray(tr2.params[n]))


def test_truncated_and_corrupt_checkpoints_fail_validation(tmp_path):
    tr = _linear_trainer(1)
    tr.train_step(*_batches(1)[0])
    p = str(tmp_path / "ck")
    tr.save(p, manifest=True)
    entry = os.path.join(p, "state.pdtrainer")
    good = open(entry, "rb").read()

    with open(entry, "wb") as f:        # truncation
        f.write(good[:10])
    assert not validate_checkpoint(p)
    with pytest.raises(ValueError, match="validation"):
        read_checkpoint(p)

    flipped = bytearray(good)           # single-bit rot, same size
    flipped[len(flipped) // 2] ^= 0xFF
    with open(entry, "wb") as f:
        f.write(bytes(flipped))
    assert not validate_checkpoint(p)

    with open(entry, "wb") as f:        # restored payload validates
        f.write(good)
    assert validate_checkpoint(p)


def test_manager_falls_back_past_truncated_newest(tmp_path):
    batches = _batches(4, seed=3)
    tr = _linear_trainer(2)
    mgr = CheckpointManager(str(tmp_path), keep_last=4, async_save=False)
    for x, y in batches[:3]:
        tr.train_step(x, y)
        mgr.save(tr)
    # truncate the NEWEST checkpoint's payload (simulated crash/bitrot)
    entry = os.path.join(str(tmp_path), "ckpt-3", "state.pdtrainer")
    with open(entry, "r+b") as f:
        f.truncate(16)
    # latest_checkpoint skips it...
    assert latest_checkpoint(str(tmp_path)).endswith("ckpt-2")
    # ...and restore falls back to step 2 instead of crashing
    tr2 = _linear_trainer(77)
    mgr2 = CheckpointManager(str(tmp_path), keep_last=4)
    assert mgr2.restore_latest(tr2) is not None
    assert tr2._step_count == 2
    assert mgr2.stats["fallbacks"] >= 1
    # continuing from the fallback matches the original trainer state
    # as of step 2: re-train step 3+4 on both and compare
    ref = _linear_trainer(2)
    for x, y in batches[:2]:
        ref.train_step(x, y)
    l_ref = [float(ref.train_step(x, y)) for x, y in batches[2:]]
    l_res = [float(tr2.train_step(x, y)) for x, y in batches[2:]]
    np.testing.assert_allclose(l_res, l_ref, rtol=2e-5, atol=2e-6)


def test_manager_keeps_last_k_and_gcs_tmps(tmp_path):
    tr = _linear_trainer(3)
    # a stale staging dir from a "crashed" earlier run
    stale = tmp_path / "ckpt-99.tmp"
    stale.mkdir()
    (stale / "state.pdtrainer").write_bytes(b"junk")
    mgr = CheckpointManager(str(tmp_path), keep_last=2, async_save=False)
    for x, y in _batches(5):
        tr.train_step(x, y)
        mgr.save(tr)
    names = sorted(os.listdir(tmp_path))
    assert names == ["ckpt-4", "ckpt-5"]  # keep-last-2, tmps GC'd


def test_async_save_does_not_block_training_thread(tmp_path, monkeypatch):
    import paddle_tpu.distributed.resilience as rmod
    tr = _linear_trainer(4)
    tr.train_step(*_batches(1)[0])
    gate = threading.Event()
    real_write = rmod.write_checkpoint

    def delayed_write(state, path):
        gate.wait(10)
        return real_write(state, path)

    monkeypatch.setattr(rmod, "write_checkpoint", delayed_write)
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    p = mgr.save(tr, extra={"k": 1})
    # save() returned while the commit is still gated: the train thread
    # paid only the host snapshot
    assert not os.path.exists(p)
    assert mgr.last_snapshot_ms is not None
    gate.set()
    mgr.wait()
    assert validate_checkpoint(p)
    assert read_checkpoint(p)["extra"] == {"k": 1}


def test_async_save_failure_surfaces_on_wait(tmp_path, monkeypatch):
    import paddle_tpu.distributed.resilience as rmod
    tr = _linear_trainer(5)
    tr.train_step(*_batches(1)[0])

    def exploding_write(state, path):
        raise IOError("disk on fire")

    monkeypatch.setattr(rmod, "write_checkpoint", exploding_write)
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(tr)
    with pytest.raises(IOError, match="disk on fire"):
        mgr.wait()


def test_latest_checkpoint_gcs_stale_tmps(tmp_path):
    d = str(tmp_path)
    (tmp_path / "ckpt-1").write_bytes(b"\x80junkpickle")
    (tmp_path / "ckpt-2.tmp").write_bytes(b"half")
    staging = tmp_path / "ckpt-3.tmp"
    staging.mkdir()
    assert latest_checkpoint(d).endswith("ckpt-1")
    assert not (tmp_path / "ckpt-2.tmp").exists()
    assert not staging.exists()


# ---------------------------------------------------------------------------
# anomaly policies
# ---------------------------------------------------------------------------
def test_anomaly_policy_validated():
    with pytest.raises(ValueError, match="raise|skip|rollback"):
        _linear_trainer(0, anomaly_policy="explode")


def test_anomaly_skip_matches_clean_run(monkeypatch):
    batches = _batches(6, seed=7)
    clean = _linear_trainer(11)
    for i, (x, y) in enumerate(batches):
        if i == 2:           # the batch the poisoned run will discard
            continue
        clean.train_step(x, y)

    monkeypatch.setenv("PADDLE_FAULT_NAN_STEP", "3")
    tr = _linear_trainer(11, anomaly_policy="skip")
    for x, y in batches:
        tr.train_step(x, y)
    st = tr.stats
    assert st["anomaly_policy"] == "skip"
    assert st["skipped_steps"] == 1
    assert tr._step_count == 6   # batches seen; optimizer saw only 5
    for n in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[n]),
                                   np.asarray(clean.params[n]),
                                   rtol=1e-6, atol=1e-7)


class _BombNet(nn.Layer):
    """Loss explodes to inf/NaN when an input row carries the sentinel
    value — a DATA-keyed anomaly (what rollback exists for: the policy
    rewinds the step counter, so a step-keyed injection would re-arm)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        out = self.fc(x)
        mask = (x > 900.0).astype("float32").max()  # 0.0 or 1.0
        # one in-range constant (a folded out-of-range product would be
        # inf and make 0*inf NaN on CLEAN batches); the squared-error
        # loss overflows it to inf only when the sentinel is present
        return out * (1.0 + mask * 3.0e38)


def _bomb_trainer(seed, anomaly_policy=None):
    paddle.seed(seed)
    model = _BombNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    return SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": 1}),
                       anomaly_policy=anomaly_policy)


def test_anomaly_rollback_matches_clean_run():
    batches = _batches(6, seed=9)
    bomb = np.full((4, 4), 1000.0, np.float32)

    clean = _bomb_trainer(13)
    for i, (x, y) in enumerate(batches):
        if i == 2:
            continue
        clean.train_step(x, y)

    tr = _bomb_trainer(13, anomaly_policy="rollback")
    for i, (x, y) in enumerate(batches):
        tr.train_step(bomb if i == 2 else x, y)
    st = tr.stats
    assert st["rollback_steps"] == 1
    assert tr._step_count == 5   # the rolled-back step never counted
    for n in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[n]),
                                   np.asarray(clean.params[n]),
                                   rtol=1e-6, atol=1e-7)


def test_anomaly_skip_state_survives_checkpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_NAN_STEP", "2")
    tr = _linear_trainer(15, anomaly_policy="skip")
    for x, y in _batches(3, seed=5):
        tr.train_step(x, y)
    assert tr.stats["skipped_steps"] == 1
    p = str(tmp_path / "ck")
    tr.save(p, manifest=True)
    monkeypatch.delenv("PADDLE_FAULT_NAN_STEP")
    tr2 = _linear_trainer(16, anomaly_policy="skip")
    tr2.load(p)
    assert tr2.stats["skipped_steps"] == 1  # counter rode the checkpoint


def test_skip_policy_adopts_legacy_checkpoint_step(tmp_path):
    """Loading a checkpoint written WITHOUT anomaly state (raise-policy
    or pre-resilience run) into a skip-policy trainer must seed the
    optimizer-visible counter from the global step — t=0 would rewind
    Adam bias correction to step 1."""
    tr = _linear_trainer(21)  # default raise policy: no anomaly state
    for x, y in _batches(4):
        tr.train_step(x, y)
    p = str(tmp_path / "legacy")
    tr.save(p)
    tr2 = _linear_trainer(22, anomaly_policy="skip")
    tr2.load(p)
    assert int(tr2._anomaly_state["t"]) == 4
    assert tr2.stats["skipped_steps"] == 0


def test_fp16_min_loss_scaling_floor(monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_NAN_STEP", "1")
    st = DistributedStrategy()
    st.amp = True
    st.amp_configs = {"use_bf16": False, "init_loss_scaling": 4.0,
                      "decr_every_n_nan_or_inf": 1,
                      "min_loss_scaling": 4.0}
    tr = _linear_trainer(17, strategy=st)
    tr.train_step(*_batches(1)[0])
    assert tr.last_step_skipped
    # old behavior would halve to 2.0; the floor holds it at 4.0
    assert tr.loss_scale == 4.0
    assert tr.stats["skipped_steps"] == 1


def test_eager_scaler_floor_and_counters_roundtrip():
    from paddle_tpu.amp import GradScaler
    sc = GradScaler(init_loss_scaling=8.0, decr_every_n_nan_or_inf=1,
                    min_loss_scaling=2.0)
    for _ in range(4):
        sc._found_inf = True
        sc.update()
    assert sc.get_loss_scaling() == 2.0   # 8 -> 4 -> 2 -> floor
    assert sc.state_dict()["total_bad_steps"] == 4

    sc._found_inf = True
    sc._unscaled = True

    class _Opt:
        def step(self):
            raise AssertionError("skipped step must not reach optimizer")

    sc.step(_Opt())
    sd = sc.state_dict()
    assert sd["skipped_steps"] == 1
    assert sd["min_loss_scaling"] == 2.0

    sc2 = GradScaler()
    sc2.load_state_dict(sd)
    assert sc2.state_dict()["skipped_steps"] == 1
    assert sc2.state_dict()["total_bad_steps"] == 4
    assert sc2._min_scale == 2.0


# ---------------------------------------------------------------------------
# dataloader worker restart
# ---------------------------------------------------------------------------
class _ArangeDS:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.full(2, i, np.float32)


def test_dataloader_bounded_worker_restart(monkeypatch):
    # worker 0 hard-exits (no cleanup) after producing 1 batch — with a
    # restart budget the epoch still completes, in order
    monkeypatch.setenv("PADDLE_FAULT_WORKER_KILL", "0:1")
    loader = DataLoader(_ArangeDS(), batch_size=2, shuffle=False,
                        num_workers=2, worker_restarts=1)
    if not loader._can_multiprocess():
        pytest.skip("shm ring unavailable")
    got = [np.asarray(b.data) for b in loader]
    ref = [np.stack([np.full(2, 2 * i, np.float32),
                     np.full(2, 2 * i + 1, np.float32)])
           for i in range(4)]
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


def test_dataloader_restart_budget_exhausted(monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_WORKER_KILL", "0:1")
    loader = DataLoader(_ArangeDS(), batch_size=2, shuffle=False,
                        num_workers=2, worker_restarts=0)
    if not loader._can_multiprocess():
        pytest.skip("shm ring unavailable")
    with pytest.raises(RuntimeError, match="died|exhausted"):
        list(loader)


# ---------------------------------------------------------------------------
# preemption: guard, in-process fit kill/resume, subprocess kill/resume
# ---------------------------------------------------------------------------
def test_preemption_guard_flags_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert not g.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert g.preempted
        assert g.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev


class _DS16:
    def __len__(self):
        return 32

    def __getitem__(self, i):
        r = np.random.RandomState(i)
        return (r.randn(16).astype(np.float32),
                np.array([i % 4], np.int64))


def _mlp_model(compiled):
    from paddle_tpu.hapi import Model
    from paddle_tpu.utils import unique_name
    paddle.seed(42)
    with unique_name.guard():
        net = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                            nn.Linear(16, 4))
    m = Model(net)
    kw = dict(mesh={"dp": 2}) if compiled else {}
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters()),
              nn.CrossEntropyLoss(), **kw)
    return m


def _fit(m, epochs, save_dir=None, auto_resume=False, callbacks=None):
    seen = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(round(float(logs["loss"]), 6))

    m.fit(_DS16(), batch_size=16, epochs=epochs, verbose=0,
          shuffle=False, save_dir=save_dir, auto_resume=auto_resume,
          callbacks=[Rec()] + (callbacks or []))
    return seen


@pytest.mark.parametrize("compiled", [
    pytest.param(True, marks=pytest.mark.slow),  # tier-1 wall budget
    False,
])
def test_fit_sigterm_mid_epoch_resumes_exactly(tmp_path, compiled):
    """Kill-and-resume e2e: SIGTERM lands mid-epoch (after global batch
    3 of 6), fit drains the step, checkpoints the mid-epoch position,
    and a fresh process-equivalent resumes at batch 4 — the combined
    loss curve equals the uninterrupted run's."""
    full = _fit(_mlp_model(compiled), 3)
    assert len(full) == 6

    class KillOnce(paddle.callbacks.Callback):
        count = 0

        def on_train_batch_end(self, step, logs=None):
            KillOnce.count += 1
            if KillOnce.count == 3:
                os.kill(os.getpid(), signal.SIGTERM)

    d = str(tmp_path / ("c" if compiled else "e"))
    m1 = _mlp_model(compiled)
    first = _fit(m1, 3, save_dir=d, auto_resume=True,
                 callbacks=[KillOnce()])
    assert m1.preempted
    np.testing.assert_allclose(first, full[:3], rtol=2e-4, atol=2e-5)

    m2 = _mlp_model(compiled)
    second = _fit(m2, 3, save_dir=d, auto_resume=True)
    assert not m2.preempted
    np.testing.assert_allclose(first + second, full, rtol=2e-4,
                               atol=2e-5)


_SUBPROC_TRAIN = """
import sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (SpmdTrainer, create_mesh,
                                    CheckpointManager, PreemptionGuard)

ckdir, mode = sys.argv[1], sys.argv[2]
N = 8


def build():
    paddle.seed(7)
    m = nn.Linear(6, 3)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=m.parameters())
    return SpmdTrainer(m, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": 1}))


rng = np.random.RandomState(0)
data = [(rng.randn(8, 6).astype(np.float32),
         rng.randn(8, 3).astype(np.float32)) for _ in range(N)]
tr = build()
mgr = CheckpointManager(ckdir, keep_last=2)
mgr.restore_latest(tr)
start = tr._step_count
losses = []
with PreemptionGuard() as g:
    for i in range(start, N):
        losses.append(float(tr.train_step(*data[i])))
        if g.preempted:
            mgr.save(tr, block=True)
            print("PREEMPTED", tr._step_count, flush=True)
            sys.exit(0)
mgr.save(tr, block=True)
mgr.wait()
if mode == "verify":
    assert start > 0, "resume did not find a checkpoint"
    ref = build()
    ref_losses = [float(ref.train_step(*b)) for b in data]
    np.testing.assert_allclose(losses, ref_losses[start:], rtol=2e-4,
                               atol=2e-5)
print("DONE", tr._step_count, flush=True)
"""


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_subprocess_sigterm_kill_and_resume(tmp_path):
    """True preemption: the child delivers itself SIGTERM mid-train
    (deterministically, via the fault harness), exits 0 after a final
    synchronous checkpoint, and a second process resumes at the
    checkpointed step with losses matching an uninterrupted run."""
    script = tmp_path / "train.py"
    script.write_text(_SUBPROC_TRAIN)
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_FAULT_NAN_STEP", None)

    env1 = dict(env)
    env1["PADDLE_FAULT_SIGTERM_STEP"] = "4"
    p1 = subprocess.run([sys.executable, str(script), ckdir, "train"],
                        env=env1, capture_output=True, text=True,
                        timeout=240)
    assert p1.returncode == 0, p1.stderr
    assert "PREEMPTED 4" in p1.stdout
    ck = latest_checkpoint(ckdir)
    assert ck is not None and validate_checkpoint(ck)

    p2 = subprocess.run([sys.executable, str(script), ckdir, "verify"],
                        env=env, capture_output=True, text=True,
                        timeout=240)
    assert p2.returncode == 0, p2.stderr
    assert "DONE 8" in p2.stdout


def test_auto_resume_falls_back_past_corrupt_newest(tmp_path):
    """hapi auto-resume: the newest auto checkpoint is truncated (crash
    during upload); fit restores the previous valid epoch instead of
    dying."""
    d = str(tmp_path / "fb")
    m1 = _mlp_model(True)
    _fit(m1, 2, save_dir=d, auto_resume=True)
    auto = os.path.join(d, "auto")
    cks = sorted((n for n in os.listdir(auto) if n.startswith("ckpt-")),
                 key=lambda n: int(n[len("ckpt-"):]))
    assert len(cks) == 2
    entry = os.path.join(auto, cks[-1], "state.pdtrainer")
    with open(entry, "r+b") as f:
        f.truncate(32)
    m2 = _mlp_model(True)
    # resumes from the older valid snapshot (epoch 0) -> retrains epoch
    # 1 and runs epoch 2: three epochs of batches, no crash
    seen = _fit(m2, 3, save_dir=d, auto_resume=True)
    assert len(seen) == 4  # epochs 1 and 2, two batches each
