"""BASELINE config 1: LeNet/MNIST-shape end-to-end (reference test
strategy: tests/book + hapi tests in python/paddle/tests/test_model.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.metric as metric
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.vision as vision
from paddle_tpu.vision.datasets import FakeData


@pytest.fixture(scope="module")
def data():
    train = FakeData(size=256, image_shape=(1, 28, 28), num_classes=10)
    test = FakeData(size=64, image_shape=(1, 28, 28), num_classes=10,
                    seed=1)
    return train, test


class TestLeNetE2E:
    @pytest.mark.slow  # tier-1 wall budget: heaviest in file
    def test_fit_evaluate_predict_save_load(self, data, tmp_path):
        train, test = data
        paddle.seed(42)
        lenet = vision.LeNet()
        model = paddle.Model(lenet)
        model.prepare(
            opt.Adam(learning_rate=1e-3, parameters=lenet.parameters()),
            nn.CrossEntropyLoss(), metric.Accuracy())
        model.fit(train, epochs=4, batch_size=64, verbose=0)
        res = model.evaluate(test, batch_size=64, verbose=0)
        assert res["acc"] > 0.8, res

        preds = model.predict(test, batch_size=32, stack_outputs=True)
        assert preds[0].shape == (64, 10)

        path = str(tmp_path / "ck" / "best")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")

        lenet2 = vision.LeNet()
        model2 = paddle.Model(lenet2)
        model2.prepare(
            opt.Adam(learning_rate=1e-3, parameters=lenet2.parameters()),
            nn.CrossEntropyLoss(), metric.Accuracy())
        model2.load(path)
        res2 = model2.evaluate(test, batch_size=64, verbose=0)
        assert abs(res2["acc"] - res["acc"]) < 1e-6

    def test_early_stopping_and_history(self, data):
        train, _ = data
        paddle.seed(0)
        lenet = vision.LeNet()
        model = paddle.Model(lenet)
        model.prepare(
            opt.Adam(learning_rate=1e-3, parameters=lenet.parameters()),
            nn.CrossEntropyLoss())
        hist = paddle.callbacks.History()
        es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0,
                                            mode="min")
        model.fit(train, epochs=3, batch_size=64, verbose=0,
                  callbacks=[hist, es])
        assert "loss" in hist.history and len(hist.history["loss"]) >= 1

    def test_summary_and_flops(self):
        lenet = vision.LeNet()
        info = paddle.summary(lenet, (1, 1, 28, 28))
        assert info["total_params"] == 61610
        fl = paddle.flops(lenet, (1, 1, 28, 28))
        assert fl > 0


class TestModelZoo:
    @pytest.mark.parametrize("ctor,ch,sz,n", [
        (lambda: vision.resnet18(num_classes=7), 3, 32, 7),
        pytest.param(lambda: vision.mobilenet_v2(num_classes=5), 3, 32, 5,
                     marks=pytest.mark.slow),  # tier-1 wall budget
    ])
    def test_forward_shapes(self, ctor, ch, sz, n):
        m = ctor()
        m.eval()
        x = paddle.to_tensor(
            np.random.randn(2, ch, sz, sz).astype(np.float32))
        assert m(x).shape == [2, n]

    def test_resnet50_param_count(self):
        m = vision.resnet50(num_classes=1000)
        total = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert total == 25_557_032  # torchvision/paddle resnet50 count

    def test_vgg_structure(self):
        m = vision.vgg11(num_classes=10)
        m.eval()
        x = paddle.to_tensor(
            np.random.randn(1, 3, 224, 224).astype(np.float32))
        assert m(x).shape == [1, 10]

    @pytest.mark.slow  # tier-1 wall budget: heaviest in file
    def test_train_resnet_step(self):
        m = vision.resnet18(num_classes=4)
        o = opt.Momentum(0.01, parameters=m.parameters())
        x = paddle.to_tensor(np.random.randn(4, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        before = m.conv1.weight.numpy().copy()
        loss = nn.functional.cross_entropy(m(x), y)
        loss.backward()
        o.step()
        assert not np.allclose(before, m.conv1.weight.numpy())


class TestTransformsAndDatasets:
    def test_transform_pipeline(self):
        from paddle_tpu.vision.transforms import (
            Compose, Normalize, RandomHorizontalFlip, Resize, ToTensor)
        t = Compose([Resize(32), RandomHorizontalFlip(0.5),
                     ToTensor(), Normalize([0.5], [0.5])])
        img = np.random.rand(28, 28, 1).astype(np.float32)
        out = t(img)
        assert out.shape == (1, 32, 32)

    def test_fakedata_distribution_shared(self):
        a = FakeData(size=10, seed=0)
        b = FakeData(size=10, seed=5)
        np.testing.assert_array_equal(a._base, b._base)

    def test_dataset_folder(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                np.save(str(d / f"{i}.npy"),
                        np.random.rand(4, 4, 3).astype(np.float32))
        ds = DatasetFolder(str(tmp_path))
        assert len(ds) == 6
        img, label = ds[0]
        assert img.shape == (4, 4, 3) and label in (0, 1)


class TestMetrics:
    def test_accuracy_topk(self):
        m = metric.Accuracy(topk=(1, 2))
        pred = paddle.to_tensor(np.array(
            [[0.1, 0.7, 0.2], [0.5, 0.3, 0.2]], np.float32))
        lab = paddle.to_tensor(np.array([[1], [2]]))
        correct = m.compute(pred, lab)
        m.update(correct)
        res = m.accumulate()
        assert res[0] == pytest.approx(0.5)  # top1: first right, second no
        assert res[1] == pytest.approx(0.5)  # top2: [1 in top2? yes][2? no]

    def test_precision_recall(self):
        p = metric.Precision()
        r = metric.Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert p.accumulate() == pytest.approx(2 / 3)
        assert r.accumulate() == pytest.approx(2 / 3)

    def test_auc_perfect(self):
        a = metric.Auc()
        preds = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
        # prob of class1 column used
        labels = np.array([0, 0, 1, 1])
        a.update(preds, labels)
        assert a.accumulate() == pytest.approx(1.0, abs=1e-3)

    def test_functional_accuracy(self):
        acc = metric.accuracy(
            paddle.to_tensor(np.array([[0.1, 0.9], [0.9, 0.1]], np.float32)),
            paddle.to_tensor(np.array([[1], [1]])))
        assert float(acc) == pytest.approx(0.5)
