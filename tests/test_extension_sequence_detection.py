"""Custom-op extension API, sequence (LoD) op family, detection ops.

References: fluid/extension (PD_BUILD_OP custom operators),
operators/sequence_ops/ (masked-dense equivalents),
operators/detection/ (iou/nms/box_coder/mAP).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.extension import register_op, get_op, list_ops
from paddle_tpu.tensor.sequence import (
    sequence_concat, sequence_enumerate, sequence_expand,
    sequence_pad, sequence_pool, sequence_reverse, sequence_slice,
    sequence_softmax, sequence_unpad)
from paddle_tpu.vision import ops as V


# ---- custom ops -----------------------------------------------------------
def test_custom_op_forward_and_builtin_grad():
    op = register_op("t_square", lambda x: x * x)
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = op(x)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [4.0, 6.0])
    assert "t_square" in list_ops()
    assert get_op("t_square") is op


def test_custom_op_custom_backward():
    calls = []

    def fwd(x):
        return jnp.exp(x)

    def bwd(inputs, outputs, cots):
        calls.append(1)
        (x,) = inputs
        return (cots * outputs * 2.0,)  # deliberately 2x the true grad

    op = register_op("t_exp2grad", fwd, backward=bwd)
    x = paddle.to_tensor(np.array([0.0, 1.0], np.float32),
                         stop_gradient=False)
    op(x).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data),
                               2.0 * np.exp([0.0, 1.0]), rtol=1e-6)
    assert calls  # the registered backward actually ran


def test_custom_op_in_jit_and_layer():
    op = register_op("t_gelu_ish", lambda x: x * jnp.tanh(x))

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(4, 4)

        def forward(self, x):
            return op(self.fc(x))

    net = Net()
    sfn = paddle.jit.to_static(net)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(np.asarray(sfn(x).data),
                               np.asarray(net(x).data), rtol=1e-6)


def test_custom_op_duplicate_name_raises():
    register_op("t_dup", lambda x: x)
    with pytest.raises(ValueError):
        register_op("t_dup", lambda x: x)


# ---- sequence ops ---------------------------------------------------------
def _ragged():
    x = np.zeros((2, 4, 3), np.float32)
    x[0, :3] = np.arange(9).reshape(3, 3)
    x[1, :2] = np.arange(6).reshape(2, 3) + 10
    return paddle.to_tensor(x), paddle.to_tensor(
        np.array([3, 2], np.int64))


def test_sequence_pool_types():
    x, ln = _ragged()
    xa = np.asarray(x.data)
    np.testing.assert_allclose(
        np.asarray(sequence_pool(x, ln, "sum").data),
        np.stack([xa[0, :3].sum(0), xa[1, :2].sum(0)]))
    np.testing.assert_allclose(
        np.asarray(sequence_pool(x, ln, "mean").data),
        np.stack([xa[0, :3].mean(0), xa[1, :2].mean(0)]))
    np.testing.assert_allclose(
        np.asarray(sequence_pool(x, ln, "max").data),
        np.stack([xa[0, :3].max(0), xa[1, :2].max(0)]))
    np.testing.assert_allclose(
        np.asarray(sequence_pool(x, ln, "last").data),
        np.stack([xa[0, 2], xa[1, 1]]))
    np.testing.assert_allclose(
        np.asarray(sequence_pool(x, ln, "first").data),
        np.stack([xa[0, 0], xa[1, 0]]))


def test_sequence_softmax_masks_padding():
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    ln = paddle.to_tensor(np.array([2, 4], np.int64))
    p = np.asarray(sequence_softmax(x, ln).data)
    np.testing.assert_allclose(p[0], [0.5, 0.5, 0, 0], atol=1e-6)
    np.testing.assert_allclose(p[1], [0.25] * 4, atol=1e-6)


def test_sequence_reverse_prefix_only():
    x, ln = _ragged()
    r = np.asarray(sequence_reverse(x, ln).data)
    xa = np.asarray(x.data)
    np.testing.assert_array_equal(r[0, :3], xa[0, :3][::-1])
    np.testing.assert_array_equal(r[0, 3], xa[0, 3])  # padding unmoved
    np.testing.assert_array_equal(r[1, :2], xa[1, :2][::-1])


def test_sequence_pad_unpad_roundtrip():
    seqs = [np.arange(3, dtype=np.float32),
            np.arange(5, dtype=np.float32) + 10]
    padded, ln = sequence_pad(seqs, pad_value=-1.0)
    assert padded.shape == [2, 5]
    assert np.asarray(padded.data)[0, 3] == -1.0
    back = sequence_unpad(padded, ln)
    for a, b in zip(seqs, back):
        np.testing.assert_array_equal(a, b)


def test_sequence_expand_concat_enumerate_slice():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    ref = paddle.to_tensor(np.array([2, 3], np.int64))
    ex = np.asarray(sequence_expand(x, ref).data)
    np.testing.assert_array_equal(ex.reshape(-1), [1, 1, 2, 2, 2])

    a = paddle.to_tensor(np.array([[1, 2, 0], [3, 0, 0]], np.float32))
    la = paddle.to_tensor(np.array([2, 1], np.int64))
    b = paddle.to_tensor(np.array([[7, 0], [8, 9]], np.float32))
    lb = paddle.to_tensor(np.array([1, 2], np.int64))
    cat, lc = sequence_concat([a, b], [la, lb])
    np.testing.assert_array_equal(np.asarray(lc.data), [3, 3])
    np.testing.assert_array_equal(np.asarray(cat.data),
                                  [[1, 2, 7], [3, 8, 9]])

    en = np.asarray(sequence_enumerate(
        paddle.to_tensor(np.array([[1, 2, 3]], np.int64)), 2).data)
    np.testing.assert_array_equal(en[0], [[1, 2], [2, 3], [3, 0]])

    s, ls = sequence_slice(cat, lc,
                           np.array([1, 0], np.int64),
                           np.array([2, 1], np.int64))
    np.testing.assert_array_equal(np.asarray(s.data), [[2, 7], [3, 0]])
    np.testing.assert_array_equal(np.asarray(ls.data), [2, 1])


def test_sequence_pool_differentiable():
    x, ln = _ragged()
    x.stop_gradient = False
    sequence_pool(x, ln, "mean").sum().backward()
    g = np.asarray(x.grad.data)
    np.testing.assert_allclose(g[0, :3], 1 / 3, atol=1e-6)
    np.testing.assert_allclose(g[0, 3], 0.0)  # padding gets no grad
    np.testing.assert_allclose(g[1, :2], 1 / 2, atol=1e-6)


# ---- detection ops --------------------------------------------------------
def test_box_iou_and_area():
    a = paddle.to_tensor(np.array([[0, 0, 2, 2]], np.float32))
    b = paddle.to_tensor(np.array([[1, 1, 3, 3], [4, 4, 5, 5]],
                                  np.float32))
    iou = np.asarray(V.box_iou(a, b).data)
    np.testing.assert_allclose(iou, [[1 / 7, 0.0]], atol=1e-6)
    np.testing.assert_allclose(np.asarray(V.box_area(b).data), [4.0, 1.0])


def test_box_coder_roundtrip():
    priors = paddle.to_tensor(
        np.array([[0, 0, 4, 4], [2, 2, 6, 8]], np.float32))
    gt = paddle.to_tensor(
        np.array([[1, 1, 3, 5], [0, 0, 8, 8]], np.float32))
    enc = V.box_coder(priors, gt, "encode_center_size")
    dec = V.box_coder(priors, enc, "decode_center_size")
    np.testing.assert_allclose(np.asarray(dec.data), np.asarray(gt.data),
                               rtol=1e-5, atol=1e-5)


def test_nms_suppresses_overlaps():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    kept = np.asarray(V.nms(boxes, scores, iou_threshold=0.5).data)
    np.testing.assert_array_equal(sorted(kept.tolist()), [0, 2])


def test_multiclass_nms_and_map():
    boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([[0.9, 0.05, 0.8],    # class 0
                       [0.1, 0.95, 0.02]],  # class 1
                      np.float32)
    det = np.asarray(V.multiclass_nms(boxes, scores,
                                      score_threshold=0.5).data)
    assert det.shape[1] == 6
    classes = det[:, 0].astype(int).tolist()
    assert sorted(classes) == [0, 0, 1]

    # perfect detections -> mAP 1.0
    gt_b = [np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)]
    gt_l = [np.array([0, 0], np.int64)]
    dets = [np.array([[0, 0.9, 0, 0, 10, 10],
                      [0, 0.8, 20, 20, 30, 30]], np.float32)]
    assert V.detection_map(dets, gt_b, gt_l) == pytest.approx(1.0)
    # one spurious extra detection lowers it
    dets2 = [np.vstack([dets[0],
                        [0, 0.95, 50, 50, 60, 60]]).astype(np.float32)]
    assert V.detection_map(dets2, gt_b, gt_l) < 1.0


def test_prior_box_and_anchors_shapes():
    pb = V.prior_box(2, 3, 100, 150, min_sizes=(30,), max_sizes=(60,),
                     aspect_ratios=(1.0, 2.0), flip=True, clip=True)
    assert pb.shape[:2] == [2, 3] and pb.shape[3] == 4
    a = np.asarray(pb.data)
    assert (a >= 0).all() and (a <= 1).all()
    an = V.generate_anchors(4, 4, stride=16, sizes=(32,),
                            aspect_ratios=(1.0,))
    assert an.shape == [4, 4, 1, 4]
    # centered on the stride grid
    np.testing.assert_allclose(np.asarray(an.data)[0, 0, 0],
                               [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_custom_op_none_grad_for_integer_input():
    """Review fix: a None gradient for an int input must produce the
    float0 cotangent convention, not int zeros."""
    def fwd(x, idx):
        return jnp.take(x, idx, axis=0)

    def bwd(inputs, outputs, cots):
        x, idx = inputs
        gx = jnp.zeros_like(x).at[idx].add(cots)
        return (gx, None)  # index input: non-differentiable

    op = register_op("t_gather_noneg", fwd, backward=bwd)
    x = paddle.to_tensor(np.arange(4, dtype=np.float32),
                         stop_gradient=False)
    idx = paddle.to_tensor(np.array([1, 3], np.int32))
    op(x, idx).sum().backward()
    np.testing.assert_array_equal(np.asarray(x.grad.data), [0, 1, 0, 1])


def test_py_func_forward_and_backward():
    """py_func (reference py_func_op.cc): arbitrary numpy code as an op
    with an optional custom numpy backward, working through the tape."""
    import scipy.special as sp
    from paddle_tpu.extension import py_func

    def host(x):
        return sp.erf(x)

    def host_grad(inputs, outputs, gs):
        (x,) = inputs
        (g,) = gs
        return g * 2.0 / np.sqrt(np.pi) * np.exp(-x * x)

    x = paddle.to_tensor(np.array([0.0, 0.5, 1.0], np.float32),
                         stop_gradient=False)
    y = py_func(host, x, ((3,), "float32"), backward_func=host_grad)
    np.testing.assert_allclose(np.asarray(y.data),
                               sp.erf([0.0, 0.5, 1.0]), rtol=1e-6)
    y.sum().backward()
    want = 2.0 / np.sqrt(np.pi) * np.exp(-np.array([0.0, 0.25, 1.0]))
    np.testing.assert_allclose(np.asarray(x.grad.data), want, rtol=1e-5)


def test_py_func_multi_output_under_jit():
    import jax
    from paddle_tpu.extension import py_func

    def host(a):
        return a + 1, a * 2

    def run(arr):
        o1, o2 = py_func(host, paddle.to_tensor(arr),
                         [((2,), "float32"), ((2,), "float32")])
        return o1.data + o2.data

    # works eagerly and inside jit (pure_callback survives tracing)
    a = np.array([1.0, 2.0], np.float32)
    np.testing.assert_allclose(np.asarray(run(a)), [4.0, 7.0])
    jitted = jax.jit(lambda v: run(np.asarray(v)) if False else v)
    # direct jit over the jnp-level op:
    import jax.numpy as jnp
    out = jax.jit(lambda v: py_func(host, paddle.to_tensor(v),
                                    [((2,), "float32"),
                                     ((2,), "float32")])[0].data)(
        jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(out), [2.0, 3.0])
