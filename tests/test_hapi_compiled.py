"""hapi Model + fleet wiring of the compiled trainer (VERDICT r2 #3).

Reference chain being replaced: Model.fit -> CompiledProgram ->
ParallelExecutor (hapi/model.py:810,1244 + fleet_base.py:1066). Done
criterion: LeNet Model.fit on the 8-CPU mesh trains compiled with a loss
curve identical to the eager loop.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.hapi import Model
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.models import LeNet


class _Digits:
    """Tiny synthetic MNIST-shaped dataset."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 1, 28, 28).astype(np.float32)
        self.y = rng.randint(0, 10, (n, 1)).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _losses_from_fit(model, data, epochs=2, bs=16):
    seen = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(float(logs["loss"]))

    model.fit(data, batch_size=bs, epochs=epochs, verbose=0,
              shuffle=False, callbacks=[Rec()])
    return seen


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_lenet_fit_compiled_matches_eager():
    data = _Digits()

    paddle.seed(7)
    m_eager = Model(LeNet())
    m_eager.prepare(paddle.optimizer.Adam(
        learning_rate=1e-3, parameters=m_eager.parameters()),
        nn.CrossEntropyLoss())
    eager = _losses_from_fit(m_eager, data)
    assert not m_eager.compiled

    paddle.seed(7)
    m_comp = Model(LeNet())
    m_comp.prepare(paddle.optimizer.Adam(
        learning_rate=1e-3, parameters=m_comp.parameters()),
        nn.CrossEntropyLoss(), mesh={"dp": 8})
    comp = _losses_from_fit(m_comp, data)
    assert m_comp.compiled
    # one executable per step, state sharded on the mesh
    tr = m_comp._trainer
    assert tr is not None and tr.step_executable is not None
    leaf = next(iter(tr.params.values()))
    assert len(leaf.sharding.device_set) == 8
    np.testing.assert_allclose(comp, eager, rtol=2e-4, atol=2e-5)


def test_compiled_fit_with_metrics_and_eval():
    data = _Digits(48)
    paddle.seed(1)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters()),
              nn.CrossEntropyLoss(), metrics=Accuracy(),
              mesh={"dp": 8})
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    res = m.evaluate(data, batch_size=16, verbose=0)
    assert "loss" in res and "acc" in res
    preds = m.predict(data, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (48, 10)


def test_compiled_fit_with_strategy_amp_recompute_free():
    """strategy= alone (no mesh) also selects the compiled path."""
    data = _Digits(32)
    paddle.seed(3)
    st = DistributedStrategy()
    st.amp = True
    m = Model(LeNet())
    m.prepare(paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), strategy=st)
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    assert m.compiled and m._trainer.amp_enabled


def test_fleet_distributed_model_builds_trainer():
    """fleet.distributed_optimizer strategy reaches the compiled trainer
    through fleet.distributed_model (reference fleet.minimize chain)."""
    from paddle_tpu.distributed import fleet
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    st = DistributedStrategy()
    st.sharding = True
    st.sharding_configs = {"stage": 2}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-3,
                              parameters=net.parameters()), st)
    loss_fn = lambda out, lab: nn.functional.cross_entropy(out, lab)
    tr = fleet.distributed_model(net, opt, loss_fn,
                                 mesh=create_mesh({"dp": 8}))
    assert tr.zero_stage == 2
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype(np.float32)
    y = rng.randint(0, 10, (16,)).astype(np.int64)
    l0 = float(tr.train_step(x, y))
    l5 = [float(tr.train_step(x, y)) for _ in range(5)][-1]
    assert l5 < l0


def test_fleet_optimizer_through_model_prepare():
    """Model.prepare picks the strategy straight off a
    fleet.DistributedOptimizer (no explicit strategy kwarg)."""
    from paddle_tpu.distributed import fleet
    data = _Digits(32)
    paddle.seed(9)
    m = Model(LeNet())
    st = DistributedStrategy()
    st.recompute = False
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-3,
                              parameters=m.parameters()), st)
    m.prepare(opt, nn.CrossEntropyLoss(), mesh={"dp": 4})
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    assert m.compiled


def test_compiled_amp_eval_casts_inputs():
    """Verify regression: eval/predict under bf16 AMP must cast floating
    inputs like the train path (conv is dtype-strict)."""
    data = _Digits(32)
    paddle.seed(11)
    st = DistributedStrategy()
    st.amp = True
    m = Model(LeNet())
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), metrics=Accuracy(), mesh={"dp": 2},
              strategy=st)
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    res = m.evaluate(data, batch_size=16, verbose=0)
    assert np.isfinite(res["loss"])
    preds = m.predict(data, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (32, 10)


def test_load_restores_compiled_trainer(tmp_path):
    """Review regression: Model.load after the trainer exists must adopt
    the loaded weights (save/load round trip reproduces outputs)."""
    data = _Digits(32)
    paddle.seed(13)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 2})
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    p = str(tmp_path / "ck")
    m.save(p)
    before = m.predict(data, batch_size=16, stack_outputs=True)[0]
    m.fit(data, batch_size=16, epochs=1, verbose=0)  # drift the weights
    drifted = m.predict(data, batch_size=16, stack_outputs=True)[0]
    assert not np.allclose(drifted, before)
    m.load(p)
    restored = m.predict(data, batch_size=16, stack_outputs=True)[0]
    np.testing.assert_allclose(restored, before, rtol=1e-5, atol=1e-6)


def test_re_prepare_rebuilds_trainer():
    """Review regression: a second prepare() must not reuse the trainer
    built for the first optimizer/loss."""
    data = _Digits(32)
    paddle.seed(17)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 2})
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    t1 = m._trainer
    m.prepare(paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 2})
    assert m._trainer is None
    m.fit(data, batch_size=16, epochs=1, verbose=0)
    assert m._trainer is not t1
    from paddle_tpu.optimizer import SGD
    assert isinstance(m._trainer.optimizer, SGD)
