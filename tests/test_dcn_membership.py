"""Multi-slice DCN tier (ISSUE 17): the dcn mesh axis, hierarchical
data parallelism, slice membership + the DCN collective guard, the
ici/dcn comm split, in-memory mid-run mesh reform, and the doctor's
slice-unhealthy / dcn-bound verdicts.

Done criteria exercised here:
- create_mesh grows a leading ``dcn`` axis (arg or PADDLE_TPU_DCN_SLICES)
  and PADDLE_FAULT_MESH_SHRINK clamps at WHOLE-slice granularity;
- comm_stats splits collective bytes into ICI (within a slice) vs DCN
  (replica groups spanning slices) for both explicit and iota
  replica_groups forms;
- SliceMembership's poll() transitions a stale slice to dead exactly
  once, PADDLE_FAULT_SLICE_DOWN swallows the armed slice's beats, and
  the per-slice heartbeat-age gauge lands in the metrics registry;
- DcnCollectiveGuard retries transient errors with backoff (feeding
  the watchdog through every wait) and escalates a persistently dead
  peer to a membership change (SliceLostError) instead of hanging;
- a 2-slice trainer losing a slice mid-run re-forms IN MEMORY onto the
  survivor, resumes with loss parity vs the uninterrupted run, and
  does not recompile after the first post-reform step;
- CheckpointManager.save() queues behind an in-flight reform;
- the doctor reads the new signals (slice-unhealthy, dcn-bound).
"""
import os
import threading

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.distributed import (SpmdTrainer, create_mesh,
                                    dcn_slice_count, slice_size)
from paddle_tpu.distributed.membership import (CallbackTransport,
                                               DcnCollectiveGuard,
                                               FileTransport,
                                               SliceLostError,
                                               SliceMembership)
from paddle_tpu.testing import faults

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for k in ("PADDLE_FAULT_SLICE_DOWN", "PADDLE_FAULT_DCN_DELAY_MS",
              "PADDLE_FAULT_MESH_SHRINK", "PADDLE_TPU_DCN_SLICES",
              "PADDLE_TPU_SLICE_HB_DIR"):
        monkeypatch.delenv(k, raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# mesh: the dcn axis
# ---------------------------------------------------------------------------
def test_create_mesh_dcn_axis_arg_and_env(monkeypatch):
    m = create_mesh({"dp": 4}, dcn_slices=2)
    assert m.axis_names[0] == "dcn"
    assert dict(m.shape) == {"dcn": 2, "dp": 4}
    assert dcn_slice_count(m) == 2 and slice_size(m) == 4

    flat = create_mesh({"dp": 8})
    assert dcn_slice_count(flat) == 1 and slice_size(flat) == 8

    monkeypatch.setenv("PADDLE_TPU_DCN_SLICES", "2")
    m2 = create_mesh({"dp": 4})
    assert dict(m2.shape) == {"dcn": 2, "dp": 4}


def test_mesh_shrink_is_slice_granular(monkeypatch):
    # 8 devices, 2 slices of 4: a shrink to 6 cannot keep half a slice
    # — it clamps DOWN to one whole slice (4 devices, dcn=1)
    monkeypatch.setenv("PADDLE_FAULT_MESH_SHRINK", "6")
    m = create_mesh({"dp": 4}, dcn_slices=2)
    assert m.devices.size == 4
    assert dict(m.shape) == {"dcn": 1, "dp": 4}
    # a flat mesh keeps the old chip-granular behavior
    flat = create_mesh({"dp": -1})
    assert flat.devices.size == 6


# ---------------------------------------------------------------------------
# comm_stats: the ici/dcn byte split
# ---------------------------------------------------------------------------
def test_comm_split_explicit_groups():
    from paddle_tpu.utils.comm_stats import parse_hlo_collectives
    hlo = """
  a = f32[256]{0} all-reduce(b), replica_groups={{0,1,2,3},{4,5,6,7}}
  c = f32[256]{0} all-reduce(d), replica_groups={{0,4},{1,5},{2,6},{3,7}}
"""
    out = parse_hlo_collectives(hlo, slice_size=4)
    assert out["ici_bytes"] == 1024 and out["dcn_bytes"] == 1024
    ar = out["by_op"]["all-reduce"]
    assert ar["ici_bytes"] == 1024 and ar["dcn_bytes"] == 1024
    # without slice_size the split is absent and totals are unchanged
    plain = parse_hlo_collectives(hlo)
    assert "ici_bytes" not in plain and plain["bytes"] == 2048


def test_comm_split_iota_groups():
    from paddle_tpu.utils.comm_stats import parse_hlo_collectives
    # [2,4]<=[8]: rows {0..3},{4..7} — within-slice at slice_size=4
    hlo_ici = ("  a = f32[100]{0} all-reduce(b), "
               "replica_groups=[2,4]<=[8]\n")
    out = parse_hlo_collectives(hlo_ici, slice_size=4)
    assert out["ici_bytes"] == 400 and out["dcn_bytes"] == 0
    # [4,2]<=[2,4]T(1,0): rows {0,4},{1,5},... — every group crosses
    hlo_dcn = ("  a = f32[100]{0} all-reduce(b), "
               "replica_groups=[4,2]<=[2,4]T(1,0)\n")
    out2 = parse_hlo_collectives(hlo_dcn, slice_size=4)
    assert out2["ici_bytes"] == 0 and out2["dcn_bytes"] == 400
    # no replica_groups = one global group = crosses slices
    hlo_glob = "  a = f32[100]{0} all-reduce(b)\n"
    out3 = parse_hlo_collectives(hlo_glob, slice_size=4)
    assert out3["dcn_bytes"] == 400


# ---------------------------------------------------------------------------
# membership: heartbeats, failure detection, fault arming
# ---------------------------------------------------------------------------
def test_membership_poll_transitions_once():
    t = {"now": 100.0}
    m = SliceMembership(2, transport=CallbackTransport(), timeout_s=1.0,
                        clock=lambda: t["now"])
    seen = []
    m.on_change(seen.append)
    assert m.poll() == []                      # seeded alive at init
    m.beat_all()
    t["now"] += 0.5
    assert m.poll() == [] and m.dead_slices() == set()
    m.beat(0)                                  # only slice 0 beats
    t["now"] += 0.8
    evs = m.poll()                             # slice 1 age 1.3 > 1.0
    assert [e["slice"] for e in evs] == [1]
    assert evs[0]["kind"] == "slice_lost" and evs[0]["alive"] == [0]
    assert m.dead_slices() == {1} and m.alive_slices() == [0]
    assert seen == evs
    assert m.poll() == []                      # once per transition
    st = m.stats()
    assert st["dead"] == [1] and st["n_slices"] == 2
    assert st["heartbeat_ages"][1] >= 1.3


def test_membership_fault_swallows_beats(monkeypatch):
    t = {"now": 0.0}
    m = SliceMembership(2, transport=CallbackTransport(), timeout_s=1.0,
                        clock=lambda: t["now"])
    monkeypatch.setenv("PADDLE_FAULT_SLICE_DOWN", "1:3")
    assert m.beat(1, step=2) is True           # before the armed step
    assert m.beat(1, step=3) is False          # armed: swallowed
    assert m.beat(0, step=3) is True           # other slices unaffected
    t["now"] += 2.0
    m.beat_all(step=5)                         # slice 1 stays silent
    evs = m.poll()
    assert [e["slice"] for e in evs] == [1]


def test_membership_file_transport(tmp_path):
    t = {"now": 1000.0}
    tr = FileTransport(str(tmp_path))
    m = SliceMembership(2, transport=tr, timeout_s=5.0,
                        clock=lambda: t["now"])
    # the documented on-disk format: one slice.<id> file, mtime = beat
    assert sorted(os.listdir(tmp_path)) == ["slice.0", "slice.1"]
    assert os.path.getmtime(tmp_path / "slice.1") == 1000.0
    t["now"] = 1004.0
    m.beat(0)
    ages = m.ages()
    assert ages[0] == 0.0 and ages[1] == 4.0
    t["now"] = 1006.0
    assert [e["slice"] for e in m.poll()] == [1]


def test_membership_env_transport_and_gauge(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SLICE_HB_DIR", str(tmp_path))
    m = SliceMembership(2, timeout_s=5.0)
    assert isinstance(m.transport, FileTransport)
    m.poll()
    from paddle_tpu import observability
    from paddle_tpu.observability import metrics
    snap = metrics.snapshot()
    assert "slice_heartbeat_age_s" in snap
    series = snap["slice_heartbeat_age_s"]["series"]
    assert {s["labels"]["slice"] for s in series} >= {"0", "1"}
    # and through the one-call package surface
    assert "slice_heartbeat_age_s" in observability.snapshot()["metrics"]


# ---------------------------------------------------------------------------
# the DCN collective guard
# ---------------------------------------------------------------------------
def test_guard_retries_then_succeeds_and_feeds_watchdog():
    calls, beats, naps = [], [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("dcn transient")
        return "ok"

    g = DcnCollectiveGuard(retries=4, timeout_s=10.0,
                           backoff_base_ms=1.0, backoff_max_ms=2.0,
                           on_beat=lambda: beats.append(1),
                           sleep=naps.append)
    assert g.run(flaky, label="allreduce") == "ok"
    assert len(calls) == 3 and g.retries_used == 2
    assert g.escalations == 0
    # the watchdog was fed on every attempt AND through each backoff
    assert len(beats) >= 4 and len(naps) >= 2


def test_guard_backoff_grows_and_is_deterministic(monkeypatch):
    from paddle_tpu.distributed import membership as mem

    class FakeTime:
        # stands in for mem.time so the backoff's chunked deadline loop
        # runs on a virtual clock — the recorded naps ARE the schedule
        def __init__(self):
            self.t = 0.0

        def monotonic(self):
            return self.t

        def time(self):
            return self.t

        def sleep(self, s):
            self.t += s

    def always_fail():
        raise OSError("dcn down")

    def run_once():
        fake = FakeTime()
        monkeypatch.setattr(mem, "time", fake)
        naps = []

        def nap(s):
            naps.append(s)
            fake.sleep(s)

        g = DcnCollectiveGuard(membership=None, retries=3,
                               backoff_base_ms=10.0,
                               backoff_max_ms=10_000.0, sleep=nap)
        with pytest.raises(SliceLostError):
            g.run(always_fail, label="x")
        return naps

    naps_a, naps_b = run_once(), run_once()
    # same seeds → identical jittered schedule; exponential growth
    assert naps_a == naps_b and len(naps_a) >= 2
    assert sum(naps_a[1:]) > naps_a[0]


def test_guard_escalates_to_membership_change():
    t = {"now": 0.0}
    m = SliceMembership(2, transport=CallbackTransport(), timeout_s=60.0,
                        clock=lambda: t["now"])
    changed = []
    m.on_change(changed.append)

    def dead_peer():
        raise TimeoutError("no ack from slice 1")

    g = DcnCollectiveGuard(membership=m, retries=2,
                           backoff_base_ms=1.0, backoff_max_ms=1.0,
                           sleep=lambda s: None)
    with pytest.raises(SliceLostError) as ei:
        g.run(dead_peer, peer_slice=1, label="grad-sync")
    err = ei.value
    assert err.slice_id == 1
    assert err.event and err.event["kind"] == "slice_lost"
    assert "dcn_guard:grad-sync" in err.event["reason"]
    # the escalation IS a membership change — well before any heartbeat
    # timeout (60s here) or stall watchdog could fire
    assert m.dead_slices() == {1} and len(changed) == 1
    assert g.stats()["escalations"] == 1 and g.retries_used == 2


def test_guard_applies_injected_dcn_delay(monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_DCN_DELAY_MS", "30")
    g = DcnCollectiveGuard(retries=1)
    import time as _time
    t0 = _time.monotonic()
    assert g.run(lambda: 7) == 7
    assert _time.monotonic() - t0 >= 0.025


# ---------------------------------------------------------------------------
# in-memory mid-run reform (the tentpole, in-process)
# ---------------------------------------------------------------------------
def _gpt_trainer(mesh, comm=False):
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    paddle.seed(3)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    return SpmdTrainer(model, opt, lambda o, l: crit(o, l), mesh=mesh,
                       comm_stats=comm)


def _gpt_batches(n=6):
    rng = np.random.RandomState(0)
    ids = [rng.randint(0, 64, (8, 16)).astype(np.int32)
           for _ in range(n)]
    return [(b, np.roll(b, -1, 1).astype(np.int64)) for b in ids]


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_hierarchical_matches_flat_dp():
    data = _gpt_batches(3)
    flat = _gpt_trainer(create_mesh({"dp": 8}))
    hier = _gpt_trainer(create_mesh({"dp": 4}, dcn_slices=2))
    for b, l in data:
        np.testing.assert_allclose(float(hier.train_step(b, l)),
                                   float(flat.train_step(b, l)),
                                   rtol=1e-5)
    assert hier.stats["dcn_slices"] == 2


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_slice_loss_reforms_in_memory_with_parity(monkeypatch):
    from paddle_tpu.utils import compile_counter
    data = _gpt_batches(6)
    ref = _gpt_trainer(create_mesh({"dp": 4}, dcn_slices=2))
    loss_ref = [float(ref.train_step(b, l)) for b, l in data]

    t = {"now": 0.0}
    m = SliceMembership(2, transport=CallbackTransport(), timeout_s=1.0,
                        clock=lambda: t["now"])
    monkeypatch.setenv("PADDLE_FAULT_SLICE_DOWN", "1:3")
    tr = _gpt_trainer(create_mesh({"dp": 4}, dcn_slices=2))
    tr.attach_membership(m, guard=DcnCollectiveGuard(retries=2))
    losses, snap = [], None
    for i, (b, l) in enumerate(data):
        losses.append(float(tr.train_step(b, l)))
        if i == 2:
            t["now"] += 5.0      # slice 1 goes silent past the timeout
        if i == 4:
            # the reform ran at the END of step 3; step 4 paid the one
            # expected new-mesh compile — everything after must not
            snap = compile_counter.snapshot()
    np.testing.assert_allclose(losses, loss_ref, rtol=1e-5)
    assert snap.new_compiles == 0, \
        f"{snap.new_compiles} recompiles after the first post-reform step"
    st = tr.stats
    assert st["mesh_reforms"] == 1 and st["lost_slices"] == [1]
    assert st["dcn_slices"] == 1 and tr.mesh.devices.size == 4
    assert st["last_reform"]["lost_slices"] == [1]
    assert st["last_reform"]["ms"] >= 0
    assert st["slices_dead"] == [1]
    assert st["dcn_guard"]["escalations"] == 0
    # the membership events recorded the alive->dead transition
    assert [e["slice"] for e in m.events] == [1]


def test_reform_to_zero_survivors_raises():
    m = SliceMembership(2, transport=CallbackTransport(), timeout_s=1.0)
    tr = _gpt_trainer(create_mesh({"dp": 4}, dcn_slices=2))
    tr.attach_membership(m)
    with pytest.raises(RuntimeError, match="no survivors"):
        tr.reform_mesh([0, 1])


# ---------------------------------------------------------------------------
# CheckpointManager vs an in-flight reform (satellite 6)
# ---------------------------------------------------------------------------
def test_manager_save_queues_behind_reform(tmp_path, monkeypatch):
    from paddle_tpu.distributed import CheckpointManager
    tr = _gpt_trainer(create_mesh({"dp": 4}, dcn_slices=2))
    b, l = _gpt_batches(1)[0]
    tr.train_step(b, l)
    mgr = CheckpointManager(str(tmp_path), async_save=False)

    tr.reform_in_progress = True
    done = threading.Event()

    def saver():
        mgr.save(tr)
        done.set()

    th = threading.Thread(target=saver, daemon=True)
    th.start()
    assert not done.wait(0.15), "save did not queue behind the reform"
    tr.reform_in_progress = False
    assert done.wait(10), "queued save never resumed"
    th.join(5)
    assert mgr.stats["reform_waits"] == 1
    # a reform stuck past the bound raises instead of wedging the saver
    tr.reform_in_progress = True
    monkeypatch.setenv("PADDLE_TPU_REFORM_WAIT_S", "0.05")
    with pytest.raises(TimeoutError, match="reform"):
        mgr.save(tr)
    tr.reform_in_progress = False


# ---------------------------------------------------------------------------
# doctor: the new verdicts
# ---------------------------------------------------------------------------
def test_doctor_slice_unhealthy():
    from paddle_tpu.observability.doctor import diagnose
    sick = {"slice_heartbeat_ages": {0: 0.1, 1: 4.0},
            "slice_timeout_s": 5.0, "slices_dead": [],
            "mesh_reforms": 0}
    v = [d for d in diagnose(sick, kind="train")
         if d["bottleneck"] == "slice-unhealthy"]
    assert v and v[0]["evidence"]["slice"] == 1
    assert v[0]["evidence"]["heartbeat_age_s"] == 4.0
    assert v[0]["action"]["env"] == "PADDLE_TPU_SLICE_HB_TIMEOUT_S"
    # a dead slice fires regardless of current ages, score >= 1
    dead = {"slice_heartbeat_ages": {0: 0.1}, "slice_timeout_s": 5.0,
            "slices_dead": [1], "mesh_reforms": 1}
    v2 = [d for d in diagnose(dead, kind="train")
          if d["bottleneck"] == "slice-unhealthy"]
    assert v2 and v2[0]["score"] >= 1.0
    assert v2[0]["evidence"]["slices_dead"] == [1]
    # healthy heartbeats: silent
    ok = {"slice_heartbeat_ages": {0: 0.1, 1: 0.2},
          "slice_timeout_s": 5.0, "slices_dead": []}
    assert not [d for d in diagnose(ok, kind="train")
                if d["bottleneck"] == "slice-unhealthy"]


def test_doctor_dcn_bound():
    from paddle_tpu.observability.doctor import diagnose
    hot = {"comm_bytes": 1000, "comm_bytes_dcn": 600,
           "comm_bytes_ici": 400, "comm_fraction": 0.3}
    v = [d for d in diagnose(hot, kind="train")
         if d["bottleneck"] == "dcn-bound"]
    assert v and v[0]["evidence"]["dcn_share"] == 0.6
    assert v[0]["action"]["param"] == "k_steps"
    # mostly-ICI traffic (a healthy hierarchy) stays silent
    cool = {"comm_bytes": 1000, "comm_bytes_dcn": 100,
            "comm_bytes_ici": 900, "comm_fraction": 0.3}
    assert not [d for d in diagnose(cool, kind="train")
                if d["bottleneck"] == "dcn-bound"]
    # heavy DCN share but negligible comm overall: not a bottleneck
    idle = {"comm_bytes": 1000, "comm_bytes_dcn": 900,
            "comm_bytes_ici": 100, "comm_fraction": 0.01}
    assert not [d for d in diagnose(idle, kind="train")
                if d["bottleneck"] == "dcn-bound"]
