"""Pallas flash attention kernel tests (interpret mode on CPU).

Ground truth is the module's own XLA composite (`_composite`), itself
verified against `_sdpa_reference` elsewhere. Covers fwd, the fused
Pallas backward (dq/dk/dv from saved logsumexp), native GQA, and the
key-padding mask.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import importlib

# ops/__init__ re-exports the flash_attention FUNCTION under the same
# name as the module; fetch the module itself
fa = importlib.import_module("paddle_tpu.ops.flash_attention")


@pytest.fixture(autouse=True)
def _interpret():
    fa.set_interpret_mode(True)
    yield
    fa.set_interpret_mode(False)


def make_qkv(b=2, s=256, h=4, hkv=None, d=64, seed=0):
    rng = np.random.RandomState(seed)
    hkv = hkv or h
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_composite(causal):
    q, k, v = make_qkv()
    out = fa.flash_attention(q, k, v, causal=causal)
    ref = fa._composite(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_multi_block():
    """S=512 with block 256 exercises the online-softmax block loop."""
    q, k, v = make_qkv(b=1, s=512, h=2)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._composite(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_composite(causal):
    q, k, v = make_qkv(b=1, s=256, h=2)

    def loss_flash(q_, k_, v_):
        return (fa.flash_attention(q_, k_, v_, causal=causal)
                .astype(jnp.float32) ** 2).sum()

    def loss_ref(q_, k_, v_):
        return (fa._composite(q_, k_, v_, causal)
                .astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_backward_multi_block_causal():
    q, k, v = make_qkv(b=1, s=512, h=2, seed=3)

    def loss(fn):
        return lambda q_, k_, v_: (fn(q_, k_, v_).astype(jnp.float32)
                                   * jnp.cos(q_)).sum()

    gf = jax.grad(loss(lambda a, b, c: fa.flash_attention(
        a, b, c, causal=True)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda a, b, c: fa._composite(a, b, c, True)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_gqa_forward_and_backward():
    """k/v with Hkv=2 < H=8 heads, never expanded: parity with the
    composite (which expands internally)."""
    q, k, v = make_qkv(b=2, s=256, h=8, hkv=2, seed=5)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._composite(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q_, k_, v_):
        return (fa.flash_attention(q_, k_, v_, causal=True)
                .astype(jnp.float32) ** 2).sum()

    def loss_ref(q_, k_, v_):
        return (fa._composite(q_, k_, v_, True)
                .astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape  # dk/dv stay at Hkv heads
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_kv_mask_forward_and_backward():
    """Key-padding mask: last quarter of keys masked out."""
    q, k, v = make_qkv(b=2, s=256, h=2, seed=7)
    mask = np.ones((2, 256), np.float32)
    mask[:, 192:] = 0.0
    mask = jnp.asarray(mask)

    out = fa.flash_attention(q, k, v, causal=False, kv_mask=mask)
    ref = fa._composite(q, k, v, False, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    gf = jax.grad(lambda a, b, c: (fa.flash_attention(
        a, b, c, causal=True, kv_mask=mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: (fa._composite(
        a, b, c, True, kv_mask=mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    # masked keys receive zero dk/dv
    assert np.allclose(np.asarray(gf[1])[:, 192:], 0.0)
    assert np.allclose(np.asarray(gf[2])[:, 192:], 0.0)


def test_bf16_inputs():
    q, k, v = make_qkv(b=1, s=256, h=2)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = fa.flash_attention(qb, kb, vb, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = fa._composite(qb, kb, vb, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_unsupported_shapes_fall_back():
    # s % 128 != 0 -> composite (still correct)
    q, k, v = make_qkv(b=1, s=100, h=2)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._composite(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)
