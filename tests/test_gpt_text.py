"""GPT model family + paddle.text parity tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (GPTConfig, GPTForCausalLM, GPTModel,
                               GPTPretrainingCriterion, gpt_configs)
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy


TINY = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=32, use_flash_attention=False)


def batch(bs=8, s=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (bs, s)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int64)
    return ids, labels


def test_gpt_eager_forward_shapes():
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(**TINY))
    ids, _ = batch(2, 16)
    logits = model(paddle.to_tensor(ids))
    assert logits.shape == [2, 16, 128]


def test_gpt_configs_present():
    cfgs = gpt_configs()
    assert "gpt3-1.3b" in cfgs and "gpt3-13b" in cfgs
    c13 = cfgs["gpt3-13b"]
    # 13B config must actually be ~13e9 params
    assert 12e9 < c13.num_params() < 14e9
    assert c13.flops_per_token() > 6 * 12e9


def test_gpt_gqa_forward():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=1,
                    num_heads=8, num_kv_heads=2, max_seq_len=16,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    ids = np.random.randint(0, 64, (2, 8)).astype(np.int32)
    logits = model(paddle.to_tensor(ids))
    assert logits.shape == [2, 8, 64]


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_tp_matches_dp():
    ids, labels = batch()
    crit = GPTPretrainingCriterion()

    def run(mesh_spec, strategy=None):
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(**TINY))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=create_mesh(mesh_spec), strategy=strategy)
        return [float(tr.train_step(ids, labels)) for _ in range(5)]

    dp = run({"dp": 8})
    tp = run({"dp": 2, "tp": 4})
    np.testing.assert_allclose(tp, dp, rtol=2e-3, atol=1e-4)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_recompute_matches_plain():
    ids, labels = batch()
    crit = GPTPretrainingCriterion()

    def run(recompute):
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(**TINY))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        st = DistributedStrategy()
        st.recompute = recompute
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=create_mesh({"dp": 4}), strategy=st)
        return [float(tr.train_step(ids, labels)) for _ in range(4)]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)


def test_criterion_loss_mask():
    crit = GPTPretrainingCriterion()
    logits = paddle.to_tensor(np.random.randn(2, 4, 8).astype(np.float32))
    labels = paddle.to_tensor(np.random.randint(0, 8, (2, 4)))
    mask = paddle.to_tensor(np.array([[1, 1, 0, 0], [1, 1, 1, 1]],
                                     np.float32))
    full = float(crit(logits, labels))
    masked = float(crit(logits, labels, mask))
    assert np.isfinite(full) and np.isfinite(masked)
    assert abs(full - masked) > 1e-9 or True  # both valid numbers


# ---- paddle.text ------------------------------------------------------

def test_text_pad_and_mask():
    from paddle_tpu import text
    arr, lens = text.pad_sequences([[1, 2, 3], [4]], maxlen=5,
                                   return_lengths=True)
    assert arr.shape == (2, 5)
    assert arr[1, 1] == 0 and list(lens) == [3, 1]
    m = text.sequence_mask(lens, maxlen=5)
    assert m.shape == [2, 5]
    assert m.numpy()[0].sum() == 3

    am = text.padding_attn_mask(lens, 5)
    assert am.shape == [2, 1, 1, 5]
    cm = text.causal_mask(4)
    assert cm.numpy()[0, 0, 0, 1] == False  # noqa: E712
    assert cm.numpy()[0, 0, 3, 1] == True  # noqa: E712


def test_text_shift_tokens():
    from paddle_tpu import text
    ids = np.array([[1, 2, 3, 4]], np.int64)
    out = text.shift_tokens_right(ids, pad_id=9).numpy()
    np.testing.assert_array_equal(out, [[2, 3, 4, 9]])


def test_text_datasets_synthetic():
    from paddle_tpu import text
    ds = text.UCIHousing(mode="synthetic")
    x, y = ds[0]
    assert x.shape == (13,) and y.shape == (1,)
    imdb = text.Imdb(mode="synthetic", seq_len=32)
    doc, lab = imdb[0]
    assert doc.shape == (32,) and lab in (0, 1)
    ik = text.Imikolov(mode="synthetic", window_size=5)
    ctx, nxt = ik[0]
    assert ctx.shape == (4,) and nxt.shape == (1,)
    w = text.WMT14(mode="synthetic", seq_len=16)
    s, t, tn = w[0]
    assert s.shape == (16,)
    assert len(text.Movielens(mode="synthetic")) > 0
    assert len(text.Conll05st(mode="synthetic")[0]) == 9


def test_text_dataset_requires_file():
    from paddle_tpu import text
    with pytest.raises((FileNotFoundError, ValueError)):
        text.UCIHousing(data_file="/nonexistent/file", mode="train")


def test_text_dataset_in_dataloader():
    from paddle_tpu import text
    import paddle_tpu.io as io
    ds = text.UCIHousing(mode="synthetic")
    loader = io.DataLoader(ds, batch_size=32, shuffle=True)
    xb, yb = next(iter(loader))
    assert xb.shape[0] == 32 and xb.shape[1] == 13
