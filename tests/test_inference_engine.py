"""Serving engine tests: static KV cache, fused decode attention,
continuous batching, and the recompile-free-decode contract.

Ground truth throughout is the ordinary full forward: prefill(k tokens)
+ N decode steps over the static cache must reproduce the logits a
single forward over the whole sequence produces (exact in f32 on CPU;
the tolerance argument covers bf16 on TPU).  The compile-count
assertions use utils.compile_counter (the PR 3-style counter
discipline: prove it, don't hand-wave it).
"""
import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM, StaticKVCache
from paddle_tpu.inference import InferenceEngine, default_prefill_buckets
from paddle_tpu.distributed import async_dispatch
from paddle_tpu.utils import compile_counter

da = importlib.import_module("paddle_tpu.ops.decode_attention")

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(**over):
    paddle.seed(0)
    cfg = GPTConfig(**{**TINY, **over})
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return tiny_model()


@pytest.fixture(scope="module")
def engine(model):
    """Shared 2-slot engine: engines are stateless between completed
    requests (slot lengths mask any stale cache rows), so sequential
    tests can reuse one and skip ~5 redundant compiles."""
    return InferenceEngine(model, batch_slots=2, prefill_buckets=[8])


def naive_greedy(model, prompt, n):
    """Argmax rollout with the ordinary full forward (no cache)."""
    ids = list(np.asarray(prompt).reshape(-1))
    outs = []
    for _ in range(n):
        lg = model(paddle.to_tensor(
            np.asarray([ids], np.int32))).numpy()[0, -1]
        t = int(np.argmax(lg))
        outs.append(t)
        ids.append(t)
    return outs


# ---- fused decode attention kernel ------------------------------------

@pytest.mark.parametrize("hkv", [4, 2, 1])
def test_decode_attention_kernel_matches_composite(hkv):
    """Pallas kernel (interpret mode) vs XLA composite, incl. GQA and
    per-slot length masking."""
    da.set_interpret_mode(True)
    try:
        rng = np.random.RandomState(0)
        b, s, h, d = 3, 256, 4, 64
        q = jnp.asarray(rng.randn(b, h, d).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32) * 0.3)
        lengths = jnp.asarray([1, 100, 256], jnp.int32)
        out = da.decode_attention(q, k, v, lengths)
        ref = da._decode_composite(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        da.set_interpret_mode(None)


def test_decode_attention_length_masks_tail():
    """Garbage beyond lengths[b] must not leak into the output."""
    rng = np.random.RandomState(1)
    b, s, hkv, d = 2, 128, 2, 16
    q = jnp.asarray(rng.randn(b, 4, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, hkv, d).astype(np.float32))
    lengths = jnp.asarray([5, 9], jnp.int32)
    base = np.asarray(da._decode_composite(q, k, v, lengths))
    poisoned_k = k.at[:, 10:].set(1e3)
    poisoned_v = v.at[:, 10:].set(-1e3)
    out = np.asarray(da._decode_composite(q, poisoned_k, poisoned_v,
                                          lengths))
    np.testing.assert_allclose(out, base, rtol=1e-6, atol=1e-6)


# ---- static cache vs full forward -------------------------------------

@pytest.mark.parametrize("kv_heads", [None, 2])
def test_prefill_plus_decode_matches_full_forward(kv_heads):
    """prefill(7 tokens) + 4 decode steps == one forward over 11 tokens
    (logit parity at every generated position; GQA covered)."""
    m = tiny_model(num_kv_heads=kv_heads)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (1, 11)).astype(np.int32)
    full = np.asarray(m(paddle.to_tensor(ids)).data)        # [1, 11, V]

    cache = m.init_kv_cache(batch_slots=3)
    logits, cache = m.prefill(jnp.asarray(ids[:, :7]), cache, 1, 7)
    np.testing.assert_allclose(np.asarray(logits)[0], full[0, 6],
                               rtol=1e-4, atol=1e-4)
    for t in range(7, 11):
        toks = np.zeros(3, np.int32)
        toks[1] = ids[0, t]
        active = jnp.asarray([0, 1, 0], jnp.int32)
        lg, cache = m.decode_step(jnp.asarray(toks), cache, active)
        np.testing.assert_allclose(np.asarray(lg)[1], full[0, t],
                                   rtol=1e-4, atol=1e-4)
    assert np.asarray(cache.lengths).tolist() == [0, 11, 0]


def test_bucket_padding_is_masked():
    """Prefill through a padded bucket (prompt 5 in a 16-bucket) must
    produce the same logits as the exact-length prefill."""
    m = tiny_model()
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 97, (5,)).astype(np.int32)
    padded = np.zeros((1, 16), np.int32)
    padded[0, :5] = prompt
    c1 = m.init_kv_cache(1)
    l1, c1 = m.prefill(jnp.asarray(prompt[None]), c1, 0, 5)
    c2 = m.init_kv_cache(1)
    l2, c2 = m.prefill(jnp.asarray(padded), c2, 0, 5)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                               rtol=1e-4, atol=1e-4)
    # and the first decode step agrees too (pad k/v stay masked)
    tok = jnp.asarray([3], jnp.int32)
    act = jnp.ones((1,), jnp.int32)
    d1, _ = m.decode_step(tok, c1, act)
    d2, _ = m.decode_step(tok, c2, act)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-4, atol=1e-4)


# ---- legacy tuple-cache API -------------------------------------------

def test_legacy_cache_fresh_matches_no_cache():
    m = tiny_model()
    attn = m.gpt.blocks[0].attn
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 5, 64).astype(np.float32))
    out_plain = attn(x)
    out_cached, triple = attn(x, cache=(None, None))
    np.testing.assert_allclose(out_plain.numpy(), out_cached.numpy(),
                               rtol=1e-5, atol=1e-5)
    k_buf, v_buf, length = triple
    assert k_buf.shape == (2, 64, 4, 16) and length == 5


def test_legacy_cache_decode_matches_full():
    """Old-style incremental decode through the tuple cache equals the
    full-sequence attention at the last position."""
    m = tiny_model()
    attn = m.gpt.blocks[0].attn
    rng = np.random.RandomState(3)
    x = rng.randn(2, 6, 64).astype(np.float32)
    full = attn(paddle.to_tensor(x)).numpy()
    out, cache = attn(paddle.to_tensor(x[:, :3]), cache=(None, None))
    for t in range(3, 6):
        out, cache = attn(paddle.to_tensor(x[:, t:t + 1]), cache=cache)
        np.testing.assert_allclose(out.numpy()[:, 0], full[:, t],
                                   rtol=1e-4, atol=1e-4)
    assert cache[0].shape == (2, 64, 4, 16)   # capacity never grew


def test_legacy_cache_adopts_dense_past():
    """A legacy 2-tuple of dense past k/v is adopted into the fixed
    buffer: next-step output equals the full-sequence reference."""
    m = tiny_model()
    attn = m.gpt.blocks[0].attn
    rng = np.random.RandomState(4)
    x = rng.randn(1, 5, 64).astype(np.float32)
    full = attn(paddle.to_tensor(x)).numpy()
    # build dense past k/v for the first 4 tokens by hand
    q, k, v = attn._qkv_arrays(paddle.to_tensor(x[:, :4]))
    out, cache = attn(paddle.to_tensor(x[:, 4:5]), cache=(k, v))
    np.testing.assert_allclose(out.numpy()[:, 0], full[:, 4],
                               rtol=1e-4, atol=1e-4)
    assert cache[2] == 5


def test_legacy_cache_overflow_raises_eagerly():
    """Eager use past capacity must raise, not silently clamp (the old
    concat cache grew unboundedly; the static buffer cannot)."""
    m = tiny_model(max_seq_len=8)
    attn = m.gpt.blocks[0].attn
    rng = np.random.RandomState(6)
    x = rng.randn(1, 6, 64).astype(np.float32)
    _, cache = attn(paddle.to_tensor(x), cache=(None, None))
    _, cache = attn(paddle.to_tensor(x[:, :2]), cache=cache)  # 8 == cap
    with pytest.raises(ValueError, match="overflow"):
        attn(paddle.to_tensor(x[:, :1]), cache=cache)


def test_legacy_cache_decode_is_recompile_free():
    """The fixed-capacity tuple cache keeps shapes static: N jitted
    decode steps = ONE trace/compile (the old concat cache recompiled
    every token)."""
    m = tiny_model()
    attn = m.gpt.blocks[0].attn
    rng = np.random.RandomState(5)
    step = jax.jit(lambda xt, cache: attn(paddle.Tensor(xt),
                                          cache=cache))
    x0 = jnp.asarray(rng.randn(1, 1, 64).astype(np.float32))
    out, cache = step(x0, (jnp.zeros((1, 64, 4, 16), jnp.float32),
                           jnp.zeros((1, 64, 4, 16), jnp.float32),
                           jnp.asarray(0, jnp.int32)))
    snap = compile_counter.snapshot()
    for _ in range(6):
        out, cache = step(
            jnp.asarray(rng.randn(1, 1, 64).astype(np.float32)), cache)
    assert snap.new_compiles == 0 and snap.new_traces == 0
    assert int(cache[2]) == 7


# ---- engine -----------------------------------------------------------

def test_engine_greedy_matches_naive_rollout(model, engine):
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 97, (5,)).astype(np.int32)
    ref = naive_greedy(model, prompt, 6)
    rid = engine.add_request(prompt, max_new_tokens=6)
    outs = engine.run()
    assert outs[rid].tolist() == ref


def test_engine_decode_is_recompile_free(model, engine):
    """THE acceptance criterion: after warmup, generating N tokens
    triggers 0 new XLA compiles AND 0 new jaxpr traces."""
    engine.warmup(buckets=[8])
    rng = np.random.RandomState(1)
    # one full request through prefill+decode to flush any lazy host-side
    # one-offs, then the counted window
    engine.add_request(rng.randint(1, 97, (4,)).astype(np.int32),
                       max_new_tokens=2)
    engine.run()
    snap = compile_counter.snapshot()
    sync0 = async_dispatch.host_sync_count()
    rid = engine.add_request(rng.randint(1, 97, (5,)).astype(np.int32),
                             max_new_tokens=10)
    outs = engine.run()
    assert len(outs[rid]) == 10
    assert snap.new_compiles == 0, \
        f"{snap.new_compiles} XLA compiles during the decode window"
    assert snap.new_traces == 0, \
        f"{snap.new_traces} jaxpr traces during the decode window"
    # sync budget: 1 per decode step (token read-back) + 1 per admission
    st = engine.stats
    syncs = async_dispatch.host_sync_count() - sync0
    assert syncs <= 10, f"{syncs} host syncs for a 10-token request"
    assert st["xla_compiles"] >= 0  # counter alive


def test_engine_continuous_batching_isolation(model, engine):
    """Admitting B mid-stream must not perturb A's tokens (slot-local
    prefill writes), and both requests complete."""
    rng = np.random.RandomState(7)
    pA = rng.randint(1, 97, (4,)).astype(np.int32)
    pB = rng.randint(1, 97, (6,)).astype(np.int32)

    ra = engine.add_request(pA, max_new_tokens=10)
    solo = engine.run()[ra].tolist()

    ra = engine.add_request(pA, max_new_tokens=10)
    for _ in range(3):
        engine.step()
    rb = engine.add_request(pB, max_new_tokens=5)
    res = engine.run()
    assert res[ra].tolist() == solo
    assert len(res[rb]) == 5
    assert res[rb].tolist() == naive_greedy(model, pB, 5)


def test_engine_queue_overflow_waits(engine):
    """More requests than slots: the queue drains as slots retire."""
    rng = np.random.RandomState(8)
    rids = [engine.add_request(rng.randint(1, 97, (3,)).astype(np.int32),
                               max_new_tokens=3) for _ in range(5)]
    res = engine.run()
    assert all(r in res for r in rids)
    assert all(len(res[r]) == 3 for r in rids)


def test_engine_eos_retirement(model, engine):
    rng = np.random.RandomState(9)
    prompt = rng.randint(1, 97, (4,)).astype(np.int32)
    first = naive_greedy(model, prompt, 1)[0]
    rid = engine.add_request(prompt, max_new_tokens=50, eos_id=first)
    res = engine.run()
    assert res[rid].tolist() == [first]       # stopped at EOS, slot freed
    assert engine.num_active == 0


def test_engine_sampling_deterministic_and_topk1_greedy(model):
    rng = np.random.RandomState(10)
    prompt = rng.randint(1, 97, (4,)).astype(np.int32)
    sampled = []
    for _ in range(2):
        eng = InferenceEngine(model, batch_slots=1, prefill_buckets=[8],
                              seed=42)
        r = eng.add_request(prompt, max_new_tokens=8, temperature=0.9,
                            top_p=0.95)
        sampled.append(eng.run()[r].tolist())
    assert sampled[0] == sampled[1]           # same seed, same stream
    eng = InferenceEngine(model, batch_slots=1, prefill_buckets=[8],
                          seed=7, top_k=1)
    r = eng.add_request(prompt, max_new_tokens=6, temperature=1.3)
    assert eng.run()[r].tolist() == naive_greedy(model, prompt, 6)


def test_engine_stats_fields(engine):
    r = engine.add_request(np.asarray([5, 6, 7], np.int32),
                           max_new_tokens=4)
    engine.run()
    st = engine.stats
    for key in ("prefill_ms", "decode_ms", "compile_ms_cold",
                "decode_steps", "tokens_generated", "slot_occupancy",
                "decode_tokens_per_sec", "xla_compiles", "jaxpr_traces",
                "batch_slots", "buckets"):
        assert key in st, key
    assert st["tokens_generated"] >= 3
    assert 0 < st["slot_occupancy"] <= 1
    assert r in engine.results


def test_generate_wrapper(model):
    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 97, (5,)).astype(np.int32)
    out = model.generate(prompt, max_new_tokens=5)
    assert out.tolist() == naive_greedy(model, prompt, 5)
    both = model.generate(prompt, max_new_tokens=3, include_prompt=True)
    assert both[:5].tolist() == prompt.tolist()


def test_default_prefill_buckets(model):
    assert default_prefill_buckets(64, lo=16) == [16, 32, 64]
    assert default_prefill_buckets(100, lo=16) == [16, 32, 64, 100]
    eng = InferenceEngine(model, batch_slots=1)   # no jit runs: cheap
    with pytest.raises(ValueError):
        eng.add_request(np.ones(65, np.int32))  # beyond largest bucket


# ---- decoding wiring + EOS early-exit ---------------------------------

@pytest.fixture(scope="module")
def wiring_model():
    return tiny_model(vocab_size=50, hidden_size=32, num_heads=2)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_greedy_search_matches_naive(wiring_model):
    from paddle_tpu.text import greedy_search, gpt_step_fn
    m = wiring_model
    step = gpt_step_fn(m)
    cache = m.init_kv_cache(2)
    toks = np.asarray(greedy_search(step, cache, 2, 6, bos_id=1,
                                    eos_id=0).data)
    ref = naive_greedy(m, [1], 6)
    stop = ref.index(0) + 1 if 0 in ref else 6
    assert toks[0].tolist()[:stop] == ref[:stop]
    assert toks.shape == (2, 6)


def test_gpt_beam_search_runs_over_cache_state(wiring_model):
    from paddle_tpu.text import beam_search, gpt_step_fn
    m = wiring_model
    K = 3
    cache = m.init_kv_cache(1 * K)
    seqs, scores = beam_search(gpt_step_fn(m), cache, 1, K, 5,
                               bos_id=1, eos_id=0)
    assert seqs.shape == [1, K, 5]
    sc = np.asarray(scores.data)[0]
    assert all(sc[i] >= sc[i + 1] for i in range(K - 1))


def _counting_lm(table):
    """LM over a fixed next-token table + a host call counter."""
    calls = []

    def step_fn(tokens, state):
        jax.debug.callback(lambda: calls.append(1))
        return jnp.asarray(table)[tokens], state

    return step_fn, calls


def test_greedy_eos_early_exit():
    """Once every row emits EOS the while-program stops: far fewer
    step_fn executions than max_len."""
    from paddle_tpu.text import greedy_search
    V, EOS, BOS = 5, 0, 1
    table = np.full((V, V), -5.0, np.float32)
    table[:, EOS] = 5.0                      # everything points at EOS
    step_fn, calls = _counting_lm(table)
    toks = np.asarray(greedy_search(step_fn, (), 3, 50, BOS, EOS).data)
    assert toks.shape == (3, 50)
    assert (toks == EOS).all()
    assert len(calls) <= 3, f"{len(calls)} steps for an instant-EOS LM"


def test_beam_eos_early_exit_matches_full_run():
    """Early exit must not change results: same sequences/scores as a
    brute-force comparison LM where EOS arrives quickly."""
    from paddle_tpu.text import beam_search
    V, EOS, BOS = 5, 0, 1
    # EOS overwhelms every alternative, so ALL K beams finish within a
    # couple of steps and the while-program exits
    table = np.full((V, V), -50.0, np.float32)
    table[:, EOS] = 0.0
    step_fn, calls = _counting_lm(table)
    seqs, scores = beam_search(step_fn, (), 1, 3, 20, BOS, EOS)
    assert np.asarray(seqs.data).shape == (1, 3, 20)
    assert len(calls) <= 5, f"no early exit: {len(calls)} steps"
    # every beam terminated with EOS and post-EOS positions are EOS
    arr = np.asarray(seqs.data)[0]
    for k in range(3):
        row = arr[k].tolist()
        assert EOS in row
        first = row.index(EOS)
        assert all(t == EOS for t in row[first:])


# ---- graceful drain + per-request deadlines (ISSUE 10 satellites) -----

def test_drain_finishes_inflight_and_returns_queued(model):
    """engine.drain(): admission stops, in-flight slots run to
    completion, still-queued requests come back to the caller, and the
    paged pool is verified leak-free."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8],
                          kv_layout="paged", kv_block_size=8)
    rng = np.random.RandomState(3)
    rids = [eng.add_request(rng.randint(1, 97, (5,)), max_new_tokens=6)
            for _ in range(5)]
    for _ in range(2):
        eng.step()                      # two admitted, three queued
    leftover = eng.drain()
    assert eng.num_active == 0
    assert len(leftover) == 3
    assert [r.rid for r in leftover] == rids[2:]   # FIFO order kept
    finished = [r for r in rids[:2] if r in eng.results]
    assert len(finished) == 2
    assert all(len(eng.results[r]) == 6 for r in finished)
    eng.check_leak_free()               # refcounts all back in the pool
    # the engine is usable again after the drain
    rid = eng.add_request(rng.randint(1, 97, (5,)), max_new_tokens=2)
    eng.run()
    assert rid in eng.results


def test_drain_timeout_force_retires_with_partial_output(model):
    eng = InferenceEngine(model, batch_slots=1, prefill_buckets=[8])
    rid = eng.add_request(np.arange(1, 6, dtype=np.int32),
                          max_new_tokens=10_000)
    eng.step()
    leftover = eng.drain(timeout_s=0.0)
    assert leftover == [] and eng.num_active == 0
    rec = eng.request_stats[rid]
    assert rec["timed_out"] and rec["tokens"] >= 1
    assert eng.stats["drain_forced_retirements"] == 1


def test_preemption_guard_drains_server(model):
    """SIGTERM mid-run: the engine finishes what it started (in-flight
    slots), parks the queue in engine.undelivered, and run() returns."""
    import os
    import signal

    from paddle_tpu.distributed import PreemptionGuard
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8])
    rng = np.random.RandomState(4)
    rids = [eng.add_request(rng.randint(1, 97, (5,)), max_new_tokens=8)
            for _ in range(6)]
    with PreemptionGuard() as g:
        eng.attach_preemption_guard(g)
        eng.step()
        os.kill(os.getpid(), signal.SIGTERM)
        res = eng.run()
    assert eng.num_active == 0
    assert len(eng.undelivered) == 4       # never admitted
    done = [r for r in rids if r in res]
    assert len(done) == 2 and all(len(res[r]) == 8 for r in done)
    # a later drain ACCUMULATES into undelivered (never overwrites),
    # and step_or_raise-only drivers (loadgen) drain instead of
    # busy-spinning a preempted engine forever
    with PreemptionGuard() as g2:
        eng.attach_preemption_guard(g2)
        late = eng.add_request(rng.randint(1, 97, (5,)),
                               max_new_tokens=4)
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(10):
            if not eng.has_work:
                break
            eng.step_or_raise()
    assert not eng.has_work
    assert [r.rid for r in eng.undelivered] == rids[2:] + [late]


def test_deadline_expires_queued_and_active(model):
    """A request past its deadline is retired — queued ones without
    ever taking a slot, active ones mid-generation with their partial
    tokens — and reported timed_out instead of wedging a decode slot."""
    import time

    eng = InferenceEngine(model, batch_slots=1, prefill_buckets=[8])
    rng = np.random.RandomState(5)
    # active past-deadline: unbounded generation, 0.15 s budget
    r_active = eng.add_request(rng.randint(1, 97, (5,)),
                               max_new_tokens=10_000, deadline_s=0.15)
    # queued past-deadline: the single slot is occupied the whole time
    r_queued = eng.add_request(rng.randint(1, 97, (5,)),
                               max_new_tokens=4, deadline_s=0.0)
    time.sleep(0.01)
    while r_active not in eng.results or r_queued not in eng.results:
        eng.step_or_raise()
    ra, rq = eng.request_stats[r_active], eng.request_stats[r_queued]
    assert ra["timed_out"] and 0 < ra["tokens"] < 10_000
    assert rq["timed_out"] and rq["tokens"] == 0 \
        and rq["ttft_ms"] is None
    assert eng.stats["deadline_retirements"] == 2
    assert eng.num_active == 0
    # a deadline generous enough never fires
    out = eng.generate(rng.randint(1, 97, (5,)), max_new_tokens=3,
                       deadline_s=60.0)
    assert len(out) == 3


def test_loadtest_reports_timed_out_column(model):
    from paddle_tpu.inference.loadgen import (SharedPrefixWorkload,
                                              run_loadtest)
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8])
    wl = SharedPrefixWorkload(97, seed=0, shared_frac=0.0,
                              prefix_len=4, tail_len=(3, 6),
                              max_new=(2, 4))
    report = run_loadtest(eng, num_requests=6, rate_rps=200.0,
                          workload=wl, deadline_s=30.0)
    assert report["deadline_s"] == 30.0
    assert report["timed_out_requests"] == 0
    report2 = run_loadtest(eng, num_requests=6, rate_rps=200.0,
                           workload=wl, deadline_s=0.0)
    assert report2["timed_out_requests"] == 6
    assert report2["tokens_per_sec"] is not None


# ---- long-sequence serve bench (slow) ---------------------------------

@pytest.mark.slow
def test_serve_bench_long_sequence():
    """Longer-horizon engine soak: 6 requests, 512-capacity cache,
    mixed admission; asserts steady-state decode stays compile-free."""
    m = tiny_model(max_seq_len=512)
    eng = InferenceEngine(m, batch_slots=4, max_seq_len=512,
                          prefill_buckets=[32, 128])
    eng.warmup(buckets=[32])
    rng = np.random.RandomState(12)
    rids = [eng.add_request(
        rng.randint(1, 97, (rng.randint(3, 100),)).astype(np.int32),
        max_new_tokens=40) for _ in range(6)]
    for _ in range(3):
        eng.step()
    snap = compile_counter.snapshot()
    res = eng.run()
    assert snap.new_compiles == 0
    assert sorted(res) == sorted(rids)
    assert all(len(res[r]) == 40 for r in rids)
    assert eng.stats["slot_occupancy"] > 0.5
