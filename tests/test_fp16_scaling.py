"""fp16 dynamic loss scaling inside the compiled SpmdTrainer step.

Reference semantics under test: /root/reference/paddle/fluid/operators/amp/
update_loss_scaling_op.cc (scale state machine) +
check_finite_and_unscale_op.cc (skip-on-overflow) +
python/paddle/fluid/dygraph/amp/loss_scaler.py:27 (AmpScaler defaults):
- the loss is multiplied by the scale before backward, grads unscaled after;
- an inf/nan in any grad skips the optimizer step entirely;
- `decr_every_n_nan_or_inf` consecutive overflows halve the scale;
- `incr_every_n_steps` consecutive good steps double it.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy


class BombLayer(nn.Layer):
    """Linear whose loss explodes (produces inf grads) when an input row
    carries a sentinel value — lets a specific step overflow on demand."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x):
        out = self.fc(x)
        # multiplying by a huge factor when the sentinel is present
        # overflows fp16 grads without touching the other steps
        mask = (x > 900.0).astype("float32").max()  # 0.0 or 1.0
        bomb = 1.0 + mask * 1.0e30
        return out * bomb


def mse(out, y):
    return F.mse_loss(out, y)


def _fp16_strategy(**cfg):
    st = DistributedStrategy()
    st.amp = True
    st.amp_configs = dict({"use_bf16": False}, **cfg)
    return st


def make_trainer(**cfg):
    paddle.seed(0)
    model = BombLayer()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    mesh = create_mesh({"dp": 1})
    return model, SpmdTrainer(model, opt, mse, mesh=mesh,
                              strategy=_fp16_strategy(**cfg))


def batch(sentinel=False, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(4, 8).astype(np.float32)
    if sentinel:
        x[0, 0] = 1000.0
    y = rng.randn(4, 4).astype(np.float32)
    return x, y


def test_fp16_trains_and_scale_initialized():
    model, tr = make_trainer()
    assert tr.fp16_scaling
    assert tr.loss_scale == 2.0 ** 15  # AmpScaler default
    x, y = batch()
    loss = float(tr.train_step(x, y))
    assert np.isfinite(loss)
    assert not tr.last_step_skipped
    # good streak advanced, scale untouched (incr_every_n_steps=1000)
    assert tr.loss_scale == 2.0 ** 15


def test_overflow_skips_update_and_halves_scale():
    # decr_every_n_nan_or_inf=1: one overflow halves the scale immediately
    model, tr = make_trainer(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=1)
    x, y = batch()
    tr.train_step(x, y)
    params_before = {n: np.asarray(a) for n, a in tr.params.items()}
    opt_before = np.asarray(tr.opt_state["fc.weight"]["moment1"])
    xb, yb = batch(sentinel=True)
    tr.train_step(xb, yb)
    assert tr.last_step_skipped
    assert tr.loss_scale == 512.0
    for n, a in tr.params.items():
        np.testing.assert_array_equal(np.asarray(a), params_before[n])
    np.testing.assert_array_equal(
        np.asarray(tr.opt_state["fc.weight"]["moment1"]), opt_before)
    # recovery: next clean step applies normally
    loss = float(tr.train_step(x, y))
    assert np.isfinite(loss)
    assert not tr.last_step_skipped
    assert tr.loss_scale == 512.0


def test_two_consecutive_overflows_needed_by_default():
    # AmpScaler default decr_every_n_nan_or_inf=2: a single overflow only
    # increments the bad counter; the second in a row halves the scale
    model, tr = make_trainer(init_loss_scaling=1024.0)
    xb, yb = batch(sentinel=True)
    tr.train_step(xb, yb)
    assert tr.loss_scale == 1024.0
    tr.train_step(xb, yb)
    assert tr.loss_scale == 512.0
    # a good step in between resets the bad streak
    x, y = batch()
    tr.train_step(x, y)
    tr.train_step(xb, yb)
    assert tr.loss_scale == 512.0


def test_good_streak_doubles_scale():
    model, tr = make_trainer(init_loss_scaling=8.0, incr_every_n_steps=3)
    x, y = batch()
    tr.train_step(x, y)
    tr.train_step(x, y)
    assert tr.loss_scale == 8.0
    tr.train_step(x, y)
    assert tr.loss_scale == 16.0
    # streak counter reset: three more steps for the next doubling
    tr.train_step(x, y)
    assert tr.loss_scale == 16.0


def test_skipped_step_does_not_advance_adam_t():
    model, tr = make_trainer(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=1)
    x, y = batch()
    tr.train_step(x, y)
    t_before = int(tr._scaler_state["t"])
    xb, yb = batch(sentinel=True)
    tr.train_step(xb, yb)
    assert int(tr._scaler_state["t"]) == t_before
    tr.train_step(x, y)
    assert int(tr._scaler_state["t"]) == t_before + 1


def test_fp16_parity_with_unscaled_reference():
    """With a scale that never changes, fp16+scaling must match plain
    fp16 training (scale/unscale is numerically transparent for
    power-of-two scales)."""
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, mse, mesh=create_mesh({"dp": 1}),
                     strategy=_fp16_strategy(init_loss_scaling=256.0))

    paddle.seed(0)
    model2 = nn.Linear(8, 4)
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=model2.parameters())
    # second trainer: fp16 with scale fixed at 1.0 == unscaled fp16
    tr2 = SpmdTrainer(model2, opt2, mse, mesh=create_mesh({"dp": 1}),
                      strategy=_fp16_strategy(init_loss_scaling=1.0))

    x, y = batch()
    for _ in range(3):
        l1 = float(tr.train_step(x, y))
        l2 = float(tr2.train_step(x, y))
        assert l1 == pytest.approx(l2, rel=2e-3)
    for n in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[n], np.float32),
                                   np.asarray(tr2.params[n], np.float32),
                                   rtol=2e-3, atol=2e-3)


def test_scaler_state_checkpoint_roundtrip(tmp_path):
    model, tr = make_trainer(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=1)
    xb, yb = batch(sentinel=True)
    tr.train_step(xb, yb)
    assert tr.loss_scale == 512.0
    p = str(tmp_path / "ck.pdtrainer")
    tr.save(p)
    model2, tr2 = make_trainer(init_loss_scaling=1024.0,
                               decr_every_n_nan_or_inf=1)
    tr2.load(p)
    assert tr2.loss_scale == 512.0
    assert int(tr2._scaler_state["bad"]) == 0  # reset after the halving


def test_fp16_with_gradient_merge_raises():
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    st = _fp16_strategy()
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 2}
    with pytest.raises(NotImplementedError):
        SpmdTrainer(model, opt, mse, mesh=create_mesh({"dp": 1}),
                    strategy=st)
