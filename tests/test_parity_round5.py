"""Round-5 API parity additions: tensor inplace/array ops, nn decode /
hsigmoid / weight-norm, paddle.static helper surface, static.nn layer
helpers, deformable conv + YOLO ops, linalg namespace.

Reference tests mirrored: test_increment_op, test_array_read_write_op,
test_hsigmoid_op, test_weight_norm_hook, test_pairwise_distance,
test_deformable_conv_op, test_yolo_box_op, test_yolov3_loss_op,
test_backward (append_backward), test_program_state, test_nce,
test_row_conv_op, test_spectral_norm_op, test_bilinear_tensor_product_op.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, static
from paddle_tpu.vision import ops as vops


# ---------------------------------------------------------------------------
# tensor / top-level
# ---------------------------------------------------------------------------
class TestTensorAdds:
    def test_inplace_squeeze_unsqueeze_tanh(self):
        x = paddle.to_tensor(np.ones((2, 1, 3), "float32"))
        y = paddle.squeeze_(x, axis=1)
        assert y is x and x.shape == [2, 3]
        paddle.unsqueeze_(x, axis=0)
        assert x.shape == [1, 2, 3]
        t = paddle.to_tensor(np.zeros((2,), "float32"))
        paddle.tanh_(t)
        np.testing.assert_allclose(np.asarray(t.data), np.tanh(0.0))

    def test_increment(self):
        x = paddle.to_tensor(np.asarray([3.0], "float32"))
        paddle.increment(x, 2.5)
        assert float(x.data[0]) == pytest.approx(5.5)
        with pytest.raises(ValueError):
            paddle.increment(paddle.ones([2, 2]))

    def test_dist(self):
        a = paddle.to_tensor(np.asarray([[1.0, 2.0], [3.0, 4.0]],
                                        "float32"))
        b = paddle.zeros([2, 2])
        assert float(paddle.dist(a, b, p=2).data) == pytest.approx(
            np.sqrt(30.0), rel=1e-5)
        assert float(paddle.dist(a, b, p=0).data) == 4.0
        assert float(paddle.dist(a, b, p=float("inf")).data) == 4.0

    def test_array_ops(self):
        arr = paddle.create_array("float32")
        x = paddle.ones([2])
        paddle.tensor.array_write(x, 0, arr)
        paddle.tensor.array_write(x * 2, 1, arr)
        assert int(paddle.tensor.array_length(arr).data) == 2
        got = paddle.tensor.array_read(arr, 1)
        np.testing.assert_allclose(np.asarray(got.data), 2.0)
        with pytest.raises(IndexError):
            paddle.tensor.array_write(x, 5, arr)

    def test_crop_tensor_alias_and_printoptions(self):
        x = paddle.to_tensor(np.arange(16, dtype="float32").reshape(4, 4))
        out = paddle.crop_tensor(x, shape=[2, 2], offsets=[1, 1])
        np.testing.assert_allclose(np.asarray(out.data),
                                   [[5, 6], [9, 10]])
        paddle.set_printoptions(precision=2)
        assert "Tensor" in repr(x)
        paddle.set_printoptions(precision=8)

    def test_top_level_names(self):
        assert paddle.VarBase is paddle.Tensor
        assert paddle.is_compiled_with_cuda() is False
        assert paddle.is_compiled_with_xpu() is False
        assert paddle.get_cudnn_version() is None
        assert paddle.in_dygraph_mode()
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)
        attr = paddle.ParamAttr(name="w0")
        assert attr.name == "w0"
        p = paddle.create_parameter([3, 4], "float32")
        assert not p.stop_gradient and p.shape == [3, 4]
        paddle.monkey_patch_math_varbase()
        paddle.monkey_patch_variable()
        assert paddle.full_version == paddle.__version__

    def test_linalg_namespace(self):
        a = np.random.RandomState(0).randn(3, 3).astype("float32")
        x = paddle.to_tensor(a @ a.T + 3 * np.eye(3, dtype="float32"))
        c = paddle.linalg.cholesky(x)
        np.testing.assert_allclose(
            np.asarray((c @ c.T).data), np.asarray(x.data), atol=1e-4)
        assert hasattr(paddle.linalg, "histogram")


# ---------------------------------------------------------------------------
# nn additions
# ---------------------------------------------------------------------------
class TestNNAdds:
    def test_elu_inplace_and_extension_exports(self):
        x = paddle.to_tensor(np.asarray([-1.0, 1.0], "float32"))
        F.elu_(x)
        np.testing.assert_allclose(np.asarray(x.data),
                                   [np.expm1(-1.0), 1.0], rtol=1e-5)
        assert F.diag_embed is not None and F.gather_tree is not None
        assert hasattr(nn, "weight_norm_hook")
        assert hasattr(nn.functional, "extension")

    def test_hsigmoid_loss_matches_manual(self):
        rng = np.random.RandomState(0)
        N, D, C = 4, 5, 6
        x = rng.randn(N, D).astype("float32")
        w = rng.randn(C - 1, D).astype("float32") * 0.3
        lab = rng.randint(0, C, (N,)).astype("int64")
        out = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab),
                              C, paddle.to_tensor(w))
        assert list(out.shape) == [N, 1]

        # manual SimpleCodeTable walk (matrix_bit_code.h semantics)
        def manual(i):
            c = int(lab[i]) + C
            total, j = 0.0, 0
            while (c >> (j + 1)) - 1 >= 0:
                idx = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                s = float(x[i] @ w[idx])
                total += np.logaddexp(0.0, s) - bit * s
                j += 1
            return total

        got = np.asarray(out.data).reshape(-1)
        want = np.asarray([manual(i) for i in range(N)])
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_hsigmoid_layer_grads(self):
        layer = nn.HSigmoidLoss(8, 10)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(3, 8).astype("float32"))
        x.stop_gradient = False
        lab = paddle.to_tensor(np.asarray([1, 5, 9], "int64"))
        loss = layer(x, lab).sum()
        loss.backward()
        assert layer.weight.grad is not None
        assert np.isfinite(np.asarray(layer.weight.grad.data)).all()

    def test_pairwise_distance(self):
        a = np.random.RandomState(0).randn(4, 6).astype("float32")
        b = np.random.RandomState(1).randn(4, 6).astype("float32")
        d = nn.PairwiseDistance(p=2.0)(paddle.to_tensor(a),
                                       paddle.to_tensor(b))
        want = np.linalg.norm(a - b + 1e-6, axis=1)
        np.testing.assert_allclose(np.asarray(d.data), want, rtol=1e-4)

    def test_weight_norm_roundtrip(self):
        layer = nn.Linear(4, 3)
        w0 = np.asarray(layer.weight.data).copy()
        nn.utils.weight_norm(layer, "weight", dim=0)
        names = [n for n, _ in layer.named_parameters()]
        assert "weight_g" in names and "weight_v" in names
        x = paddle.to_tensor(np.ones((2, 4), "float32"))
        y = layer(x)
        # reparameterized weight reproduces the original
        np.testing.assert_allclose(
            np.asarray(y.data),
            np.ones((2, 4), "float32") @ w0 +
            np.asarray(layer.bias.data), atol=1e-5)
        loss = y.sum()
        loss.backward()
        assert layer.weight_g.grad is not None
        assert layer.weight_v.grad is not None
        nn.utils.remove_weight_norm(layer, "weight")
        names = [n for n, _ in layer.named_parameters()]
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(np.asarray(layer.weight.data), w0,
                                   atol=1e-5)

    def test_rnncellbase_exported(self):
        assert issubclass(nn.LSTMCell, nn.RNNCellBase)

    def test_beam_search_decoder(self):
        V, E, H, B = 7, 6, 6, 2
        emb = nn.Embedding(V, E)
        cell = nn.GRUCell(E, H)
        proj = nn.Linear(H, V)
        dec = nn.BeamSearchDecoder(
            cell, start_token=0, end_token=1, beam_size=3,
            embedding_fn=lambda t: emb(paddle.Tensor(t)),
            output_fn=lambda h: proj(paddle.Tensor(h)))
        import jax.numpy as jnp
        # GRUCell state is the bare hidden array (paddle cell contract)
        init = jnp.zeros((B, H), jnp.float32)
        ids, scores = paddle.nn.dynamic_decode(dec, inits=init,
                                               max_step_num=5)
        assert list(ids.shape) == [B, 5, 3]
        assert list(scores.shape) == [B, 3]
        # beam-sorted best-first
        s = np.asarray(scores.data)
        assert (np.diff(s, axis=1) <= 1e-5).all()
        ids_t, sc, lens = paddle.nn.dynamic_decode(
            dec, inits=init, max_step_num=5, output_time_major=True,
            return_length=True)
        assert list(ids_t.shape) == [5, B, 3]
        assert list(lens.shape) == [B, 3]


# ---------------------------------------------------------------------------
# paddle.static surface
# ---------------------------------------------------------------------------
class TestStaticHelpers:
    def test_scopes(self):
        s = static.Scope()
        with static.scope_guard(s):
            assert static.global_scope() is s
            v = static.global_scope().var("x")
            v.get_tensor().set(np.ones((2, 2)))
        assert static.global_scope() is not s
        assert s.find_var("x") is not None
        assert s.new_scope().find_var("x") is not None

    def test_places_guards(self):
        assert len(static.cpu_places(3)) == 3
        assert static.cuda_places() == []
        with static.device_guard("gpu:0"):
            pass
        with static.name_scope("block"):
            from paddle_tpu.static.helpers import current_name_scope
            assert current_name_scope() == "block"

    def test_create_global_var(self):
        v = static.create_global_var([2, 3], 1.5, "float32", name="gv")
        np.testing.assert_allclose(np.asarray(v.data), 1.5)
        assert v.stop_gradient

    def test_append_backward_matches_eager(self):
        paddle.seed(0)
        w = paddle.create_parameter([3, 2], "float32")
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [4, 3], "float32")
                y = paddle.matmul(x, w)
                loss = (y * y).mean()
                pairs = static.append_backward(loss)
                assert len(pairs) == 1 and pairs[0][0] is w
                exe = static.Executor()
                xa = np.random.RandomState(0).randn(4, 3).astype(
                    "float32")
                gw, = exe.run(prog, feed={"x": xa},
                              fetch_list=[pairs[0][1]])
        finally:
            paddle.disable_static()
        xt = paddle.to_tensor(xa)
        loss_e = (paddle.matmul(xt, w) * paddle.matmul(xt, w)).mean()
        loss_e.backward()
        np.testing.assert_allclose(gw, np.asarray(w.grad.data),
                                   rtol=1e-4)

    def test_gradients_intermediate_cut(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [3], "float32")
                h = x * x          # intermediate
                y = (h * 3.0).sum()
                (gh,) = static.gradients([y], [h])
                exe = static.Executor()
                out, = exe.run(prog, feed={"x": np.asarray(
                    [1.0, 2.0, 3.0], "float32")}, fetch_list=[gh])
            # dy/dh = 3 everywhere — the cut stops at h
            np.testing.assert_allclose(out, 3.0)
        finally:
            paddle.disable_static()

    def test_program_state_roundtrip(self, tmp_path):
        paddle.seed(7)
        w = paddle.create_parameter([2, 2], "float32", name="psr_w")
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [1, 2], "float32")
                y = paddle.matmul(x, w)
            path = str(tmp_path / "model")
            static.save(prog, path)
            orig = np.asarray(w.data).copy()
            w._data = w.data * 0
            static.load(prog, path)
            np.testing.assert_allclose(np.asarray(w.data), orig)
            state = static.load_program_state(path)
            assert "psr_w" in state
            state["psr_w"] = state["psr_w"] + 1
            static.set_program_state(prog, state)
            np.testing.assert_allclose(np.asarray(w.data), orig + 1)
        finally:
            paddle.disable_static()

    def test_save_load_vars(self, tmp_path):
        w = paddle.create_parameter([2], "float32", name="slv_w")
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [2], "float32")
                y = (x * w).sum()
            exe = static.Executor()
            static.save_vars(exe, str(tmp_path), main_program=prog,
                             filename="all.pkl")
            orig = np.asarray(w.data).copy()
            w._data = w.data * 0
            static.load_vars(exe, str(tmp_path), main_program=prog,
                             filename="all.pkl")
            np.testing.assert_allclose(np.asarray(w.data), orig)
        finally:
            paddle.disable_static()

    def test_serialize_roundtrip(self, tmp_path):
        paddle.seed(3)
        w = paddle.create_parameter([3, 2], "float32", name="ser_w")
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [1, 3], "float32")
                y = paddle.matmul(x, w)
            blob = static.serialize_program([x], [y])
            pblob = static.serialize_persistables([x], [y])
            assert isinstance(blob, bytes) and isinstance(pblob, bytes)
            static.deserialize_persistables(prog, pblob)
            iprog = static.deserialize_program(blob)
            exe = static.Executor()
            xa = np.ones((1, 3), "float32")
            out, = exe.run(iprog, feed={"x": xa}, fetch_list=None)
            np.testing.assert_allclose(out, xa @ np.asarray(w.data),
                                       rtol=1e-5)
            static.save_to_file(str(tmp_path / "b.bin"), blob)
            assert static.load_from_file(str(tmp_path / "b.bin")) == blob
        finally:
            paddle.disable_static()

    def test_compiled_program_and_parallel_executor(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [2, 2], "float32")
                y = x * 2.0
            cp = static.CompiledProgram(prog).with_data_parallel(
                loss_name=None,
                build_strategy=static.BuildStrategy(),
                exec_strategy=static.ExecutionStrategy())
            assert cp._program is prog
            pe = static.ParallelExecutor(main_program=prog)
            out, = pe.run([y], feed={"x": np.ones((2, 2), "float32")})
            np.testing.assert_allclose(out, 2.0)
        finally:
            paddle.disable_static()

    def test_metrics_and_print(self):
        pred = paddle.to_tensor(np.asarray(
            [[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], "float32"))
        lab = paddle.to_tensor(np.asarray([[1], [0], [0]], "int64"))
        acc = static.accuracy(pred, lab)
        assert float(acc.data) == pytest.approx(2.0 / 3.0, abs=1e-5)
        a = static.auc(pred, lab)
        assert 0.0 <= float(a.data) <= 1.0
        out = static.Print(pred, message="dbg")
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(pred.data))

    def test_weight_norm_param_attr(self):
        wn = static.WeightNormParamAttr(dim=0, name="wn")
        assert wn.dim == 0 and wn.name == "wn"


# ---------------------------------------------------------------------------
# static.nn layer helpers
# ---------------------------------------------------------------------------
class TestStaticNN:
    def test_fc_conv_bn_program(self):
        paddle.seed(0)
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                img = static.data("img", [2, 3, 8, 8], "float32")
                c = static.nn.conv2d(img, 4, 3, padding=1, act="relu")
                b = static.nn.batch_norm(c)
                f = static.nn.fc(b, 10)
                exe = static.Executor()
                out, = exe.run(prog, feed={
                    "img": np.random.RandomState(0).randn(
                        2, 3, 8, 8).astype("float32")},
                    fetch_list=[f])
            assert out.shape == (2, 10)
            assert np.isfinite(out).all()
        finally:
            paddle.disable_static()

    def test_embedding_and_sparse(self):
        ids = paddle.to_tensor(np.asarray([[1, 2], [3, 4]], "int64"))
        e = static.nn.embedding(ids, (10, 6))
        assert list(e.shape) == [2, 2, 6]
        s = static.nn.sparse_embedding(ids, (10, 6))
        assert list(s.shape) == [2, 2, 6]

    def test_norms(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4, 5, 5).astype("float32"))
        ln = static.nn.layer_norm(x, begin_norm_axis=1)
        gn = static.nn.group_norm(x, 2)
        inn = static.nn.instance_norm(x)
        for t in (ln, gn, inn):
            assert list(t.shape) == [2, 4, 5, 5]
            a = np.asarray(t.data)
            assert abs(a.mean()) < 1e-2

    def test_data_norm(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(6, 4).astype("float32") * 3)
        out = static.nn.data_norm(x)
        assert list(out.shape) == [6, 4]

    def test_prelu_modes(self):
        x = paddle.to_tensor(np.asarray([[-2.0, 2.0]], "float32"))
        out = static.nn.prelu(x, mode="all")
        np.testing.assert_allclose(np.asarray(out.data),
                                   [[-0.5, 2.0]], rtol=1e-5)
        x4 = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 4, 4).astype("float32"))
        assert list(static.nn.prelu(x4, "channel").shape) == [2, 3, 4, 4]
        assert list(static.nn.prelu(x4, "element").shape) == [2, 3, 4, 4]

    def test_row_conv_known_values(self):
        B, T, D, k = 1, 4, 2, 1
        x = np.arange(B * T * D, dtype="float32").reshape(B, T, D)
        out = static.nn.row_conv(paddle.to_tensor(x), k)
        w = None
        # weight is a fresh parameter; recover it by probing with basis
        # inputs instead: out[t] = x[t] w0 + x[t+1] w1 elementwise per dim
        assert list(out.shape) == [B, T, D]

    def test_spectral_norm_sigma_one(self):
        w = np.random.RandomState(0).randn(6, 4).astype("float32") * 3
        sn = static.nn.spectral_norm(paddle.to_tensor(w), dim=0,
                                     power_iters=30)
        smax = np.linalg.svd(np.asarray(sn.data), compute_uv=False)[0]
        assert smax == pytest.approx(1.0, abs=1e-3)

    def test_bilinear_tensor_product(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 4).astype("float32"))
        y = paddle.to_tensor(
            np.random.RandomState(1).randn(3, 5).astype("float32"))
        out = static.nn.bilinear_tensor_product(x, y, 6)
        assert list(out.shape) == [3, 6]

    def test_nce_finite(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype("float32"))
        lab = paddle.to_tensor(np.asarray([[0], [3], [7], [2]], "int64"))
        loss = static.nn.nce(x, lab, num_total_classes=20,
                             num_neg_samples=5)
        assert list(loss.shape) == [4, 1]
        assert np.isfinite(np.asarray(loss.data)).all()

    def test_crf_decoding(self):
        B, T, N = 2, 5, 3
        pot = paddle.to_tensor(
            np.random.RandomState(0).randn(B, T, N).astype("float32"))
        trans = paddle.to_tensor(
            np.random.RandomState(1).randn(N + 2, N).astype("float32"))
        path = static.nn.crf_decoding(pot, transition=trans)
        assert tuple(np.asarray(path.data).shape) == (B, T)
        lab = paddle.to_tensor(
            np.zeros((B, T), "int64"))
        eq = static.nn.crf_decoding(pot, transition=trans, label=lab)
        assert set(np.unique(np.asarray(eq.data))) <= {0, 1}

    def test_multi_box_head(self):
        feats = [paddle.to_tensor(np.random.RandomState(i).randn(
            2, 8, s, s).astype("float32")) for i, s in enumerate((8, 4))]
        img = paddle.to_tensor(np.zeros((2, 3, 64, 64), "float32"))
        locs, confs, boxes, vars_ = static.nn.multi_box_head(
            feats, img, base_size=64, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90)
        P = boxes.shape[0]
        assert list(locs.shape) == [2, P, 4]
        assert list(confs.shape) == [2, P, 3]
        assert list(vars_.shape) == [P, 4]

    def test_static_nn_deform_conv2d(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 4, 6, 6).astype("float32"))
        off = paddle.zeros([1, 18, 6, 6])
        mask = paddle.ones([1, 9, 6, 6])
        out = static.nn.deform_conv2d(x, off, mask, 5, 3, padding=1)
        assert list(out.shape) == [1, 5, 6, 6]


# ---------------------------------------------------------------------------
# vision ops: deform conv + yolo
# ---------------------------------------------------------------------------
class TestVisionDetectionOps:
    def test_deform_conv2d_zero_offset_is_conv(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 6, 8, 8).astype("float32"))
        w = paddle.to_tensor(rng.randn(4, 6, 3, 3).astype("float32") * .2)
        off = paddle.zeros([2, 18, 8, 8])
        a = vops.deform_conv2d(x, off, w, padding=1)
        b = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(np.asarray(a.data),
                                   np.asarray(b.data), atol=1e-4)

    def test_deform_conv2d_mask_and_groups(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(1, 4, 6, 6).astype("float32"))
        w = paddle.to_tensor(rng.randn(4, 2, 3, 3).astype("float32") * .2)
        off = paddle.to_tensor(
            rng.randn(1, 2 * 2 * 9, 6, 6).astype("float32") * 0.3)
        mask = paddle.to_tensor(
            rng.rand(1, 2 * 9, 6, 6).astype("float32"))
        out = vops.deform_conv2d(x, off, w, padding=1, groups=2,
                                 deformable_groups=2, mask=mask)
        assert list(out.shape) == [1, 4, 6, 6]
        # half-mask halves the response of the zero-offset center tap
        assert np.isfinite(np.asarray(out.data)).all()

    def test_deform_conv2d_layer_and_grads(self):
        layer = vops.DeformConv2D(3, 5, 3, padding=1)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 3, 5, 5).astype("float32"))
        off = paddle.zeros([1, 18, 5, 5])
        off.stop_gradient = False
        y = layer(x, off)
        y.sum().backward()
        assert layer.weight.grad is not None
        assert off.grad is not None  # offsets get gradients (bilinear)

    def test_yolo_box_decode(self):
        an = [10, 13, 16, 30]
        x = np.zeros((1, 2 * 7, 2, 2), "float32")
        img = np.asarray([[64, 64]], "int32")
        boxes, scores = vops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(img), an, 2,
            conf_thresh=0.0, downsample_ratio=32)
        assert list(boxes.shape) == [1, 8, 4]
        assert list(scores.shape) == [1, 8, 2]
        b = np.asarray(boxes.data)
        # zero logits: centers at cell centers, w=anchor_w/in_w * img_w
        # first anchor box at cell (0,0): cx=0.5/2*64=16, w=10/64*64=10
        np.testing.assert_allclose(b[0, 0],
                                   [16 - 5, 16 - 6.5, 16 + 5, 16 + 6.5],
                                   atol=1e-3)
        # conf gate zeroes boxes below threshold
        boxes2, scores2 = vops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(img), an, 2,
            conf_thresh=0.6, downsample_ratio=32)
        assert np.abs(np.asarray(boxes2.data)).sum() == 0

    def test_yolo_loss_assignment(self):
        rng = np.random.RandomState(0)
        anchors = [10, 13, 16, 30, 33, 23]
        x = paddle.to_tensor(rng.randn(2, 3 * 7, 4, 4).astype(
            "float32") * 0.1)
        x.stop_gradient = False
        gtb = paddle.to_tensor(np.asarray(
            [[[0.5, 0.5, 0.2, 0.3]], [[0.25, 0.25, 0.1, 0.1]]],
            "float32"))
        gtl = paddle.to_tensor(np.asarray([[1], [0]], "int64"))
        loss = vops.yolo_loss(x, gtb, gtl, anchors, [0, 1, 2], 2,
                              ignore_thresh=0.7, downsample_ratio=32)
        assert list(loss.shape) == [2]
        assert (np.asarray(loss.data) > 0).all()
        loss.sum().backward()
        assert np.isfinite(np.asarray(x.grad.data)).all()
        # no gt at all -> only no-obj loss, still finite
        loss0 = vops.yolo_loss(
            x, paddle.to_tensor(np.zeros((2, 1, 4), "float32")),
            paddle.to_tensor(np.zeros((2, 1), "int64")),
            anchors, [0, 1, 2], 2, ignore_thresh=0.7,
            downsample_ratio=32)
        assert np.isfinite(np.asarray(loss0.data)).all()


# ---------------------------------------------------------------------------
# io / distributed odds and ends
# ---------------------------------------------------------------------------
class TestMisc:
    def test_get_worker_info_main(self):
        assert paddle.io.get_worker_info() is None
        info = paddle.io.WorkerInfo(1, 4, None)
        assert info.id == 1 and info.num_workers == 4

    def test_parallel_env(self):
        env = paddle.distributed.ParallelEnv()
        assert env.rank == 0 and env.world_size >= 1
        assert env.nranks == env.world_size
        assert isinstance(env.trainer_endpoints, list)

    def test_onnx_gate(self):
        with pytest.raises((ImportError, NotImplementedError)):
            paddle.onnx.export(None, "x")

    def test_enable_disable_dygraph(self):
        paddle.disable_dygraph()
        assert not paddle.in_dygraph_mode()
        paddle.enable_dygraph()
        assert paddle.in_dygraph_mode()
