"""Async dispatch & input-pipeline overlap (ISSUE 3).

The pipelined step loop's contracts:
- DevicePrefetcher delivers batches in order, committed with the
  trainer's sharding, and its fast-path re-entry into train_step is a
  no-op placement;
- worker/iterator failures surface on the consumer; early exit joins the
  transfer thread (no leaked daemons);
- anomaly_policy='rollback' stays correct when batches arrive through
  the prefetcher (the host snapshot never aliases a prefetched buffer);
- Model.fit performs at most ONE blocking host sync per log_freq window
  (counted, not eyeballed);
- the persistent XLA compile cache serves a warm second compile on the
  CPU backend;
- the flash autotune sweep table persists across (simulated) processes;
- `python bench.py --smoke` holds the whole contract end to end.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import SpmdTrainer, async_dispatch, create_mesh
from paddle_tpu.distributed.async_dispatch import LazyValue, StepResult
from paddle_tpu.io import DataLoader
from paddle_tpu.io.device_prefetch import DevicePrefetcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_mlp(seed):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))


def ce_loss(out, label):
    return nn.functional.cross_entropy(out, label)


def make_batches(n=4, bs=16, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(bs, 16).astype(np.float32),
             rng.randint(0, 10, size=(bs,)).astype(np.int64))
            for _ in range(n)]


def _trainer(seed=0, mesh_spec=None, **kw):
    mesh = create_mesh(mesh_spec or {"dp": 8})
    model = make_mlp(seed)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    return SpmdTrainer(model, opt, ce_loss, mesh=mesh, **kw)


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------
def test_device_prefetch_order_and_sharding():
    tr = _trainer()
    batches = make_batches(6)
    pref = DevicePrefetcher(iter(batches), tr.shard_batch, depth=2)
    out = list(pref)
    assert len(out) == 6
    for (hx, hy), (dx, dy) in zip(batches, out):
        np.testing.assert_array_equal(np.asarray(dx), hx)
        np.testing.assert_array_equal(np.asarray(dy), hy)
        # committed with the trainer's batch sharding on the full mesh
        assert getattr(dx, "committed", False)
        assert len(dx.sharding.device_set) == 8
        assert dx.sharding == tr._batch_sharding(dx)
    assert not pref.alive  # producer drained and exited


def test_prefetched_steps_match_direct_feed():
    batches = make_batches(4)
    ref = _trainer(0)
    direct = [float(ref.train_step(x, y)) for x, y in batches]

    tr = _trainer(0)
    pref = DevicePrefetcher(iter(batches), tr.shard_batch, depth=3)
    got = [float(tr.train_step(x, y)) for x, y in pref]
    np.testing.assert_allclose(got, direct, rtol=1e-6, atol=1e-7)
    # fast path: re-sharding an already-committed batch found them placed
    assert pref.batches_prefetched == 4


def test_prefetcher_propagates_source_exception():
    tr = _trainer()
    batches = make_batches(2)

    def gen():
        yield batches[0]
        raise RuntimeError("boom in the loader")

    pref = DevicePrefetcher(gen(), tr.shard_batch, depth=2)
    it = iter(pref)
    next(it)
    with pytest.raises(RuntimeError, match="boom in the loader"):
        next(it)
    assert not pref.alive


def test_prefetcher_early_exit_joins_thread():
    tr = _trainer()
    pref = DevicePrefetcher(iter(make_batches(50)), tr.shard_batch,
                            depth=2)
    it = iter(pref)
    next(it)
    next(it)
    it.close()  # consumer leaves the loop early
    assert not pref.alive


# ---------------------------------------------------------------------------
# StepResult laziness
# ---------------------------------------------------------------------------
def test_train_step_returns_lazy_step_result():
    tr = _trainer(0)
    x, y = make_batches(1)[0]
    res = tr.train_step(x, y)
    assert isinstance(res, StepResult)
    before = async_dispatch.host_sync_count()
    v1 = float(res)
    v2 = float(res)  # cached: no second sync
    assert v1 == v2 and np.isfinite(v1)
    assert async_dispatch.host_sync_count() == before + 1
    assert f"{res:.4f}" == f"{v1:.4f}"
    # stats carry the step-time breakdown fields
    st = tr.stats
    for k in ("data_wait_ms", "h2d_ms", "dispatch_ms", "sync_ms",
              "compile_ms_cold", "steps_timed"):
        assert k in st
    assert st["compile_ms_cold"] > 0
    assert st["steps_timed"] == 0  # single step was the compile call


# ---------------------------------------------------------------------------
# rollback + prefetch: donation safety
# ---------------------------------------------------------------------------
def test_step_result_wraps_plain_numpy_values():
    # numpy exposes .data as a memoryview — the unwrap must not grab it
    assert float(StepResult(np.float32(2.5))) == 2.5
    assert float(StepResult(np.array(1.25))) == 1.25
    assert float(LazyValue(lambda: np.float64(0.5))) == 0.5


def test_thread_prefetcher_slow_iterator_does_not_block_emission():
    """A slow batch ITERATOR must not stall delivery of batches that are
    already collated (workers pull tasks outside the emit lock)."""
    from paddle_tpu.io.dataloader import _Prefetcher

    def make_iter():
        def gen():
            yield (lambda: "fast")
            time.sleep(1.5)  # stream stall while producing task 2
            yield (lambda: "slow")
        return gen()

    p = _Prefetcher(make_iter, num_workers=2, capacity=4)
    it = iter(p)
    t0 = time.monotonic()
    first = next(it)
    waited = time.monotonic() - t0
    assert first == "fast"
    assert waited < 1.0, f"emission blocked {waited:.2f}s on the iterator"
    assert next(it) == "slow"


def test_rollback_correct_with_prefetched_batches():
    batches = make_batches(5, bs=8, seed=3)
    bomb_x = batches[2][0].copy()
    bomb_x[0, 0] = np.nan
    fed = [(bomb_x if i == 2 else x, y)
           for i, (x, y) in enumerate(batches)]

    clean = _trainer(13, {"dp": 2})
    for i, (x, y) in enumerate(batches):
        if i != 2:
            clean.train_step(x, y)

    tr = _trainer(13, {"dp": 2}, anomaly_policy="rollback")
    pref = DevicePrefetcher(iter(fed), tr.shard_batch, depth=3)
    for x, y in pref:
        tr.train_step(x, y)
    assert tr.stats["rollback_steps"] == 1
    assert tr._step_count == 4  # the poisoned step never counted
    # the restored state must match a run that never saw the bomb: a
    # host snapshot aliasing a prefetched/donated buffer would diverge
    for n in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[n]),
                                   np.asarray(clean.params[n]),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# fit(): at most one blocking sync per log_freq window
# ---------------------------------------------------------------------------
class _DS:
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 16).astype(np.float32)
        self.y = rng.randint(0, 10, (n,)).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_fit_syncs_at_most_once_per_log_window():
    from paddle_tpu.hapi import Model
    paddle.seed(11)
    m = Model(make_mlp(11))
    m.prepare(paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 8})
    steps, log_freq = 8, 4
    async_dispatch.reset_host_sync_count()
    m.fit(_DS(8 * 8), batch_size=8, epochs=1, verbose=0, shuffle=False,
          log_freq=log_freq)
    syncs = async_dispatch.host_sync_count()
    # windows at steps 0 and 4, plus the end-of-epoch resolve
    assert 1 <= syncs <= steps // log_freq + 2, syncs
    assert syncs < steps  # and emphatically not one per step


def test_fit_loss_curve_unchanged_by_async_loop():
    """Laziness must not change WHAT is computed: per-batch losses seen
    by a callback equal the eager loop's (the PR-0 parity bar)."""
    from paddle_tpu.hapi import Model

    def run(mesh):
        paddle.seed(7)
        m = Model(make_mlp(7))
        kw = {"mesh": mesh} if mesh else {}
        m.prepare(paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=m.parameters()),
                  nn.CrossEntropyLoss(), **kw)
        seen = []

        class Rec(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.append(float(logs["loss"]))

        m.fit(_DS(48), batch_size=16, epochs=2, verbose=0, shuffle=False,
              callbacks=[Rec()])
        return seen

    np.testing.assert_allclose(run({"dp": 8}), run(None),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# persistent compile cache: warm start on the CPU backend
# ---------------------------------------------------------------------------
def test_compile_cache_warm_start_cpu(monkeypatch):
    from jax._src import compilation_cache as _cc
    import jax

    x, y = make_batches(1)[0]
    tr = _trainer(0, {"dp": 1})
    float(tr.train_step(x, y))  # populates the persistent cache

    jax.clear_caches()  # drop in-memory executables, keep the disk cache
    tr2 = _trainer(0, {"dp": 1})
    hits = [0]
    orig = _cc.get_executable_and_time

    def counting(*a, **kw):
        ex, t = orig(*a, **kw)
        if ex is not None:
            hits[0] += 1
        return ex, t

    monkeypatch.setattr(_cc, "get_executable_and_time", counting)
    float(tr2.train_step(x, y))
    assert hits[0] >= 1  # the recompile was served from disk


def test_compile_cache_env_off(monkeypatch):
    from paddle_tpu.utils import compile_cache as cc
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", "off")
    monkeypatch.setattr(cc, "_STATE", {"resolved": False, "dir": None})
    assert cc.ensure_compile_cache() is None
    assert not cc.compile_cache_enabled()


# ---------------------------------------------------------------------------
# DataLoader thread-prefetcher hygiene
# ---------------------------------------------------------------------------
class _CountingDS:
    fetched = 0

    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        type(self).fetched += 1
        return np.full(4, i, np.float32)


def test_thread_prefetcher_backpressure():
    """Workers must not collate the whole dataset ahead of a slow
    consumer — the reorder buffer is bounded."""
    _CountingDS.fetched = 0
    loader = DataLoader(_CountingDS(64), batch_size=4, num_workers=2,
                        prefetch_factor=2, use_shared_memory=False)
    it = iter(loader)
    next(it)
    time.sleep(0.5)  # let unbounded workers run away, if they could
    # capacity (2*2=4 batches) + in-flight (2) + consumed (1), in items
    assert _CountingDS.fetched <= 10 * 4, _CountingDS.fetched
    it.close()


def test_thread_prefetcher_propagates_dataset_error():
    class Bad:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            if i >= 8:
                raise ValueError("bad sample")
            return np.zeros(4, np.float32)

    loader = DataLoader(Bad(), batch_size=4, num_workers=2,
                        use_shared_memory=False)
    with pytest.raises(ValueError, match="bad sample"):
        list(loader)


def test_thread_prefetcher_iterator_error_no_deadlock():
    from paddle_tpu.io.dataloader import _Prefetcher

    def make_iter():
        def gen():
            yield (lambda: 1)
            raise RuntimeError("iter broke")
        return gen()

    p = _Prefetcher(make_iter, num_workers=2, capacity=4)
    out = []
    with pytest.raises(RuntimeError, match="iter broke"):
        for v in p:
            out.append(v)
    assert out == [1]


def test_thread_prefetcher_early_exit_joins_workers():
    base = threading.active_count()
    loader = DataLoader(_CountingDS(64), batch_size=4, num_workers=3,
                        use_shared_memory=False)
    it = iter(loader)
    next(it)
    it.close()  # break out early: workers must be woken and joined
    deadline = time.monotonic() + 5
    while threading.active_count() > base and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= base


# ---------------------------------------------------------------------------
# metrics: device-array update path (no eager np.asarray per step)
# ---------------------------------------------------------------------------
def test_accuracy_update_stays_on_device():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.metric import Accuracy

    rng = np.random.RandomState(0)
    logits = rng.randn(8, 10).astype(np.float32)
    labels = rng.randint(0, 10, (8, 1)).astype(np.int64)

    m = Accuracy()
    pre = m.compute(Tensor(jnp.asarray(logits)), Tensor(jnp.asarray(labels)))
    assert isinstance(pre.data, jax.Array)
    m.update(pre)
    # the running total is a device scalar — nothing was pulled to host
    assert isinstance(m.total[0], jax.Array)

    ref = Accuracy()
    ref_pre = ref.compute(Tensor(np.asarray(logits)), labels)
    ref.update(np.asarray(ref_pre.data))
    assert m.accumulate() == pytest.approx(ref.accumulate())


# ---------------------------------------------------------------------------
# flash autotune sweep table persistence
# ---------------------------------------------------------------------------
def _flash_mod():
    # paddle_tpu.ops re-exports flash_attention the FUNCTION; fetch the
    # module itself
    import importlib
    return importlib.import_module("paddle_tpu.ops.flash_attention")


def test_autotune_sweep_table_roundtrip(tmp_path, monkeypatch):
    fa = _flash_mod()
    path = tmp_path / "flash_autotune.json"
    monkeypatch.setenv("PADDLE_TPU_FLASH_AUTOTUNE_CACHE", str(path))
    key = ("v5e", 2048, 64, True)
    fa._persist_sweep_entry(key, (256, 512))
    assert json.loads(path.read_text()) == {"v5e|2048|64|1": [256, 512]}

    # a "new process": empty in-memory cache, unloaded store
    monkeypatch.setattr(fa, "_SWEEP_STORE_STATE", {"loaded": False})
    monkeypatch.setattr(fa, "_SWEEP_CACHE", {})
    fa._load_sweep_store()
    assert fa._SWEEP_CACHE[key] == (256, 512)

    # corrupt table: ignored, never raises
    path.write_text("{not json")
    monkeypatch.setattr(fa, "_SWEEP_STORE_STATE", {"loaded": False})
    monkeypatch.setattr(fa, "_SWEEP_CACHE", {})
    fa._load_sweep_store()
    assert fa._SWEEP_CACHE == {}


def test_autotune_cache_env_off(monkeypatch):
    fa = _flash_mod()
    monkeypatch.setenv("PADDLE_TPU_FLASH_AUTOTUNE_CACHE", "off")
    assert fa._sweep_store_path() is None
    fa._persist_sweep_entry(("v5e", 1024, 64, True), (128, 128))  # no-op


# ---------------------------------------------------------------------------
# bench --smoke: the dispatch-path contract, end to end
# ---------------------------------------------------------------------------
@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_bench_smoke_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "bench.py", "--smoke"], cwd=REPO,
                       capture_output=True, text=True, timeout=580,
                       env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "bench_smoke" and out["ok"]
    for k in ("data_wait_ms", "h2d_ms", "dispatch_ms", "sync_ms",
              "compile_ms_cold", "compile_ms_warm"):
        assert k in out, k
    assert out["host_syncs_measured"] <= 1
