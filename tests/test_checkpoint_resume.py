"""Trainer checkpoint / auto-resume tests (VERDICT r2 #10).

Done criterion: kill/restore mid-training reproduces the uninterrupted
loss curve — asserted at step level for SpmdTrainer/GPipeTrainer and at
epoch level for Model.fit(auto_resume=True).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)

CRIT = GPTPretrainingCriterion()


def _gpt_trainer(seed, mesh_axes, zero=0, k_steps=1, scheduler=False):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    lr = paddle.optimizer.lr.StepDecay(learning_rate=1e-3, step_size=2,
                                       gamma=0.5) if scheduler else 1e-3
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    st = DistributedStrategy()
    if zero:
        st.sharding = True
        st.sharding_configs = {"stage": zero}
    if k_steps > 1:
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": k_steps}
    return SpmdTrainer(model, opt, lambda o, l: CRIT(o, l),
                       mesh=create_mesh(mesh_axes), strategy=st)


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = rng.randint(0, 128, (4, 16)).astype(np.int32)
        out.append((ids, np.roll(ids, -1, 1).astype(np.int64)))
    return out


def test_spmd_trainer_save_load_resumes_exactly(tmp_path):
    batches = _batches(6)
    ref = _gpt_trainer(1, {"dp": 2}, zero=2, scheduler=True)
    full = [float(ref.train_step(x, y)) for x, y in batches]

    a = _gpt_trainer(1, {"dp": 2}, zero=2, scheduler=True)
    for x, y in batches[:3]:
        a.train_step(x, y)
    p = str(tmp_path / "ck")
    a.save(p, extra={"note": "mid"})

    # a DIFFERENTLY seeded trainer adopts the checkpoint
    b = _gpt_trainer(99, {"dp": 2}, zero=2, scheduler=True)
    extra = b.load(p)
    assert extra == {"note": "mid"}
    assert b._step_count == 3
    resumed = [float(b.train_step(x, y)) for x, y in batches[3:]]
    np.testing.assert_allclose(resumed, full[3:], rtol=2e-4, atol=2e-5)


def test_checkpoint_restores_onto_different_mesh(tmp_path):
    """Shardings come from the loading trainer: dp8/ZeRO-3 checkpoint
    restores onto a dp2 mesh and continues identically."""
    batches = _batches(4, seed=3)
    a = _gpt_trainer(5, {"dp": 8}, zero=3)
    for x, y in batches[:2]:
        a.train_step(x, y)
    p = str(tmp_path / "ck8")
    a.save(p)
    rest_a = [float(a.train_step(x, y)) for x, y in batches[2:]]

    b = _gpt_trainer(6, {"dp": 2}, zero=1)
    b.load(p)
    rest_b = [float(b.train_step(x, y)) for x, y in batches[2:]]
    np.testing.assert_allclose(rest_b, rest_a, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gradient_merge_buffer_checkpointed(tmp_path):
    """Mid-accumulation kill: the grad-merge buffer rides the
    checkpoint so the k-step window continues, not restarts."""
    batches = _batches(8, seed=7)
    ref = _gpt_trainer(2, {"dp": 2}, k_steps=4)
    full = [float(ref.train_step(x, y)) for x, y in batches]

    a = _gpt_trainer(2, {"dp": 2}, k_steps=4)
    for x, y in batches[:2]:   # mid-window (2 of 4 accumulated)
        a.train_step(x, y)
    p = str(tmp_path / "ckgm")
    a.save(p)
    b = _gpt_trainer(55, {"dp": 2}, k_steps=4)
    b.load(p)
    resumed = [float(b.train_step(x, y)) for x, y in batches[2:]]
    np.testing.assert_allclose(resumed, full[2:], rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpipe_trainer_save_load(tmp_path):
    from paddle_tpu.distributed.pipeline import GPipeTrainer
    from paddle_tpu.models.gpt import gpt_pipeline_parts

    def build(seed):
        paddle.seed(seed)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False,
                        tie_word_embeddings=False)
        model = GPTForCausalLM(cfg)
        pre, blocks, post = gpt_pipeline_parts(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        return GPipeTrainer(pre, blocks, post, opt,
                            lambda o, l: CRIT(o, l),
                            mesh=create_mesh({"pp": 2}),
                            num_microbatches=2, remat=False)

    batches = _batches(4, seed=11)
    ref = build(3)
    full = [float(ref.train_step(x, y)) for x, y in batches]
    a = build(3)
    for x, y in batches[:2]:
        a.train_step(x, y)
    p = str(tmp_path / "ckpp")
    a.save(p)
    b = build(77)
    b.load(p)
    resumed = [float(b.train_step(x, y)) for x, y in batches[2:]]
    np.testing.assert_allclose(resumed, full[2:], rtol=2e-4, atol=2e-5)


def test_load_rejects_mismatched_model(tmp_path):
    a = _gpt_trainer(1, {"dp": 2})
    p = str(tmp_path / "ckbad")
    a.save(p)
    paddle.seed(0)
    other = nn.Sequential(nn.Linear(8, 8))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=other.parameters())
    tr = SpmdTrainer(other, opt, lambda o, l: (o - l).square().mean(),
                     mesh=create_mesh({"dp": 2}))
    with pytest.raises(ValueError):
        tr.load(p)


def _fit_losses(model_factory, data, epochs, save_dir=None,
                auto_resume=False, compiled=True):
    from paddle_tpu.hapi import Model
    m = Model(model_factory())
    kw = dict(mesh={"dp": 2}) if compiled else {}
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters()),
              nn.CrossEntropyLoss(), **kw)
    seen = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(round(float(logs["loss"]), 6))

    m.fit(data, batch_size=16, epochs=epochs, verbose=0, shuffle=False,
          save_dir=save_dir, auto_resume=auto_resume, callbacks=[Rec()])
    return seen


@pytest.mark.parametrize("compiled", [True, False])
def test_model_fit_auto_resume(tmp_path, compiled):
    """Kill after 2 of 4 epochs; a fresh Model resumes at epoch 2 and
    reproduces the uninterrupted loss curve."""
    from paddle_tpu.vision.models import LeNet

    class DS:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randn(1, 28, 28).astype(np.float32),
                    np.array([i % 10], np.int64))

    def factory():
        # fresh name scope = what a restarted process sees (state-dict
        # keys are name-based, reference unique_name semantics)
        from paddle_tpu.utils import unique_name
        paddle.seed(42)
        with unique_name.guard():
            return LeNet()

    full = _fit_losses(factory, DS(), 4, compiled=compiled)

    d = str(tmp_path / ("c" if compiled else "e"))
    first = _fit_losses(factory, DS(), 2, save_dir=d, auto_resume=True,
                        compiled=compiled)
    second = _fit_losses(factory, DS(), 4, save_dir=d, auto_resume=True,
                         compiled=compiled)
    np.testing.assert_allclose(first + second, full, rtol=2e-4,
                               atol=2e-5)


def test_auto_resume_mode_mismatch_raises(tmp_path):
    """Compiled checkpoint + eager restart (or vice versa) must fail
    with a clear message, not a deserialization error."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.models import LeNet

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randn(1, 28, 28).astype(np.float32),
                    np.array([i % 10], np.int64))

    d = str(tmp_path / "mix")
    paddle.seed(0)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 2})
    m.fit(DS(), batch_size=16, epochs=1, verbose=0, save_dir=d,
          auto_resume=True)

    paddle.seed(0)
    m2 = Model(LeNet())
    m2.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                    parameters=m2.parameters()),
               nn.CrossEntropyLoss())  # eager this time
    with pytest.raises(RuntimeError, match="compiled mode"):
        m2.fit(DS(), batch_size=16, epochs=2, verbose=0, save_dir=d,
               auto_resume=True)


def test_auto_checkpoints_pruned(tmp_path):
    import os
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.models import LeNet

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randn(1, 28, 28).astype(np.float32),
                    np.array([i % 10], np.int64))

    d = str(tmp_path / "pr")
    paddle.seed(0)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss(), mesh={"dp": 2})
    m.fit(DS(), batch_size=16, epochs=5, verbose=0, save_dir=d,
          auto_resume=True)
    auto = os.path.join(d, "auto")
    cks = [n for n in os.listdir(auto) if n.startswith("ckpt-")]
    assert len(cks) == Model._AUTO_KEEP
