"""FLAGS_check_nan_inf coverage for the COMPILED train step.

Reference: paddle/fluid/framework/details/nan_inf_utils_detail.cc:293
(every kernel output is scanned when the flag is on and training aborts
naming the bad tensor). Here the jitted step returns a per-tensor bool
vector and the host raises PreconditionNotMetError with the names.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.errors import PreconditionNotMetError
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy


class NanAt(nn.Layer):
    """Emits NaN when an input row carries the sentinel value."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        out = self.fc(x)
        mask = (x > 900.0).astype("float32").max()
        # log(1-mask): 0 on clean batches, -inf when the sentinel is
        # present — poisons loss and grads only on demand
        return out + paddle.log(1.0 - mask)


def mse(out, y):
    return F.mse_loss(out, y)


def batch(sentinel=False):
    rng = np.random.RandomState(0)
    x = rng.randn(4, 4).astype(np.float32)
    if sentinel:
        x[0, 0] = 1000.0
    return x, rng.randn(4, 2).astype(np.float32)


def make_trainer(**kw):
    paddle.seed(0)
    model = NanAt()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    return SpmdTrainer(model, opt, mse, mesh=create_mesh({"dp": 1}), **kw)


def test_guard_off_by_default_trains_through_nan():
    tr = make_trainer()
    assert not tr._check_nan_inf
    x, y = batch(sentinel=True)
    loss = float(tr.train_step(x, y))  # silently inf, like any compiled fn
    assert not np.isfinite(loss)


def test_guard_catches_injected_nan_with_names():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        tr = make_trainer()
        assert tr._check_nan_inf
        x, y = batch()
        assert np.isfinite(float(tr.train_step(x, y)))  # clean step ok
        xb, yb = batch(sentinel=True)
        with pytest.raises(PreconditionNotMetError) as ei:
            tr.train_step(xb, yb)
        assert "loss" in str(ei.value)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_guard_fp16_catches_nan_loss_but_not_grad_overflow():
    """Under fp16 scaling, grad infs are the scaler's skip signal (no
    abort), but a non-finite UNSCALED loss must still raise — otherwise
    the scaler shrinks forever on a genuinely divergent model."""
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        paddle.seed(0)
        model = NanAt()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        st = DistributedStrategy()
        st.amp = True
        st.amp_configs = {"use_bf16": False,
                          "init_loss_scaling": 2.0 ** 14}
        tr = SpmdTrainer(model, opt, mse, mesh=create_mesh({"dp": 1}),
                         strategy=st)
        x, y = batch()
        assert np.isfinite(float(tr.train_step(x, y)))
        xb, yb = batch(sentinel=True)
        with pytest.raises(PreconditionNotMetError, match="loss"):
            tr.train_step(xb, yb)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_guard_covers_gradient_merge_accum_path():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        paddle.seed(0)
        model = NanAt()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        st = DistributedStrategy()
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 2}
        tr = SpmdTrainer(model, opt, mse, mesh=create_mesh({"dp": 1}),
                         strategy=st)
        x, y = batch()
        tr.train_step(x, y)  # clean accum
        xb, yb = batch(sentinel=True)
        with pytest.raises(PreconditionNotMetError):
            tr.train_step(xb, yb)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
