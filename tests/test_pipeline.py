"""GPipe pipeline trainer tests on the virtual 8-device CPU mesh.

Reference analogue: pipeline_mnist.py under test_dist_base (2-stage loss
parity vs single-process) + SectionWorker schedule semantics.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import create_mesh
from paddle_tpu.distributed.pipeline import GPipeTrainer, stack_block_params
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)
from paddle_tpu.models.gpt import gpt_pipeline_parts


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(32, 32)

    def forward(self, x):
        return F.relu(self.fc(x))


def build_model(n_blocks=4, seed=0):
    paddle.seed(seed)
    pre = nn.Linear(16, 32)
    blocks = [Block() for _ in range(n_blocks)]
    post = nn.Linear(32, 10)
    return pre, blocks, post


def eager_reference(batches, n_blocks=4, lr=0.1, seed=0):
    pre, blocks, post = build_model(n_blocks, seed)
    params = (list(pre.parameters()) +
              [p for b in blocks for p in b.parameters()] +
              list(post.parameters()))
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=params)
    losses = []
    for x, y in batches:
        h = pre(paddle.to_tensor(x))
        for b in blocks:
            h = b(h)
        out = post(h)
        loss = F.cross_entropy(out, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def make_batches(n=3, bs=16, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(bs, 16).astype(np.float32),
             rng.randint(0, 10, (bs,)).astype(np.int64))
            for _ in range(n)]


def run_pipeline(batches, mesh_spec, num_micro, n_blocks=4, lr=0.1,
                 seed=0, remat=False):
    pre, blocks, post = build_model(n_blocks, seed)
    params = (list(pre.parameters()) +
              [p for b in blocks for p in b.parameters()] +
              list(post.parameters()))
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=params)
    tr = GPipeTrainer(pre, blocks, post, opt,
                      lambda o, l: F.cross_entropy(o, l),
                      mesh=create_mesh(mesh_spec),
                      num_microbatches=num_micro, remat=remat)
    return tr, [float(tr.train_step(x, y)) for x, y in batches]


def test_pp4_matches_eager():
    batches = make_batches()
    ref = eager_reference(batches)
    _, got = run_pipeline(batches, {"pp": 4}, num_micro=4)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_pp2_dp2_matches_eager():
    batches = make_batches()
    ref = eager_reference(batches)
    _, got = run_pipeline(batches, {"dp": 2, "pp": 2}, num_micro=2)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_pp2_with_remat_matches():
    batches = make_batches(2)
    _, plain = run_pipeline(batches, {"pp": 2}, num_micro=2, remat=False)
    _, remat = run_pipeline(batches, {"pp": 2}, num_micro=2, remat=True)
    np.testing.assert_allclose(plain, remat, rtol=1e-5, atol=1e-6)


def test_microbatch_count_independent():
    batches = make_batches(2)
    _, m2 = run_pipeline(batches, {"pp": 2}, num_micro=2)
    _, m4 = run_pipeline(batches, {"pp": 2}, num_micro=4)
    np.testing.assert_allclose(m2, m4, rtol=2e-4, atol=2e-5)


def test_block_params_sharded_over_pp():
    batches = make_batches(1)
    tr, _ = run_pipeline(batches, {"pp": 4}, num_micro=2)
    stacked = tr.params["blocks"]["fc.weight"]
    assert stacked.shape == (4, 32, 32)
    # each pp rank holds 1 of 4 layers
    assert stacked.addressable_shards[0].data.shape == (1, 32, 32)


def test_sync_to_model_roundtrip():
    batches = make_batches(2)
    tr, _ = run_pipeline(batches, {"pp": 2}, num_micro=2)
    tr.sync_to_model()
    w0 = np.asarray(tr._blocks_ref[0].fc.weight.data)
    assert np.all(np.isfinite(w0))
    np.testing.assert_allclose(
        w0, np.asarray(tr.params["blocks"]["fc.weight"])[0])


def test_non_divisible_blocks_raises():
    pre, blocks, post = build_model(3)
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=pre.parameters() + post.parameters())
    with pytest.raises(ValueError):
        GPipeTrainer(pre, blocks, post, opt,
                     lambda o, l: F.cross_entropy(o, l),
                     mesh=create_mesh({"pp": 2}), num_microbatches=2)


def test_buffered_stage_raises():
    paddle.seed(0)
    pre = nn.Sequential(nn.Linear(16, 32), nn.BatchNorm1D(32))
    blocks = [Block(), Block()]
    post = nn.Linear(32, 10)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=pre.parameters())
    with pytest.raises(NotImplementedError):
        GPipeTrainer(pre, blocks, post, opt,
                     lambda o, l: F.cross_entropy(o, l),
                     mesh=create_mesh({"pp": 2}), num_microbatches=2)


def test_gpt_pipeline_pp2dp2():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16,
                    use_flash_attention=False,
                    tie_word_embeddings=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    pre, blocks, post = gpt_pipeline_parts(model)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    tr = GPipeTrainer(pre, blocks, post, opt,
                      lambda o, l: crit(o, l),
                      mesh=create_mesh({"dp": 2, "pp": 2}),
                      num_microbatches=2, remat=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int64)
    losses = [float(tr.train_step(ids, labels)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))

    # eager single-device reference on the same init
    paddle.seed(0)
    model2 = GPTForCausalLM(cfg)
    opt2 = paddle.optimizer.Adam(learning_rate=1e-3,
                                 parameters=model2.parameters())
    ref = []
    for _ in range(6):
        out = model2(paddle.to_tensor(ids))
        loss = crit(out, paddle.to_tensor(labels))
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        ref.append(float(loss))
    np.testing.assert_allclose(losses, ref, rtol=2e-3, atol=1e-4)


def test_tied_embeddings_rejected():
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16,
                    tie_word_embeddings=True)
    model = GPTForCausalLM(cfg)
    with pytest.raises(ValueError):
        gpt_pipeline_parts(model)


def _flops_of(pipe, ids, labels):
    import jax.numpy as jnp
    micro_in = pipe._microbatch(ids)
    micro_lab = pipe._microbatch(labels)
    step = pipe._build(training=True)
    c = step.lower(pipe.params, pipe.opt_state,
                   jnp.asarray(0.1, jnp.float32),
                   jnp.asarray(1, jnp.int32), micro_in, micro_lab).compile()
    ca = c.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca["flops"])


def _head_pipe(dedupe, M=4, seed=33):
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=1024, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=32, use_flash_attention=False,
                    tie_word_embeddings=False)
    model = GPTForCausalLM(cfg)
    pre, blocks, post = gpt_pipeline_parts(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    mesh = create_mesh({"pp": 4})
    return GPipeTrainer(pre, blocks, post, opt, lambda o, l: crit(o, l),
                        mesh=mesh, num_microbatches=M, remat=False,
                        dedupe_head=dedupe)


@pytest.mark.slow
def test_dedupe_head_cuts_compiled_flops():
    # efficiency claim (compiled-flops comparison, extra AOT lowering);
    # slow-marked under the tight tier-1 budget — head-dedup
    # CORRECTNESS stays tier-1 via test_dedupe_head_parity
    """VERDICT r2 #9 'Done' criterion: sharding the vocab head over pp
    ranks cuts compiled FLOPs >=30% vs the masked-everywhere GPipe at
    pp=4 (head was computed M times per rank, now M/S)."""
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)
    f_masked = _flops_of(_head_pipe(False), ids, labels)
    f_dedupe = _flops_of(_head_pipe(True), ids, labels)
    assert f_dedupe < 0.7 * f_masked, (f_dedupe, f_masked)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_dedupe_head_parity():
    """Deduped head computes the same losses as the masked fallback."""
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (8, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)
    a = _head_pipe(True, seed=5)
    b = _head_pipe(False, seed=5)
    la = [float(a.train_step(ids, labels)) for _ in range(3)]
    lb = [float(b.train_step(ids, labels)) for _ in range(3)]
    np.testing.assert_allclose(la, lb, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_dedupe_head_falls_back_when_not_divisible():
    """M=6 not divisible by pp=4: trainer quietly uses the masked head."""
    pipe = _head_pipe(True, M=6, seed=9)
    assert not pipe.dedupe_head
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 1024, (12, 32)).astype(np.int32)
    loss = float(pipe.train_step(ids, np.roll(ids, -1, 1).astype(np.int64)))
    assert np.isfinite(loss)
