"""PR-1 step-time performance pass: blocked cross-entropy parity,
flash-attention autotuner lookup, scan-over-layers parity.

The contract under test (ISSUE 1): the fused LM loss must match
`cross_entropy` values AND gradients without ever materializing the
[N, V] logits tensor; the autotuner must return tabled tiles with a
safe fallback; the scanned block stack must be numerically identical
to the unrolled loop (loss + grads) both standalone and through
SpmdTrainer's recompute_configs={'scan_layers': True} knob.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import get_block_sizes, pick_vocab_block
from paddle_tpu.ops.fused_cross_entropy import fused_linear_cross_entropy


# ---------------------------------------------------------------------------
# blocked cross-entropy: value + gradient parity vs the reference op
# ---------------------------------------------------------------------------
def _ref_loss(x, w, lab, ignore_index=-100):
    """Reference: full-logits softmax CE, mean over non-ignored rows."""
    logits = x.astype(jnp.float32) @ w.astype(jnp.float32).T
    lse = jax.scipy.special.logsumexp(logits, axis=1)
    picked = jnp.take_along_axis(
        logits, jnp.clip(lab, 0, w.shape[0] - 1)[:, None], axis=1)[:, 0]
    valid = lab != ignore_index
    loss = jnp.where(valid, lse - picked, 0.0)
    return jnp.sum(loss) / jnp.maximum(
        jnp.sum(valid.astype(jnp.float32)), 1.0)


def _problem(n=48, h=24, v=103, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, h).astype(dtype))
    w = jnp.asarray(rng.randn(v, h).astype(dtype))
    lab = jnp.asarray(rng.randint(0, v, (n,)).astype(np.int32))
    return x, w, lab


@pytest.mark.parametrize("block", [16, 32, 128])  # 103 vocab: pad + partial
def test_fused_ce_matches_reference_fp32(block):
    x, w, lab = _problem()
    lab = lab.at[5].set(-100).at[11].set(-100)

    fused = lambda a, b: fused_linear_cross_entropy(a, b, lab,
                                                    block_size=block)
    ref = lambda a, b: _ref_loss(a, b, lab)
    assert float(fused(x, w)) == pytest.approx(float(ref(x, w)), abs=1e-5)
    gf = jax.grad(fused, argnums=(0, 1))(x, w)
    gr = jax.grad(ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gf[0], gr[0], atol=1e-5)
    np.testing.assert_allclose(gf[1], gr[1], atol=1e-5)


def test_fused_ce_reductions_and_all_ignored():
    x, w, lab = _problem(n=8, v=50)
    none = fused_linear_cross_entropy(x, w, lab, reduction="none",
                                      block_size=16)
    assert none.shape == (8,)
    s = fused_linear_cross_entropy(x, w, lab, reduction="sum",
                                   block_size=16)
    assert float(s) == pytest.approx(float(jnp.sum(none)), rel=1e-6)
    # every row ignored: loss 0, no NaN from the 0-count denominator
    ig = jnp.full_like(lab, -100)
    m = fused_linear_cross_entropy(x, w, ig, block_size=16)
    assert float(m) == 0.0


def test_fused_ce_bf16_keeps_fp32_accumulation():
    x, w, lab = _problem(n=32, h=32, v=96, dtype=np.float32)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    got = float(fused_linear_cross_entropy(xb, wb, lab, block_size=32))
    want = float(_ref_loss(xb, wb, lab))
    assert got == pytest.approx(want, rel=2e-2)
    gx, gw = jax.grad(
        lambda a, b: fused_linear_cross_entropy(a, b, lab, block_size=32),
        argnums=(0, 1))(xb, wb)
    assert gx.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(gx.astype(jnp.float32))))


def test_fused_ce_never_materializes_logits():
    """The point of the op: no [N, V] (or [N, V_padded]) buffer in the
    compiled fwd+bwd. Checked against the lowered HLO text — the
    reference formulation demonstrably contains the tensor, the fused
    one must not."""
    n, h, v, block = 128, 16, 512, 128
    x, w, lab = _problem(n=n, h=h, v=v)
    full = f"{n}x{v}x"          # tensor<128x512xf32> etc.

    ref_txt = jax.jit(jax.grad(lambda a: _ref_loss(a, w, lab))) \
        .lower(x).as_text()
    assert full in ref_txt      # the probe string actually detects it

    fused_txt = jax.jit(jax.grad(
        lambda a, b: fused_linear_cross_entropy(a, b, lab,
                                                block_size=block),
        argnums=(0, 1))).lower(x, w).as_text()
    assert full not in fused_txt


def test_fused_ce_functional_wrapper_grads():
    """nn.functional.fused_linear_cross_entropy: tape-level parity with
    cross_entropy(matmul(x, w.T)) — same loss, same dx/dw."""
    xn, wn, labn = _problem(n=16, h=8, v=40)
    lab2d = np.asarray(labn)[:, None].astype(np.int64)

    x1 = paddle.to_tensor(np.asarray(xn), stop_gradient=False)
    w1 = paddle.to_tensor(np.asarray(wn), stop_gradient=False)
    loss1 = F.fused_linear_cross_entropy(x1, w1,
                                         paddle.to_tensor(lab2d))
    loss1.backward()

    x2 = paddle.to_tensor(np.asarray(xn), stop_gradient=False)
    w2 = paddle.to_tensor(np.asarray(wn), stop_gradient=False)
    logits = paddle.matmul(x2, w2, transpose_y=True)
    loss2 = F.cross_entropy(logits, paddle.to_tensor(lab2d))
    loss2.backward()

    assert float(loss1) == pytest.approx(float(loss2), abs=1e-6)
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(w1.grad.numpy(), w2.grad.numpy(),
                               atol=1e-5)


def test_pick_vocab_block():
    assert pick_vocab_block(50304) == 2048
    assert pick_vocab_block(100) == 64     # <= vocab, power of two
    assert pick_vocab_block(1) == 1
    assert pick_vocab_block(50304, want=512) == 512


# ---------------------------------------------------------------------------
# flash-attention block-size autotuner
# ---------------------------------------------------------------------------
def test_autotune_table_exact_hit():
    assert get_block_sizes(2048, 64, True, device_kind="v5e") == (512, 1024)
    # device_kind strings come from jax verbatim; aliases normalize
    assert get_block_sizes(2048, 64, True, device_kind="TPU v5 lite") \
        == (512, 1024)


def test_autotune_nearest_seq_fallback():
    # 16384 is not tabled for (v5e, d64, causal): nearest tabled seq
    # (8192) supplies the tiles, clamped to divide the actual seq
    assert get_block_sizes(16384, 64, True, device_kind="v5e") \
        == (1024, 1024)


def test_autotune_unknown_kind_uses_defaults():
    assert get_block_sizes(2048, 64, True, device_kind="gpu-h100") \
        == (512, 512)


def test_autotune_clamps_to_short_seq():
    bq, bk = get_block_sizes(128, 64, True, device_kind="v5e")
    assert bq <= 128 and bk <= 128 and 128 % bq == 0 and 128 % bk == 0


def test_autotune_env_kill_switch(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLASH_AUTOTUNE", "0")
    assert get_block_sizes(2048, 64, True, device_kind="v5e") == (512, 512)


def test_autotune_sweep_mode_foreign_kind_uses_table(monkeypatch):
    # sweep only tunes the local device; asking for another kind must
    # fall through to the table, not run (and rerun) a local sweep
    monkeypatch.setenv("PADDLE_TPU_FLASH_AUTOTUNE", "sweep")
    assert get_block_sizes(2048, 64, True, device_kind="v5e") \
        == (512, 1024)


@pytest.mark.slow
def test_autotune_sweep_on_device():
    """One-shot on-device sweep (TPU only): must return valid tiles and
    cache them for the process."""
    if jax.devices()[0].platform == "cpu":
        pytest.skip("sweep timings are meaningless off-TPU")
    from paddle_tpu.ops import flash_attention as fa
    bq, bk = fa.autotune_sweep(1024, 64, True, iters=2)
    assert 1024 % bq == 0 and 1024 % bk == 0
    key = (fa._device_kind(), 1024, 64, True)
    assert fa._SWEEP_CACHE[key] == (bq, bk)


# ---------------------------------------------------------------------------
# scan-over-layers
# ---------------------------------------------------------------------------
def _tiny_cfg(**kw):
    from dataclasses import replace
    from paddle_tpu.models.gpt import gpt_configs
    return replace(gpt_configs()["gpt3-tiny"], use_flash_attention=False,
                   **kw)


def _gpt_loss_and_grads(cfg, ids, labels, scan, recompute=False):
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.train()
    if recompute:
        m.enable_recompute(policy="dots_no_batch")
    m.enable_scan_layers(scan)
    loss = GPTPretrainingCriterion()(m(paddle.to_tensor(ids)),
                                     paddle.to_tensor(labels))
    loss.backward()
    grads = {n: np.asarray(p.grad.data) for n, p in m.named_parameters()
             if p.grad is not None}
    return float(loss), grads


def test_scan_layers_matches_unrolled():
    cfg = _tiny_cfg()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    l0, g0 = _gpt_loss_and_grads(cfg, ids, labels, scan=False)
    l1, g1 = _gpt_loss_and_grads(cfg, ids, labels, scan=True)
    assert l1 == pytest.approx(l0, abs=1e-5)
    assert set(g0) == set(g1)   # every per-layer param still gets a grad
    for name in g0:
        np.testing.assert_allclose(g1[name], g0[name], atol=2e-4,
                                   err_msg=name)


def test_scan_layers_with_fused_ce_and_remat():
    """The bench path: scan + per-iteration jax.checkpoint + blocked CE
    — still bit-comparable to the plain unrolled full-logits run."""
    cfg = _tiny_cfg()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    l0, g0 = _gpt_loss_and_grads(cfg, ids, labels, scan=False)
    l1, g1 = _gpt_loss_and_grads(_tiny_cfg(fused_ce=True), ids, labels,
                                 scan=True, recompute=True)
    assert l1 == pytest.approx(l0, abs=1e-5)
    assert set(g0) == set(g1)
    for name in g0:
        np.testing.assert_allclose(g1[name], g0[name], atol=2e-4,
                                   err_msg=name)


def test_scan_falls_back_when_not_scannable():
    """Dropout>0 in train mode would share one mask across layers under
    scan; the model must silently take the unrolled path, not diverge."""
    from paddle_tpu.models import GPTForCausalLM
    cfg = _tiny_cfg(dropout=0.1)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.train()
    m.enable_scan_layers(True)
    assert not m.gpt._scan_ok(None)
    m.eval()                      # dropout dead: scan becomes legal
    assert m.gpt._scan_ok(None)


def test_spmd_trainer_scan_layers_knob():
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion

    cfg = _tiny_cfg(fused_ce=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    def run(scan):
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        crit = GPTPretrainingCriterion()
        st = DistributedStrategy()
        st.recompute = True
        st.recompute_configs = {"policy": "dots_no_batch",
                                "scan_layers": scan}
        mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
        tr = SpmdTrainer(m, opt, lambda o, l: crit(o, l), mesh=mesh,
                         strategy=st)
        return [float(tr.train_step(ids, labels)) for _ in range(3)]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4,
                               atol=1e-5)


def test_spmd_trainer_scan_layers_rejects_scanless_model(monkeypatch):
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy
    import paddle_tpu.nn as nn

    m = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    st = DistributedStrategy()
    st.recompute_configs = {"scan_layers": True}
    mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
    with pytest.raises(NotImplementedError, match="enable_scan_layers"):
        SpmdTrainer(m, opt, lambda o, l: o.sum(), mesh=mesh, strategy=st)
