"""MoE / expert parallelism tests (virtual 8-device CPU mesh).

The reference snapshot has no MoE (SURVEY.md §2.5: "ABSENT — design
fresh"), so the ground truth here is an independent per-token numpy
reference, and the parity contract is: dense single-device == GSPMD
expert-parallel == explicit shard_map all_to_all formulation.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.mesh import (NamedSharding, PartitionSpec,
                                         mesh_guard)
from paddle_tpu.distributed.moe import (MoELayer, collect_aux_losses,
                                        moe_capacity, top_k_gating)


def softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def moe_reference(x, gate_w, w_up, b_up, w_down, b_down, top_k, capacity,
                  normalize=True):
    """Independent per-token loop implementation of Switch/GShard routing
    (sequential greedy capacity assignment, gelu FFN experts)."""
    B, S, H = x.shape
    E = gate_w.shape[1]
    y = np.zeros_like(x)
    for b in range(B):
        fill = np.zeros(E, dtype=int)
        # choices per token (top-k by prob, chosen greedily in seq order)
        probs = softmax(x[b] @ gate_w)         # [S, E]
        order = np.argsort(-probs, axis=-1)[:, :top_k]  # [S, k]
        gates = np.take_along_axis(probs, order, axis=-1)
        if normalize and top_k > 1:
            gates = gates / (gates.sum(-1, keepdims=True) + 1e-9)
        # capacity filled in choice-major order (all 1st choices, then
        # 2nd choices), matching the layer's per-choice cumsum
        keep = np.zeros((S, top_k), dtype=bool)
        for kk in range(top_k):
            for s in range(S):
                e = order[s, kk]
                if fill[e] < capacity:
                    keep[s, kk] = True
                    fill[e] += 1
        for s in range(S):
            for kk in range(top_k):
                if not keep[s, kk]:
                    continue
                e = order[s, kk]
                h1 = x[b, s] @ w_up[e] + b_up[e]
                h1 = 0.5 * h1 * (1 + np.tanh(
                    np.sqrt(2 / np.pi) * (h1 + 0.044715 * h1 ** 3)))
                y[b, s] += gates[s, kk] * (h1 @ w_down[e] + b_down[e])
    return y


def make_layer(E=4, H=8, F=16, top_k=2, cf=8.0, seed=0):
    paddle.seed(seed)
    return MoELayer(H, F, num_experts=E, top_k=top_k, capacity_factor=cf,
                    aux_loss_coeff=0.01)


def test_gating_shapes_and_capacity():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(2, 16, 4).astype(np.float32))
    cap = 3
    dispatch, combine, aux, zloss = top_k_gating(logits, 2, cap)
    assert dispatch.shape == (2, 16, 4, cap)
    # every capacity slot used at most once per expert
    per_slot = np.asarray(dispatch).sum(axis=1)       # [B, E, C]
    assert per_slot.max() <= 1.0 + 1e-6
    # each token dispatched to at most top_k slots
    per_tok = np.asarray(dispatch).sum(axis=(2, 3))   # [B, S]
    assert per_tok.max() <= 2 + 1e-6
    # combine weights of surviving tokens sum to ~1 (normalized)
    surv = per_tok == 2
    csum = np.asarray(combine).sum(axis=(2, 3))
    np.testing.assert_allclose(csum[surv], 1.0, rtol=1e-5)
    assert float(aux) > 0 and float(zloss) > 0


def test_moe_matches_loop_reference():
    layer = make_layer(E=4, H=8, F=16, top_k=2, cf=8.0)
    rng = np.random.RandomState(1)
    x = rng.randn(2, 12, 8).astype(np.float32)
    out = layer(paddle.to_tensor(x))
    cap = moe_capacity(12, 4, 2, 8.0)
    ref = moe_reference(
        x, np.asarray(layer.gate.data),
        np.asarray(layer.experts.w_up.data),
        np.asarray(layer.experts.b_up.data),
        np.asarray(layer.experts.w_down.data),
        np.asarray(layer.experts.b_down.data), 2, cap)
    np.testing.assert_allclose(np.asarray(out.data), ref, rtol=1e-4,
                               atol=1e-5)


def test_moe_drops_tokens_at_low_capacity():
    """cf small => some tokens overflow; their output is 0 (residual
    carries them in a transformer block)."""
    layer = make_layer(E=4, H=8, F=16, top_k=1, cf=0.3)
    rng = np.random.RandomState(2)
    x = rng.randn(1, 16, 8).astype(np.float32)
    out = np.asarray(layer(paddle.to_tensor(x)).data)
    dropped = np.all(out == 0.0, axis=-1)
    assert dropped.sum() > 0


def test_shard_map_all_to_all_matches_dense():
    """Explicit lax.all_to_all formulation over an 8-device 'ep' axis
    reproduces the dense single-device layer bit-for-bit (dp==ep: tokens
    sharded on batch, experts sharded on E)."""
    E, H, Fd = 8, 8, 16
    layer = make_layer(E=E, H=H, F=Fd, top_k=2, cf=8.0)
    rng = np.random.RandomState(3)
    x = rng.randn(8, 6, H).astype(np.float32)
    dense_out = np.asarray(layer(paddle.to_tensor(x)).data)

    mesh = create_mesh({"ep": 8})
    from paddle_tpu.distributed.mesh import shard_map

    gate = layer.gate.data
    wu, bu = layer.experts.w_up.data, layer.experts.b_up.data
    wd, bd = layer.experts.w_down.data, layer.experts.b_down.data

    def fn(xs, gate, wu, bu, wd, bd):
        y, aux, zl = layer._fn_shard_map(xs, gate, wu, bu, wd, bd)
        return y

    P = PartitionSpec
    smapped = shard_map(
        fn, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=P("ep"))
    out = np.asarray(jax.jit(smapped)(jnp.asarray(x), gate, wu, bu,
                                      wd, bd))
    np.testing.assert_allclose(out, dense_out, rtol=1e-4, atol=1e-5)


def test_aux_loss_collected_and_differentiable():
    layer = make_layer()
    rng = np.random.RandomState(4)
    x = paddle.to_tensor(rng.randn(2, 8, 8).astype(np.float32))
    with collect_aux_losses() as aux:
        out = layer(x)
    assert len(aux) == 1 and float(aux[0].data) > 0
    # aux loss backprops into the gate
    total = out.sum() + aux[0]
    total.backward()
    assert layer.gate.grad is not None
    assert np.any(np.asarray(layer.gate.grad.data) != 0)


def _moe_gpt(seed=0, ep_experts=4):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, use_flash_attention=False,
                    moe_num_experts=ep_experts, moe_top_k=2,
                    moe_capacity_factor=4.0, moe_every_n_layers=2)
    return cfg, GPTForCausalLM(cfg)


def test_gpt_moe_spmd_trainer_parity():
    """GPT-MoE under SpmdTrainer: dp2 x ep4 mesh loss matches the
    single-device run step by step (the expert-parallel layout changes
    placement, not math)."""
    from paddle_tpu.models import GPTPretrainingCriterion
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(3):
        ids = rng.randint(0, 64, (4, 16)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1).astype(np.int64)
        batches.append((ids, labels))

    losses = {}
    for name, mesh_axes in [("single", {"dp": 1}),
                            ("ep", {"dp": 2, "ep": 4})]:
        cfg, model = _moe_gpt(seed=7)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        mesh = create_mesh(mesh_axes)
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l), mesh=mesh)
        losses[name] = [float(tr.train_step(x, y)) for x, y in batches]
        # expert weights actually sharded over ep
        if name == "ep":
            wu = tr.params["gpt.blocks.1.mlp.experts.w_up"]
            assert "ep" in str(wu.sharding.spec)
    np.testing.assert_allclose(losses["ep"], losses["single"], rtol=2e-4,
                               atol=2e-5)
    # training moves the loss
    assert losses["ep"][-1] != losses["ep"][0]


def test_gpt_moe_aux_loss_in_compiled_trainer():
    """The compiled trainer adds router aux losses: a trainer whose
    criterion is constant-zero still produces a positive loss (the aux
    term), proving collection inside the traced step."""
    cfg, model = _moe_gpt(seed=1)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=model.parameters())
    mesh = create_mesh({"dp": 1})
    zero = lambda o, l: (o.sum() * 0.0)
    tr = SpmdTrainer(model, opt, zero, mesh=mesh)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (2, 16)).astype(np.int32)
    loss = float(tr.train_step(ids, ids.astype(np.int64)))
    assert loss > 0.0


def test_gpt_moe_with_recompute():
    """Review regression: MoE + activation recompute (aux losses must
    leave the jax.checkpoint region as explicit outputs, not leak as
    tracers through the collector)."""
    from paddle_tpu.models import GPTPretrainingCriterion
    cfg, model = _moe_gpt(seed=3)
    model.enable_recompute()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    st = DistributedStrategy()
    st.recompute = True
    tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                     mesh=create_mesh({"dp": 1}), strategy=st)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (2, 16)).astype(np.int32)
    l0 = float(tr.train_step(ids, ids.astype(np.int64)))
    l1 = float(tr.train_step(ids, ids.astype(np.int64)))
    assert np.isfinite(l0) and l1 < l0


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_moe_pipeline_aux_flows():
    """MoE blocks under GPipeTrainer: the router aux loss reaches the
    training loss (gate weights receive gradient and move)."""
    from paddle_tpu.distributed.pipeline import GPipeTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, use_flash_attention=False,
                    tie_word_embeddings=False, moe_num_experts=4,
                    moe_top_k=2, moe_capacity_factor=4.0,
                    moe_aux_loss_coeff=0.05)
    model = GPTForCausalLM(cfg)
    pre, blocks, post = gpt_pipeline_parts(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    mesh = create_mesh({"dp": 2, "pp": 2})
    pipe = GPipeTrainer(pre, blocks, post, opt, lambda o, l: crit(o, l),
                        mesh=mesh, num_microbatches=2, remat=True)
    gate_key = [k for k in pipe.params["blocks"] if "gate" in k][0]
    g0 = np.asarray(pipe.params["blocks"][gate_key]).copy()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (4, 16)).astype(np.int32)
    loss = float(pipe.train_step(ids, np.roll(ids, -1, 1).astype(np.int64)))
    assert np.isfinite(loss)
    g1 = np.asarray(pipe.params["blocks"][gate_key])
    assert np.any(g0 != g1), "router gate got no gradient under pipeline"


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_gpt_moe_pipeline_loss_includes_aux():
    """Pipeline loss parity with SpmdTrainer for an MoE model on the
    FIRST step (same params, same batch): both must include the router
    aux term."""
    from paddle_tpu.distributed.pipeline import GPipeTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    crit = GPTPretrainingCriterion()
    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=16, use_flash_attention=False,
              tie_word_embeddings=False, moe_num_experts=4, moe_top_k=2,
              moe_capacity_factor=8.0, moe_aux_loss_coeff=0.05)
    rng = np.random.RandomState(5)
    ids = rng.randint(0, 64, (4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    paddle.seed(21)
    m1 = GPTForCausalLM(GPTConfig(**kw))
    tr = SpmdTrainer(m1, paddle.optimizer.SGD(
        learning_rate=0.0, parameters=m1.parameters()),
        lambda o, l: crit(o, l), mesh=create_mesh({"dp": 1}))
    ref = float(tr.train_step(ids, labels))

    paddle.seed(21)
    m2 = GPTForCausalLM(GPTConfig(**kw))
    pre, blocks, post = gpt_pipeline_parts(m2)
    pipe = GPipeTrainer(pre, blocks, post, paddle.optimizer.SGD(
        learning_rate=0.0, parameters=m2.parameters()),
        lambda o, l: crit(o, l), mesh=create_mesh({"pp": 2}),
        num_microbatches=2, remat=False)
    got = float(pipe.train_step(ids, labels))
    np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_moe_trainer_ignores_stale_global_mesh():
    """Review regression: a process-global mesh left over from earlier
    code (default_mesh/dp_train_step) must not leak wrong-mesh sharding
    constraints into an MoE trainer built on its own explicit mesh."""
    from paddle_tpu.distributed.mesh import set_mesh
    from paddle_tpu.models import GPTPretrainingCriterion
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    cfg, model = _moe_gpt(seed=9)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    ref_cfg, ref_model = _moe_gpt(seed=9)
    ref_opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=ref_model.parameters())
    ref_tr = SpmdTrainer(ref_model, ref_opt, lambda o, l: crit(o, l),
                         mesh=create_mesh({"dp": 1}))
    ref = [float(ref_tr.train_step(ids, labels)) for _ in range(2)]

    stale = create_mesh({"dp": 8})
    set_mesh(stale)
    try:
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=create_mesh({"dp": 2, "ep": 4}))
        got = [float(tr.train_step(ids, labels)) for _ in range(2)]
    finally:
        set_mesh(None)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_eager_moe_backward_after_default_mesh_pollution():
    """Full-suite regression: an earlier default_mesh() (hapi strategy-
    only path) must not leak sharding constraints into the eager tape's
    vjp trace — batch 2 is not divisible by the cached dp-8 mesh."""
    from paddle_tpu.distributed.mesh import default_mesh, set_mesh
    default_mesh()  # caches a dp-8 global mesh
    try:
        layer = make_layer(E=4, H=8, F=16)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 8, 8).astype(np.float32),
                             stop_gradient=False)
        y = layer(x)
        y.sum().backward()
        assert layer.gate.grad is not None
    finally:
        set_mesh(None)


def test_moe_trainer_handles_ragged_batch():
    """Batch not divisible by dp: the dispatch constraint drops to
    replicated instead of crashing the compile."""
    from paddle_tpu.models import GPTPretrainingCriterion
    crit = GPTPretrainingCriterion()
    cfg, model = _moe_gpt(seed=13)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                     mesh=create_mesh({"dp": 8}))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (2, 16)).astype(np.int32)  # 2 % 8 != 0
    loss = float(tr.train_step(ids, ids.astype(np.int64)))
    assert np.isfinite(loss)
