"""Router RPC shim (ISSUE 18 satellite): a replica behind the
length-prefixed msgpack-over-socket boundary must be indistinguishable
from an in-process engine — same results, same prefix fingerprints,
same aggregator scrape — and the fleet loadtest must run end-to-end
with every replica behind a proxy."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.inference.loadgen import MultiTenantWorkload, \
    run_fleet_loadtest
from paddle_tpu.inference.router import Router, ReplicaRPCServer, \
    RPCReplicaProxy
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import FleetAggregator

VOCAB = 97


@pytest.fixture(scope="module")
def engines():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    # kv_block_size=4: prefix fingerprints only exist for FULL blocks,
    # and the proxy test asserts a non-empty fingerprint set
    return [InferenceEngine(m, batch_slots=2, kv_layout="paged",
                            kv_block_size=4, seed=i) for i in range(2)]


def test_rpc_proxy_and_fleet_loadtest(engines):
    """One replica served over a loopback socket: add/step/results,
    prefix summary parity with the in-process engine, a
    FleetAggregator scrape THROUGH the proxy — then the full fleet
    loadtest with rpc=True wrapping EVERY routed replica in a
    server+proxy pair (concurrent replica threads: the regression
    guard for the cold-trace race)."""
    srv = ReplicaRPCServer(engines[0]).start()
    px = RPCReplicaProxy(srv.address)
    try:
        rid = px.add_request(np.arange(1, 9, dtype=np.int32),
                             max_new_tokens=4)
        assert px.has_work
        while px.has_work:
            px.step_or_raise()
        px.refresh_stats()
        assert rid in px.results and len(px.results[rid]) == 4
        summ = px.prefix_summary()
        assert isinstance(summ["fingerprints"], set)
        assert summ["fingerprints"] == \
            engines[0].prefix_summary()["fingerprints"]
        assert summ["fingerprints"], "prompt of 8 tokens with block=4 " \
            "must fingerprint at least one full block"
        out = FleetAggregator([px]).scrape()
        assert out["new_requests"] == 1
    finally:
        px.close()
        srv.stop()

    rep = run_fleet_loadtest(Router(engines, policy="prefix"),
                             num_requests=6, rate_rps=200.0,
                             workload=MultiTenantWorkload(VOCAB, seed=0),
                             seed=0, rpc=True)
    assert rep["rpc"] is True
    assert rep["num_requests"] == 6
    assert rep["tokens_generated"] > 0
