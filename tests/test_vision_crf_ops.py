"""grid_sample/affine_grid/temporal_shift, RoI ops, new losses,
clip_by_norm, crop, mean_iou, viterbi_decode.

References: grid_sampler_op.h, affine_grid_op.h, temporal_shift_op.h,
roi_align_op.h, fluid dice_loss/npair_loss, clip_by_norm_op.h,
crop_tensor_op, mean_iou_op.h, crf_decoding_op.h.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.metric import mean_iou
from paddle_tpu.text import viterbi_decode
from paddle_tpu.vision import ops as V


def test_affine_grid_identity_and_grid_sample_roundtrip():
    n, c, h, w = 2, 3, 5, 7
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(n, c, h, w).astype(np.float32))
    theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32),
                    (n, 1, 1))
    grid = F.affine_grid(paddle.to_tensor(theta), (n, c, h, w))
    out = F.grid_sample(x, grid)
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(x.data),
                               rtol=1e-5, atol=1e-5)


def test_grid_sample_flip_and_zero_padding():
    x = paddle.to_tensor(
        np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    # horizontal flip
    theta = np.array([[[-1, 0, 0], [0, 1, 0]]], np.float32)
    grid = F.affine_grid(paddle.to_tensor(theta), (1, 1, 2, 2))
    out = np.asarray(F.grid_sample(x, grid).data)
    np.testing.assert_allclose(out[0, 0], [[1, 0], [3, 2]], atol=1e-5)
    # sampling fully outside -> zeros
    far = np.full((1, 2, 2, 2), 5.0, np.float32)
    out2 = np.asarray(F.grid_sample(x, paddle.to_tensor(far)).data)
    np.testing.assert_allclose(out2, 0.0)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_grid_sample_differentiable():
    x = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32),
                         stop_gradient=False)
    grid = paddle.to_tensor(np.zeros((1, 2, 2, 2), np.float32))
    F.grid_sample(x, grid).sum().backward()
    assert float(np.asarray(x.grad.data).sum()) == pytest.approx(4.0)


def test_temporal_shift_moves_channels():
    nt, c, h, w = 4, 4, 1, 1   # n=2 videos of t=2
    x = np.arange(nt * c, dtype=np.float32).reshape(nt, c, h, w)
    out = np.asarray(F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                                      shift_ratio=0.25).data)
    v = x.reshape(2, 2, c)
    # channel 0 shifts from t+1; channel 1 from t-1; channels 2-3 stay
    assert out[0, 0, 0, 0] == v[0, 1, 0]       # t=0 takes t=1
    assert out[1, 0, 0, 0] == 0.0              # t=1 takes padding
    assert out[0, 1, 0, 0] == 0.0              # t=0 takes padding
    assert out[1, 1, 0, 0] == v[0, 0, 1]       # t=1 takes t=0
    np.testing.assert_array_equal(out[:, 2:, 0, 0], x[:, 2:, 0, 0])


def test_roi_align_constant_map():
    """On a constant feature map every RoI bin must equal the constant."""
    x = paddle.to_tensor(np.full((1, 2, 16, 16), 3.5, np.float32))
    boxes = paddle.to_tensor(
        np.array([[0, 0, 8, 8], [4, 4, 12, 15]], np.float32))
    out = np.asarray(V.roi_align(x, boxes, output_size=4).data)
    assert out.shape == (2, 2, 4, 4)
    np.testing.assert_allclose(out, 3.5, atol=1e-5)


def test_roi_align_gradient_ramp():
    """On a horizontal ramp, bin means must increase left to right."""
    ramp = np.tile(np.arange(16, dtype=np.float32), (16, 1))
    x = paddle.to_tensor(ramp.reshape(1, 1, 16, 16))
    boxes = paddle.to_tensor(np.array([[0, 0, 15, 15]], np.float32))
    out = np.asarray(V.roi_align(x, boxes, output_size=4).data)[0, 0]
    for j in range(3):
        assert (out[:, j] < out[:, j + 1]).all()


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_roi_pool_takes_max():
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 1, 1] = 9.0
    out = np.asarray(V.roi_pool(
        paddle.to_tensor(x),
        paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32)),
        output_size=2).data)
    # sampled max (bilinear grid) peaks NEAR the spike, exact argmax-bin
    # parity is documented as not preserved
    assert out[0, 0, 0, 0] > 5.0
    assert out[0, 0, 1, 1] == pytest.approx(0.0, abs=1e-4)
    assert out[0, 0, 0, 0] == out.max()


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_dice_and_npair_losses():
    probs = paddle.to_tensor(
        np.array([[[0.9, 0.1], [0.2, 0.8]]], np.float32))
    labels = paddle.to_tensor(np.array([[[0], [1]]], np.int64))
    d = float(F.dice_loss(probs, labels))
    assert 0.0 < d < 0.2  # near-perfect prediction -> small loss

    rng = np.random.RandomState(0)
    a = paddle.to_tensor(rng.randn(4, 8).astype(np.float32),
                         stop_gradient=False)
    p = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    lab = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
    loss = F.npair_loss(a, p, lab)
    loss.backward()
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(a.grad.data)).all()


def test_clip_by_norm():
    x = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    out = np.asarray(paddle.clip_by_norm(x, 1.0).data)
    np.testing.assert_allclose(out, [0.6, 0.8], rtol=1e-5)
    # under the cap: unchanged
    np.testing.assert_allclose(
        np.asarray(paddle.clip_by_norm(x, 100.0).data), [3.0, 4.0])


def test_crop():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(4, 6))
    out = np.asarray(paddle.crop(x, shape=[2, 3], offsets=[1, 2]).data)
    np.testing.assert_array_equal(out, np.asarray(x.data)[1:3, 2:5])
    out2 = np.asarray(paddle.crop(x, shape=[-1, 2], offsets=[2, 0]).data)
    np.testing.assert_array_equal(out2, np.asarray(x.data)[2:, :2])
    with pytest.raises(ValueError):
        paddle.crop(x, shape=[9, 9], offsets=[0, 0])


def test_mean_iou():
    pred = np.array([[0, 0, 1, 1]], np.int64)
    gt = np.array([[0, 1, 1, 1]], np.int64)
    miou, wrong, correct = mean_iou(pred, gt, num_classes=3)
    # one mismatch (pred 0, gt 1) increments wrong for BOTH classes
    np.testing.assert_array_equal(correct, [1, 2, 0])
    np.testing.assert_array_equal(wrong, [1, 1, 0])
    # class 0: 1/2; class 1: 2/3; class 2 has no pixels (excluded)
    assert miou == pytest.approx((0.5 + 2 / 3) / 2)


def brute_viterbi(em, tr, length):
    best, path = -np.inf, None
    t, n = em.shape
    for seq in itertools.product(range(n), repeat=length):
        s = em[0, seq[0]]
        for i in range(1, length):
            s += tr[seq[i - 1], seq[i]] + em[i, seq[i]]
        if s > best:
            best, path = s, seq
    return best, path


def test_viterbi_decode_matches_brute_force():
    rng = np.random.RandomState(0)
    b, t, n = 3, 5, 4
    em = rng.randn(b, t, n).astype(np.float32)
    tr = rng.randn(n, n).astype(np.float32)
    lengths = np.array([5, 3, 4], np.int64)
    scores, paths = viterbi_decode(em, tr, lengths,
                                   include_bos_eos_tag=False)
    for i in range(b):
        want_s, want_p = brute_viterbi(em[i], tr, int(lengths[i]))
        assert float(np.asarray(scores.data)[i]) == \
            pytest.approx(want_s, rel=1e-4), f"row {i}"
        got = tuple(np.asarray(paths.data)[i][:int(lengths[i])].tolist())
        assert got == want_p, f"row {i}: {got} vs {want_p}"


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_roi_align_differentiable():
    """Review fix: roi_align must connect to autograd (a detection
    backbone trains through it)."""
    x = paddle.to_tensor(np.ones((1, 1, 8, 8), np.float32),
                         stop_gradient=False)
    boxes = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
    V.roi_align(x, boxes, output_size=2).sum().backward()
    g = np.asarray(x.grad.data)
    assert g.sum() == pytest.approx(4.0, rel=1e-4)  # 2x2 bins of mean 1
    assert (g >= 0).all() and g.max() > 0


def test_roi_align_border_clamp_and_mean_iou_ignore_index():
    """Review fixes: border samples clamp to the edge pixel with full
    weight (reference bilinear_interpolate), and out-of-range labels
    (ignore_index) contribute nothing to mean_iou."""
    x = paddle.to_tensor(np.ones((1, 1, 8, 8), np.float32))
    # tiny edge RoI: aligned sampling puts centers slightly outside;
    # on an all-ones map every bin must still be exactly 1.0
    boxes = paddle.to_tensor(np.array([[0, 0, 1, 1]], np.float32))
    out = np.asarray(V.roi_align(x, boxes, output_size=2).data)
    np.testing.assert_allclose(out, 1.0, atol=1e-6)

    pred = np.array([0, 1, 1], np.int64)
    gt = np.array([0, 255, -1], np.int64)   # ignore labels
    miou, wrong, correct = mean_iou(pred, gt, num_classes=2)
    np.testing.assert_array_equal(correct, [1, 0])
    # the two mismatches count the (in-range) predicted class only
    np.testing.assert_array_equal(wrong, [0, 2])
