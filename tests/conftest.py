"""Test configuration.

Mirrors the reference CI strategy (SURVEY.md §4): everything runs on host
devices so the suite is hermetic; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (XLA_FLAGS host-platform device count), the same
way the reference tests Fleet transforms without a cluster.

Must set env BEFORE jax is imported anywhere.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force the host backend even when an accelerator plugin (axon TPU tunnel)
# was registered at interpreter start: env vars are too late by then, the
# config flag is not. 8 virtual CPU devices exercise the multi-chip
# sharding paths (SURVEY.md §4's "multi-node without a cluster" strategy).
jax.config.update("jax_platforms", "cpu")

# Numeric-grad checks need exact fp32 matmuls (the backend's default
# precision is bf16-pass based, fine for training, too loose for OpTest).
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compile cache: the suite is dominated by XLA compiles of tiny
# graphs; cache them across pytest processes (same trick as the reference's
# ccache-heavy CI).
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

# ... with one guard: jaxlib 0.4.37's CPU backend ABORTS (duplicate JIT
# symbol registration) when a multi-device SPMD executable is
# deserialized from the persistent cache — two identically-configured
# SpmdTrainers (test_checkpoint_resume) used to kill the whole pytest
# run with it, and a warm cache killed even the first trainer (latent in
# the seed, masked there by that file failing collection on the old
# `from jax import shard_map`). Single-device executables (the hundreds
# of tiny jits that dominate suite compile time) deserialize fine, so:
# serve cache hits only for 1-partition/1-replica programs; SPMD
# programs always recompile (their entries are still written, so
# nothing else regresses if a future jaxlib fixes deserialization).
# The guard also honors compile_cache.suspend_cpu_cache_hits(): the
# serving engine (inference.engine) brackets DONATED prefill/decode
# compiles with it on CPU, because deserialized executables mis-alias
# donated operands on this jaxlib (PR 2's rollback hazard) — that is
# what lets the engine tests run safely under this suite's warm cache.
from paddle_tpu.utils.compile_cache import \
    _install_cpu_spmd_guard  # noqa: E402

_install_cpu_spmd_guard()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


# ---- tier-1 wall-budget guard (opt-in: PADDLE_TPU_TIER1_AUTOSPLIT=1) ----
#
# The fast lane (-m 'not slow') runs under one hard timeout (ROADMAP's
# 870s); a single overgrown test file can push the whole suite past it.
# With autosplit on, each run records per-file fast-lane wall time to
# tests/.tier1_durations.json, and at collection any file whose LAST
# recorded fast lane exceeded the per-file budget (~60s,
# PADDLE_TPU_TIER1_FILE_BUDGET_S) has its unmarked tests auto-promoted
# to the slow lane — the suite self-heals instead of timing out.
# bench.py --smoke reads the same recording and goes red on drift, so
# the promotion never hides silently.  Off by default: the default
# tier-1 collection is byte-identical to a repo without this hook.

_AUTOSPLIT = os.environ.get("PADDLE_TPU_TIER1_AUTOSPLIT", "") == "1"
_T1_DURATIONS: dict = {}


def pytest_collection_modifyitems(config, items):
    if not _AUTOSPLIT:
        return
    from paddle_tpu.testing import tier1_budget
    recorded = tier1_budget.load_durations()
    if not recorded:
        return
    over = {f for f, _ in tier1_budget.files_over_budget(recorded)}
    if not over:
        return
    slow = pytest.mark.slow
    for item in items:
        fname = os.path.basename(str(item.fspath))
        if fname in over and item.get_closest_marker("slow") is None:
            item.add_marker(slow)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    if not _AUTOSPLIT or item.get_closest_marker("slow") is not None:
        yield
        return
    import time
    t0 = time.perf_counter()
    yield
    fname = os.path.basename(str(item.fspath))
    _T1_DURATIONS[fname] = (_T1_DURATIONS.get(fname, 0.0)
                            + time.perf_counter() - t0)


def pytest_sessionfinish(session, exitstatus):
    if not _AUTOSPLIT or not _T1_DURATIONS:
        return
    from paddle_tpu.testing import tier1_budget
    tier1_budget.record_durations(
        _T1_DURATIONS,
        tier1_budget.durations_path(os.path.dirname(__file__)))
