"""Test configuration.

Mirrors the reference CI strategy (SURVEY.md §4): everything runs on host
devices so the suite is hermetic; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (XLA_FLAGS host-platform device count), the same
way the reference tests Fleet transforms without a cluster.

Must set env BEFORE jax is imported anywhere.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force the host backend even when an accelerator plugin (axon TPU tunnel)
# was registered at interpreter start: env vars are too late by then, the
# config flag is not. 8 virtual CPU devices exercise the multi-chip
# sharding paths (SURVEY.md §4's "multi-node without a cluster" strategy).
jax.config.update("jax_platforms", "cpu")

# Numeric-grad checks need exact fp32 matmuls (the backend's default
# precision is bf16-pass based, fine for training, too loose for OpTest).
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compile cache: the suite is dominated by XLA compiles of tiny
# graphs; cache them across pytest processes (same trick as the reference's
# ccache-heavy CI).
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

# ... with one guard: jaxlib 0.4.37's CPU backend ABORTS (duplicate JIT
# symbol registration) when a multi-device SPMD executable is
# deserialized from the persistent cache — two identically-configured
# SpmdTrainers (test_checkpoint_resume) used to kill the whole pytest
# run with it, and a warm cache killed even the first trainer (latent in
# the seed, masked there by that file failing collection on the old
# `from jax import shard_map`). Single-device executables (the hundreds
# of tiny jits that dominate suite compile time) deserialize fine, so:
# serve cache hits only for 1-partition/1-replica programs; SPMD
# programs always recompile (their entries are still written, so
# nothing else regresses if a future jaxlib fixes deserialization).
# The guard also honors compile_cache.suspend_cpu_cache_hits(): the
# serving engine (inference.engine) brackets DONATED prefill/decode
# compiles with it on CPU, because deserialized executables mis-alias
# donated operands on this jaxlib (PR 2's rollback hazard) — that is
# what lets the engine tests run safely under this suite's warm cache.
from paddle_tpu.utils.compile_cache import \
    _install_cpu_spmd_guard  # noqa: E402

_install_cpu_spmd_guard()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield
