"""Doctor-driven autotune controller (ISSUE 16).

The perf loop, contract-tested end to end:

- doctor verdicts carry MACHINE-readable actions (op/param/env/
  candidates) and the knob-axis registry resolves them — nobody
  string-parses advice;
- the greedy coordinate-descent controller converges to a planted best
  on a synthetic K-knob surface in <= K+2 trials (vs the full grid),
  never revisits a trialed (axis, value), accepts only beyond the noise
  floor, and rolls back planted regressions / recompile storms with an
  ``autotune-rollback`` flight-recorder bundle each;
- accepted winners commit to the unified tuning table WITH provenance
  (source/run/improvement) and round-trip through the on-disk table;
- the live tier is edge-triggered (one episode per SLO signal, no
  retrigger storm), quiesce-gated, hot-applies a merged prefill-bucket
  subset with ZERO recompiles on a real warmed engine, and survives an
  episode failure without killing serving;
- BENCH_rows.jsonl compaction keeps the newest rows per (run,
  candidate) and leaves sweep-resume semantics unchanged.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:                          # `import bench`
    sys.path.insert(0, REPO)

from paddle_tpu.autotune import AutotuneController, autotune_mode
from paddle_tpu.autotune.knobs import AXES, axis_for, axis_for_action
from paddle_tpu.autotune.live import (LiveRetuner, TrainerRetuner,
                                      arm_engine, arm_trainer)
from paddle_tpu.observability import doctor, flightrec
from paddle_tpu.observability.report import render_doctor, render_tuning
from paddle_tpu.utils import tuning


@pytest.fixture
def tmp_tables(tmp_path, monkeypatch):
    """Isolate the tuning table and flightrec dumps per test."""
    monkeypatch.setenv("PADDLE_TPU_TUNING_CACHE",
                       str(tmp_path / "tuning.json"))
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_DIR",
                       str(tmp_path / "flightrec"))
    tuning.reset_for_tests()
    yield tmp_path
    tuning.reset_for_tests()


# ---- knob-axis registry ------------------------------------------------

def test_axis_trial_values_suggested_wins_and_skips_incumbent():
    ax = AXES["remat_policy"]
    assert ax.trial_values("off") == ["dots_no_batch", "dots", "full"]
    # a doctor action's candidate list overrides the axis defaults
    assert ax.trial_values("dots", suggested=["off", "dots"]) == ["off"]


def test_axis_for_action_behavioral_and_unknown_are_none():
    assert axis_for_action(None) is None
    assert axis_for_action({"op": None, "param": None,
                            "candidates": []}) is None
    assert axis_for_action({"param": "not-a-knob"}) is None
    assert axis_for_action({"param": "quantize"}) is AXES["quantize"]
    assert axis_for("prefill_buckets").hot_apply


# ---- doctor actions (satellite 1) --------------------------------------

def test_every_rule_carries_an_action():
    for rule in doctor.RULES:
        assert rule.action is not None, rule.bottleneck


def test_doctor_verdicts_carry_structured_actions():
    v = doctor.diagnose({"comm_fraction": 0.4}, "train")
    assert v and v[0]["bottleneck"] == "comm-bound"
    a = v[0]["action"]
    assert a == {"op": "moe_a2a_chunks", "param": "moe_a2a_chunks",
                 "env": "PADDLE_TPU_MOE_A2A_CHUNKS",
                 "candidates": [1, 2, 4, 8]}


def test_spec_k_action_candidates_halve_below_current():
    v = doctor.diagnose({"spec_acceptance_rate": 0.1, "spec_k": 8},
                        "serve")
    top = [x for x in v if x["bottleneck"] == "low-spec-acceptance"][0]
    assert top["action"]["candidates"] == [4, 2, 1]


def test_behavioral_action_has_no_param():
    v = doctor.diagnose({"host_syncs_measured": 40, "steps": 10},
                        "train")
    top = [x for x in v if x["bottleneck"] == "host-sync-bound"][0]
    assert top["action"]["param"] is None
    assert axis_for_action(top["action"]) is None


def test_render_doctor_shows_action_column():
    out = render_doctor(doctor.diagnose({"comm_fraction": 0.4}, "train"))
    assert "action" in out
    assert "moe_a2a_chunks in [1,2,4,8] ->moe_a2a_chunks" in out


# ---- tuning provenance (satellite 2) -----------------------------------

def test_record_provenance_roundtrips_through_disk(tmp_tables):
    key = ("v5e", "4096")
    tuning.record("remat_policy", key, "dots", source="autotune",
                  run="r42", improvement=0.0731)
    tuning.reset_for_tests()            # force the disk read
    assert tuning.lookup("remat_policy", key) == "dots"
    meta = tuning.provenance("remat_policy", key)
    assert meta == {"source": "autotune", "run": "r42",
                    "improvement": 0.0731}


def test_record_without_provenance_and_all_entries(tmp_tables):
    tuning.record("qmm_tiles", ("cpu", "64"), [128, 128])
    assert tuning.provenance("qmm_tiles", ("cpu", "64")) is None
    tuning.record("remat_policy", ("cpu", "1"), "off", source="sweep",
                  run="r1", improvement=0.1)
    ents = tuning.all_entries()
    assert tuning.META_OP not in ents       # meta never leaks as an op
    assert set(ents) == {"qmm_tiles", "remat_policy"}


def test_report_tuning_cli_prints_provenance(tmp_tables, capsys):
    tuning.record("remat_policy", ("cpu", "64"), "dots_no_batch",
                  source="autotune", run="r06", improvement=0.05)
    from paddle_tpu.observability.report import main as report_main
    assert report_main(["--tuning"]) == 0
    out = capsys.readouterr().out
    assert "tuning table" in out
    for frag in ("remat_policy", "autotune", "r06", "+5.00%"):
        assert frag in out
    assert "dots_no_batch" in out


# ---- controller convergence (tentpole + satellite 4) -------------------

BEST = {"quantize": "int8", "remat_policy": "off", "overlap": True,
        "prefetch_depth": 4, "scan": True}
START = {"quantize": None, "remat_policy": "dots_no_batch",
         "overlap": False, "prefetch_depth": 2, "scan": True}


def _objective(cfg):
    mfu = 0.30
    mfu += 0.05 if cfg["quantize"] == "int8" else 0.0
    mfu += 0.04 if cfg["remat_policy"] == "off" else 0.0
    mfu += 0.03 if cfg["overlap"] else 0.0
    if cfg["prefetch_depth"] == 4:
        mfu += 0.02
    elif cfg["prefetch_depth"] == 0:
        mfu -= 0.20                     # planted regression trial
    return round(mfu, 6)


def _verdicts(cfg):
    v = []
    if cfg["quantize"] != "int8":
        v.append({"bottleneck": "mfu-below-target", "score": 0.9,
                  "action": {"op": "qmm_tiles", "param": "quantize",
                             "env": "BENCH_QUANTIZE",
                             "candidates": ["int8"]}})
    if cfg["remat_policy"] != "off":
        v.append({"bottleneck": "mfu-below-target", "score": 0.8,
                  "action": {"op": "remat_policy",
                             "param": "remat_policy", "env": None,
                             "candidates": ["off"]}})
    if not cfg["overlap"]:
        v.append({"bottleneck": "comm-bound", "score": 0.7,
                  "action": {"op": None, "param": "overlap",
                             "env": "PADDLE_TPU_OVERLAP",
                             "candidates": [True]}})
    if cfg["prefetch_depth"] != 4:
        v.append({"bottleneck": "data-starved", "score": 0.6,
                  "action": {"op": None, "param": "prefetch_depth",
                             "env": "PADDLE_TPU_PREFETCH_DEPTH",
                             "candidates": [0, 4]}})
    # behavioral advice the controller must skip, ranked above the bait
    v.append({"bottleneck": "host-sync-bound", "score": 0.55,
              "action": {"op": None, "param": None, "env": None,
                         "candidates": []}})
    # bait: trialing scan=False recompile-storms (see _measure)
    v.append({"bottleneck": "mfu-below-target", "score": 0.5,
              "action": {"op": None, "param": "scan", "env": None,
                         "candidates": [False]}})
    return v


def _measure(cfg):
    return {"mfu": _objective(cfg), "doctor": _verdicts(cfg),
            "xla_compiles_measured": 7 if cfg["scan"] is False else 0}


def _controller(tmp_tables, **over):
    kw = dict(kind="train", objective_key="mfu", noise_floor=0.02,
              run_id="t-run",
              commit_keys={"remat_policy":
                           ("remat_policy", ("t", "64", "2", "32"))},
              axes=["quantize", "remat_policy", "overlap",
                    "prefetch_depth", "scan"])
    kw.update(over)
    return AutotuneController(_measure, **kw)


def test_controller_converges_in_O_knobs_not_grid(tmp_tables):
    ctl = _controller(tmp_tables)
    s = ctl.run(dict(START))
    assert {k: s["config"][k] for k in BEST} == BEST
    k = len(START)
    grid = 2 * 4 * 2 * 3 * 2
    assert s["measured_trials"] <= k + 2 < grid
    assert s["converged"] and s["accepted"] == 4
    assert s["best"] == pytest.approx(0.44)
    assert s["improvement"] > 0.4


def test_controller_never_revisits_and_accepts_beyond_noise(tmp_tables):
    ctl = _controller(tmp_tables)
    s = ctl.run(dict(START))
    pairs = [(t["axis"], repr(t["value"])) for t in s["trials"]]
    assert len(pairs) == len(set(pairs))
    for t in s["trials"]:
        if t["outcome"] == "accept":
            assert t["improvement"] > ctl.noise_floor


def test_controller_rolls_back_regression_and_storm(tmp_tables):
    ctl = _controller(tmp_tables)
    s = ctl.run(dict(START))
    rb = {t["reason"]: t for t in s["trials"]
          if t["outcome"] == "rollback"}
    assert set(rb) == {"regression", "recompile-storm"}
    assert rb["regression"]["axis"] == "prefetch_depth"
    assert rb["regression"]["value"] == 0
    assert rb["recompile-storm"]["axis"] == "scan"
    # every rollback shipped an evidence bundle
    frdir = str(tmp_tables / "flightrec")
    bundles = [b for b in flightrec.find_bundles(frdir)
               if b.endswith("autotune-rollback")]
    assert len(bundles) == 2
    info = flightrec.load_bundle(bundles[0])["bundle"]
    assert info["autotune"]["run"] == "t-run"
    assert info["autotune"]["reason"] in ("regression",
                                          "recompile-storm")


def test_controller_zero_compiles_outside_trials(tmp_tables):
    s = _controller(tmp_tables).run(dict(START))
    assert s["compiles_outside_trials"] == 0


def test_controller_commits_winner_with_provenance(tmp_tables):
    s = _controller(tmp_tables).run(dict(START))
    assert any(c["op"] == "remat_policy" for c in s["committed"])
    tuning.reset_for_tests()            # fresh process stand-in
    key = ("t", "64", "2", "32")
    assert tuning.lookup("remat_policy", key) == "off"
    meta = tuning.provenance("remat_policy", key)
    assert meta["source"] == "autotune" and meta["run"] == "t-run"
    assert meta["improvement"] > 0


def test_controller_minimize_direction(tmp_tables):
    def measure(cfg):
        ms = 10.0 - (3.0 if cfg["kv_dtype"] == "int8" else 0.0)
        return {"ttft_ms": ms, "doctor": [
            {"bottleneck": "kv-pressure", "score": 0.9,
             "action": {"op": None, "param": "kv_dtype",
                        "env": None, "candidates": ["int8"]}}]
            if cfg["kv_dtype"] == "dense" else []}
    ctl = AutotuneController(measure, kind="serve",
                             objective_key="ttft_ms", maximize=False,
                             noise_floor=0.02, axes=["kv_dtype"])
    s = ctl.run({"kv_dtype": "dense"})
    assert s["config"]["kv_dtype"] == "int8"
    assert s["improvement"] == pytest.approx(0.3)


def test_controller_error_trial_rolls_back(tmp_tables):
    calls = {"n": 0}

    def measure(cfg):
        calls["n"] += 1
        if cfg.get("overlap"):
            raise RuntimeError("watchdog: stalled")
        return {"mfu": 0.3, "doctor": [
            {"bottleneck": "comm-bound", "score": 0.7,
             "action": {"op": None, "param": "overlap", "env": None,
                        "candidates": [True]}}]}
    ctl = AutotuneController(measure, kind="train", noise_floor=0.02,
                             axes=["overlap"])
    s = ctl.run({"overlap": False})
    t = s["trials"][0]
    assert t["outcome"] == "rollback" and t["reason"] == "error"
    assert "watchdog" in t["error"]
    assert s["config"] == {"overlap": False}    # incumbent kept


def test_controller_missing_objective_is_an_error(tmp_tables):
    s = AutotuneController(lambda cfg: {"rows": []},
                           axes=["overlap"]).run({"overlap": False})
    assert "error" in s and s["measured_trials"] == 0


# ---- live tier: LiveRetuner unit (tentpole, live rails) ----------------

class FakeEngine:
    kv_layout = "dense"
    max_seq_len = 64
    batch_slots = 2

    def __init__(self, buckets=(8, 16, 64)):
        self.buckets = sorted(buckets)
        self._queue = []
        self.num_active = 0


def test_notify_slo_edge_trigger_no_retrigger_storm():
    r = LiveRetuner(FakeEngine())
    healthy = {"regressed": False, "breached": False}
    bad = {"regressed": True, "breached": False, "p99_ms": 99.0}
    assert r.notify_slo(healthy) is False
    assert r.notify_slo(bad) is True        # edge: schedules ONE episode
    for _ in range(10):                     # still-regressed rescrapes
        assert r.notify_slo(bad) is False   # do NOT retrigger
    assert r._pending
    assert r.notify_slo(healthy) is False   # healthy resets the latch


def test_notify_slo_cooldown_bounds_episode_rate():
    import time as _time
    r = LiveRetuner(FakeEngine(), cooldown_s=3600.0)
    r._last_episode_t = _time.monotonic()   # an episode just ran
    bad = {"regressed": True}
    assert r.notify_slo(bad) is False       # inside cooldown: suppressed
    r2 = LiveRetuner(FakeEngine(), cooldown_s=0.0)
    r2._last_episode_t = _time.monotonic()
    assert r2.notify_slo(bad) is True


def test_on_tick_quiesce_gate(monkeypatch):
    eng = FakeEngine()
    r = LiveRetuner(eng)
    ran = []
    monkeypatch.setattr(r, "_episode", lambda: ran.append(1))
    assert r.on_tick() is False             # nothing pending: O(1) no-op
    r.notify_slo({"regressed": True})
    eng.num_active = 1
    assert r.on_tick() is False and r._pending      # busy: deferred
    eng.num_active, eng._queue = 0, ["queued"]
    assert r.on_tick() is False and r._pending      # queued: deferred
    eng._queue = []
    assert r.on_tick() is True and not r._pending   # quiesced: runs
    assert ran == [1]


def test_episode_hot_applies_merged_subset(tmp_tables, monkeypatch):
    eng = FakeEngine([8, 16, 64])
    r = LiveRetuner(eng)
    # bucket 8's executable measures SLOWER than 16's (the live
    # regression story): pad-up rule drops it, mean cost improves
    times = {8: 2.0, 16: 1.0, 64: 5.0}
    monkeypatch.setattr(r, "_time_buckets", lambda bs: dict(times))
    r._pending = True
    assert r.on_tick() is True
    assert eng.buckets == [16, 64]          # hot-applied subset
    assert r.applied and r.applied[0]["improvement"] > 0.02
    # winner persisted with live-autotune provenance
    tuning.reset_for_tests()
    assert tuning.lookup("prefill_buckets", ("cpu", 64)) == [16, 64]
    meta = tuning.provenance("prefill_buckets", ("cpu", 64))
    assert meta["source"] == "autotune" and meta["run"] == "live-1"


def test_episode_within_noise_is_a_noop(tmp_tables, monkeypatch):
    eng = FakeEngine([8, 64])
    r = LiveRetuner(eng)
    # healthy bucket spacing: merging would RAISE the mean cost, so the
    # incumbent list must survive
    monkeypatch.setattr(r, "_time_buckets",
                        lambda bs: {8: 1.0, 64: 5.0})
    r._pending = True
    r.on_tick()
    assert eng.buckets == [8, 64] and not r.applied


def test_episode_error_rolls_back_and_serving_survives(tmp_tables,
                                                       monkeypatch):
    eng = FakeEngine()
    r = LiveRetuner(eng)

    def boom(bs):
        raise RuntimeError("no free blocks for trial")
    monkeypatch.setattr(r, "_time_buckets", boom)
    r._pending = True
    assert r.on_tick() is True              # the failure is CONTAINED
    assert eng.buckets == [8, 16, 64]       # incumbent kept
    frdir = str(tmp_tables / "flightrec")
    bundles = [b for b in flightrec.find_bundles(frdir)
               if b.endswith("autotune-rollback")]
    assert len(bundles) == 1
    info = flightrec.load_bundle(bundles[0])["bundle"]
    assert info["autotune"]["tier"] == "live"


def test_merge_matches_offline_pad_up_rule():
    # same keep rule as bench.py's _sweep_prefill_buckets: keep b iff
    # times[b] < times[next_kept] / 1.25
    times = {8: 1.0, 16: 1.1, 32: 2.0, 64: 5.0}
    kept = LiveRetuner._merge([8, 16, 32, 64], times)
    ref = [64]
    for b in (32, 16, 8):
        if times[b] < times[ref[0]] / 1.25:
            ref.insert(0, b)
    assert kept == ref == [16, 32, 64]


def test_arm_gating_follows_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
    assert autotune_mode() == "off"
    assert arm_engine(FakeEngine()) is None
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "once")
    assert arm_engine(FakeEngine()) is None
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "live")
    assert autotune_mode() == "live"
    assert isinstance(arm_engine(FakeEngine()), LiveRetuner)


# ---- live tier: trainer advisory ---------------------------------------

class FakeTrainer:
    _timings = {"dispatch_ms": 100.0, "sync_ms": 900.0,
                "data_wait_ms": 0.0, "steps_timed": 64}


def test_trainer_retuner_one_advisory_per_regression(tmp_tables,
                                                     monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "live")
    r = arm_trainer(FakeTrainer())
    assert isinstance(r, TrainerRetuner)
    r.window, r.cooldown_steps = 4, 0
    fired = [r.on_step(10.0) for _ in range(8)]     # healthy baseline
    assert not any(fired)
    fired = [r.on_step(30.0) for _ in range(8)]     # sustained 3x
    assert sum(fired) == 1                  # ONE episode, latch holds
    assert r.episodes == 1
    advice = r.last_advice
    assert advice and advice[0]["bottleneck"] == "host-sync-bound"
    assert advice[0]["action"]["param"] is None     # behavioral


# ---- live tier: real engine contract (zero-recompile hot-apply) --------

@pytest.fixture(scope="module")
def live_engine():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.inference import InferenceEngine
    os.environ["PADDLE_TPU_AUTOTUNE"] = "live"
    try:
        paddle.seed(0)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False))
        m.eval()
        eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[8, 16])
        eng.warmup(eng.buckets)
        yield eng
    finally:
        os.environ.pop("PADDLE_TPU_AUTOTUNE", None)


def test_live_engine_is_armed_and_episode_is_compile_free(live_engine):
    from paddle_tpu.utils import compile_counter
    eng = live_engine
    r = eng._retuner
    assert isinstance(r, LiveRetuner)
    assert r.notify_slo({"regressed": True, "p99_ms": 50.0})
    old = list(eng.buckets)
    with compile_counter.assert_no_recompiles(
            "live autotune episode", traces=True):
        ran = r.on_tick()               # engine.step() calls this hook
    assert ran and r.episodes == 1
    # hot-apply contract: the (possibly) merged list is a SUBSET of the
    # warmed buckets with the capacity bucket intact
    assert set(eng.buckets) <= set(old)
    assert eng.buckets[-1] == old[-1]


def test_live_engine_still_serves_after_episode(live_engine):
    out = live_engine.generate(np.arange(5, dtype=np.int32),
                               max_new_tokens=4)
    assert len(np.asarray(out).reshape(-1)) > 0


def test_slo_monitor_feeds_retuner_listener():
    from paddle_tpu.observability.slo import SLOMonitor
    r = LiveRetuner(FakeEngine())
    mon = SLOMonitor(ttft_p99_ms=1.0,
                     baseline_ttft_p99_ms=1.0).add_listener(r.notify_slo)
    for _ in range(8):
        mon.observe(100.0)              # way over target AND baseline
    verdict = mon.check()
    assert verdict["breached"] and verdict["regressed"]
    assert r._pending                   # the signal reached the retuner


# ---- rows compaction (satellite 3) -------------------------------------

def test_compact_rows_keeps_newest_per_key_resume_unchanged(
        tmp_path, monkeypatch):
    import bench
    path = str(tmp_path / "rows.jsonl")
    monkeypatch.setenv("BENCH_ROWS_FILE", path)
    monkeypatch.setenv("BENCH_RUN", "r-compact")
    monkeypatch.setenv("BENCH_RESUME", "1")
    base = dict(kind="train", run="r-compact", config="gpt3-tiny",
                batch=2, seq=64, use_flash=False, remat=False,
                remat_policy=None, scan_layers=True, overlap=True,
                quantize=None)
    with open(path, "w") as f:
        for i in range(40):             # 40 rewrites of the SAME key
            f.write(json.dumps({**base, "mfu": float(i),
                                "pad": "x" * 256}) + "\n")
        f.write(json.dumps({**base, "quantize": "int8",
                            "mfu": 7.0}) + "\n")
    before = bench._measured_rows("train")
    assert len(before) == 2
    assert before[bench._train_row_key(base)]["mfu"] == 39.0
    assert bench._compact_rows(path, max_bytes=4096, keep_per_key=4)
    # newest N per (run, candidate) survive; resume sees the SAME rows
    with open(path) as f:
        kept = [json.loads(l) for l in f]
    dup = [r for r in kept if r.get("quantize") is None]
    assert len(dup) <= 4
    assert dup[-1]["mfu"] == 39.0
    after = bench._measured_rows("train")
    assert set(after) == set(before)
    assert after[bench._train_row_key(base)]["mfu"] == 39.0
    # int8 row (different candidate key) survived the purge
    assert any(r.get("quantize") == "int8" for r in kept)


def test_compact_rows_noop_under_budget(tmp_path):
    import bench
    path = str(tmp_path / "rows.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "smoke", "metric": "m"}) + "\n")
    assert bench._compact_rows(path, max_bytes=1 << 20) is False


# ---- bench CLI wiring (satellite 6 + acceptance) -----------------------

def test_bench_autotune_smoke_cli(tmp_path):
    """`python bench.py --autotune --smoke` end to end: the controller
    drives real bench_train measurements on CPU and exits 0 with the
    one-line summary row (zero compiles outside trial windows)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "BENCH_RUN": "pytest-autotune",
           "BENCH_ROWS_FILE": str(tmp_path / "rows.jsonl")}
    p = subprocess.run([sys.executable, "bench.py", "--autotune",
                        "--smoke"], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=560)
    assert p.returncode == 0, p.stdout + p.stderr
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["metric"] == "autotune_train_mfu"
    assert row["run"] == "pytest-autotune"
    assert row["compiles_outside_trials"] == 0
    # the summary row itself persisted for the next resume
    kinds = [json.loads(l).get("kind")
             for l in open(tmp_path / "rows.jsonl")]
    assert "autotune" in kinds
