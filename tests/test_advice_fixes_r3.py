"""Regression tests for round-5 advisor findings (ADVICE.md, PR 1).

Four fixes ride along with the perf pass: in-place ops must rebind
their tape creator (elu_ grads were silently wrong, squeeze_ crashed
backward); static batch_norm must keep real moving statistics;
static nce must resample negatives every execution; program
checkpoints must use deterministic parameter names and refuse
silent-overwrite duplicates.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.tensor.manipulation import squeeze_, unsqueeze_
from paddle_tpu.tensor.math import tanh_


# ---------------------------------------------------------------------------
# in-place ops on the tape
# ---------------------------------------------------------------------------
def test_elu_inplace_grad_correct():
    # y = elu(2x); at x=-1 the ELU branch is exp(2x): dy/dx = 2 e^{-2},
    # NOT the 2.0 a creator-less rebind used to leak through
    x = paddle.to_tensor(np.asarray([[-1.0, 2.0]], "float32"),
                         stop_gradient=False)
    y = x * 2.0
    out = F.elu_(y)
    assert out is y
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[2 * np.exp(-2.0), 2.0]], rtol=1e-6)


def test_tanh_inplace_grad_correct():
    x = paddle.to_tensor(np.asarray([0.5], "float32"),
                         stop_gradient=False)
    y = x * 1.0
    tanh_(y)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [1 - np.tanh(0.5) ** 2], rtol=1e-6)


def test_squeeze_unsqueeze_inplace_backward():
    # squeeze_ used to crash backward: the tape node's saved input was
    # the mutated tensor itself
    x = paddle.to_tensor(np.asarray([[3.0]], "float32"),
                         stop_gradient=False)
    y = x * 2.0
    squeeze_(y, 0)
    assert list(y.shape) == [1]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2.0]])

    x2 = paddle.to_tensor(np.asarray([1.0, 4.0], "float32"),
                          stop_gradient=False)
    y2 = x2 * 3.0
    unsqueeze_(y2, 0)
    assert list(y2.shape) == [1, 2]
    y2.sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), [3.0, 3.0])


# ---------------------------------------------------------------------------
# static batch_norm moving statistics
# ---------------------------------------------------------------------------
def test_static_batch_norm_updates_moving_stats():
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 4], "float32")
            out = static.nn.batch_norm(x, momentum=0.5)
            exe = static.Executor()
            feed = {"x": np.random.RandomState(0).randn(8, 4)
                    .astype("float32") * 3 + 5}
            # momentum writebacks are registered on the program
            assert len(prog._updates) == 2
            (rm, _), (rv, _) = prog._updates
            assert rm.persistable and rv.persistable
            exe.run(prog, feed=feed, fetch_list=[out])
            m1 = rm.numpy().copy()
            exe.run(prog, feed=feed, fetch_list=[out])
            m2 = rm.numpy().copy()
            # mean pulls toward the batch mean (~5) a bit more each run
            assert np.all(m1 > 0.5) and np.all(m2 > m1)
            assert np.abs(rv.numpy() - 1.0).sum() > 0.01
    finally:
        paddle.disable_static()


def test_static_batch_norm_is_test_uses_loaded_stats(tmp_path):
    """Inference normalizes with the persisted moving statistics — the
    old code normalized with fresh (0,1) constants, so loading a trained
    checkpoint changed nothing."""
    paddle.enable_static()
    try:
        mean = np.asarray([2.0, -1.0, 0.5], "float32")
        var = np.asarray([4.0, 0.25, 1.0], "float32")

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 3], "float32")
            out = static.nn.batch_norm(x, is_test=True,
                                       moving_mean_name="bn_mean",
                                       moving_variance_name="bn_var")
            # moving stats are persistables: a saved training state
            # restores them by name
            static.set_program_state(prog, {"bn_mean": mean,
                                            "bn_var": var})
            xs = np.random.RandomState(0).randn(4, 3).astype("float32")
            (got,) = static.Executor().run(prog, feed={"x": xs},
                                           fetch_list=[out])
        want = (xs - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    finally:
        paddle.disable_static()


def test_static_batch_norm_unrelated_fetch_not_forced():
    """Fetching a branch independent of batch_norm must neither demand
    the batch-norm branch's feeds nor execute its momentum update —
    even when the branches share a fed input."""
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 4], "float32")
            bn_out = static.nn.batch_norm(x)
            y = static.data("y", [2, 2], "float32")
            other = y * 2.0
            shared = x * 3.0          # same feed as BN, no BN dependency
            (rm, _), _ = prog._updates
            before = rm.numpy().copy()
            exe = static.Executor()
            # different feed: must not demand 'x'
            (got,) = exe.run(prog,
                             feed={"y": np.ones((2, 2), np.float32)},
                             fetch_list=[other])
            np.testing.assert_allclose(got, 2.0)
            np.testing.assert_allclose(rm.numpy(), before)
            # shared feed: BN subgraph still not in the fetch closure
            xs = np.random.RandomState(0).randn(8, 4).astype("float32")
            exe.run(prog, feed={"x": xs}, fetch_list=[shared])
            np.testing.assert_allclose(rm.numpy(), before)
            # fetching the BN branch itself DOES advance the stats
            exe.run(prog, feed={"x": xs + 5}, fetch_list=[bn_out])
            assert np.abs(rm.numpy() - before).sum() > 0.01
    finally:
        paddle.disable_static()


def test_static_batch_norm_test_clone_uses_moving_stats():
    """The reference workflow: train program + clone(for_test=True).
    The clone must normalize with the trained moving statistics, not
    re-derive batch statistics from the inference batch."""
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 3], "float32")
            out = static.nn.batch_norm(x, momentum=0.0)  # stats <- batch
            exe = static.Executor()
            xs = (np.random.RandomState(0).randn(64, 3)
                  .astype("float32") * 2 + 3)
            exe.run(prog, feed={"x": xs}, fetch_list=[out])
            (rm, _), (rv, _) = prog._updates
            infer = prog.clone(for_test=True)
            one = np.asarray([[5.0, 5.0, 5.0]], "float32")
            (got,) = static.Executor().run(infer, feed={"x": one},
                                           fetch_list=[out])
        # batch stats of a single row would zero the output; moving
        # stats (momentum=0 -> exactly the training batch's stats) must
        # be used instead
        want = (one - rm.numpy()) / np.sqrt(rv.numpy() + 1e-5)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    finally:
        paddle.disable_static()


def test_static_batch_norm_test_clone_drops_updates():
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 4], "float32")
            static.nn.batch_norm(x)
        assert len(prog._updates) == 2
        assert prog.clone(for_test=True)._updates == []
        assert len(prog.clone()._updates) == 2
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------------------
# static nce negative resampling
# ---------------------------------------------------------------------------
def test_static_nce_resamples_negatives_per_run():
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [6, 8], "float32")
            lab = static.data("lab", [6, 1], "int64")
            loss = static.nn.nce(x, lab, num_total_classes=50,
                                 num_neg_samples=5, seed=7)
            exe = static.Executor()
            feed = {"x": np.random.RandomState(0).randn(6, 8)
                    .astype("float32"),
                    "lab": np.asarray([[1], [2], [3], [4], [5], [6]],
                                      "int64")}
            runs = [exe.run(prog, feed=feed, fetch_list=[loss])[0]
                    for _ in range(3)]
        # same feed, same params — only the negative sample set moves.
        # One fixed PRNGKey(seed) used to make every run identical.
        assert not np.allclose(runs[0], runs[1])
        assert not np.allclose(runs[1], runs[2])
        assert all(np.isfinite(r).all() for r in runs)
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------------------
# deterministic checkpoint parameter names
# ---------------------------------------------------------------------------
def _build_fc_program():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4], "float32")
        h = static.nn.fc(x, 8, activation="relu")
        out = static.nn.fc(h, 2)
    return prog, out


def test_checkpoint_names_survive_tensor_counter_shift(tmp_path):
    """Auto-generated names depend on the global tensor counter; a
    process that allocated a different number of tensors first could
    never load its own checkpoint. Canonical per-program names must
    round-trip regardless."""
    from paddle_tpu.core.tensor import Tensor
    path = str(tmp_path / "model")
    paddle.enable_static()
    try:
        paddle.seed(0)
        prog_a, _ = _build_fc_program()
        static.save(prog_a, path)
        state_a = static.load_program_state(path)

        # shift the global counter the way an unrelated allocation would
        for _ in range(13):
            Tensor(np.zeros(1, np.float32))

        paddle.seed(1)  # different init values: loading must overwrite
        prog_b, _ = _build_fc_program()
        static.set_program_state(prog_b, state_a)
        from paddle_tpu.static.helpers import _canonical_named_params
        pa = _canonical_named_params(prog_a)
        pb = _canonical_named_params(prog_b)
        assert sorted(pa) == sorted(pb)
        for name in pa:
            np.testing.assert_allclose(np.asarray(pb[name].data),
                                       np.asarray(pa[name].data))
    finally:
        paddle.disable_static()


def test_save_load_vars_use_canonical_names(tmp_path):
    """save_vars/load_vars file names must survive a shifted global
    tensor counter, same as save()/load()."""
    from paddle_tpu.core.tensor import Tensor
    d = str(tmp_path / "vars")
    paddle.enable_static()
    try:
        paddle.seed(0)
        prog_a, _ = _build_fc_program()
        static.save_vars(None, d, main_program=prog_a)
        from paddle_tpu.static.helpers import _canonical_named_params
        import os as _os
        assert sorted(_os.listdir(d)) == \
            sorted(_canonical_named_params(prog_a))

        for _ in range(7):
            Tensor(np.zeros(1, np.float32))
        paddle.seed(1)
        prog_b, _ = _build_fc_program()
        static.load_vars(None, d, main_program=prog_b)
        pa = _canonical_named_params(prog_a)
        pb = _canonical_named_params(prog_b)
        for name in pa:
            np.testing.assert_allclose(np.asarray(pb[name].data),
                                       np.asarray(pa[name].data))
    finally:
        paddle.disable_static()


def test_checkpoint_duplicate_names_raise(tmp_path):
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            h = static.nn.fc(x, 4,
                             weight_attr=paddle.ParamAttr(name="shared_w"))
            static.nn.fc(h, 4,
                         weight_attr=paddle.ParamAttr(name="shared_w"))
        with pytest.raises(ValueError, match="duplicate parameter name"):
            static.save(prog, str(tmp_path / "dup"))
        # ... but saving a DIFFERENT var from the same program is fine:
        # duplicates outside the selected subset must not block it
        static.save_vars(None, str(tmp_path / "subset"),
                         main_program=prog, vars=["_param_1"])
    finally:
        paddle.disable_static()


def test_set_program_state_accepts_legacy_raw_names():
    """A state dict keyed by the raw auto-generated names (pre-canonical
    checkpoints) still loads when those names match this process."""
    paddle.enable_static()
    try:
        paddle.seed(0)
        prog, _ = _build_fc_program()
        from paddle_tpu.static.helpers import (_canonical_named_params,
                                               _program_params)
        legacy = {p.name: np.full(tuple(p.data.shape), 0.5, "float32")
                  for p in _program_params(prog)}
        static.set_program_state(prog, legacy)
        for p in _canonical_named_params(prog).values():
            np.testing.assert_allclose(np.asarray(p.data), 0.5)
    finally:
        paddle.disable_static()


def test_fused_ce_falls_back_on_tp_mesh():
    """tp>1 keeps the vocab-sharded full-logits path: the blocked CE
    loop would all-gather the LM head every step."""
    from dataclasses import replace
    import jax
    from paddle_tpu.distributed.mesh import (create_mesh, get_mesh,
                                             set_mesh)
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_configs

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for a tp axis")
    cfg = replace(gpt_configs()["gpt3-tiny"], use_flash_attention=False,
                  fused_ce=True)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.train()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
        .astype(np.int32))
    old = get_mesh()
    try:
        set_mesh(create_mesh({"dp": 2}, devices=jax.devices()[:2]))
        assert isinstance(m(ids), tuple)   # no tp axis: fused path
        set_mesh(create_mesh({"tp": 2}, devices=jax.devices()[:2]))
        out = m(ids)
        assert not isinstance(out, tuple)  # tp mesh: full logits
        assert out.shape[-1] == cfg.vocab_size
    finally:
        set_mesh(old)
