"""Latency-hiding collectives (virtual 8-device CPU mesh).

Covers the overlap pass end to end: the mesh.py collective shims, the
ZeRO-3 overlapped-gather scan (parity vs the synchronous GSPMD stage-3
placement), the 1F1B pipeline schedule (parity vs GPipe + the structural
peak-activation claim), chunked MoE all-to-all (bitwise parity), the
comm_ms/comm_fraction stats plumbing, and the PADDLE_TPU_OVERLAP knob.

Fixture discipline: meshes and batches are module-scoped (tier-1 runs
~700-780s of its 870s budget — every shared compile matters); the
longer multi-step soaks are marked `slow`.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed import overlap as overlap_mod
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.mesh import PartitionSpec as P, shard_map
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import GPipeTrainer
from paddle_tpu.utils import comm_stats, compile_counter


# ---------------------------------------------------------------------------
# module-scoped fixtures (one mesh / batch set for the whole module)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def dp8_mesh():
    return create_mesh({"dp": 8})


@pytest.fixture(scope="module")
def ep8_mesh():
    return create_mesh({"ep": 8})


@pytest.fixture(scope="module")
def pp2_mesh():
    return create_mesh({"pp": 2})


@pytest.fixture(scope="module")
def gpt_batch():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
    return ids, np.roll(ids, -1, 1).astype(np.int64)


# ---------------------------------------------------------------------------
# mesh.py collective shims
# ---------------------------------------------------------------------------
def test_mesh_collective_helpers(dp8_mesh):
    """all_gather/reduce_scatter/ppermute shims: gather ∘ scatter over a
    ring behaves like the identities they claim."""
    x = jnp.arange(64.0).reshape(8, 8)

    def body(xs):
        full = mesh_mod.all_gather(xs, "dp", axis=0)          # [8, 8]
        rs = mesh_mod.reduce_scatter(full, "dp", axis=0)      # [1, 8]
        rolled = mesh_mod.ppermute(
            xs, "dp", [(i, (i + 1) % 8) for i in range(8)])
        return full, rs, rolled

    full, rs, rolled = jax.jit(shard_map(
        body, mesh=dp8_mesh, in_specs=P("dp"),
        out_specs=(P(), P("dp"), P("dp")), check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(full), np.asarray(x))
    # reduce_scatter of a replicated value = 8x each rank's slice
    np.testing.assert_allclose(np.asarray(rs), np.asarray(x) * 8)
    np.testing.assert_allclose(np.asarray(rolled),
                               np.roll(np.asarray(x), 1, axis=0))


def test_collective_all_to_all_list_api_in_trace(ep8_mesh):
    """The reference list-API all_to_all now works inside shard_map (the
    path chunked MoE dispatch needed): 8 slices exchanged = the global
    block transpose."""
    from paddle_tpu.distributed import collective

    def body(x):
        outs = []
        collective.all_to_all(outs, [Tensor(x[i]) for i in range(8)],
                              axis_name="ep")
        return jnp.stack([o.data if isinstance(o, Tensor) else o
                          for o in outs])

    sm = jax.jit(shard_map(body, mesh=ep8_mesh, in_specs=P("ep"),
                           out_specs=P("ep")))
    got = np.asarray(sm(jnp.arange(64.0)))
    np.testing.assert_allclose(got,
                               np.arange(64.0).reshape(8, 8).T.ravel())


# ---------------------------------------------------------------------------
# comm-stats plumbing
# ---------------------------------------------------------------------------
def test_comm_stats_parser_counts_and_bytes():
    hlo = """
  %all-gather.3 = f32[4,16]{1,0} all-gather(f32[1,16]{1,0} %p), dims={0}
  %all-reduce = bf16[8]{0} all-reduce(bf16[8]{0} %x), to_apply=%add
  %rs = f32[2,4]{1,0} reduce-scatter(f32[16,4]{1,0} %y), dims={0}
  %a2a = (f32[1,8]{1,0}, f32[1,8]{1,0}, /*index=2*/f32[1,8]{1,0}) all-to-all(%a, %b, %c)
  %ags = (f32[1,16]{1,0}, f32[4,16]{1,0}) all-gather-start(f32[1,16]{1,0} %p)
  %agd = f32[4,16]{1,0} all-gather-done((f32[1,16]{1,0}, f32[4,16]{1,0}) %ags)
  %cp-start = f32[4]{0} collective-permute-start(f32[4]{0} %z)
  %cp-done = f32[4]{0} collective-permute-done(f32[4]{0} %cp-start)
  %cps2 = (f32[8]{0}, f32[8]{0}, u32[]{:T(128)}, u32[]{:T(128)}) collective-permute-start(f32[8]{0} %w)
  %rss = (f32[64,4]{1,0}, f32[8,4]{1,0}) reduce-scatter-start(f32[64,4]{1,0} %v)
"""
    out = comm_stats.parse_hlo_collectives(hlo)
    # sync all-gather 256B + async -start (operand, result) tuple: only
    # the result half (256B) is wire traffic; the -done is bookkeeping
    assert out["by_op"]["all-gather"] == {"count": 2,
                                          "bytes": 4 * 16 * 4 * 2}
    assert out["by_op"]["all-reduce"] == {"count": 1, "bytes": 8 * 2}
    # sync form sums its shape; the async -start (operand, result)
    # tuple takes the SMALLEST data buffer — reduce-scatter's result is
    # operand/groupsize, which a relative filter would misread as a
    # context token at large group sizes
    assert out["by_op"]["reduce-scatter"] == {"count": 2,
                                              "bytes": 2 * 4 * 4
                                              + 8 * 4 * 4}
    # variadic sync all-to-all: every tuple element is a result
    assert out["by_op"]["all-to-all"] == {"count": 1, "bytes": 3 * 8 * 4}
    # -start counted once, -done not double counted; the TPU 4-tuple
    # form (op, result, ctx, ctx — nested-paren layout annotations)
    # counts the result buffer, not the u32 sync contexts
    assert out["by_op"]["collective-permute"] == {"count": 2,
                                                  "bytes": 16 + 32}
    assert out["count"] == 8
    est = comm_stats.estimate_comm_ms(out["bytes"])
    assert est > 0


def test_comm_stats_parser_scales_while_bodies():
    """A collective inside a scan/while body executes once per trip —
    the ZeRO-3 layer scan and the 1F1B tick scan would otherwise
    underreport comm by the trip count."""
    hlo = """
%region_0.9_spmd (p: (s32[], f32[2,4])) -> (s32[], f32[2,4]) {
  %ag.1 = f32[16,4]{1,0} all-gather(f32[2,4]{1,0} %x), dims={0}
}
%region_1.9_spmd (p: (s32[], f32[2,4])) -> pred[] {
  %c.4 = s32[] constant(6)
  ROOT %cmp = pred[] compare(s32[] %i, s32[] %c.4), direction=LT
}
ENTRY %main (a: f32[2,4]) -> f32[2,4] {
  %ag.0 = f32[16,4]{1,0} all-gather(f32[2,4]{1,0} %a), dims={0}
  %w = (s32[], f32[2,4]) while((s32[], f32[2,4]) %t), condition=%region_1.9_spmd, body=%region_0.9_spmd
}
"""
    out = comm_stats.parse_hlo_collectives(hlo)
    # 1 top-level + 6 trips x 1 in-body
    assert out["by_op"]["all-gather"]["count"] == 7, out
    assert out["by_op"]["all-gather"]["bytes"] == 7 * 16 * 4 * 4, out


# ---------------------------------------------------------------------------
# ZeRO-3 overlapped all-gather
# ---------------------------------------------------------------------------
def _zero3_trainer(overlap, dp8_mesh, seed=7, comm=False):
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    st = DistributedStrategy()
    st.sharding = True
    st.sharding_configs = {"stage": 3, "overlap": overlap}
    st.recompute_configs = {"scan_layers": True}
    # comm analysis AOT-compiles the step a second time — only the
    # trainer whose HLO the test asserts on pays for it (time budget)
    return SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                       mesh=dp8_mesh, strategy=st, comm_stats=comm)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_zero3_overlap_matches_sync_and_recompile_free(dp8_mesh,
                                                       gpt_batch):
    """The tentpole contract: overlapped ZeRO-3 losses == synchronous
    GSPMD stage-3 (rtol 1e-5 fp32), zero XLA compiles across steps 2..N,
    grads leave the backward as reduce-scatter, and comm_ms /
    comm_fraction are reported."""
    ids, labels = gpt_batch
    steps = 3

    def run(overlap, comm):
        tr = _zero3_trainer(overlap, dp8_mesh, comm=comm)
        assert tr.zero3_overlap == overlap
        losses = [float(tr.train_step(ids, labels))]
        snap = compile_counter.snapshot()
        for _ in range(steps - 1):
            losses.append(float(tr.train_step(ids, labels)))
        return tr, losses, snap.new_compiles, tr.stats

    _, loss_sync, _, _ = run(False, comm=False)
    tr, loss_ovl, compiles, stats = run(True, comm=True)
    np.testing.assert_allclose(loss_ovl, loss_sync, rtol=1e-5)
    assert compiles == 0
    # structural: explicit gathers + reduce-scattered grads in the HLO
    by_op = stats["comm_by_op"]
    assert by_op.get("all-gather", {}).get("count", 0) > 0
    assert by_op.get("reduce-scatter", {}).get("count", 0) > 0
    assert stats["comm_ms"] is not None
    assert stats["comm_fraction"] is not None
    assert stats["comm_bytes"] > 0
    # ZeRO-3 memory: block params live dp-sharded (1/dp per device)
    w = tr.params["gpt.blocks.0.mlp.up_proj.weight"]
    assert "dp" in str(w.sharding.spec)
    assert w.addressable_shards[0].data.size == w.size // 8


# ---------------------------------------------------------------------------
# 1F1B pipeline schedule
# ---------------------------------------------------------------------------
class _Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(16, 16)

    def forward(self, x):
        return F.relu(self.fc(x))


def _pipe(schedule, mesh, num_micro, seed=0, n_blocks=2, comm=False):
    paddle.seed(seed)
    pre = nn.Linear(8, 16)
    blocks = [_Block() for _ in range(n_blocks)]
    post = nn.Linear(16, 10)
    params = (list(pre.parameters())
              + [p for b in blocks for p in b.parameters()]
              + list(post.parameters()))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=params)
    return GPipeTrainer(pre, blocks, post, opt,
                        lambda o, l: F.cross_entropy(o, l), mesh=mesh,
                        num_microbatches=num_micro, remat=False,
                        schedule=schedule, comm_stats=comm)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_1f1b_matches_gpipe_and_recompile_free(pp2_mesh):
    """1F1B loss parity vs GPipe at pp=2, M=8 (the acceptance config),
    zero recompiles across steps 2..N, and comm fields reported."""
    rng = np.random.RandomState(1)
    batches = [(rng.randn(16, 8).astype(np.float32),
                rng.randint(0, 10, (16,)).astype(np.int64))
               for _ in range(3)]

    def run(schedule, comm=False):
        tr = _pipe(schedule, pp2_mesh, num_micro=8, comm=comm)
        losses = [float(tr.train_step(*batches[0]))]
        snap = compile_counter.snapshot()
        for x, y in batches[1:]:
            losses.append(float(tr.train_step(x, y)))
        return tr, losses, snap.new_compiles

    tr_g, loss_g, _ = run("gpipe")
    tr_o, loss_o, compiles = run("1f1b", comm=True)
    np.testing.assert_allclose(loss_o, loss_g, rtol=1e-5, atol=1e-7)
    assert compiles == 0
    # the structural memory claim: the 1F1B stage-input stash allocates
    # min(2*pp-1, M) microbatch slots — 3 here — vs GPipe's M=8 banked
    # outputs (peak live activation count <= GPipe's)
    assert tr_o.peak_activation_slots() == 3
    assert tr_g.peak_activation_slots() == 8
    assert tr_o.peak_activation_slots() <= tr_g.peak_activation_slots()
    st = tr_o.stats
    assert st["schedule"] == "1f1b"
    assert st["comm_ms"] is not None and st["comm_fraction"] is not None


def test_1f1b_schedule_validation(pp2_mesh):
    with pytest.raises(ValueError):
        _pipe("zigzag", pp2_mesh, num_micro=2)


def test_microbatch_remainder_raises(pp2_mesh):
    """Satellite: a batch not divisible by num_microbatches must raise a
    clear error (never silently truncate)."""
    tr = _pipe("gpipe", pp2_mesh, num_micro=4)
    x = np.random.RandomState(0).randn(10, 8).astype(np.float32)
    y = np.zeros((10,), np.int64)
    with pytest.raises(ValueError, match="num_microbatches"):
        tr.train_step(x, y)


# ---------------------------------------------------------------------------
# chunked MoE all-to-all
# ---------------------------------------------------------------------------
def test_moe_chunked_a2a_bitwise_equal(ep8_mesh):
    """K-chunked dispatch/combine is bitwise-equal to the monolithic
    exchange and issues K times the all-to-alls."""
    from paddle_tpu.distributed.moe import MoELayer
    paddle.seed(0)
    layer = MoELayer(8, 16, num_experts=8, top_k=2, capacity_factor=4.0)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 8, 8).astype(np.float32))
    args = (x, layer.gate.data, layer.experts.w_up.data,
            layer.experts.b_up.data, layer.experts.w_down.data,
            layer.experts.b_down.data)

    def make(k):
        def fn(xs, gate, wu, bu, wd, bd):
            layer.a2a_chunks = k      # bound at trace time
            y, _, _ = layer._fn_shard_map(xs, gate, wu, bu, wd, bd)
            return y
        return jax.jit(shard_map(
            fn, mesh=ep8_mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep")))

    f1, f2 = make(1), make(2)
    c1 = comm_stats.analyze_jit(f1, *args)
    c2 = comm_stats.analyze_jit(f2, *args)
    np.testing.assert_array_equal(np.asarray(f2(*args)),
                                  np.asarray(f1(*args)))
    n1 = c1["by_op"]["all-to-all"]["count"]
    n2 = c2["by_op"]["all-to-all"]["count"]
    assert n1 >= 2 and n2 == 2 * n1
    # an explicit K on the GSPMD (non-shard_map) path is refused, not
    # silently ignored — that path's a2a is XLA-inserted
    layer.a2a_chunks = 2
    with pytest.raises(NotImplementedError, match="a2a_chunks"):
        layer(paddle.to_tensor(np.asarray(x)))


# ---------------------------------------------------------------------------
# the PADDLE_TPU_OVERLAP knob
# ---------------------------------------------------------------------------
def test_overlap_knob_defaults(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
    monkeypatch.delenv("PADDLE_TPU_MOE_A2A_CHUNKS", raising=False)
    assert overlap_mod.overlap_enabled() is True
    assert overlap_mod.moe_a2a_chunks(8) == 2
    monkeypatch.setenv("PADDLE_TPU_PIPELINE_SCHEDULE", "1f1b")
    assert overlap_mod.pipeline_schedule_default() == "1f1b"
    monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
    assert overlap_mod.overlap_enabled() is False
    assert overlap_mod.moe_a2a_chunks(8) == 1
    # the kill switch also downgrades the env-selected schedule AND an
    # env-selected chunk count: an A/B flip of the ONE knob must
    # actually change the compiled program
    assert overlap_mod.pipeline_schedule_default() == "gpipe"
    monkeypatch.setenv("PADDLE_TPU_MOE_A2A_CHUNKS", "4")
    assert overlap_mod.moe_a2a_chunks(8) == 1
    monkeypatch.delenv("PADDLE_TPU_MOE_A2A_CHUNKS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_PIPELINE_SCHEDULE", raising=False)
    monkeypatch.setenv("PADDLE_TPU_OVERLAP", "1")
    monkeypatch.setenv("PADDLE_TPU_MOE_A2A_CHUNKS", "4")
    assert overlap_mod.moe_a2a_chunks(8) == 4
    # clamped to a divisor: 4 doesn't divide 6 -> 3
    assert overlap_mod.moe_a2a_chunks(6) == 3


def test_overlap_flags_cpu_noop(monkeypatch):
    """On the host platform the XLA accelerator flags must NOT be
    appended (the CPU backend aborts on unknown flags)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "")
    assert overlap_mod.ensure_xla_overlap_flags() is False
    assert "async" not in os.environ.get("XLA_FLAGS", "")


# ---------------------------------------------------------------------------
# slow soaks
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_zero3_overlap_gpt_soak(dp8_mesh):
    """Longer ZeRO-3 parity soak: 4 layers + remat policy, 6 steps."""
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (16, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    def run(overlap):
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        crit = GPTPretrainingCriterion()
        st = DistributedStrategy()
        st.sharding = True
        st.sharding_configs = {"stage": 3, "overlap": overlap}
        st.recompute = True
        st.recompute_configs = {"scan_layers": True,
                                "policy": "dots_no_batch"}
        model.enable_recompute("dots_no_batch")
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=dp8_mesh, strategy=st)
        return [float(tr.train_step(ids, labels)) for _ in range(6)]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


@pytest.mark.slow
def test_1f1b_gpt_moe_soak():
    """1F1B carries MoE router aux losses through its explicit backward:
    parity vs GPipe on a dp2 x pp2 GPT-MoE."""
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    crit = GPTPretrainingCriterion()
    mesh = create_mesh({"dp": 2, "pp": 2})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    def run(schedule):
        paddle.seed(5)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False,
                        tie_word_embeddings=False, moe_num_experts=4,
                        moe_top_k=2, moe_capacity_factor=4.0,
                        moe_aux_loss_coeff=0.05)
        model = GPTForCausalLM(cfg)
        pre, blocks, post = gpt_pipeline_parts(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        tr = GPipeTrainer(pre, blocks, post, opt,
                          lambda o, l: crit(o, l), mesh=mesh,
                          num_microbatches=2, remat=True,
                          schedule=schedule)
        return [float(tr.train_step(ids, labels)) for _ in range(4)]

    np.testing.assert_allclose(run("1f1b"), run("gpipe"), rtol=1e-5)
