"""Native training entry: exported StableHLO train step driven from C.

Reference: paddle/fluid/train/demo/demo_trainer.cc (a C++ binary that
loads a saved train program and steps it). Here the artifact is
SpmdTrainer.export_train_step's serialized fwd+bwd+update program.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import SpmdTrainer, create_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_trainer():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.02,
                                parameters=model.parameters())
    return SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": 1}))


def example_batch(bs=8, nf=6):
    rng = np.random.RandomState(0)
    x = rng.randn(bs, nf).astype(np.float32)
    return x, x.sum(axis=1, keepdims=True).astype(np.float32)


@pytest.fixture(scope="module")
def exported_trainer(tmp_path_factory):
    tr = make_trainer()
    x, y = example_batch()
    path = str(tmp_path_factory.mktemp("train") / "reg")
    tr.export_train_step(path, x, y)
    return path


def test_exported_step_matches_live_trainer(exported_trainer):
    """Stepping the deserialized program must equal the live trainer."""
    from paddle_tpu.inference import capi_bridge as B
    x, y = example_batch()
    h = B.create_trainer(exported_trainer)
    live = make_trainer()
    for i in range(5):
        raw, shape, dtype = B.trainer_step(
            h, [(x.tobytes(), x.shape, "float32"),
                (y.tobytes(), y.shape, "float32")])
        got = float(np.frombuffer(raw, np.dtype(dtype)))
        want = float(live.train_step(x, y))
        assert got == pytest.approx(want, rel=1e-4), f"step {i}"
    B.destroy_trainer(h)


@pytest.mark.slow
def test_standalone_c_binary_trains(exported_trainer, tmp_path_factory):
    from paddle_tpu.inference.capi.build import build_demo
    try:
        exe = build_demo(str(tmp_path_factory.mktemp("bin") /
                             "pd_capi_train_demo"),
                         source="capi_train_demo.c")
    except Exception as e:
        pytest.skip(f"cannot build train demo: {e}")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_")):
            del env[k]
    proc = subprocess.run([exe, exported_trainer, "6", "8"], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout={proc.stdout}\nstderr={proc.stderr}"
    assert "CAPI-TRAIN-OK" in proc.stdout


def test_export_refuses_fp16_and_guard():
    import paddle_tpu
    from paddle_tpu.distributed.fleet import DistributedStrategy
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    st = DistributedStrategy()
    st.amp = True
    st.amp_configs = {"use_bf16": False}
    tr = SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                     mesh=create_mesh({"dp": 1}), strategy=st)
    with pytest.raises(NotImplementedError):
        tr.export_train_step("/tmp/nope", np.ones((2, 4), np.float32),
                             np.ones((2, 2), np.float32))
