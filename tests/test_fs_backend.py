"""Filesystem backend: local atomic writes + HDFS via the hadoop CLI.

Reference: paddle/fluid/framework/io/fs.cc (LocalFS + HDFS shelling out
to `hadoop fs`). A fake `hadoop` executable backed by a local directory
stands in for the cluster, exactly how the reference's fs tests work.
"""
import os
import stat

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.fs import (HadoopFS, LocalFS, get_fs,
                                     open_for_read, open_for_write)

FAKE_HADOOP = r"""#!/bin/bash
# minimal fake `hadoop fs` for tests, backed by $FAKE_HDFS_ROOT
ROOT="$FAKE_HDFS_ROOT"
[ "$1" = fs ] || exit 2
shift
op=$1; shift
map() { echo "$ROOT/$(echo "$1" | sed 's|^[a-z]*://||')"; }
case $op in
  -test) shift; p=$(map "$1"); [ -e "$p" ] ;;
  -mkdir) [ "$1" = -p ] && shift; mkdir -p "$(map "$1")" ;;
  -put) [ "$1" = -f ] && shift; src=$1; dst=$(map "$2")
        mkdir -p "$(dirname "$dst")"; cp "$src" "$dst" ;;
  -get) src=$(map "$1"); cp "$src" "$2" ;;
  -rm) while [[ "$1" == -* ]]; do shift; done
       rm -rf "$(map "$1")" ;;
  -ls) p=$(map "$1")
       for f in "$p"/*; do
         [ -e "$f" ] && echo "-rw-r--r-- 1 u g 0 2026-01-01 00:00 ${1%/}/$(basename "$f")"
       done ;;
  *) exit 2 ;;
esac
"""


@pytest.fixture
def fake_hdfs(tmp_path, monkeypatch):
    bin_path = tmp_path / "hadoop"
    bin_path.write_text(FAKE_HADOOP)
    bin_path.chmod(bin_path.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "hdfs_root"
    root.mkdir()
    monkeypatch.setenv("PADDLE_HADOOP_BIN", str(bin_path))
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    return root


def test_get_fs_dispatch():
    assert isinstance(get_fs("/tmp/x"), LocalFS)
    assert isinstance(get_fs("hdfs://ns/a"), HadoopFS)
    assert isinstance(get_fs("afs://x/y"), HadoopFS)


def test_local_atomic_write(tmp_path):
    p = str(tmp_path / "sub" / "f.bin")
    with open_for_write(p) as f:
        f.write(b"hello")
    assert open(p, "rb").read() == b"hello"
    assert not os.path.exists(p + ".tmp")


def test_hdfs_roundtrip(fake_hdfs):
    path = "hdfs://ns/ckpt/model.bin"
    with open_for_write(path) as f:
        f.write(b"abc123")
    fs = get_fs(path)
    assert fs.exists(path)
    with open_for_read(path) as f:
        assert f.read() == b"abc123"
    assert "model.bin" in fs.list_dir("hdfs://ns/ckpt")
    fs.remove(path)
    assert not fs.exists(path)


def test_paddle_save_load_over_hdfs(fake_hdfs):
    sd = {"w": paddle.to_tensor(np.arange(6, dtype=np.float32))}
    paddle.save(sd, "hdfs://ns/models/lin.pdparams")
    back = paddle.load("hdfs://ns/models/lin.pdparams")
    np.testing.assert_array_equal(np.asarray(back["w"].data),
                                  np.arange(6, dtype=np.float32))


def test_trainer_checkpoint_over_hdfs(fake_hdfs):
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import SpmdTrainer, create_mesh

    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    tr = SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                     mesh=create_mesh({"dp": 1}))
    x = np.random.RandomState(0).randn(4, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(4, 2).astype(np.float32)
    tr.train_step(x, y)
    tr.save("hdfs://ns/train/ck.pdtrainer")

    paddle.seed(0)
    model2 = nn.Linear(4, 2)
    opt2 = paddle.optimizer.Adam(learning_rate=1e-2,
                                 parameters=model2.parameters())
    tr2 = SpmdTrainer(model2, opt2, lambda o, y: F.mse_loss(o, y),
                      mesh=create_mesh({"dp": 1}))
    tr2.load("hdfs://ns/train/ck.pdtrainer")
    for n in tr.params:
        np.testing.assert_array_equal(np.asarray(tr.params[n]),
                                      np.asarray(tr2.params[n]))
    assert tr2._step_count == 1


def test_missing_hadoop_binary_raises(monkeypatch):
    monkeypatch.setenv("PADDLE_HADOOP_BIN", "/nonexistent/hadoop")
    with pytest.raises(RuntimeError, match="hadoop CLI"):
        HadoopFS().exists("hdfs://x/y")
