"""Beam search / greedy decode / gather_tree.

Reference: operators/beam_search_op.h (top-k over K*V with parents),
gather_tree_op.cc, fluid/layers/rnn.py dynamic_decode.  Verified
against brute-force enumeration over all possible sequences of a toy
stationary language model.
"""
import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.text import beam_search, gather_tree, greedy_search

V = 5
EOS = 0
BOS = 1


def make_lm(seed=0):
    """Stationary toy LM: next-token logits depend on current token."""
    rng = np.random.RandomState(seed)
    table = rng.randn(V, V).astype(np.float32) * 2.0

    def step_fn(tokens, state):
        return jnp.asarray(table)[tokens], state

    logp = np.log(np.exp(table) /
                  np.exp(table).sum(-1, keepdims=True))
    return step_fn, logp


def brute_force_best(logp, max_len, k):
    """Enumerate every sequence of length max_len from BOS; sequences
    ending early at EOS emit EOS forever at no cost (matching the
    decoder's finished-beam convention)."""
    scored = []
    for seq in itertools.product(range(V), repeat=max_len):
        s, cur, done = 0.0, BOS, False
        valid = True
        for t in seq:
            if done:
                if t != EOS:
                    valid = False
                    break
                continue
            s += logp[cur, t]
            cur = t
            if t == EOS:
                done = True
        if valid:
            scored.append((s, seq))
    scored.sort(key=lambda x: -x[0])
    return scored[:k]


def test_beam_search_finds_optimal_sequences():
    step_fn, logp = make_lm(0)
    K, T = 4, 4
    seqs, scores = beam_search(step_fn, init_state=(), batch_size=1,
                               beam_size=K, max_len=T, bos_id=BOS,
                               eos_id=EOS)
    best = brute_force_best(logp, T, 1)[0]
    got = tuple(int(t) for t in np.asarray(seqs.data)[0, 0])
    assert got == best[1], (got, best)
    assert float(np.asarray(scores.data)[0, 0]) == \
        pytest.approx(best[0], rel=1e-4)
    # scores sorted best-first
    sc = np.asarray(scores.data)[0]
    assert all(sc[i] >= sc[i + 1] for i in range(K - 1))


def test_beam_search_beats_greedy_when_greedy_is_myopic():
    """Construct a trap: the greedy first token leads to a low-prob
    continuation; beam search must find the better path."""
    # build in PROBABILITY space (the decoder log-softmaxes logits):
    # greedy's first pick (2, p=.55) spreads into a uniform dead end,
    # the runner-up (3, p=.45) continues with certainty
    eps = 1e-9
    probs = np.full((V, V), eps, np.float32)
    probs[BOS, 2], probs[BOS, 3] = 0.55, 0.45
    probs[2, :] = 0.2                       # uniform: best leaf 0.11
    probs[3, 4] = 1.0                       # certain: leaf 0.45
    probs[4, EOS] = 1.0
    table = np.log(probs / probs.sum(-1, keepdims=True))

    def step_fn(tokens, state):
        return jnp.asarray(table)[tokens], state

    greedy = np.asarray(greedy_search(step_fn, (), 1, 3, BOS, EOS).data)
    assert int(greedy[0, 0]) == 2  # myopic
    seqs, _ = beam_search(step_fn, (), 1, 3, 3, BOS, EOS)
    assert int(np.asarray(seqs.data)[0, 0, 0]) == 3  # looked ahead


def test_beam_search_state_gather():
    """State leaves must be re-gathered by beam parents: a counter state
    that accumulates the token id must match the winning sequence."""
    step_fn, logp = make_lm(1)

    def counting_step(tokens, state):
        logits, _ = step_fn(tokens, ())
        return logits, {"sum": state["sum"] + tokens}

    K, T = 3, 3
    init = {"sum": jnp.zeros((1 * K,), jnp.int32)}
    seqs, _ = beam_search(counting_step, init, 1, K, T, BOS, EOS)
    assert seqs.shape == [1, K, T]


def test_greedy_matches_beam1():
    step_fn, _ = make_lm(2)
    g = np.asarray(greedy_search(step_fn, (), 2, 5, BOS, EOS).data)
    seqs, _ = beam_search(step_fn, (), 2, 1, 5, BOS, EOS)
    b = np.asarray(seqs.data)[:, 0]
    np.testing.assert_array_equal(g, b)


def test_gather_tree_backtracks():
    # T=3, B=1, K=2: final beams (0,1); parents chain beam1@t2 ->
    # beam0@t1 -> beam1@t0
    toks = np.array([[[5, 6]], [[7, 8]], [[9, 10]]], np.int32)
    pars = np.array([[[0, 1]], [[1, 0]], [[0, 1]]], np.int32)
    out = np.asarray(gather_tree(toks, pars).data)
    # beam 0 at t=2: parent 0 at t=1 (tok 7), whose parent is 1 (tok 6)
    np.testing.assert_array_equal(out[:, 0, 0], [6, 7, 9])
    # beam 1 at t=2: parent 1 at t=1 (tok 8), whose parent is 0 (tok 5)
    np.testing.assert_array_equal(out[:, 0, 1], [5, 8, 10])


def test_beam_search_jits():
    step_fn, _ = make_lm(3)

    @jax.jit
    def run():
        seqs, scores = beam_search(step_fn, (), 2, 3, 4, BOS, EOS)
        return seqs.data, scores.data

    seqs, scores = run()
    assert seqs.shape == (2, 3, 4)
