"""Quantized compute path tests (ISSUE 7): int8/fp8 matmul + fake-quant
VJP, quantized KV caches, unified tuning table.

The contracts under test:
- ops.quantized_matmul: the Pallas int8 kernel reproduces the XLA
  composite (the CPU parity oracle) bitwise-within-epsilon, and the
  composite tracks the fp matmul at int8 tolerance;
- ops.fake_quant_matmul's custom VJP ≡ the straight-through-estimator
  reference ``fq(x) @ fq(w)`` with ``fq(t) = t + sg(qdq(t) - t)`` —
  values AND grads;
- GPTConfig(quantize='int8') / strategy.qat train (loss decreases,
  params move) without touching the optimizer;
- int8 KV decode stays within tolerance of the dense decode on BOTH
  cache layouts (static and paged, GQA included), and a warmed int8
  engine churns admissions/retirements with ZERO recompiles;
- utils.tuning round-trips through its JSON store, shrugs off a
  corrupt file, and serves flash blocks / prefill buckets / MoE a2a
  chunk counts.
"""
import importlib
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)
from paddle_tpu.utils import compile_counter, tuning

qm = importlib.import_module("paddle_tpu.ops.quantized_matmul")
da = importlib.import_module("paddle_tpu.ops.decode_attention")

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(**over):
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(**{**TINY, **over}))
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return tiny_model()


@pytest.fixture(scope="module")
def int8_dense_eng(model):
    """Shared warmed int8 dense-layout engine (tier-1 budget: one
    construction + warmup serves the churn and rollout tests)."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8],
                          kv_dtype="int8")
    eng.warmup(buckets=[8])
    return eng


@pytest.fixture(scope="module")
def int8_paged_eng(model):
    """Shared warmed int8 paged-layout engine (churn + prefix-hit)."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8, 16],
                          kv_layout="paged", kv_block_size=8,
                          kv_dtype="int8")
    eng.warmup(buckets=eng.buckets)
    return eng


# ---------------------------------------------------------------------------
# quantized matmul op
# ---------------------------------------------------------------------------
def _xw(m=32, k=256, n=128, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(m, k).astype(np.float32)),
            jnp.asarray(rng.randn(k, n).astype(np.float32)))


def test_quantized_matmul_composite_tracks_fp():
    x, w = _xw()
    y = qm.quantized_matmul(x, w)
    ref = x @ w
    rel = float(jnp.max(jnp.abs(y - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.05, rel          # int8 noise, not garbage
    assert y.dtype == x.dtype


def test_quantized_matmul_kernel_matches_composite():
    """Pallas int8 kernel (interpret mode) vs the dot_general composite:
    both accumulate in exact int32, so the only difference is the f32
    rescale ordering — epsilon, not tolerance."""
    if not qm._fa._HAS_PLTPU:
        pytest.skip("pallas TPU backend unavailable")
    x, w = _xw()
    ref = qm.quantized_matmul(x, w)          # composite on CPU
    qm._fa.set_interpret_mode(True)
    try:
        out = qm.quantized_matmul(x, w)      # kernel path
    finally:
        qm._fa.set_interpret_mode(False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_fake_quant_vjp_matches_ste_reference():
    """The custom VJP ≡ grad of fq(x)@fq(w) with straight-through
    fake-quant — grads bit-for-bit, forward at fp-reassociation eps."""
    x, w = _xw(m=12, k=96, n=40, seed=1)     # odd shapes: composite path

    def qdq(t, axis):
        q, s = qm.quantize_channel(t, axis=axis)
        return (q.astype(jnp.float32) * s).astype(t.dtype)

    def ref(x, w):
        fx = x + jax.lax.stop_gradient(qdq(x, 1) - x)
        fw = w + jax.lax.stop_gradient(qdq(w, 0) - w)
        return (fx @ fw).sum()

    def fq(x, w):
        return qm.fake_quant_matmul(x, w).sum()

    assert float(ref(x, w)) == pytest.approx(float(fq(x, w)), rel=1e-5)
    gr = jax.grad(ref, argnums=(0, 1))(x, w)
    gf = jax.grad(fq, argnums=(0, 1))(x, w)
    np.testing.assert_array_equal(np.asarray(gf[0]), np.asarray(gr[0]))
    np.testing.assert_array_equal(np.asarray(gf[1]), np.asarray(gr[1]))


def test_fake_quant_matmul_leading_dims_and_dtype():
    rng = np.random.RandomState(2)
    x3 = jnp.asarray(rng.randn(2, 8, 64).astype(np.float32))
    w = jnp.asarray(rng.randn(64, 32).astype(np.float32))
    y = qm.fake_quant_matmul(x3, w)
    assert y.shape == (2, 8, 32) and y.dtype == x3.dtype


def test_quantize_mode_validation():
    with pytest.raises(ValueError, match="quantize dtype"):
        GPTConfig(**{**TINY, "quantize": "int4"})
    # MoE expert FFNs have no quantized path: raising beats silently
    # quantizing only attention and misattributing the measured MFU
    with pytest.raises(NotImplementedError, match="MoE"):
        GPTConfig(**{**TINY, "quantize": "int8", "moe_num_experts": 2})
    assert qm.resolve_kv_quant("") is None
    assert qm.resolve_kv_quant("int8") == "int8"
    with pytest.raises(ValueError):
        qm.resolve_kv_quant("int4")


def test_kv_quant_roundtrip_idempotent():
    """Requantizing a dequantized buffer with fresh per-token scales is
    exact (amax positions land on ±127), which is what lets the paged
    prefill requant-scatter untouched prefix blocks bit-for-bit."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(5, 4, 64).astype(np.float32))
    q1, s1 = qm.quantize_kv(x)
    deq = qm.dequantize_kv(q1, s1)
    q2, s2 = qm.quantize_kv(deq)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=1e-6, atol=0)


# ---------------------------------------------------------------------------
# quantized training (AQT / straight-through)
# ---------------------------------------------------------------------------
def test_quantized_training_and_strategy_qat():
    """GPTConfig(quantize='int8') trains through the compiled trainer
    (loss decreases, optimizer untouched), and strategy.qat=True on an
    unquantized model reproduces the same first steps exactly."""
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy

    cfg = GPTConfig(**{**TINY, "quantize": "int8"})
    crit = GPTPretrainingCriterion()
    mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, TINY["vocab_size"], (4, 32)).astype(np.int32)
    lab = np.roll(ids, -1, 1).astype(np.int32)

    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=m.parameters())
    tr = SpmdTrainer(m, opt, lambda o, l: crit(o, l), mesh=mesh,
                     strategy=DistributedStrategy())
    losses = [float(tr.train_step(ids, lab)) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses

    paddle.seed(0)
    m2 = GPTForCausalLM(GPTConfig(**TINY))
    opt2 = paddle.optimizer.Adam(learning_rate=1e-3,
                                 parameters=m2.parameters())
    st = DistributedStrategy()
    st.qat = True
    tr2 = SpmdTrainer(m2, opt2, lambda o, l: crit(o, l), mesh=mesh,
                      strategy=st)
    assert m2.cfg.quantize == "int8"        # enable_quantize() ran
    l2 = [float(tr2.train_step(ids, lab)) for _ in range(2)]
    np.testing.assert_allclose(l2, losses[:2], rtol=1e-6)


# ---------------------------------------------------------------------------
# int8 KV cache: static layout
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("kv_heads", [
    None,
    # tier-1 wall budget: GQA variant rides the slow lane
    pytest.param(2, marks=pytest.mark.slow)])
def test_int8_kv_decode_tracks_dense_static(model, kv_heads):
    """prefill + teacher-forced decode over an int8 StaticKVCache stays
    within quantization tolerance of the full forward at every step
    (GQA covered)."""
    m = model if kv_heads is None else tiny_model(num_kv_heads=kv_heads)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (1, 10)).astype(np.int32)
    full = np.asarray(m(paddle.to_tensor(ids)).data)     # [1, 10, V]
    scale = float(np.max(np.abs(full)))

    cache = m.init_kv_cache(batch_slots=2, kv_dtype="int8")
    assert cache.quantized and cache.k.dtype == jnp.int8
    logits, cache = m.prefill(jnp.asarray(ids[:, :7]), cache, 0, 7)
    # prefill attends the fp k/v (only the stored copy is quantized):
    # bitwise the dense prefill
    np.testing.assert_allclose(np.asarray(logits)[0], full[0, 6],
                               rtol=1e-4, atol=1e-4)
    for t in range(7, 9):
        toks = np.zeros(2, np.int32)
        toks[0] = ids[0, t]
        lg, cache = m.decode_step(jnp.asarray(toks), cache,
                                  jnp.asarray([1, 0], jnp.int32))
        diff = float(np.max(np.abs(np.asarray(lg)[0] - full[0, t])))
        assert diff < 0.05 * scale, (t, diff, scale)


# ---------------------------------------------------------------------------
# int8 KV cache: paged layout
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("kv_heads", [
    None,
    # tier-1 wall budget: GQA variant rides the slow lane
    pytest.param(2, marks=pytest.mark.slow)])
def test_int8_kv_decode_tracks_dense_paged(model, kv_heads):
    """Same contract over a paged int8 pool: manual block tables, cold
    prefill + teacher-forced paged decode vs the full forward."""
    from paddle_tpu.inference.paged_kv import init_paged_cache
    m = model if kv_heads is None else tiny_model(num_kv_heads=kv_heads)
    bs, mb = 8, 2                            # 16 positions: covers 10
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 97, (1, 10)).astype(np.int32)
    full = np.asarray(m(paddle.to_tensor(ids)).data)
    scale = float(np.max(np.abs(full)))

    cache = init_paged_cache(m, num_blocks=1 + mb, block_size=bs,
                             kv_dtype="int8")
    assert cache.quantized and cache.k.dtype == jnp.int8
    row = np.arange(1, mb + 1, dtype=np.int32)   # blocks 1..mb
    padded = np.zeros((1, 16), np.int32)
    padded[0, :7] = ids[0, :7]
    logits, cache = m.prefill_paged(jnp.asarray(padded), cache,
                                    jnp.asarray(row), 0, np.int32(7))
    np.testing.assert_allclose(np.asarray(logits)[0], full[0, 6],
                               rtol=1e-4, atol=1e-4)
    # 2 steps: position 8 crosses into the slot's second block
    lengths = np.asarray([7], np.int64)
    for t in range(7, 9):
        toks = jnp.asarray([ids[0, t]], jnp.int32)
        lg, cache = m.decode_step_paged(
            toks, cache, jnp.asarray(row[None]),
            jnp.asarray(lengths.astype(np.int32)))
        lengths += 1
        diff = float(np.max(np.abs(np.asarray(lg)[0] - full[0, t])))
        assert diff < 0.05 * scale, (t, diff, scale)


def test_paged_quant_op_parity_with_dense_quant_op():
    """ops-level: paged int8 decode attention through a shuffled block
    table ≡ dense int8 decode attention on identical cache contents
    (both composites), and the interpret-mode kernels match them."""
    rng = np.random.RandomState(4)
    b, s, h, hkv, d, bs = 2, 256, 4, 2, 64, 128
    q = jnp.asarray(rng.randn(b, h, d).astype(np.float32) * 0.3)
    k = rng.randn(b, s, hkv, d).astype(np.float32) * 0.3
    v = rng.randn(b, s, hkv, d).astype(np.float32) * 0.3
    lengths = jnp.asarray([37, 256], jnp.int32)
    qk, sk = qm.quantize_kv(jnp.asarray(k))
    qv, sv = qm.quantize_kv(jnp.asarray(v))
    dense = da._decode_composite(q, qk, qv, lengths, sk, sv)

    mb = s // bs
    tables = (1 + rng.permutation(b * mb)).reshape(b, mb).astype(np.int32)
    nb = b * mb + 1
    kp = np.zeros((nb, bs, hkv, d), np.int8)
    vp = np.zeros((nb, bs, hkv, d), np.int8)
    ksp = np.zeros((nb, bs, hkv), np.float32)
    vsp = np.zeros((nb, bs, hkv), np.float32)
    for bi in range(b):
        for j in range(mb):
            kp[tables[bi, j]] = np.asarray(qk)[bi, j * bs:(j + 1) * bs]
            vp[tables[bi, j]] = np.asarray(qv)[bi, j * bs:(j + 1) * bs]
            ksp[tables[bi, j]] = np.asarray(sk)[bi, j * bs:(j + 1) * bs]
            vsp[tables[bi, j]] = np.asarray(sv)[bi, j * bs:(j + 1) * bs]
    paged = da._paged_composite(q, jnp.asarray(kp), jnp.asarray(vp),
                                jnp.asarray(tables), lengths,
                                jnp.asarray(ksp), jnp.asarray(vsp))
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))

    if not da._fa._HAS_PLTPU:
        return
    da.set_interpret_mode(True)
    try:
        kd = da.decode_attention(q, qk, qv, lengths, sk, sv)
        kpg = da.paged_decode_attention(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
            lengths, jnp.asarray(ksp), jnp.asarray(vsp))
    finally:
        da.set_interpret_mode(None)
    np.testing.assert_allclose(np.asarray(kd), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(kpg), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# zero-recompile churn over quantized engines
# ---------------------------------------------------------------------------
def test_quantized_decode_zero_recompile_churn(int8_dense_eng,
                                               int8_paged_eng):
    """THE acceptance leg: warmed int8 engines (dense AND paged layout)
    churn admissions/retirements with 0 XLA compiles and 0 jaxpr
    traces — the scale operands are as shape-stable as the caches."""
    rng = np.random.RandomState(5)
    for eng in (int8_dense_eng, int8_paged_eng):
        assert eng.stats["kv_dtype"] == "int8"
        # flush one request through to touch lazy host one-offs
        eng.add_request(rng.randint(1, 97, (4,)).astype(np.int32),
                        max_new_tokens=2)
        eng.run()
        with compile_counter.assert_no_recompiles(
                f"int8 {eng.kv_layout} decode churn"):
            rids = [eng.add_request(
                rng.randint(1, 97, (n,)).astype(np.int32),
                max_new_tokens=5) for n in (3, 6, 4)]
            outs = eng.run()
        assert all(len(outs[r]) == 5 for r in rids)


def test_int8_prefix_hit_matches_cold(int8_paged_eng):
    """Radix-cache hit over QUANTIZED prefix blocks: the hit admission
    dequant-gathers the cached int8 prefix, prefills only the suffix,
    and requant-scatters — and still reproduces the cold request's
    exact tokens (the requant-idempotency property end to end)."""
    eng = int8_paged_eng
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, 97, (13,)).astype(np.int32)
    r1 = eng.add_request(prompt, max_new_tokens=5)
    out1 = eng.run()[r1]
    h0 = eng._prefix.hit_queries
    r2 = eng.add_request(prompt, max_new_tokens=5)
    out2 = eng.run()[r2]
    assert eng._prefix.hit_queries == h0 + 1
    assert out2.tolist() == out1.tolist()
    eng.flush_prefix_cache()
    eng._alloc.check_leak_free()


def test_int8_engine_matches_model_level_rollout(model, int8_dense_eng):
    """The int8 dense engine's greedy tokens ≡ a model-level int8-cache
    greedy rollout (same executable math, scheduler adds nothing)."""
    rng = np.random.RandomState(6)
    prompt = rng.randint(1, 97, (6,)).astype(np.int32)
    rid = int8_dense_eng.add_request(prompt, max_new_tokens=4)
    out = int8_dense_eng.run()[rid]

    padded = np.zeros((1, 8), np.int32)
    padded[0, :6] = prompt
    cache = model.init_kv_cache(1, kv_dtype="int8")
    lg, cache = model.prefill(jnp.asarray(padded), cache, 0, 6)
    toks = [int(np.argmax(np.asarray(lg)[0]))]
    act = jnp.ones((1,), jnp.int32)
    for _ in range(3):
        lg, cache = model.decode_step(
            jnp.asarray([toks[-1]], jnp.int32), cache, act)
        toks.append(int(np.argmax(np.asarray(lg)[0])))
    assert out.tolist() == toks


# ---------------------------------------------------------------------------
# unified tuning table
# ---------------------------------------------------------------------------
@pytest.fixture()
def tuning_tmp(tmp_path, monkeypatch):
    """Point the unified table at a tmp file and reset the process
    cache on both sides of the test."""
    path = tmp_path / "tuning.json"
    monkeypatch.setenv("PADDLE_TPU_TUNING_CACHE", str(path))
    tuning.reset_for_tests()
    yield path
    tuning.reset_for_tests()


def test_tuning_table_roundtrip_and_corrupt_fallback(tuning_tmp):
    key = ("v5e", 2048, 64, True)
    tuning.record("flash_blocks", key, [256, 512])
    data = json.loads(tuning_tmp.read_text())
    assert data["flash_blocks|v5e|2048|64|1"] == [256, 512]

    # "new process": cache dropped, reload from disk
    tuning.reset_for_tests()
    assert tuning.lookup("flash_blocks", key) == [256, 512]
    assert tuning.entries("flash_blocks") == {
        ("v5e", "2048", "64", "1"): [256, 512]}

    # corrupt table: lookups degrade to None, record() rewrites it
    tuning_tmp.write_text("{not json")
    tuning.reset_for_tests()
    assert tuning.lookup("flash_blocks", key) is None
    tuning.record("qmm_tiles", ("v5e", 256, 512, 512, "int8"),
                  [256, 256, 512])
    assert json.loads(tuning_tmp.read_text())  # valid JSON again
    tuning.reset_for_tests()
    assert tuning.lookup("qmm_tiles",
                         ("v5e", 256, 512, 512, "int8")) == [256, 256, 512]


def test_tuning_serves_flash_blocks(tuning_tmp, monkeypatch):
    """get_block_sizes consults the unified table (outside sweep mode)
    when the legacy flash env var is unset."""
    monkeypatch.delenv("PADDLE_TPU_FLASH_AUTOTUNE_CACHE", raising=False)
    fa = importlib.import_module("paddle_tpu.ops.flash_attention")
    tuning.record("flash_blocks", ("v9z", 2048, 64, True), [128, 256])
    from paddle_tpu.ops import get_block_sizes
    assert get_block_sizes(2048, 64, True, device_kind="v9z") == (128, 256)
    # clamped through _pick_block like every other source
    assert get_block_sizes(2048, 64, True, device_kind="v9z") \
        == (fa._pick_block(2048, 128), fa._pick_block(2048, 256))


def test_tuning_serves_prefill_buckets_and_a2a_chunks(tuning_tmp,
                                                      monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_PREFILL_BUCKETS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_MOE_A2A_CHUNKS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
    kind = tuning.device_kind()
    from paddle_tpu.inference.engine import default_prefill_buckets
    tuning.record("prefill_buckets", (kind, 64), [8, 32, 64])
    assert default_prefill_buckets(64) == [8, 32, 64]
    # entries past max_seq are filtered like the env path's
    tuning.record("prefill_buckets", (kind, 32), [8, 64])
    assert default_prefill_buckets(32) == [8]

    from paddle_tpu.distributed.overlap import moe_a2a_chunks
    tuning.record("moe_a2a_chunks", (kind, 8), 4)
    assert moe_a2a_chunks(8) == 4
    # NEARBY token counts inherit the tuned value (bounded nearest —
    # the sweep measures at the bench shape, MoE resolves at b×capacity
    # which rarely matches exactly), clamped to a divisor: 4 -> 3 for 6
    assert moe_a2a_chunks(6) == 3
    # FAR counts (outside the ~4× nearest bound) keep the default
    assert moe_a2a_chunks(96) == 2
    monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
    assert moe_a2a_chunks(8) == 1            # kill switch still wins


def test_qmm_tiles_consult_table(tuning_tmp):
    kind = tuning.device_kind()
    tuning.record("qmm_tiles", (kind, 16, 128, 256, "int8"),
                  [8, 128, 128])
    assert qm.get_qmm_tiles(16, 128, 256) == (8, 128, 128)
    # untuned shape: defaults clamped to divide the problem
    bm, bn, bk = qm.get_qmm_tiles(64, 256, 512)
    assert 64 % bm == 0 and 256 % bn == 0 and 512 % bk == 0
