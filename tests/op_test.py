"""OpTest-style harness: numeric gradient checking for eager ops.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py:232 —
`check_output` compares op output to a numpy reference and `check_grad`
compares tape gradients against central finite differences
(get_numeric_gradient, op_test.py:101).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def numeric_grad(fn_np, inputs, wrt, eps=1e-3):
    """Central finite differences of scalar-valued fn_np w.r.t inputs[wrt]."""
    base = [np.array(a, dtype=np.float64) for a in inputs]
    g = np.zeros_like(base[wrt])
    it = np.nditer(base[wrt], flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = base[wrt][idx]
        base[wrt][idx] = orig + eps
        f1 = float(fn_np(*base))
        base[wrt][idx] = orig - eps
        f2 = float(fn_np(*base))
        base[wrt][idx] = orig
        g[idx] = (f1 - f2) / (2 * eps)
        it.iternext()
    return g


def check_grad(fn, fn_np, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """fn: paddle op over Tensors returning a Tensor (any shape; summed to
    scalar). fn_np: numpy equivalent. Checks every input's gradient."""
    tensors = [paddle.to_tensor(np.asarray(a, dtype=np.float32),
                                stop_gradient=False) for a in inputs]
    out = fn(*tensors)
    loss = out.sum() if out.size != 1 else out
    loss.backward()

    def scalar_np(*arrs):
        return np.sum(fn_np(*arrs))

    for i, t in enumerate(tensors):
        assert t.grad is not None, f"input {i} got no gradient"
        num = numeric_grad(scalar_np, [np.asarray(a) for a in inputs], i, eps)
        np.testing.assert_allclose(
            t.grad.numpy().astype(np.float64), num, rtol=rtol, atol=atol,
            err_msg=f"analytic vs numeric grad mismatch for input {i}")


def check_output(fn, fn_np, inputs, rtol=1e-5, atol=1e-6, **kwargs):
    tensors = [paddle.to_tensor(np.asarray(a)) for a in inputs]
    out = fn(*tensors, **kwargs)
    ref = fn_np(*[np.asarray(a) for a in inputs])
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, ref):
            np.testing.assert_allclose(o.numpy(), r, rtol=rtol, atol=atol)
    else:
        np.testing.assert_allclose(out.numpy(), ref, rtol=rtol, atol=atol)
