"""Executable observatory (ISSUE 15): per-executable cost/memory
registry, roofline attribution, HBM ledger, roofline-aware doctor,
report CLI, flight-recorder bundle GC, metrics snapshot rotation.

The overhead half of the contract (registry armed adds 0 syncs / 0
recompiles) lives in tests/test_telemetry.py's suite; this file covers
the observatory's own behavior: registration at compile time, DEFERRED
analysis (reading stats never compiles), degradation to timing-only on
broken backends/dead owners, roofline math against pinned peaks, ledger
accounting, and the offline report round-trip.
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import observability as obs
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import doctor
from paddle_tpu.observability import exec_registry as er
from paddle_tpu.observability import flightrec, report
from paddle_tpu.utils import compile_counter


def tiny_model(seed=0):
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(seed)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def linear_trainer():
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    return SpmdTrainer(m, opt, lambda o, y: F.cross_entropy(o, y),
                       mesh=create_mesh({"dp": 1}))


def drive_engine(eng, n=8, seed=0):
    rng = np.random.RandomState(seed)
    prompt = rng.randint(1, 97, (7,)).astype(np.int32)
    rid = eng.add_request(prompt, max_new_tokens=n)
    eng.run()
    return rid


# ---------------------------------------------------------------------------
# registration + runtime pairing
# ---------------------------------------------------------------------------
def test_engine_executables_join_registry_at_compile_time():
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    kinds = {e.kind for e in er.registry().entries(eng._exec_component)}
    assert {"prefill", "decode", "sample"} <= kinds
    # runtime pairing: decode steady-state calls accumulate
    drive_engine(eng)
    dec = [e for e in er.registry().entries(eng._exec_component)
           if e.kind == "decode"][0]
    assert dec.calls >= 7 and dec.runtime_ms > 0
    assert dec.compile_ms is not None and dec.compile_ms > 0
    # registration captured donation + sharding metadata host-side
    assert dec.meta["kv_layout"] == "dense"
    assert dec.in_shardings        # non-empty summary


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_spec_and_paged_kinds_registered():
    m = tiny_model()
    eng = InferenceEngine(m, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, prefill_buckets=[16],
                          spec_k=2, draft_model=m)
    eng.warmup(buckets=[16])
    drive_engine(eng, n=6, seed=1)
    kinds = {e.kind for e in er.registry().entries(eng._exec_component)}
    assert "spec_verify" in kinds
    assert "prefill" in kinds and "sample" in kinds
    spec = [e for e in er.registry().entries(eng._exec_component)
            if e.kind == "spec_verify"][0]
    assert spec.meta["spec_k"] == 2


def test_megakernel_decode_kind():
    m = tiny_model()
    m.enable_decode_megakernel(True)
    try:
        eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16])
        eng.warmup(buckets=[16])
        kinds = {e.kind for e in
                 er.registry().entries(eng._exec_component)}
        assert "megakernel_decode" in kinds
    finally:
        m.enable_decode_megakernel(False)


def test_trainer_train_step_registered_and_analyzed():
    tr = linear_trainer()
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.RandomState(0).randint(0, 10, size=(8,)) \
        .astype(np.int64)
    for _ in range(3):
        tr.train_step(x, y)
    es = er.registry().entries(tr._exec_component)
    assert [e.kind for e in es] == ["train_step"]
    assert es[0].calls == 2        # first call was the compile
    # stats never analyze (no compiles from a stats read) ...
    snap0 = compile_counter.snapshot()
    assert tr.stats["exec_profile"] is None
    assert snap0.new_compiles == 0
    # ... the explicit deferred analysis does, and populates the digest
    assert er.analyze_all(tr._exec_component) == 1
    prof = tr.stats["exec_profile"]
    ts = prof["train_step"]
    assert ts["flops"] and ts["bytes_accessed"]
    assert ts["bound"] in ("compute", "bandwidth")
    assert ts["mfu"] is not None and ts["mean_ms"] > 0


# ---------------------------------------------------------------------------
# degradation (satellite: timing-only instead of throwing)
# ---------------------------------------------------------------------------
def test_dead_owner_degrades_to_timing_only():
    import gc
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    comp = eng._exec_component
    entries = er.registry().entries(comp)
    assert entries
    del eng
    gc.collect()
    before = obs.counter("exec_analysis_failures_total",
                         labels=("stage",)) \
        .labels(stage="owner_released").value
    e = entries[0]
    assert not er.registry().analyze(e)
    assert e.analysis is None and "released" in e.analysis_error
    after = obs.counter("exec_analysis_failures_total",
                        labels=("stage",)) \
        .labels(stage="owner_released").value
    assert after == before + 1
    # the snapshot still renders the entry, timing-only
    row = [r for r in er.snapshot(comp)["executables"]
           if str(e.key) == r["key"]][0]
    assert row["analyzed"] is False and row["calls"] == e.calls


def test_cost_memory_stats_guard_none_and_raise():
    from paddle_tpu import profiler

    class NoneAnalysis:
        def cost_analysis(self):
            return None

        def memory_analysis(self):
            return None

    class RaisingAnalysis:
        def cost_analysis(self):
            raise RuntimeError("deserialized executable")

        def memory_analysis(self):
            raise RuntimeError("deserialized executable")

    c = obs.counter("exec_analysis_failures_total", labels=("stage",))
    before = c.labels(stage="cost_analysis").value
    assert profiler.cost_stats(NoneAnalysis()) == {}
    assert profiler.cost_stats(RaisingAnalysis()) == {}
    assert profiler.memory_stats(NoneAnalysis()) == {}
    assert profiler.memory_stats(RaisingAnalysis()) == {}
    assert c.labels(stage="cost_analysis").value == before + 2


def test_registry_disabled_registers_nothing(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_EXEC_REGISTRY", "0")
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    assert er.registry().entries(eng._exec_component) == []


# ---------------------------------------------------------------------------
# roofline math (pinned peaks)
# ---------------------------------------------------------------------------
def test_roofline_classification_and_attribution(monkeypatch):
    reg = er.ExecRegistry()
    # pinned peaks: 100 GFLOP/s, 10 GB/s -> ridge AI = 10
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "100e9")
    monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_GBPS", "10")
    compute = er.ExecEntry("c", ("big_matmul",), "train_step",
                           "big_matmul")
    compute.analysis = {"cost": {"flops": 1e9, "bytes_accessed": 1e6},
                        "memory": {}}
    compute.calls, compute.runtime_ms = 10, 200.0     # 20ms/call
    bandwidth = er.ExecEntry("c", ("decode",), "decode", "decode")
    bandwidth.analysis = {"cost": {"flops": 1e7, "bytes_accessed": 1e8},
                          "memory": {}}
    bandwidth.calls, bandwidth.runtime_ms = 10, 200.0
    reg._entries = {("c", ("big_matmul",)): compute,
                    ("c", ("decode",)): bandwidth}
    snap = reg.snapshot("c")
    assert snap["peaks_nominal"] is False
    rows = {r["name"]: r for r in snap["executables"]}
    mm, dec = rows["big_matmul"], rows["decode"]
    # AI 1000 vs ridge 10 -> compute; AI 0.1 -> bandwidth
    assert mm["bound"] == "compute" and dec["bound"] == "bandwidth"
    # 1e9 flops / 20ms = 5e10 -> 50% MFU
    assert mm["mfu"] == pytest.approx(0.5, rel=1e-3)
    # 1e8 bytes / 20ms = 5e9 B/s -> 50% of the 10 GB/s roof
    assert dec["hbm_bw_frac"] == pytest.approx(0.5, rel=1e-3)
    assert dec["roof_frac"] == pytest.approx(0.5, rel=1e-3)
    # equal wall time -> equal time share; gap_share reflects each
    # entry's distance from the 45% target
    assert mm["time_share"] == pytest.approx(0.5, abs=1e-3)
    assert dec["time_share"] == pytest.approx(0.5, abs=1e-3)
    assert mm["gap_share"] == pytest.approx(0.0, abs=1e-3)  # above 45%
    assert dec["gap_share"] > 0.4                           # way below
    assert snap["overall"]["mfu"] == pytest.approx(
        (1e9 * 10 + 1e7 * 10) / 0.4 / 100e9, rel=1e-3)


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------
def test_hbm_ledger_tracks_and_drops_dead_owners(monkeypatch):
    import gc
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", str(512 * 1024 * 1024))
    led = er.HBMLedger()

    class Owner:
        pass

    o = Owner()
    led.track(o, "params", "t0", 100 << 20)
    led.track(o, "kv_cache", "t0", 50 << 20)
    led.track(None, "static", "x", 1 << 20)
    reg = er.ExecRegistry()
    snap = led.snapshot(exec_registry=reg)
    assert snap["by_category"] == {"params": 100 << 20,
                                   "kv_cache": 50 << 20,
                                   "static": 1 << 20}
    assert snap["capacity_bytes"] == 512 * 1024 * 1024
    assert snap["headroom_frac"] == pytest.approx(
        (512 - 151) / 512, abs=0.01)
    assert snap["oom_risk"] is False
    # owner dies -> its entries fall out; the ownerless one stays
    del o
    gc.collect()
    snap = led.snapshot(exec_registry=reg)
    assert snap["by_category"] == {"static": 1 << 20}


def test_engine_feeds_ledger_params_and_kv():
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    h = er.ledger().snapshot()
    mine = [t for t in h["tracked"]
            if t["name"] == eng.telemetry_label]
    cats = {t["category"] for t in mine}
    assert {"params", "kv_cache"} <= cats
    kv = [t for t in mine if t["category"] == "kv_cache"][0]
    # dense cache: 2 (k,v) * L * slots * seq * Hkv * D * 4B + lengths
    cfg = eng.model.cfg
    expect = 2 * cfg.num_layers * 2 * 64 * cfg.num_kv_heads * \
        cfg.head_dim * 4
    assert abs(kv["bytes"] - expect) <= 64   # lengths array slack


# ---------------------------------------------------------------------------
# roofline-aware doctor
# ---------------------------------------------------------------------------
def _decode_profile(bw_frac, bound="bandwidth", nominal=False):
    return {
        "decode": {"kind": "decode", "bound": bound,
                   "hbm_bw_frac": bw_frac, "achieved_hbm_gbps": 590.0,
                   "arithmetic_intensity": 1.2, "ridge_ai": 240.0,
                   "mfu": 0.04, "calls": 100, "runtime_ms": 500.0},
        "_peaks": {"peaks_nominal": nominal, "device_kind": "tpu v5e"},
    }


def test_doctor_bandwidth_bound_decode_roofline():
    v = doctor.diagnose(
        {"decode_steps": 100, "kv_dtype": None,
         "decode_megakernel": False,
         "exec_profile": _decode_profile(0.72)}, kind="serve")
    names = [x["bottleneck"] for x in v]
    assert "bandwidth-bound-decode" in names
    hit = v[names.index("bandwidth-bound-decode")]
    assert hit["evidence"]["hbm_bw_frac"] == 0.72
    assert hit["evidence"]["bound"] == "bandwidth"
    assert "PADDLE_TPU_KV_DTYPE=int8" in hit["knob"]
    assert "MEGAKERNEL" in hit["knob"].upper()
    assert hit["score"] == pytest.approx(0.72, abs=1e-4)


def test_doctor_roofline_skips_nominal_peaks():
    v = doctor.diagnose(
        {"decode_steps": 100, "kv_dtype": "int8",
         "decode_megakernel": True,
         "exec_profile": _decode_profile(0.9, nominal=True)},
        kind="serve")
    assert "bandwidth-bound-decode" not in \
        [x["bottleneck"] for x in v]


def test_doctor_threshold_fallback_without_exec_profile():
    # pre-registry evidence still produces the advisory verdict
    v = doctor.diagnose(
        {"decode_steps": 100, "decode_hbm_bytes_per_tok": 10_000_000,
         "kv_dtype": None, "decode_megakernel": False}, kind="serve")
    assert "bandwidth-bound-decode" in [x["bottleneck"] for x in v]


def test_doctor_measured_compute_bound_beats_byte_fallback():
    # a roofline row classifying decode COMPUTE-bound is authoritative:
    # the byte-count heuristic must not fall through and contradict it
    v = doctor.diagnose(
        {"decode_steps": 100, "decode_hbm_bytes_per_tok": 10_000_000,
         "kv_dtype": None, "decode_megakernel": False,
         "exec_profile": _decode_profile(0.2, bound="compute")},
        kind="serve")
    assert "bandwidth-bound-decode" not in \
        [x["bottleneck"] for x in v]


def test_doctor_mfu_below_target_train_rule():
    stats = {"exec_profile": {
        "train_step": {"kind": "train_step", "bound": "compute",
                       "mfu": 0.35, "arithmetic_intensity": 300.0,
                       "ridge_ai": 240.0, "mean_ms": 120.0,
                       "gap_share": 0.2, "runtime_ms": 2400.0,
                       "calls": 20},
        "_peaks": {"peaks_nominal": False}}}
    v = doctor.diagnose(stats, kind="train")
    names = [x["bottleneck"] for x in v]
    assert "mfu-below-target" in names
    hit = v[names.index("mfu-below-target")]
    assert hit["evidence"]["mfu"] == 0.35
    assert hit["evidence"]["bound"] == "compute"


def test_doctor_oom_risk_rule():
    v = doctor.diagnose(
        {"hbm": {"headroom_frac": 0.03, "tracked_bytes": 15 << 30,
                 "capacity_bytes": 16 << 30,
                 "exec_temp_bytes": 400 << 20,
                 "exec_temp_worst": "trainer:s0:fused/1/1"}},
        kind="train")
    names = [x["bottleneck"] for x in v]
    assert "oom-risk" in names
    hit = v[names.index("oom-risk")]
    assert hit["evidence"]["headroom_frac"] == 0.03
    assert "exec_temp_worst" in hit["evidence"]
    # healthy headroom: silent
    assert doctor.diagnose({"hbm": {"headroom_frac": 0.4}}) == []


# ---------------------------------------------------------------------------
# snapshot -> report round-trip
# ---------------------------------------------------------------------------
def test_snapshot_and_report_round_trip(tmp_path):
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    drive_engine(eng, seed=3)
    er.analyze_all(eng._exec_component)
    snap = obs.snapshot()
    assert "executables" in snap and "hbm" in snap
    rows = [r for r in snap["executables"]["executables"]
            if r["component"] == eng._exec_component]
    kinds = {r["kind"] for r in rows}
    assert {"prefill", "decode", "sample"} <= kinds
    dec = [r for r in rows if r["kind"] == "decode"][0]
    for fld in ("flops", "bytes_accessed", "peak_bytes", "bound",
                "mfu", "hbm_bw_frac", "time_share"):
        assert dec.get(fld) is not None, fld

    # offline: write_snapshot -> report renders from the file only
    path = str(tmp_path / "snap.jsonl")
    obs.write_snapshot(path)
    rec = report.load_snapshot_file(path)
    assert rec is not None
    text = report.render_snapshot(rec)
    assert "decode" in text and "hbm ledger" in text
    assert "executables on" in text
    # CLI main() exits 0 on the same file
    assert report.main(["--snapshot", path]) == 0


def test_report_cli_exit_codes(tmp_path, capsys):
    missing = str(tmp_path / "nope.jsonl")
    assert report.main(["--snapshot", missing]) == 2
    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text("not json\n{broken\n")
    assert report.main(["--snapshot", str(garbage)]) == 2


def test_report_cli_rows_only_renders_doctor(tmp_path, capsys):
    # the documented `--rows BENCH_rows.jsonl` standalone invocation
    rows = tmp_path / "rows.jsonl"
    rows.write_text(json.dumps({
        "kind": "train", "mfu": 0.35,
        "doctor": [{"bottleneck": "comm-bound",
                    "evidence": {"comm_fraction": 0.4},
                    "knob": "PADDLE_TPU_OVERLAP=1", "score": 0.4}],
    }) + "\n")
    assert report.main(["--rows", str(rows)]) == 0
    out = capsys.readouterr().out
    assert "comm-bound" in out and "PADDLE_TPU_OVERLAP" in out


def test_ledger_oom_flag_agrees_with_doctor_threshold(monkeypatch):
    # one constant: the ledger's oom_risk flag and the doctor's rule
    # must flip on the same headroom line
    assert doctor.HBM_HEADROOM_MIN == er.OOM_HEADROOM_MIN
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", str(1000))
    led = er.HBMLedger()
    led.track(None, "params", "edge", 1000 - int(1000 * 0.07))
    snap = led.snapshot(exec_registry=er.ExecRegistry())
    assert snap["oom_risk"] is True
    assert doctor.diagnose({"hbm": snap})[0]["bottleneck"] == "oom-risk"


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_engine_registered_donation_matches_jit_construction():
    m = tiny_model()
    eng = InferenceEngine(m, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, prefill_buckets=[16],
                          spec_k=2, draft_model=m, donate=True)
    eng.warmup(buckets=[16])
    by_kind = {e.kind: e for e in
               er.registry().entries(eng._exec_component)}
    assert by_kind["sample"].donate_argnums == ()        # never donates
    assert by_kind["spec_verify"].donate_argnums == (2, 3)  # both caches
    assert by_kind["prefill"].donate_argnums == (1,)
    assert by_kind["decode"].donate_argnums == (1,)


def test_flightrec_bundle_carries_executables(tmp_path):
    eng = InferenceEngine(tiny_model(), batch_slots=2,
                          prefill_buckets=[16])
    eng.warmup(buckets=[16])
    rec = flightrec.FlightRecorder()
    rec.record("decode_tick", dur_ms=1.0, tick=1)
    path = rec.dump("test", directory=str(tmp_path))
    assert path is not None
    bundle = flightrec.load_bundle(path)["bundle"]
    assert "executables" in bundle and "hbm" in bundle
    comps = {r["component"]
             for r in bundle["executables"]["executables"]}
    assert eng._exec_component in comps
    # the report CLI renders a bundle too
    assert report.main(["--bundle", path]) == 0


# ---------------------------------------------------------------------------
# flight-recorder bundle GC (satellite)
# ---------------------------------------------------------------------------
def test_flightrec_gc_prunes_oldest_and_tmp_orphans(tmp_path,
                                                    monkeypatch):
    base = str(tmp_path)
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_KEEP", "3")
    now = time.time()
    for i in range(6):
        d = os.path.join(base, f"flightrec-111-{i:03d}-old")
        os.makedirs(d)
        os.utime(d, (now - 1000 + i, now - 1000 + i))
    # stale .tmp orphan (dead process) and a fresh one (live dump)
    stale = os.path.join(base, "flightrec-222-001-x.tmp")
    fresh = os.path.join(base, "flightrec-333-001-y.tmp")
    os.makedirs(stale)
    os.utime(stale, (now - 7200, now - 7200))
    os.makedirs(fresh)
    # unrelated files are never touched
    other = os.path.join(base, "notes.txt")
    with open(other, "w") as f:
        f.write("keep me")
    flightrec.gc_bundles(base)
    left = sorted(os.listdir(base))
    assert "notes.txt" in left
    assert "flightrec-333-001-y.tmp" in left          # fresh tmp kept
    assert "flightrec-222-001-x.tmp" not in left      # stale tmp gone
    committed = [n for n in left if n.startswith("flightrec-111")]
    assert committed == ["flightrec-111-003-old", "flightrec-111-004-old",
                         "flightrec-111-005-old"]     # newest 3 kept


def test_flightrec_dump_triggers_gc(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHTREC_KEEP", "2")
    rec = flightrec.FlightRecorder()
    paths = [rec.dump(f"r{i}", directory=str(tmp_path))
             for i in range(4)]
    assert all(paths)
    left = [n for n in os.listdir(str(tmp_path))
            if n.startswith("flightrec-")]
    assert len(left) == 2


# ---------------------------------------------------------------------------
# metrics snapshot size rotation (satellite)
# ---------------------------------------------------------------------------
def test_snapshot_file_size_rotation(tmp_path, monkeypatch):
    from paddle_tpu.observability.metrics import Registry
    r = Registry()
    g = r.gauge("fat_gauge", "x" * 200, labels=("k",))
    for i in range(40):
        g.labels(k=f"label-{i}-{'y' * 100}").set(i)
    path = str(tmp_path / "snap.jsonl")
    monkeypatch.setenv("PADDLE_TPU_METRICS_SNAPSHOT_MAX_MB", "0.02")
    for _ in range(50):
        r.write_snapshot(path)
    size = os.path.getsize(path)
    assert size <= 0.02 * 1e6 + 1024     # bounded (one-line slack)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines and "metrics" in lines[-1]     # newest always lands
    # no .tmp orphan from the rotating writes
    assert not [p for p in os.listdir(str(tmp_path))
                if p.endswith(".tmp")]


def test_snapshot_single_fat_line_still_lands(tmp_path, monkeypatch):
    from paddle_tpu.observability.metrics import Registry
    r = Registry()
    g = r.gauge("huge", "h" * 500, labels=("k",))
    for i in range(100):
        g.labels(k=f"{i}-{'z' * 200}").set(i)
    path = str(tmp_path / "snap.jsonl")
    monkeypatch.setenv("PADDLE_TPU_METRICS_SNAPSHOT_MAX_MB", "0.001")
    r.write_snapshot(path)
    r.write_snapshot(path)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert len(lines) == 1               # history dropped, state kept
