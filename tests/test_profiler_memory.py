"""Profiler + memory/FLOP evidence tests (VERDICT r2 #8).

Replaces the shape-only assertions: ZeRO-3 is proven by per-device
param BYTES, recompute by compiled FLOP counts (the CPU backend reports
temp_size_in_bytes=0, so the peak-HBM assertion is TPU-gated; the FLOPs
side of the remat trade is assertable everywhere).
"""
import glob
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.distributed import SpmdTrainer, create_mesh
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)


@pytest.mark.slow
def test_record_event_and_trace_capture(tmp_path):
    """profiler ctx writes a real trace artifact; RecordEvent nests.
    Spinning up the real JAX profiler costs ~15s — slow-marked under
    the tight tier-1 budget; the start/stop state machine and step
    timer below keep the API surface covered in tier-1."""
    d = str(tmp_path / "trace")
    with profiler.profiler(log_dir=d):
        with profiler.RecordEvent("train_step"):
            x = jnp.ones((128, 128))
            (x @ x).block_until_ready()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace written"


def test_start_stop_profiler_state_machine(tmp_path):
    d = str(tmp_path / "t2")
    profiler.start_profiler(d)
    with pytest.raises(RuntimeError):
        profiler.start_profiler(d)
    assert profiler.stop_profiler() == d
    assert profiler.stop_profiler() is None  # idempotent


def test_step_timer():
    t = profiler.StepTimer(warmup=1)
    t.start()
    for _ in range(4):
        t.tick()
    s = t.summary()
    assert s["steps"] == 3 and s["mean_ms"] >= 0


def test_hapi_fit_logs_step_time():
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.models import LeNet

    class DS:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randn(1, 28, 28).astype(np.float32),
                    np.array([i % 10], np.int64))

    seen = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            if "step_time_ms" in logs:
                seen.append(logs["step_time_ms"])

    paddle.seed(0)
    m = Model(LeNet())
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters()),
              nn.CrossEntropyLoss())
    m.fit(DS(), batch_size=16, epochs=1, verbose=0, callbacks=[Rec()])
    assert seen and all(v >= 0 for v in seen)


def _gpt_loss_grad(remat: bool):
    from paddle_tpu.func import functional_call
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    if remat:
        model.enable_recompute()
    model.train()
    crit = GPTPretrainingCriterion()
    params = {n: p.data for n, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (4, 64)).astype(np.int32))
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1))

    def loss_fn(p):
        from paddle_tpu.core.autograd import no_grad
        from paddle_tpu.core.tensor import Tensor
        with no_grad():
            out, _ = functional_call(model, p, {}, ids, training=True)
        return crit(Tensor(out, stop_gradient=True),
                    Tensor(labels)).data

    return jax.jit(jax.grad(loss_fn)).lower(params).compile()


def test_recompute_trades_flops_for_memory():
    """recompute re-executes forwards in backward: compiled FLOPs must
    rise; on a real accelerator peak temp memory must drop (the CPU
    backend reports temp=0, so that half is TPU-gated)."""
    plain = _gpt_loss_grad(remat=False)
    remat = _gpt_loss_grad(remat=True)
    f_plain = profiler.cost_stats(plain)["flops"]
    f_remat = profiler.cost_stats(remat)["flops"]
    assert f_remat > f_plain * 1.15, (f_plain, f_remat)
    if jax.default_backend() not in ("cpu",):  # pragma: no cover
        m_plain = profiler.memory_stats(plain)["temp_bytes"]
        m_remat = profiler.memory_stats(remat)["temp_bytes"]
        if m_plain > 0:  # some remote-compile paths omit memory stats
            assert m_remat < m_plain


def test_zero3_shards_param_bytes():
    """ZeRO-3: per-device param bytes ~ total/dp for shardable params
    (byte-level evidence replacing round-2's shape-only assertion)."""
    import paddle_tpu.nn as nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, 64))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    st = DistributedStrategy()
    st.sharding = True
    st.sharding_configs = {"stage": 3}
    mesh = create_mesh({"dp": 8})
    tr = SpmdTrainer(model, opt, lambda o, l: (o - l).square().mean(),
                     mesh=mesh, strategy=st)
    dev0 = mesh.devices.ravel()[0]
    for name, arr in tr.params.items():
        total = arr.nbytes
        local = sum(sh.data.nbytes for sh in arr.addressable_shards
                    if sh.device == dev0)
        if any(d % 8 == 0 and d >= 8 for d in arr.shape):
            assert local * 8 == total, \
                f"{name}: local {local} * 8 != total {total}"
    # optimizer moment state sharded the same way (stage>=1)
    m0 = tr.opt_state["0.weight"]["moment1"]
    local = sum(sh.data.nbytes for sh in m0.addressable_shards
                if sh.device == dev0)
    assert local * 8 == m0.nbytes
