"""Speculative decoding tests: the ISSUE-12 token-identity contract.

The whole value of greedy speculative decoding is that it is a pure
SCHEDULING change — the emitted stream must be bit-identical to the
non-speculative engine's (which test_inference_engine/test_paged_kv
prove equal to the naive full-forward rollout).  This file pins that
down across the serving matrix: dense AND paged targets, fp AND int8 KV
caches, GQA, draft window K ∈ {1, 2, 4}, EOS mid-window — plus the
zero-recompile churn contract for the three new executables (draft
prefill, spec tick, verify window) and the windowed-attention op layer
(composite ≡ sequential single-token oracle; interpret-mode Pallas
kernels ≡ composite).
"""
import importlib

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.func import functional_apply, functional_state
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.utils import compile_counter

da = importlib.import_module("paddle_tpu.ops.decode_attention")

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(seed=0, **over):
    paddle.seed(seed)
    cfg = GPTConfig(**{**TINY, **over})
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def target():
    return tiny_model(0)


@pytest.fixture(scope="module")
def draft():
    # a genuinely DIFFERENT model (fewer layers, different init): the
    # acceptance rule must keep output identical even when the draft
    # disagrees with the target
    return tiny_model(1, num_layers=1)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(0)
    return [rng.randint(1, 97, (n,)).astype(np.int32)
            for n in (5, 9, 3)]


@pytest.fixture(scope="module")
def reference(target, prompts):
    """The non-speculative dense engine's greedy output — the ground
    truth every spec configuration must reproduce exactly."""
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16])
    for p in prompts:
        eng.add_request(p, max_new_tokens=12)
    return eng.run()


# ---- op level: window attention -----------------------------------------

def test_window_attention_matches_sequential():
    """decode_attention_window(q[:, i]) must equal a sequential chain
    of single-token decode_attention calls — that equivalence IS the
    spec-decode verify correctness argument."""
    rng = np.random.RandomState(0)
    B, S, H, Hkv, D, W = 2, 16, 4, 2, 8, 3
    k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    lens = jnp.asarray(np.array([5, 9], np.int32))
    out = da.decode_attention_window(q, k, v, lens)
    for i in range(W):
        ref = da.decode_attention(q[:, i], k, v, lens + i + 1)
        np.testing.assert_allclose(np.asarray(out[:, i]),
                                   np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_paged_window_matches_dense_window():
    """The paged window composite over a scattered pool must equal the
    dense window on identical contents (the paged parity-oracle chain
    extended to W > 1)."""
    rng = np.random.RandomState(1)
    B, S, H, Hkv, D, W, bs = 2, 16, 4, 2, 8, 3, 8
    k = rng.randn(B, S, Hkv, D).astype(np.float32)
    v = rng.randn(B, S, Hkv, D).astype(np.float32)
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    lens = jnp.asarray(np.array([4, 8], np.int32))
    tables = np.array([[1, 2], [3, 4]], np.int32)
    pool_k = np.zeros((5, bs, Hkv, D), np.float32)
    pool_v = np.zeros_like(pool_k)
    for b in range(B):
        for j in range(S // bs):
            pool_k[tables[b, j]] = k[b, j * bs:(j + 1) * bs]
            pool_v[tables[b, j]] = v[b, j * bs:(j + 1) * bs]
    dense = da.decode_attention_window(q, jnp.asarray(k), jnp.asarray(v),
                                       lens)
    paged = da.paged_decode_attention_window(
        q, jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(tables), lens)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("quantized", [False, True])
def test_window_kernel_interpret_vs_composite(quantized):
    """Interpret-mode Pallas window kernel ≡ the XLA composite (dense
    layout, kernel-eligible shapes, GQA, fp and int8)."""
    if not da._fa._HAS_PLTPU:
        pytest.skip("pallas TPU surface unavailable")
    rng = np.random.RandomState(2)
    B, S, H, Hkv, D, W = 2, 128, 4, 2, 64, 3
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    lens = jnp.asarray(np.array([37, 90], np.int32))
    if quantized:
        k = jnp.asarray(rng.randint(-127, 128, (B, S, Hkv, D))
                        .astype(np.int8))
        v = jnp.asarray(rng.randint(-127, 128, (B, S, Hkv, D))
                        .astype(np.int8))
        ks = jnp.asarray(rng.rand(B, S, Hkv).astype(np.float32) * 0.02)
        vs = jnp.asarray(rng.rand(B, S, Hkv).astype(np.float32) * 0.02)
        args = (q, k, v, lens, ks, vs)
    else:
        k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        args = (q, k, v, lens)
    ref = da._window_composite(q, args[1], args[2], lens,
                               *(args[4:] if quantized else ()))
    da.set_interpret_mode(True)
    try:
        out = da.decode_attention_window(*args)
    finally:
        da.set_interpret_mode(None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_paged_window_kernel_interpret_vs_composite(quantized):
    """Interpret-mode scalar-prefetch paged window kernel ≡ the gather
    composite."""
    if not da.paged_decode_attention_available() and \
            not da._fa._HAS_PLTPU:
        pytest.skip("pallas TPU surface unavailable")
    if da._fa.pltpu is None:
        pytest.skip("scalar prefetch unavailable")
    rng = np.random.RandomState(3)
    B, H, Hkv, D, W, bs, nb, mb = 2, 4, 2, 64, 3, 128, 5, 2
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    tables = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    lens = jnp.asarray(np.array([100, 200], np.int32))
    if quantized:
        kp = jnp.asarray(rng.randint(-127, 128, (nb, bs, Hkv, D))
                         .astype(np.int8))
        vp = jnp.asarray(rng.randint(-127, 128, (nb, bs, Hkv, D))
                         .astype(np.int8))
        ks = jnp.asarray(rng.rand(nb, bs, Hkv).astype(np.float32) * 0.02)
        vs = jnp.asarray(rng.rand(nb, bs, Hkv).astype(np.float32) * 0.02)
        args = (q, kp, vp, tables, lens, ks, vs)
        ref = da._paged_window_composite(*args)
    else:
        kp = jnp.asarray(rng.randn(nb, bs, Hkv, D).astype(np.float32))
        vp = jnp.asarray(rng.randn(nb, bs, Hkv, D).astype(np.float32))
        args = (q, kp, vp, tables, lens)
        ref = da._paged_window_composite(*args)
    da.set_interpret_mode(True)
    try:
        out = da.paged_decode_attention_window(*args)
    finally:
        da.set_interpret_mode(None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- model level: verify_step ≡ sequential decode -----------------------

@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_verify_step_matches_sequential(target, kv_dtype):
    """One verify_step window over W tokens reproduces W sequential
    decode_step calls — logits at every position, cache contents
    included (fp bitwise-tight tolerance; int8 goes through the SAME
    quantization on both paths so it stays tight too)."""
    m = target
    params, _ = functional_state(m)
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 97, (2, 5)).astype(np.int32)
    toks = rng.randint(1, 97, (2, 3)).astype(np.int32)
    cache = m.init_kv_cache(2, 64, kv_dtype=kv_dtype)
    for s in range(2):
        _, cache = functional_apply(
            m, "prefill", params, jnp.asarray(prompt[s:s + 1]), cache,
            np.int32(s), np.int32(5))
    seq_cache = cache
    seq_logits = []
    for i in range(3):
        lg, seq_cache = functional_apply(
            m, "decode_step", params, jnp.asarray(toks[:, i]),
            seq_cache, jnp.ones(2, jnp.int32))
        seq_logits.append(np.asarray(lg))
    win_logits, win_cache = functional_apply(
        m, "verify_step", params, jnp.asarray(toks), cache)
    win_logits = np.asarray(win_logits)
    for i in range(3):
        np.testing.assert_allclose(win_logits[:, i], seq_logits[i],
                                   rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(win_cache.k).astype(np.float32)[:, :, :8],
        np.asarray(seq_cache.k).astype(np.float32)[:, :, :8],
        rtol=1e-5, atol=1e-5)


# ---- engine level: the token-identity matrix ----------------------------

# tier-1 wall budget: the fast lane keeps the 4 corners (k extremes ×
# dtype × layout, every axis value covered); the 8 interior combos of
# the k × dtype × layout cube ride the slow lane
_MATRIX_CORNERS = {(1, None, "dense"), (1, "int8", "paged"),
                   (4, None, "paged"), (4, "int8", "dense")}
_MATRIX = [
    pytest.param(k, kv, lay, id=f"{k}-{kv}-{lay}",
                 marks=() if (k, kv, lay) in _MATRIX_CORNERS
                 else pytest.mark.slow)
    for k in (1, 2, 4) for kv in (None, "int8")
    for lay in ("dense", "paged")]


@pytest.mark.parametrize("k,kv_dtype,layout", _MATRIX)
def test_spec_token_identity_matrix(target, draft, prompts, reference,
                                    layout, kv_dtype, k):
    """Greedy speculative output ≡ the non-speculative rollout across
    the full serving matrix, with ZERO XLA compiles after warmup (the
    draft-prefill / spec-tick / verify executables are shape-stable).
    int8 targets are compared against an int8 NON-spec engine — the
    identity claim is per-configuration (quantization changes logits,
    never the spec/non-spec equivalence)."""
    kw = dict(kv_layout=layout)
    if layout == "paged":
        kw.update(kv_block_size=8)
    if kv_dtype is None:
        ref = reference
    else:
        ref_eng = InferenceEngine(target, batch_slots=2,
                                  prefill_buckets=[16],
                                  kv_dtype=kv_dtype, **kw)
        for p in prompts:
            ref_eng.add_request(p, max_new_tokens=12)
        ref = ref_eng.run()
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16],
                          spec_k=k, draft_model=draft,
                          kv_dtype=kv_dtype, **kw)
    eng.warmup(buckets=eng.buckets)
    with compile_counter.assert_no_recompiles(
            f"spec churn {layout}/{kv_dtype}/K={k}"):
        for p in prompts:
            eng.add_request(p, max_new_tokens=12)
        out = eng.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    if layout == "paged":
        eng.check_leak_free()
    st = eng.stats
    assert st["spec_ticks"] > 0
    assert st["accepted_tokens_per_tick"] >= 1.0


def test_spec_token_identity_gqa(prompts):
    """The matrix's GQA leg: grouped-query target + draft."""
    tgt = tiny_model(0, num_kv_heads=2)
    drf = tiny_model(1, num_kv_heads=2, num_layers=1)
    ref_eng = InferenceEngine(tgt, batch_slots=2, prefill_buckets=[16])
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=12)
    ref = ref_eng.run()
    for layout in ("dense", "paged"):
        kw = {"kv_block_size": 8} if layout == "paged" else {}
        eng = InferenceEngine(tgt, batch_slots=2, prefill_buckets=[16],
                              spec_k=2, draft_model=drf,
                              kv_layout=layout, **kw)
        for p in prompts:
            eng.add_request(p, max_new_tokens=12)
        out = eng.run()
        for rr, ss in zip(sorted(ref), sorted(out)):
            np.testing.assert_array_equal(ref[rr], out[ss])


def test_spec_eos_mid_window(target, draft):
    """EOS landing INSIDE an accepted window truncates exactly where
    the sequential rollout stops — find a prompt whose greedy rollout
    emits some token t, declare t the EOS id, and check both engines
    stop identically."""
    rng = np.random.RandomState(7)
    hit = 0
    for trial in range(12):
        prompt = rng.randint(1, 97, (rng.randint(3, 9),)).astype(np.int32)
        ref_eng = InferenceEngine(target, batch_slots=1,
                                  prefill_buckets=[16])
        base = ref_eng.generate(prompt, max_new_tokens=10)
        if len(base) < 3:
            continue
        eos = int(base[len(base) // 2])    # a token mid-stream
        ref_eng2 = InferenceEngine(target, batch_slots=1,
                                   prefill_buckets=[16])
        want = ref_eng2.generate(prompt, max_new_tokens=10, eos_id=eos)
        spec = InferenceEngine(target, batch_slots=1,
                               prefill_buckets=[16], spec_k=3,
                               draft_model=draft)
        got = spec.generate(prompt, max_new_tokens=10, eos_id=eos)
        np.testing.assert_array_equal(want, got)
        assert int(got[-1]) == eos
        hit += 1
        if hit >= 3:
            break
    assert hit >= 1, "no rollout long enough to plant a mid-stream EOS"


def test_spec_self_draft_accepts_everything(target, prompts):
    """Drafting with the target itself is the acceptance ceiling: every
    proposal matches, so each tick commits K+1 tokens except the final
    max-new-truncated window (metrics count tokens that actually
    reached the stream: 11 remaining tokens over 3 ticks per request =
    3.67/tick at K=3) — the harness the fleet smoke leans on."""
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[16],
                          spec_k=3, draft_model=target)
    for p in prompts:
        eng.add_request(p, max_new_tokens=12)
    eng.run()
    st = eng.stats
    assert st["accepted_tokens_per_tick"] > 3.0      # ceiling K+1 = 4
    assert st["spec_acceptance_rate"] > 0.85
    assert st["spec_capacity_retirements"] == 0


def test_spec_sampled_seeded_determinism(target, draft):
    """Sampled-request speculation (ISSUE 18): temperature>0 requests
    ride the spec path (full rejection-sampling residual) and a seeded
    engine replays the exact same stream — the determinism half of the
    correctness contract (distribution fidelity is pinned by
    test_spec_sampled_residual_distribution)."""
    prompt = np.array([1, 2, 3], np.int32)

    def run(seed):
        eng = InferenceEngine(target, batch_slots=2,
                              prefill_buckets=[16], seed=seed,
                              spec_k=2, draft_model=draft)
        r_s = eng.add_request(prompt, max_new_tokens=10,
                              temperature=0.8, top_p=0.9)
        r_g = eng.add_request(prompt, max_new_tokens=10)
        out = eng.run()
        return out[r_s], out[r_g]

    s0, g0 = run(7)
    s1, g1 = run(7)
    s2, _ = run(8)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_array_equal(g0, g1)
    assert len(s0) == 10 and len(s2) == 10
    # the greedy slot of a mixed batch must still match the greedy
    # reference engine exactly (the sampled neighbor consumes PRNG but
    # greedy outputs never depend on it)
    ref = InferenceEngine(target, batch_slots=1, prefill_buckets=[16])
    rid = ref.add_request(prompt, max_new_tokens=10)
    np.testing.assert_array_equal(g0, ref.run()[rid])


def test_spec_sampled_residual_distribution(target, draft):
    """The rejection-sampling identity, checked exactly where it must
    hold: for draft ~ q, accept with min(1, p/q), else resample from
    norm(max(p-q, 0)) — the committed token's marginal IS p.  Run
    SpecDecoder._accept over thousands of independent rows with known
    p != q and bound the total-variation distance of the committed
    first token against p, plus the acceptance rate against the
    distribution overlap sum(min(p, q))."""
    import jax

    eng = InferenceEngine(target, batch_slots=1, prefill_buckets=[16],
                          spec_k=1, draft_model=draft)
    sd = eng._spec
    rng = np.random.RandomState(0)
    V, N = 8, 8192
    p = np.array([.30, .20, .15, .10, .10, .08, .05, .02], np.float32)
    q = p[::-1].copy()                      # reversed: TV(p, q) = 0.46
    drafts = rng.choice(V, size=(N, 1),
                        p=q / q.sum()).astype(np.int32)
    # temps=1, top_p=1, top_k=0 make the warped target distribution
    # exactly softmax(logits) = p at every position
    logits = np.broadcast_to(np.log(p), (N, 2, V)).astype(np.float32)
    toks, n_acc, n_emit, _ = jax.jit(sd._accept)(
        jnp.asarray(drafts),
        jnp.asarray(np.broadcast_to(q, (N, 1, V)).copy()),
        jnp.asarray(logits), jnp.ones(N, jnp.int32),
        jax.random.PRNGKey(0), jnp.ones(N, jnp.float32),
        jnp.ones(N, jnp.float32))
    assert int(np.asarray(n_emit).min()) >= 1
    h = np.bincount(np.asarray(toks[:, 0]), minlength=V) / N
    tv = 0.5 * float(np.abs(h - p).sum())
    # statistical floor at N=8192 is ~0.015; sampling q instead of the
    # residual (or always taking the draft) lands near TV(p,q)=0.46
    assert tv < 0.05, f"committed-token marginal diverged from p: {tv}"
    acc = float(np.asarray(n_acc).mean())
    overlap = float(np.minimum(p, q).sum())
    assert abs(acc - overlap) < 0.05, (acc, overlap)


def test_spec_draft_validation(target):
    """Draft/target contract checks: vocab and position-table
    mismatches raise at construction."""
    bad_vocab = tiny_model(2, vocab_size=64)
    with pytest.raises(ValueError, match="vocab"):
        InferenceEngine(target, batch_slots=1, spec_k=2,
                        draft_model=bad_vocab)
    bad_seq = tiny_model(2, max_seq_len=32)
    with pytest.raises(ValueError, match="max_seq_len"):
        InferenceEngine(target, batch_slots=1, spec_k=2,
                        draft_model=bad_seq)
    with pytest.raises(ValueError, match="draft_model"):
        InferenceEngine(target, batch_slots=1, spec_k=2)


def test_spec_preemption_resume_identity(target, draft):
    """A spec engine under pool pressure (preempt-to-queue) still
    reproduces the non-speculative output: the resume prefill re-seeds
    both the target blocks and the draft cache."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 97, (6,)).astype(np.int32)
               for _ in range(4)]
    ref_eng = InferenceEngine(target, batch_slots=2,
                              prefill_buckets=[8, 16])
    for p in prompts:
        ref_eng.add_request(p, max_new_tokens=10)
    ref = ref_eng.run()
    # a pool just big enough to admit but tight enough to preempt
    eng = InferenceEngine(target, batch_slots=2, prefill_buckets=[8, 16],
                          kv_layout="paged", kv_block_size=8,
                          kv_num_blocks=7, spec_k=2, draft_model=draft)
    for p in prompts:
        eng.add_request(p, max_new_tokens=10)
    out = eng.run()
    for rr, ss in zip(sorted(ref), sorted(out)):
        np.testing.assert_array_equal(ref[rr], out[ss])
    eng.check_leak_free()
