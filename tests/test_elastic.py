"""Elastic cross-topology resilience (ISSUE 10): reshardable manifest-v2
checkpoints, shrink/grow restores, the expanded fault harness, and the
serving-side drain/deadline satellites.

Done criteria exercised here:
- a checkpoint written on one mesh (dp=8 / ZeRO-3 dp=4 / pp=4) restores
  onto a SMALLER mesh with loss-curve parity (bitwise for plain dp,
  rtol 1e-5 where the collective structure changes) and records the
  reshard in trainer/manager stats;
- MANIFEST.json v2 carries mesh_axes + per-leaf global shape/dtype/
  logical sharding spec; legacy v1 states still load on an identical
  mesh;
- restore_latest falls back past a corrupt newest candidate onto the
  newest LOADABLE one and reshards it when its topology differs;
- the new fault knobs are deterministic: PADDLE_FAULT_CKPT_TRUNCATE
  commits a partial shard and kills the process, PADDLE_FAULT_MESH_SHRINK
  clamps the devices create_mesh sees, PADDLE_FAULT_FS_DELAY_MS injects
  write jitter;
- kill-and-resume onto a SHRUNK mesh reproduces the uninterrupted loss
  curve end to end (subprocess tests; the dp variant also rides
  `bench.py --multichip-smoke`'s elastic phase);
- CheckpointManager surfaces background commit failures (on_error /
  wait timeout), and the InferenceEngine drains gracefully and enforces
  per-request deadlines.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (CheckpointManager, SpmdTrainer,
                                    create_mesh, latest_checkpoint)
from paddle_tpu.distributed.checkpoint import (read_checkpoint,
                                               read_manifest,
                                               validate_checkpoint)
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _mesh(dp):
    return create_mesh({"dp": dp}, devices=jax.devices()[:dp])


def _trainer(dp, seed=0, strategy=None, **kw):
    paddle.seed(seed)
    model = nn.Linear(6, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    return SpmdTrainer(model, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=_mesh(dp), strategy=strategy, **kw)


def _batches(n, seed=0, cols=6, out=4):
    rng = np.random.RandomState(seed)
    return [(rng.randn(8, cols).astype(np.float32),
             rng.randn(8, out).astype(np.float32)) for _ in range(n)]


# ---------------------------------------------------------------------------
# manifest v2 metadata
# ---------------------------------------------------------------------------
def test_manifest_v2_records_topology(tmp_path):
    tr = _trainer(4)
    for x, y in _batches(2):
        tr.train_step(x, y)
    p = str(tmp_path / "ck")
    tr.save(p, manifest=True)
    man = read_manifest(p)
    assert man["version"] == 2
    assert man["mesh_axes"] == {"dp": 4}
    # per-leaf global shape + dtype + LOGICAL spec (no device ids)
    leaves = man["leaves"]
    w = leaves["params['weight']"]
    assert w["shape"] == [6, 4] and w["dtype"] == "float32"
    assert all(e is None or isinstance(e, (str, list))
               for e in w["spec"])
    # the pickled state carries the same record
    state = read_checkpoint(p)
    assert state["version"] == 2
    assert state["mesh_axes"] == {"dp": 4}
    assert "params" in state["sharding_specs"]
    # still validates under the v1 manifest walker
    assert validate_checkpoint(p)


def test_legacy_v1_state_restores_on_identical_mesh(tmp_path):
    """A pre-v2 checkpoint (no topology record) must keep loading
    unchanged on the same layout."""
    tr = _trainer(2)
    for x, y in _batches(3):
        tr.train_step(x, y)
    from paddle_tpu.distributed.checkpoint import (snapshot_trainer,
                                                   write_checkpoint)
    state = snapshot_trainer(tr)
    for k in ("version", "mesh_axes", "sharding_specs"):
        state.pop(k, None)               # forge the PR-2 layout
    p = str(tmp_path / "legacy")
    write_checkpoint(state, p)
    assert read_manifest(p)["version"] == 1
    tr2 = _trainer(2, seed=9)
    tr2.load(p)
    assert tr2._step_count == 3
    assert tr2._last_restore_info["resharded"] is False
    assert tr2._last_restore_info["version"] == 1
    for n in tr.params:
        np.testing.assert_array_equal(np.asarray(tr.params[n]),
                                      np.asarray(tr2.params[n]))


# ---------------------------------------------------------------------------
# elastic restores: dp shrink (bitwise), ZeRO-3, pipeline, strict mode
# ---------------------------------------------------------------------------
def test_dp_shrink_restore_parity(tmp_path):
    """dp=4 -> dp=2: the canonical elastic shrink.  Plain dp resharding
    leaves the math identical up to the dp-reduce tree's summation
    order, so parity is ulp-tight (the SUBPROCESS test below runs the
    default-precision environment where the dp8->dp4 curve is bitwise;
    this suite forces jax_default_matmul_precision=highest, which
    reorders the reduce)."""
    data = _batches(5, seed=3)
    ref = _trainer(4, seed=1)
    ref_losses = [float(ref.train_step(x, y)) for x, y in data]

    tr = _trainer(4, seed=1)
    for x, y in data[:3]:
        tr.train_step(x, y)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(tr)

    tr2 = _trainer(2, seed=8)
    mgr2 = CheckpointManager(str(tmp_path))
    assert mgr2.restore_latest(tr2) is not None
    assert tr2._step_count == 3
    info = tr2._last_restore_info
    assert info["resharded"] and info["saved_mesh_axes"] == {"dp": 4} \
        and info["mesh_axes"] == {"dp": 2}
    assert mgr2.stats["reshard_restores"] == 1
    assert tr2.stats["reshard_restores"] == 1
    res = [float(tr2.train_step(x, y)) for x, y in data[3:]]
    np.testing.assert_allclose(res, ref_losses[3:], rtol=1e-6)


def test_grow_restore_dp2_to_dp4(tmp_path):
    """Elastic GROW: the mesh got its chips back."""
    data = _batches(4, seed=5)
    ref = _trainer(4, seed=2)
    ref_losses = [float(ref.train_step(x, y)) for x, y in data]
    tr = _trainer(2, seed=2)
    for x, y in data[:2]:
        tr.train_step(x, y)
    p = str(tmp_path / "ck")
    tr.save(p, manifest=True)
    tr2 = _trainer(4, seed=6)
    tr2.load(p)
    assert tr2._last_restore_info["resharded"]
    res = [float(tr2.train_step(x, y)) for x, y in data[2:]]
    np.testing.assert_allclose(res, ref_losses[2:], rtol=1e-6)


def test_zero3_stage3_repartition_on_shrink(tmp_path):
    """ZeRO-3: params/optimizer state live dp-SHARDED; a shrink restore
    must repartition every shard set onto the new dp extent (the
    reduce/gather structure changes, so parity is rtol 1e-5, not
    bitwise)."""
    def build(dp):
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=m.parameters())
        st = DistributedStrategy()
        st.sharding = True
        st.sharding_configs = {"stage": 3}
        return SpmdTrainer(m, opt, lambda o, y: F.mse_loss(o, y),
                           mesh=_mesh(dp), strategy=st)

    data = _batches(5, seed=1, cols=8)
    ref = build(4)
    ref_losses = [float(ref.train_step(x, y)) for x, y in data]
    tr = build(4)
    for x, y in data[:3]:
        tr.train_step(x, y)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(tr)
    tr2 = build(2)
    mgr2 = CheckpointManager(str(tmp_path))
    mgr2.restore_latest(tr2)
    assert tr2._last_restore_info["resharded"]
    res = [float(tr2.train_step(x, y)) for x, y in data[3:]]
    np.testing.assert_allclose(res, ref_losses[3:], rtol=1e-5)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_pipeline_restore_pp4_to_pp2(tmp_path):
    """GPipeTrainer pp=4 -> pp=2: the stacked [L, ...] slabs re-split
    over the new pp extent (each rank's stage param group doubles),
    optimizer state riding along; parity rtol 1e-5."""
    from paddle_tpu.distributed.pipeline import GPipeTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    crit = GPTPretrainingCriterion()

    def build(pp):
        paddle.seed(5)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False,
                        tie_word_embeddings=False)
        model = GPTForCausalLM(cfg)
        pre, blocks, post = gpt_pipeline_parts(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        return GPipeTrainer(
            pre, blocks, post, opt, lambda o, l: crit(o, l),
            mesh=create_mesh({"pp": pp}, devices=jax.devices()[:pp]),
            num_microbatches=4)

    rng = np.random.RandomState(2)
    ids = [rng.randint(0, 64, (4, 16)).astype(np.int32)
           for _ in range(5)]
    labs = [np.roll(i, -1, 1).astype(np.int64) for i in ids]
    ref = build(4)
    ref_losses = [float(ref.train_step(i, l))
                  for i, l in zip(ids, labs)]
    tr = build(4)
    for i, l in zip(ids[:3], labs[:3]):
        tr.train_step(i, l)
    p = str(tmp_path / "ppck")
    tr.save(p, manifest=True)
    assert read_manifest(p)["mesh_axes"] == {"pp": 4}
    tr2 = build(2)
    tr2.load(p)
    assert tr2._last_restore_info["resharded"]
    assert tr2.stats["reshard_restores"] == 1
    res = [float(tr2.train_step(i, l))
           for i, l in zip(ids[3:], labs[3:])]
    np.testing.assert_allclose(res, ref_losses[3:], rtol=1e-5)


def test_tensor_parallel_reshard_tp_to_dp(tmp_path):
    """tp=2 -> dp=2: a tensor-parallel trainer's column/row-sharded
    params restore onto a pure-dp mesh (and the reverse path grows tp
    back) — the train-on-one-topology/serve-on-another direction."""
    from paddle_tpu.distributed import (ColumnParallelLinear,
                                        RowParallelLinear)

    def build(axes):
        paddle.seed(4)
        m = nn.Sequential(ColumnParallelLinear(8, 8),
                          nn.ReLU(),
                          RowParallelLinear(8, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=m.parameters())
        n = int(np.prod(list(axes.values())))
        return SpmdTrainer(m, opt, lambda o, y: F.mse_loss(o, y),
                           mesh=create_mesh(
                               axes, devices=jax.devices()[:n]))

    data = _batches(5, seed=7, cols=8)
    ref = build({"dp": 1, "tp": 2})
    ref_losses = [float(ref.train_step(x, y)) for x, y in data]
    tr = build({"dp": 1, "tp": 2})
    for x, y in data[:3]:
        tr.train_step(x, y)
    p = str(tmp_path / "tpck")
    tr.save(p, manifest=True)
    tr2 = build({"dp": 2, "tp": 1})
    tr2.load(p)
    assert tr2._last_restore_info["resharded"]
    res = [float(tr2.train_step(x, y)) for x, y in data[3:]]
    np.testing.assert_allclose(res, ref_losses[3:], rtol=1e-5)


def test_resume_elastic_false_rejects_cross_topology(tmp_path):
    tr = _trainer(4)
    tr.train_step(*_batches(1)[0])
    p = str(tmp_path / "ck")
    tr.save(p, manifest=True)
    strict = _trainer(2, resume_elastic=False)
    assert strict.stats["resume_elastic"] is False
    with pytest.raises(ValueError, match="resume_elastic"):
        strict.load(p)
    # same topology stays fine under strict mode
    strict4 = _trainer(4, seed=9, resume_elastic=False)
    strict4.load(p)
    assert strict4._step_count == 1


# ---------------------------------------------------------------------------
# restore-fallback ordering (satellite)
# ---------------------------------------------------------------------------
def test_restore_fallback_ordering_prefers_newest_loadable(tmp_path):
    """Newest ckpt corrupt, middle from a DIFFERENT topology, oldest
    same-topology: restore must land on the middle one (newest
    loadable) and reshard it — never fall through to the older
    same-topology candidate."""
    d = str(tmp_path)
    mgr = CheckpointManager(d, keep_last=5, async_save=False)
    data = _batches(3, seed=11)
    # oldest: written on dp=2 (the topology we restore on)
    t2 = _trainer(2, seed=1)
    t2.train_step(*data[0])
    mgr.save(t2, step=1)
    # middle: written on dp=4 — different topology
    t4 = _trainer(4, seed=1)
    for x, y in data[:2]:
        t4.train_step(x, y)
    mgr.save(t4, step=2)
    # newest: corrupt (truncated payload)
    t4.train_step(*data[2])
    mgr.save(t4, step=3)
    entry = os.path.join(d, "ckpt-3", "state.pdtrainer")
    with open(entry, "r+b") as f:
        f.truncate(16)

    live = _trainer(2, seed=5)
    mgr2 = CheckpointManager(d)
    assert mgr2.restore_latest(live) is not None
    assert live._step_count == 2          # the middle candidate
    assert mgr2.stats["fallbacks"] == 1
    assert mgr2.stats["reshard_restores"] == 1
    assert live._last_restore_info["saved_mesh_axes"] == {"dp": 4}
    # and its params match what the dp=4 writer committed at step 2
    step2 = read_checkpoint(os.path.join(d, "ckpt-2"))
    for n in live.params:
        np.testing.assert_array_equal(np.asarray(live.params[n]),
                                      step2["params"][n])


# ---------------------------------------------------------------------------
# new fault knobs
# ---------------------------------------------------------------------------
def test_mesh_shrink_fault_clamps_devices(monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_MESH_SHRINK", "4")
    m = create_mesh({"dp": -1})
    assert m.shape["dp"] == 4
    monkeypatch.delenv("PADDLE_FAULT_MESH_SHRINK")
    assert create_mesh({"dp": -1}).shape["dp"] == len(jax.devices())


def test_fs_delay_jitter(monkeypatch, tmp_path):
    from paddle_tpu.framework.fs import open_for_write
    monkeypatch.setenv("PADDLE_FAULT_FS_DELAY_MS", "open_write:120")
    t0 = time.perf_counter()
    with open_for_write(str(tmp_path / "slow.bin")) as f:
        f.write(b"x")
    assert time.perf_counter() - t0 >= 0.1
    # non-matching ops are not delayed
    monkeypatch.setenv("PADDLE_FAULT_FS_DELAY_MS", "put:5000")
    t0 = time.perf_counter()
    with open_for_write(str(tmp_path / "fast.bin")) as f:
        f.write(b"x")
    assert time.perf_counter() - t0 < 2.0


def test_ckpt_truncate_counter_arms_nth(monkeypatch):
    monkeypatch.setenv("PADDLE_FAULT_CKPT_TRUNCATE", "2")
    assert faults.ckpt_truncate_commit() is False   # 1st commit
    assert faults.ckpt_truncate_commit() is True    # 2nd: armed
    assert faults.ckpt_truncate_commit() is False   # 3rd


# ---------------------------------------------------------------------------
# CheckpointManager: commit-failure surfacing (satellite)
# ---------------------------------------------------------------------------
def test_manager_on_error_callback_and_counter(tmp_path, monkeypatch):
    import paddle_tpu.distributed.resilience as rmod
    tr = _trainer(1)
    tr.train_step(*_batches(1)[0])
    monkeypatch.setattr(rmod, "write_checkpoint",
                        lambda state, path: (_ for _ in ()).throw(
                            IOError("dead dir")))
    seen = []
    mgr = CheckpointManager(str(tmp_path), async_save=True,
                            on_error=seen.append)
    mgr.save(tr)
    mgr.wait()                       # routed to the callback, no raise
    assert len(seen) == 1 and "dead dir" in str(seen[0])
    assert mgr.stats["commit_failures"] == 1
    # without a callback the NEXT save() call re-raises
    mgr2 = CheckpointManager(str(tmp_path), async_save=True)
    mgr2.save(tr)
    with pytest.raises(IOError, match="dead dir"):
        mgr2.save(tr)
    assert mgr2.stats["commit_failures"] == 1


def test_manager_wait_timeout(tmp_path, monkeypatch):
    import threading

    import paddle_tpu.distributed.resilience as rmod
    tr = _trainer(1)
    tr.train_step(*_batches(1)[0])
    gate = threading.Event()
    real = rmod.write_checkpoint

    def gated(state, path):
        gate.wait(30)
        return real(state, path)

    monkeypatch.setattr(rmod, "write_checkpoint", gated)
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    p = mgr.save(tr)
    with pytest.raises(TimeoutError, match="still running"):
        mgr.wait(timeout=0.1)
    # every untimed join against the known-stuck commit refuses fast
    # instead of hanging forever — save() included (restore_latest and
    # latest() go through the same wait())
    with pytest.raises(TimeoutError, match="still stuck"):
        mgr.save(tr)
    with pytest.raises(TimeoutError, match="still stuck"):
        mgr.wait()
    gate.set()
    mgr.wait(timeout=30)       # storage recovered: a TIMED join clears
    assert validate_checkpoint(p)
    mgr.save(tr)                               # and saves work again
    mgr.wait()


# ---------------------------------------------------------------------------
# kill-and-resume onto a SHRUNK mesh (subprocess, end to end)
# ---------------------------------------------------------------------------
_ELASTIC_TRAIN = """
import sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (SpmdTrainer, create_mesh,
                                    CheckpointManager, PreemptionGuard)

ckdir, mode = sys.argv[1], sys.argv[2]
N = 6


def build():
    paddle.seed(7)
    m = nn.Linear(6, 3)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=m.parameters())
    return SpmdTrainer(m, opt, lambda o, y: F.mse_loss(o, y),
                       mesh=create_mesh({"dp": -1}))


rng = np.random.RandomState(0)
data = [(rng.randn(8, 6).astype(np.float32),
         rng.randn(8, 3).astype(np.float32)) for _ in range(N)]
tr = build()
print("DP", tr.dp_size, flush=True)
mgr = CheckpointManager(ckdir, keep_last=2)
mgr.restore_latest(tr)
start = tr._step_count
if mode == "resume_shrunk":
    assert start > 0, "resume did not find a checkpoint"
    assert tr._last_restore_info["resharded"], tr._last_restore_info
    assert mgr.stats["reshard_restores"] == 1
losses = []
with PreemptionGuard() as g:
    for i in range(start, N):
        losses.append(float(tr.train_step(*data[i])))
        if g.preempted:
            mgr.save(tr, block=True)
            print("PREEMPTED", tr._step_count, flush=True)
            sys.exit(0)
mgr.wait()
for l in losses:
    print("LOSS", repr(l), flush=True)
print("DONE", tr._step_count, flush=True)
"""


def _run_elastic_child(script, ckdir, mode, extra_env, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    for k in ("PADDLE_FAULT_SIGTERM_STEP", "PADDLE_FAULT_MESH_SHRINK",
              "PADDLE_FAULT_NAN_STEP", "PADDLE_FAULT_CKPT_TRUNCATE"):
        env.pop(k, None)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, str(script), ckdir, mode],
        env=env, capture_output=True, text=True, timeout=timeout)


def _losses_from(stdout):
    return [float(line.split(" ", 1)[1])
            for line in stdout.splitlines() if line.startswith("LOSS")]


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_subprocess_dp8_kill_resumes_on_dp4(tmp_path):
    """The acceptance run: a dp=8 trainer is SIGTERM-killed mid-run by
    the fault harness, drains + checkpoints, and a second process that
    WAKES UP WITH 4 DEVICES (PADDLE_FAULT_MESH_SHRINK) resumes from the
    same directory — the combined loss curve matches an uninterrupted
    dp=8 run to the last ulps (the dp-reduce tree is the only thing
    that changed; the state itself round-trips bitwise)."""
    script = tmp_path / "train.py"
    script.write_text(_ELASTIC_TRAIN)
    ckdir = str(tmp_path / "ck")

    p_ref = _run_elastic_child(script, str(tmp_path / "ref"), "ref", {})
    assert p_ref.returncode == 0, p_ref.stderr
    ref = _losses_from(p_ref.stdout)
    assert len(ref) == 6 and "DP 8" in p_ref.stdout

    p1 = _run_elastic_child(script, ckdir, "train",
                            {"PADDLE_FAULT_SIGTERM_STEP": "3"})
    assert p1.returncode == 0, p1.stderr
    assert "PREEMPTED 3" in p1.stdout
    ck = latest_checkpoint(ckdir)
    assert ck is not None and validate_checkpoint(ck)
    assert read_manifest(ck)["mesh_axes"] == {"dp": 8}

    p2 = _run_elastic_child(script, ckdir, "resume_shrunk",
                            {"PADDLE_FAULT_MESH_SHRINK": "4"})
    assert p2.returncode == 0, p2.stderr
    assert "DP 4" in p2.stdout and "DONE 6" in p2.stdout
    np.testing.assert_allclose(_losses_from(p2.stdout), ref[3:],
                               rtol=1e-6)


def test_subprocess_ckpt_truncate_falls_back(tmp_path):
    """PADDLE_FAULT_CKPT_TRUNCATE: the 2nd commit dies mid-write
    leaving a committed-LOOKING dir whose shard is cut; the resumed
    process must fall back to the older valid checkpoint and finish
    with the uninterrupted curve's tail."""
    script = tmp_path / "train.py"
    script.write_text(_ELASTIC_TRAIN)
    ckdir = str(tmp_path / "ck")

    p_ref = _run_elastic_child(script, str(tmp_path / "ref"), "ref", {})
    assert p_ref.returncode == 0, p_ref.stderr
    ref = _losses_from(p_ref.stdout)

    # run 1: checkpoint at step 2 (clean), die inside the step-4 commit
    p1 = _run_elastic_child(
        script, ckdir, "train",
        {"PADDLE_FAULT_SIGTERM_STEP": "2"})
    assert p1.returncode == 0 and "PREEMPTED 2" in p1.stdout, p1.stderr
    p2 = _run_elastic_child(
        script, ckdir, "train",
        {"PADDLE_FAULT_SIGTERM_STEP": "4",
         "PADDLE_FAULT_CKPT_TRUNCATE": "1"})
    assert p2.returncode == 137, (p2.returncode, p2.stderr)
    # the partial shard is at its FINAL name but fails validation...
    names = sorted(n for n in os.listdir(ckdir) if n.startswith("ckpt-")
                   and not n.endswith(".tmp"))
    assert "ckpt-4" in names
    assert not validate_checkpoint(os.path.join(ckdir, "ckpt-4"))
    # ...so resume lands on ckpt-2 and re-trains 3..6 to the same curve
    p3 = _run_elastic_child(script, ckdir, "train", {})
    assert p3.returncode == 0, p3.stderr
    assert "DONE 6" in p3.stdout
    assert _losses_from(p3.stdout) == ref[2:]


_ELASTIC_PIPE = """
import sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import create_mesh, CheckpointManager
from paddle_tpu.distributed.resilience import PreemptionGuard
from paddle_tpu.distributed.pipeline import GPipeTrainer
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)
from paddle_tpu.models.gpt import gpt_pipeline_parts
import jax

ckdir, mode = sys.argv[1], sys.argv[2]
N = 5
crit = GPTPretrainingCriterion()


def build():
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16,
                    use_flash_attention=False,
                    tie_word_embeddings=False)
    model = GPTForCausalLM(cfg)
    pre, blocks, post = gpt_pipeline_parts(model)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    # surviving device count (PADDLE_FAULT_MESH_SHRINK clamps it),
    # capped at 4: dp=2/pp=2 healthy, dp=1/pp=2 after the shrink to 2
    from paddle_tpu.testing import faults
    n = min(faults.mesh_shrink() or len(jax.devices()), 4)
    pp = 2
    dp = max(n // pp, 1)
    mesh = create_mesh({"dp": dp, "pp": pp},
                       devices=jax.devices()[:dp * pp])
    return GPipeTrainer(pre, blocks, post, opt,
                        lambda o, l: crit(o, l), mesh=mesh,
                        num_microbatches=4)


rng = np.random.RandomState(2)
# 8 rows / 4 microbatches -> microbatch of 2, divisible by dp in {1, 2}
ids = [rng.randint(0, 64, (8, 16)).astype(np.int32) for _ in range(N)]
labs = [np.roll(i, -1, 1).astype(np.int64) for i in ids]
tr = build()
print("MESH", dict(tr.mesh.shape), flush=True)
mgr = CheckpointManager(ckdir, keep_last=2)
mgr.restore_latest(tr)
start = tr._step_count
if mode == "resume_shrunk":
    assert start > 0, "no checkpoint found"
    assert tr._last_restore_info["resharded"], tr._last_restore_info
losses = []
with PreemptionGuard() as g:
    for i in range(start, N):
        losses.append(float(tr.train_step(ids[i], labs[i])))
        if g.preempted:
            mgr.save(tr, block=True)
            print("PREEMPTED", tr._step_count, flush=True)
            sys.exit(0)
mgr.wait()
for l in losses:
    print("LOSS", repr(l), flush=True)
print("DONE", tr._step_count, flush=True)
"""


@pytest.mark.slow
def test_subprocess_dp2pp2_kill_resumes_on_pp2(tmp_path):
    """The tp/pp acceptance leg: a dp=2/pp=2 pipeline run killed by the
    fault harness resumes on a 4-device mesh (dp=1/pp=2) with rtol-1e-5
    loss parity against the uninterrupted run."""
    script = tmp_path / "train.py"
    script.write_text(_ELASTIC_PIPE)
    ckdir = str(tmp_path / "ck")

    p_ref = _run_elastic_child(script, str(tmp_path / "ref"), "ref", {},
                               timeout=420)
    assert p_ref.returncode == 0, p_ref.stderr
    ref = _losses_from(p_ref.stdout)
    assert len(ref) == 5

    p1 = _run_elastic_child(script, ckdir, "train",
                            {"PADDLE_FAULT_SIGTERM_STEP": "3"},
                            timeout=420)
    assert p1.returncode == 0, p1.stderr
    assert "PREEMPTED 3" in p1.stdout

    p2 = _run_elastic_child(script, ckdir, "resume_shrunk",
                            {"PADDLE_FAULT_MESH_SHRINK": "2"},
                            timeout=420)
    assert p2.returncode == 0, p2.stderr
    assert "{'dp': 1, 'pp': 2}" in p2.stdout and "DONE 5" in p2.stdout
    np.testing.assert_allclose(_losses_from(p2.stdout), ref[3:],
                               rtol=1e-5)
