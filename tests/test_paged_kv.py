"""Paged KV cache tests: block pool + block tables + radix prefix cache.

Parity chain: tests/test_inference_engine.py proves the DENSE engine
reproduces the naive full-forward rollout exactly; this file proves the
PAGED engine reproduces the same rollout (so paged ≡ dense ≡ full
forward, including GQA and non-uniform lengths), that the paged decode
attention op is BITWISE the dense composite on identical cache
contents, and the allocator-policy claims of ISSUE 6: admission by free
blocks sustains strictly more concurrent requests than dense slots at
equal memory, pool exhaustion preempts-to-queue instead of
deadlocking, prefix-cache hits skip prefill work (prefill token count
measured), the block pool drains leak-free, and the whole thing stays
recompile-free after warmup (utils.compile_counter.assert_no_recompiles
— the PR 3/4 prove-it discipline).
"""
import importlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.inference import (BlockAllocator, InferenceEngine,
                                  RadixPrefixCache, blocks_for)
from paddle_tpu.utils import compile_counter

da = importlib.import_module("paddle_tpu.ops.decode_attention")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(**over):
    paddle.seed(0)
    cfg = GPTConfig(**{**TINY, **over})
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return tiny_model()


@pytest.fixture(scope="module")
def paged_eng(model):
    """Shared paged engine, all executables warmed up front so the
    tests after the first run compile-free."""
    eng = InferenceEngine(model, batch_slots=3, prefill_buckets=[8, 16],
                          kv_layout="paged", kv_block_size=8)
    eng.warmup(buckets=eng.buckets)
    return eng


def assert_greedy_rollout(model, prompt, gen):
    """Teacher-forcing oracle: ONE full forward over prompt+generated
    must reproduce every generated token by argmax at its position —
    exactly equivalent to a step-by-step naive greedy rollout (the
    dense engine's proven ground truth in test_inference_engine.py),
    but one compile per sequence length instead of one per token."""
    gen = np.asarray(gen).reshape(-1)
    seq = np.concatenate([np.asarray(prompt, np.int32).reshape(-1),
                          gen.astype(np.int32)])
    logits = model(paddle.to_tensor(seq[None])).numpy()[0]
    plen = len(seq) - len(gen)
    for i, t in enumerate(gen):
        want = int(np.argmax(logits[plen + i - 1]))
        assert int(t) == want, f"position {i}: got {t}, greedy {want}"


# ---- paged decode attention op ----------------------------------------

def _pool_from_dense(k_dense, tables, bs):
    """Scatter a dense [B, S, Hkv, D] cache into a pool laid out by
    `tables` (so a gather through the table reconstructs it exactly)."""
    b, s, hkv, d = k_dense.shape
    mb = s // bs
    nb = int(tables.max()) + 1
    pool = np.zeros((nb, bs, hkv, d), k_dense.dtype)
    for bi in range(b):
        for j in range(mb):
            pool[tables[bi, j]] = k_dense[bi, j * bs:(j + 1) * bs]
    return pool


def test_paged_composite_bitwise_matches_dense_composite():
    """Identical cache contents through the block table must give the
    BITWISE same output as the dense composite (same values, same
    reduction order) — the 'bitwise where dense is' acceptance leg."""
    rng = np.random.RandomState(0)
    b, s, h, hkv, d, bs = 3, 64, 4, 2, 16, 16
    q = jnp.asarray(rng.randn(b, h, d).astype(np.float32) * 0.3)
    k = rng.randn(b, s, hkv, d).astype(np.float32) * 0.3
    v = rng.randn(b, s, hkv, d).astype(np.float32) * 0.3
    # distinct shuffled blocks per slot, as a real allocator would hand out
    tables = (1 + rng.permutation(b * (s // bs))).reshape(b, s // bs) \
        .astype(np.int32)
    k_pool = _pool_from_dense(k, tables, bs)
    v_pool = _pool_from_dense(v, tables, bs)
    lengths = jnp.asarray([5, 33, 64], jnp.int32)
    dense = da._decode_composite(q, jnp.asarray(k), jnp.asarray(v),
                                 lengths)
    paged = da.paged_decode_attention(q, jnp.asarray(k_pool),
                                      jnp.asarray(v_pool),
                                      jnp.asarray(tables), lengths)
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))


@pytest.mark.parametrize("hkv", [4, 2, 1])
def test_paged_kernel_matches_composite(hkv):
    """Pallas paged kernel (interpret mode, scalar-prefetched block
    table) vs the gather composite, incl. GQA and length masking."""
    if not da._fa._HAS_PLTPU:
        pytest.skip("pallas TPU backend unavailable")
    da.set_interpret_mode(True)
    try:
        rng = np.random.RandomState(1)
        b, h, d, bs, mb, nb = 3, 4, 64, 128, 2, 8
        q = jnp.asarray(rng.randn(b, h, d).astype(np.float32) * 0.3)
        k_pool = jnp.asarray(
            rng.randn(nb, bs, hkv, d).astype(np.float32) * 0.3)
        v_pool = jnp.asarray(
            rng.randn(nb, bs, hkv, d).astype(np.float32) * 0.3)
        tables = jnp.asarray(
            (1 + rng.permutation(nb - 1))[:b * mb].reshape(b, mb)
            .astype(np.int32))
        lengths = jnp.asarray([1, 140, 256], jnp.int32)
        out = da.paged_decode_attention(q, k_pool, v_pool, tables,
                                        lengths)
        ref = da._paged_composite(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        da.set_interpret_mode(None)


# ---- host-side allocator + radix tree ---------------------------------

def test_block_allocator_invariants():
    al = BlockAllocator(9, 4)                      # 8 usable + null
    assert al.capacity == 8 and al.num_free == 8
    a = al.alloc(3)
    b = al.alloc(5)
    assert al.alloc(1) is None                     # refuses, not raises
    al.incref(a)
    al.decref(a)
    assert al.num_free == 0                        # still held once
    al.decref(a)
    al.decref(b)
    al.check_leak_free()
    with pytest.raises(RuntimeError, match="double free"):
        al.decref([a[0]])


def test_radix_match_insert_evict_pinning():
    al = BlockAllocator(9, 4)
    pc = RadixPrefixCache(al, block_size=4)
    toks = list(range(10, 22))                     # 3 full blocks
    blocks = al.alloc(3)
    assert pc.insert(toks, blocks) == 3            # tree pins all 3
    hit, n = pc.match(toks)
    assert hit == blocks[:2] and n == 8            # last block held back:
    # a full-prompt match must leave >= 1 token to prefill
    hit, n = pc.match(toks + [99])
    assert hit == blocks and n == 12               # now all 3 match
    miss, n = pc.match([7] * 12)
    assert miss == [] and n == 0
    # slot releases its copies; tree still holds one ref each
    al.decref(blocks)
    assert al.num_free == 8 - 3
    # pin the deepest block as a live slot would; evict frees only LRU
    # leaves nobody else references
    al.incref([blocks[2]])
    assert pc.evict(3) == 0                        # leaf pinned -> stuck
    al.decref([blocks[2]])
    assert pc.evict(3) == 3
    al.check_leak_free()
    assert pc.stats["prefix_hit_queries"] == 2


# ---- paged engine vs ground truth -------------------------------------

def test_paged_engine_matches_naive_mixed_lengths(model, paged_eng):
    """Mixed-length prompts through continuous batching: every paged
    request reproduces the full-forward greedy rollout (the dense
    engine's proven oracle), across block boundaries (max_new 12 > 8)."""
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 97, (n,)).astype(np.int32)
               for n in (3, 7, 12, 5)]
    rids = [paged_eng.add_request(p, max_new_tokens=12) for p in prompts]
    outs = paged_eng.run()
    for p, r in zip(prompts, rids):
        assert len(outs[r]) == 12
        assert_greedy_rollout(model, p, outs[r])
    paged_eng.flush_prefix_cache()
    paged_eng._alloc.check_leak_free()


def test_paged_engine_gqa_parity():
    """GQA leg of the parity acceptance criterion (num_kv_heads=2)."""
    m = tiny_model(num_kv_heads=2)
    eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[8],
                          kv_layout="paged", kv_block_size=8)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 97, (n,)).astype(np.int32) for n in (4, 7)]
    rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    outs = eng.run()
    for p, r in zip(prompts, rids):
        assert len(outs[r]) == 5
        assert_greedy_rollout(m, p, outs[r])
    eng.check_leak_free()


def test_paged_zero_recompiles_after_warmup(model, paged_eng):
    """THE zero-recompile acceptance leg: continuous admission AND
    retirement churn with mixed prompt lengths (both buckets, prefix
    hits and misses, block-boundary crossings) triggers 0 XLA compiles
    and 0 jaxpr traces after warmup."""
    rng = np.random.RandomState(4)
    shared = rng.randint(1, 97, (9,)).astype(np.int32)
    # flush one request through to touch any lazy host one-offs
    paged_eng.add_request(shared, max_new_tokens=2)
    paged_eng.run()
    with compile_counter.assert_no_recompiles("paged decode window"):
        rids = []
        for n in (3, 9, 14, 5, 11):
            rids.append(paged_eng.add_request(
                rng.randint(1, 97, (n,)).astype(np.int32),
                max_new_tokens=6))
        rids.append(paged_eng.add_request(shared, max_new_tokens=6))
        outs = paged_eng.run()
    assert all(len(outs[r]) == 6 for r in rids)
    st = paged_eng.stats
    assert st["prefix_hit_queries"] >= 1      # the repeated prompt hit


def test_prefix_hit_matches_cold_and_skips_prefill_work(model, paged_eng):
    """A prompt sharing a cached prefix must produce the cold prefill's
    exact tokens while PREFILLING FEWER TOKENS (the divergent suffix's
    bucket, not the whole prompt's) — measured by the prefill token
    counter."""
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 97, (13,)).astype(np.int32)   # 1 full block
    t0 = paged_eng._timings["prefill_tokens"]
    r1 = paged_eng.add_request(prompt, max_new_tokens=5)
    out1 = paged_eng.run()[r1]
    cold_tokens = paged_eng._timings["prefill_tokens"] - t0
    h0 = paged_eng._prefix.hit_queries
    t0 = paged_eng._timings["prefill_tokens"]
    r2 = paged_eng.add_request(prompt, max_new_tokens=5)
    out2 = paged_eng.run()[r2]
    hit_tokens = paged_eng._timings["prefill_tokens"] - t0
    assert paged_eng._prefix.hit_queries == h0 + 1
    assert out2.tolist() == out1.tolist()
    assert_greedy_rollout(model, prompt, out1)
    # cold: bucket_for(13)=16 prefilled; hit: suffix 13-8=5 -> bucket 8
    assert hit_tokens < cold_tokens, (hit_tokens, cold_tokens)


def test_more_concurrent_requests_than_dense_at_equal_memory(model):
    """The capacity acceptance criterion: at DENSE-EQUIVALENT memory for
    2 slots (2·64 positions = 16 blocks of 8), the paged engine holds
    strictly more than 2 short requests in flight at once."""
    dense_slots, bs = 2, 8
    equal_memory_blocks = dense_slots * blocks_for(TINY["max_seq_len"], bs)
    eng = InferenceEngine(model, batch_slots=6, prefill_buckets=[8],
                          kv_layout="paged", kv_block_size=bs,
                          kv_num_blocks=equal_memory_blocks,
                          prefix_cache=False)
    rng = np.random.RandomState(6)
    rids = [eng.add_request(rng.randint(1, 97, (4,)).astype(np.int32),
                            max_new_tokens=8) for _ in range(6)]
    eng.step()
    # all 6 admitted concurrently: each holds ceil(8/8)=1..2 blocks,
    # where the dense layout would cap out at 2 slots
    assert eng.num_active == 6 > dense_slots
    assert eng.blocks_in_use <= equal_memory_blocks
    outs = eng.run()
    assert all(len(outs[r]) == 8 for r in rids)
    eng.check_leak_free()


def test_pool_exhaustion_preempts_to_queue(model):
    """6-block pool, 3 requests that each grow to 3 blocks: the pool
    MUST run dry mid-decode; the scheduler preempts the youngest
    request back onto the queue (resume via re-prefill) instead of
    deadlocking, and every request still completes with the exact
    greedy rollout."""
    eng = InferenceEngine(model, batch_slots=3, prefill_buckets=[8, 32],
                          kv_layout="paged", kv_block_size=8,
                          kv_num_blocks=6, prefix_cache=False)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 97, (7,)).astype(np.int32)
               for _ in range(3)]
    rids = [eng.add_request(p, max_new_tokens=14) for p in prompts]
    outs = eng.run()
    assert eng.stats["preemptions"] > 0
    for p, r in zip(prompts, rids):
        assert len(outs[r]) == 14
        assert_greedy_rollout(model, p, outs[r])
    eng.check_leak_free()


def test_generate_blocks_on_full_engine(model, paged_eng):
    """The queue-not-raise satellite: generate() on a fully occupied
    engine waits its turn through the admission queue and returns the
    right tokens (in-flight requests keep decoding meanwhile)."""
    rng = np.random.RandomState(8)
    fillers = [paged_eng.add_request(
        rng.randint(1, 97, (5,)).astype(np.int32), max_new_tokens=10)
        for _ in range(3)]                    # all 3 slots busy
    for _ in range(2):
        paged_eng.step()
    assert paged_eng.num_active == 3
    prompt = rng.randint(1, 97, (6,)).astype(np.int32)
    out = paged_eng.generate(prompt, max_new_tokens=4)
    assert len(out) == 4
    assert_greedy_rollout(model, prompt, out)
    res = paged_eng.run()
    assert all(len(res[r]) == 10 for r in fillers)


def test_per_request_stats_recorded(paged_eng):
    """Satellite: TTFT and decode tokens/sec land PER REQUEST in
    engine.stats, plus the aggregates the load harness reports."""
    rid = paged_eng.add_request(np.asarray([5, 6, 7], np.int32),
                                max_new_tokens=4)
    paged_eng.run()
    st = paged_eng.stats
    rec = st["per_request"][rid]
    for key in ("ttft_ms", "queued_ms", "decode_tokens_per_sec",
                "tokens", "preemptions", "prompt_tokens"):
        assert key in rec, key
    assert rec["tokens"] == 4 and rec["ttft_ms"] >= 0
    assert st["ttft_ms_p50"] <= st["ttft_ms_p99"]
    for key in ("kv_layout", "kv_block_size", "kv_blocks_total",
                "block_occupancy", "prefix_hit_rate", "preemptions",
                "prefill_tokens"):
        assert key in st, key


def test_matched_prefix_blocks_survive_admission_eviction(model):
    """Review regression: a radix-matched prefix whose only reference
    is the tree's must be PINNED before admission allocates (allocation
    may evict refcount-1 leaves) — otherwise the matched blocks get
    freed and re-handed out as the same request's suffix blocks,
    aliasing the block table.  Near-dry pool + cached prefix + a
    pool-draining interloper reproduces it."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8, 16],
                          kv_layout="paged", kv_block_size=4,
                          kv_num_blocks=6)
    rng = np.random.RandomState(11)
    base = rng.randint(1, 97, (9,)).astype(np.int32)
    r0 = eng.add_request(base, max_new_tokens=2)     # caches 2 blocks
    out0 = eng.run()[r0]
    assert_greedy_rollout(model, base, out0)
    filler = eng.add_request(rng.randint(1, 97, (12,)).astype(np.int32),
                             max_new_tokens=2)       # drains free list
    hit_prompt = np.concatenate(
        [base[:8], rng.randint(1, 97, (3,)).astype(np.int32)])
    hit = eng.add_request(hit_prompt, max_new_tokens=4)
    outs = eng.run()
    assert filler in outs and hit in outs
    # exact rollout = the matched prefix KV was NOT clobbered by the
    # suffix prefill landing in re-handed-out aliased blocks
    assert_greedy_rollout(model, hit_prompt, outs[hit])
    eng.check_leak_free()


def test_prefix_hit_on_shrunk_pool_sheds_instead_of_stalling(model):
    """Review regression: on a pool SMALLER than a slot's max extent, a
    large prefix hit can make prefix+bucket demand more blocks than the
    pool holds; admission must shed prefix blocks down to what fits
    (the cold path is guaranteed to) rather than stall the queue head
    forever behind an unallocatable request."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[32],
                          kv_layout="paged", kv_block_size=8,
                          kv_num_blocks=6)
    rng = np.random.RandomState(12)
    base = rng.randint(1, 97, (30,)).astype(np.int32)
    r1 = eng.add_request(base, max_new_tokens=2)      # caches 3 blocks
    eng.run()
    # prefix hit 24 -> 24+bucket(32)=56 needs 7 blocks > 6 in the pool;
    # must shed to prefix 16 (16+32=48 -> 6 blocks) and still complete
    prompt2 = np.concatenate(
        [base[:24], rng.randint(1, 97, (6,)).astype(np.int32)])
    r2 = eng.add_request(prompt2, max_new_tokens=3)
    out2 = eng.run()[r2]
    assert_greedy_rollout(model, prompt2, out2)
    eng.check_leak_free()


def test_exhaustion_without_resumable_victim_degrades_not_dies(model):
    """Review regression: with a coarse bucket list, every active
    request can outgrow the largest bucket — no one is preemptable.
    Exhaustion must then retire the REQUESTER with the tokens it has
    (memory-capped finish) and keep serving, not kill the engine with
    a RuntimeError that loses every in-flight request."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[8],
                          kv_layout="paged", kv_block_size=8,
                          kv_num_blocks=4, prefix_cache=False)
    rng = np.random.RandomState(13)
    prompts = [rng.randint(1, 97, (4,)).astype(np.int32)
               for _ in range(2)]
    rids = [eng.add_request(p, max_new_tokens=20) for p in prompts]
    outs = eng.run()                                 # must not raise
    st = eng.stats
    assert st["memory_capped_retirements"] >= 1
    lens = sorted(len(outs[r]) for r in rids)
    assert lens[1] == 20                 # the survivor ran to the end
    assert 1 <= lens[0] < 20             # the capped one kept its work
    for p, r in zip(prompts, rids):      # partials are still exact
        assert_greedy_rollout(model, p, outs[r])
    eng.check_leak_free()


def test_prefix_clamped_when_padded_extent_overflows_table(model):
    """Coarse bucket sets can push prefix_len + bucket_for(suffix) past
    max_seq; admission must shed cached prefix blocks (recompute those
    tokens) rather than overflow the slot's block table — and still
    produce the exact greedy rollout."""
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[16, 64],
                          kv_layout="paged", kv_block_size=8)
    rng = np.random.RandomState(10)
    base = rng.randint(1, 97, (59,)).astype(np.int32)
    r1 = eng.add_request(base[:57], max_new_tokens=2)
    out1 = eng.run()[r1]
    # shares 48 cached tokens (full blocks of 56); raw suffix 11 ->
    # bucket 16 -> 56+16=72 > 64 would need 9 blocks in an 8-entry
    # table; the clamp sheds one shared block (prefix 48, 48+16=64)
    prompt2 = np.concatenate(
        [base[:56], rng.randint(1, 97, (3,)).astype(np.int32)])
    r2 = eng.add_request(prompt2, max_new_tokens=2)
    out2 = eng.run()[r2]
    assert eng._prefix.hit_queries >= 1
    assert_greedy_rollout(model, prompt2, out2)
    assert_greedy_rollout(model, base[:57], out1)
    eng.check_leak_free()


# ---- loadgen + bench wiring (the CI smoke satellite) -------------------

@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_bench_loadtest_smoke_contract():
    """`python bench.py --serve --loadtest --smoke` end to end: a few
    dozen Poisson arrivals with shared-prefix prompts, asserting inside
    the subprocess 0 recompiles after warmup, block pool leak-free at
    drain (free == total) and prefix hit rate > 0 — plus the ISSUE-12
    serving-FLEET smoke that rides along (2 replicas + prefix-aware
    router + spec decode): cache-aware routing must beat round-robin on
    prefix hit rate AND p99 TTFT in a paired skewed-tenant run, with
    accepted_tokens_per_tick > 1.5 and zero compiles fleet-wide."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "bench.py", "--serve",
                        "--loadtest", "--smoke"], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=560)
    assert p.returncode == 0, p.stderr[-3000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "loadtest_smoke" and out["ok"]
    assert out["xla_compiles_measured"] == 0
    assert out["kv_blocks_free_at_drain"] == out["kv_blocks_total"]
    assert out["prefix_hit_rate"] > 0
    assert out["ttft_ms_p99"] >= out["ttft_ms_p50"] > 0
    # the fleet columns (asserted inside the subprocess; re-checked
    # here so a silently-skipped fleet phase cannot pass)
    assert out["fleet_replicas"] == 2
    assert out["accepted_tokens_per_tick"] > 1.5
    assert out["fleet_prefix_hit_rate"] > out["fleet_rr_prefix_hit_rate"]
    assert out["fleet_ttft_ms_p99"] < out["fleet_rr_ttft_ms_p99"]


# ---- churn soak (slow) -------------------------------------------------

@pytest.mark.slow
def test_block_refcount_churn_soak(model, paged_eng):
    """Longer admission/retirement churn: waves of mixed-length,
    mixed-temperature requests with prefix sharing; after every wave the
    allocator's refcounts stay consistent, and at drain the pool is
    leak-free with zero recompiles across the whole soak."""
    rng = np.random.RandomState(9)
    shared = rng.randint(1, 97, (10,)).astype(np.int32)
    with compile_counter.assert_no_recompiles("paged churn soak"):
        for wave in range(6):
            rids = []
            for i in range(5):
                if rng.rand() < 0.4:
                    p = np.concatenate([shared, rng.randint(
                        1, 97, (rng.randint(1, 5),)).astype(np.int32)])
                else:
                    p = rng.randint(1, 97, (rng.randint(2, 15),)) \
                        .astype(np.int32)
                rids.append(paged_eng.add_request(
                    p, max_new_tokens=int(rng.randint(2, 10)),
                    temperature=0.8 if i % 2 else 0.0))
            outs = paged_eng.run()
            assert all(r in outs for r in rids)
            in_use = paged_eng._alloc.num_in_use
            cached = paged_eng._prefix.cached_blocks
            assert in_use == cached, (in_use, cached)
    assert paged_eng.stats["prefix_hit_rate"] > 0
    paged_eng.check_leak_free()
