"""Regression tests for round-2 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_two_optimizer_minimize_loops_both_fresh():
    """Medium: optimizer B's backward must not mask optimizer A's stale
    grads — each minimize() tracks freshness of its OWN params' grads."""
    paddle.seed(0)
    a = paddle.nn.Linear(4, 1)
    b = paddle.nn.Linear(4, 1)
    opt_a = paddle.optimizer.SGD(learning_rate=0.05, parameters=a.parameters())
    opt_b = paddle.optimizer.SGD(learning_rate=0.05, parameters=b.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                         .astype(np.float32))
    tgt = paddle.to_tensor(np.ones((8, 1), np.float32))

    losses_a = []
    for _ in range(6):
        # interleaved minimize-only loops: A then B each iteration
        la = ((a(x) - tgt) ** 2).mean()
        opt_a.minimize(la)
        opt_a.clear_grad()
        lb = ((b(x) - tgt) ** 2).mean()
        opt_b.minimize(lb)
        opt_b.clear_grad()
        losses_a.append(float(la.numpy()))
    # A must keep training (its grads must be recomputed each minimize,
    # not frozen at iteration 0 because B's backward advanced a counter)
    assert losses_a[-1] < losses_a[0] * 0.5, losses_a


def test_minimize_reuses_caller_backward_grads_once():
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = lin(x).sum()
    loss.backward()
    opt.minimize(loss)  # consumes the caller's grads, no second backward
    w1 = lin.weight.numpy().copy()
    # second minimize with no new backward: grads are stale now, so
    # minimize must run a fresh backward (graph freed -> rebuild loss)
    loss2 = lin(x).sum()
    opt.minimize(loss2)
    assert not np.allclose(lin.weight.numpy(), w1)


def test_gpt_prefill_with_empty_cache_is_causal():
    """Low: cache=(None, None) multi-token prefill must still be causal —
    output at position t must not depend on tokens after t."""
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt import GPTAttention

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16, dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    attn = GPTAttention(cfg)
    attn.eval()
    rng = np.random.RandomState(0)
    x = rng.randn(1, 8, 32).astype(np.float32)
    x2 = x.copy()
    x2[0, -1] += 1.0

    out1, _ = attn(paddle.to_tensor(x), cache=(None, None))
    out2, _ = attn(paddle.to_tensor(x2), cache=(None, None))
    # positions < 7 must be identical despite the last-position change
    np.testing.assert_allclose(out1.numpy()[:, :7], out2.numpy()[:, :7],
                               atol=1e-5)


def test_gpt_prefill_matches_no_cache_forward():
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt import GPTAttention

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16, dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    attn = GPTAttention(cfg)
    attn.eval()
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(2, 8, 32).astype(np.float32))
    out_plain = attn(x)
    out_prefill, kv = attn(x, cache=(None, None))
    np.testing.assert_allclose(out_plain.numpy(), out_prefill.numpy(),
                               atol=1e-5)
    # and the populated cache supports a correct decode step: the full
    # 9-token forward must agree with prefill(8) + decode(1)
    x9 = paddle.to_tensor(np.concatenate(
        [x.numpy(), np.random.RandomState(2).randn(2, 1, 32)
         .astype(np.float32)], axis=1))
    out_full = attn(x9)
    out_step, _ = attn(x9[:, 8:9], cache=kv)
    np.testing.assert_allclose(out_full.numpy()[:, 8:], out_step.numpy(),
                               atol=1e-5)


def test_apply_gradients_honors_per_param_lr():
    """Low: ParamAttr.learning_rate must scale the functional path too."""
    lin = paddle.nn.Linear(
        4, 2, weight_attr=paddle.nn.ParamAttr(learning_rate=0.0))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    params = {n: p.data for n, p in lin.named_parameters()}
    grads = {n: np.ones_like(p) for n, p in params.items()}
    state = opt.init_state(params)
    opt._param_name_map = {n: n for n in params}
    opt._param_obj_map = dict(lin.named_parameters())
    new_params, _ = opt.apply_gradients(params, grads, state)
    # weight lr multiplier 0.0 -> frozen; bias moves
    np.testing.assert_allclose(np.asarray(new_params["weight"]),
                               np.asarray(params["weight"]))
    assert np.abs(np.asarray(new_params["bias"])
                  - np.asarray(params["bias"])).max() > 1e-4


def test_layer_names_counted_per_class():
    from paddle_tpu.nn import layer_base

    layer_base._layer_name_counters.clear()
    l0 = paddle.nn.Linear(2, 2)
    n0 = paddle.nn.LayerNorm(2)
    l1 = paddle.nn.Linear(2, 2)
    assert l0.full_name() == "linear_0"
    assert n0.full_name() == "layernorm_0"
    assert l1.full_name() == "linear_1"


def test_pipeline_strategy_error_names_real_class():
    from paddle_tpu.distributed import SpmdTrainer
    from paddle_tpu.distributed.fleet import DistributedStrategy

    lin = paddle.nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    st = DistributedStrategy()
    st.pipeline = True
    with pytest.raises(NotImplementedError, match="GPipeTrainer"):
        SpmdTrainer(lin, opt, lambda o, l: o.sum(), strategy=st)
