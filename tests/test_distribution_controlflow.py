"""paddle.distribution + control-flow surface + double grad tests.

Reference: python/paddle/distribution.py, operators/controlflow/ via
fluid/layers/control_flow.py, partial_grad_engine.cc:1064 (double grad).
"""
import math

import numpy as np
import pytest
from scipy import stats as sps

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu.static import case, cond, switch_case, while_loop


# ---------------------------------------------------------------------------
# distribution
# ---------------------------------------------------------------------------
def test_normal_moments_and_logprob():
    paddle.seed(0)
    d = D.Normal(1.5, 2.0)
    s = np.asarray(d.sample((20000,)).data)
    assert abs(s.mean() - 1.5) < 0.1 and abs(s.std() - 2.0) < 0.1
    v = np.array([0.0, 1.5, 4.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(d.log_prob(paddle.to_tensor(v)).data),
        sps.norm(1.5, 2.0).logpdf(v), rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy().data),
                               sps.norm(1.5, 2.0).entropy(), rtol=1e-6)


def test_uniform_sample_and_entropy():
    paddle.seed(1)
    d = D.Uniform(-1.0, 3.0)
    s = np.asarray(d.sample((10000,)).data)
    assert s.min() >= -1.0 and s.max() < 3.0
    np.testing.assert_allclose(float(d.entropy().data), math.log(4.0),
                               rtol=1e-6)
    lp = np.asarray(d.log_prob(paddle.to_tensor(
        np.array([0.0, 5.0], np.float32))).data)
    np.testing.assert_allclose(lp[0], -math.log(4.0), rtol=1e-6)
    assert lp[1] == -np.inf


def test_categorical_and_kl():
    paddle.seed(2)
    logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
    d = D.Categorical(logits)
    s = np.asarray(d.sample((20000,)).data)
    freq = np.bincount(s, minlength=3) / len(s)
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
    np.testing.assert_allclose(
        float(d.entropy().data),
        -(0.2 * math.log(0.2) + 0.3 * math.log(0.3) + 0.5 * math.log(0.5)),
        rtol=1e-5)
    d2 = D.Categorical(np.zeros(3, np.float32))
    kl = float(D.kl_divergence(d, d2).data)
    expect = sum(p * math.log(p / (1 / 3)) for p in [0.2, 0.3, 0.5])
    np.testing.assert_allclose(kl, expect, rtol=1e-5)


def test_normal_kl_matches_closed_form():
    a, b = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
    kl = float(D.kl_divergence(a, b).data)
    expect = math.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, expect, rtol=1e-6)


# ---------------------------------------------------------------------------
# control flow
# ---------------------------------------------------------------------------
def test_cond_eager_and_traced():
    t = paddle.to_tensor(np.float32(3.0))
    out = cond(t > 0, lambda: t * 2, lambda: t - 1)
    assert float(out.data) == 6.0

    def f(x):
        return cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)

    from paddle_tpu.func import functional_forward
    import jax
    g = jax.jit(lambda a: (f(paddle.to_tensor(a)).data))
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([1.0, 2.0]))),
                               [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([-1.0, -2.0]))),
                               [-2.0, -3.0])


def test_while_loop_eager_and_traced():
    i = paddle.to_tensor(np.int32(0))
    s = paddle.to_tensor(np.float32(0.0))
    i2, s2 = while_loop(lambda i, s: i < 5,
                        lambda i, s: (i + 1, s + 2.0), [i, s])
    assert int(i2.data) == 5 and float(s2.data) == 10.0

    def traced(n):
        i0 = paddle.to_tensor(jnp.asarray(0, jnp.int32))
        a0 = paddle.to_tensor(n)
        _, out = while_loop(lambda i, a: i < 4,
                            lambda i, a: (i + 1, a * 2), [i0, a0])
        return out.data

    got = jax.jit(traced)(jnp.asarray(3.0))
    assert float(got) == 48.0


def test_case_and_switch_case():
    x = paddle.to_tensor(np.float32(2.0))
    out = case([(x > 3, lambda: x * 10), (x > 1, lambda: x * 100)],
               default=lambda: x)
    assert float(out.data) == 200.0

    out2 = switch_case(paddle.to_tensor(np.int32(1)),
                       {0: lambda: x + 1, 1: lambda: x + 2,
                        2: lambda: x + 3})
    assert float(out2.data) == 4.0

    def traced(ix):
        return switch_case(paddle.to_tensor(ix),
                           {0: lambda: x + 1, 5: lambda: x + 2},
                           default=lambda: x * 0).data

    g = jax.jit(traced)
    assert float(g(jnp.asarray(5, jnp.int32))) == 4.0
    assert float(g(jnp.asarray(7, jnp.int32))) == 0.0  # default


# ---------------------------------------------------------------------------
# double grad (VERDICT 'double grad partial' row)
# ---------------------------------------------------------------------------
def test_double_grad_scalar():
    from paddle_tpu.core.autograd import grad
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x ** 3).sum()
    (g1,) = grad(y, x, create_graph=True)
    np.testing.assert_allclose(np.asarray(g1.data), [12.0, 27.0],
                               rtol=1e-6)
    (g2,) = grad(g1.sum(), x)
    np.testing.assert_allclose(np.asarray(g2.data), [12.0, 18.0],
                               rtol=1e-6)


def test_gradient_penalty_backward():
    """WGAN-GP pattern: penalty on |df/dx| trains f's parameters."""
    from paddle_tpu.core.autograd import grad
    w = paddle.to_tensor(np.array([1.5], np.float32),
                         stop_gradient=False)
    x = paddle.to_tensor(np.array([2.0], np.float32),
                         stop_gradient=False)
    y = (w * x * x).sum()
    (gx,) = grad(y, x, create_graph=True)      # 2wx
    penalty = (gx * gx).sum()                  # 4 w^2 x^2
    penalty.backward()
    np.testing.assert_allclose(np.asarray(w.grad.data), [48.0],
                               rtol=1e-5)     # 8 w x^2


def test_double_grad_through_layer():
    import paddle_tpu.nn as nn
    from paddle_tpu.core.autograd import grad
    paddle.seed(0)
    lin = nn.Linear(3, 1)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 3).astype(np.float32),
                         stop_gradient=False)
    y = paddle.tanh(lin(x)).sum()
    (gx,) = grad(y, x, create_graph=True)
    # second derivative exists and is nonzero (tanh'' != 0)
    (ggx,) = grad((gx ** 2).sum(), x)
    assert np.any(np.asarray(ggx.data) != 0)


def test_first_order_grad_unchanged():
    from paddle_tpu.core.autograd import grad
    x = paddle.to_tensor(np.array([4.0], np.float32),
                         stop_gradient=False)
    (g,) = grad((x ** 2).sum(), x)
    assert g.stop_gradient
    np.testing.assert_allclose(np.asarray(g.data), [8.0])


def test_switch_case_default_none_matches_reference():
    """Review regression: default=None means LAST branch, identically
    in eager and traced modes."""
    x = paddle.to_tensor(np.float32(1.0))
    out = switch_case(paddle.to_tensor(np.int32(7)),
                      {1: lambda: x + 1, 2: lambda: x + 2})
    assert float(out.data) == 3.0  # falls to last branch eagerly too


def test_unique_name_guard_prefix():
    import paddle_tpu.nn as nn
    from paddle_tpu.utils import unique_name
    with unique_name.guard("ns1_"):
        a = nn.Linear(2, 2)
        g1 = unique_name.generate("fc")
    with unique_name.guard("ns2_"):
        b = nn.Linear(2, 2)
        g2 = unique_name.generate("fc")
    assert a.full_name() != b.full_name()
    assert a.full_name().startswith("ns1_")
    assert g1 == "ns1_fc_0" and g2 == "ns2_fc_0"


class _ExpLayer:
    pass


def test_pylayer_custom_backward():
    """paddle.autograd.PyLayer: custom forward/backward pair on the
    eager tape (reference python/paddle/autograd PyLayer)."""
    import numpy as np
    from paddle_tpu.autograd import PyLayer

    calls = []

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x, scale):
            ctx.save_for_backward(x)
            return x * scale

        @staticmethod
        def backward(ctx, dy):
            calls.append(1)
            (x,) = ctx.saved_tensor()
            return dy * 3.0  # deliberately NOT the true grad (2.0)

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = Double.apply(x, 2.0)
    np.testing.assert_allclose(np.asarray(y.data), [2.0, 4.0])
    y.sum().backward()
    assert calls  # the custom backward ran
    np.testing.assert_allclose(np.asarray(x.grad.data), [3.0, 3.0])


def test_pylayer_multi_output_and_none_grad():
    import numpy as np
    from paddle_tpu.autograd import PyLayer

    class SplitScale(PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return a * 2.0, b * 5.0

        @staticmethod
        def backward(ctx, da, db):
            return da * 2.0, None  # b: no gradient

    a = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    o1, o2 = SplitScale.apply(a, b)
    (o1.sum() + o2.sum()).backward()
    np.testing.assert_allclose(np.asarray(a.grad.data), 2.0)
    assert b.grad is None  # None grad skipped cleanly
