"""LocalSGD + DGC meta-optimizers and the bucketed DDP reducer.

Reference: fleet/meta_optimizers/localsgd_optimizer.py:440 (periodic
parameter averaging), dgc_optimizer.py + fluid DGCMomentumOptimizer +
operators/dgc_op.h (top-k compression with momentum correction),
imperative/reducer.h:48 (bucket fusion). Single-process numeric tests
here; the REAL 2-process run is test_meta_opts_two_process below
(test_dist_base.py:668 localhost-subprocess style).
"""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DGCMomentum, DistributedStrategy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dgc_sparsity_schedule():
    opt = DGCMomentum(parameters=[], rampup_begin_step=2, rampup_step=4,
                      sparsity=[0.75, 0.9375, 0.984, 0.999])
    assert opt.current_sparsity(0) == 0.0      # before rampup
    assert opt.current_sparsity(2) == 0.75
    assert opt.current_sparsity(3) == 0.9375
    assert opt.current_sparsity(5) == 0.999
    assert opt.current_sparsity(50) == 0.999   # holds after rampup


def test_dgc_single_process_matches_numpy_replica():
    """world=1: the DGC update (momentum correction + top-k residuals)
    must match a hand-rolled numpy implementation bit-for-bit in
    structure (which entries move, which accumulate)."""
    lr, m, sp = 0.1, 0.9, 0.5
    paddle.seed(3)
    model = nn.Linear(4, 4, bias_attr=False)  # 16 elements
    opt = DGCMomentum(learning_rate=lr, momentum=m,
                      parameters=model.parameters(),
                      sparsity=[sp], min_dgc_size=1)
    w = np.asarray(model.weight.data, np.float64).copy()
    u = np.zeros_like(w)
    v = np.zeros_like(w)
    rng = np.random.RandomState(0)
    for _ in range(4):
        x = rng.randn(8, 4).astype(np.float32)
        tgt = rng.randn(8, 4).astype(np.float32)
        xt = paddle.to_tensor(x)
        loss = ((model(xt) - paddle.to_tensor(tgt)) ** 2).mean()
        loss.backward()
        g = np.asarray(model.weight.grad.data, np.float64)
        opt.step()
        opt.clear_grad()
        # numpy replica
        u = m * u + g
        v = v + u
        flat = v.reshape(-1)
        k = max(1, int(round(flat.size * (1 - sp))))
        idx = np.argsort(-np.abs(flat))[:k]
        g_sync = np.zeros_like(flat)
        g_sync[idx] = flat[idx]
        flat[idx] = 0.0
        u.reshape(-1)[idx] = 0.0
        v = flat.reshape(v.shape)
        w = w - lr * g_sync.reshape(w.shape)
        np.testing.assert_allclose(np.asarray(model.weight.data), w,
                                   rtol=1e-5, atol=1e-6)


def test_dgc_small_params_take_dense_path():
    opt = DGCMomentum(parameters=[], min_dgc_size=10_000)

    class P:
        shape = (8, 8)
    assert not opt._use_dgc(P(), step=5)

    class Q:
        shape = (200, 200)
    assert opt._use_dgc(Q(), step=5)
    assert not opt._use_dgc(Q(), step=0) or opt.rampup_begin_step == 0


def test_localsgd_world1_is_plain_training():
    """At world 1 the periodic average is the identity — LocalSGD must
    equal vanilla SGD."""
    def train(with_localsgd):
        paddle.seed(0)
        model = nn.Linear(4, 2, bias_attr=False)
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        if with_localsgd:
            st = DistributedStrategy()
            st.localsgd = True
            st.localsgd_configs = {"k_steps": 2, "begin_step": 1}
            opt = fleet.distributed_optimizer(sgd, st)
        else:
            opt = sgd
        rng = np.random.RandomState(0)
        for _ in range(4):
            x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            loss = model(x).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(model.weight.data)

    np.testing.assert_array_equal(train(True), train(False))


def test_dgc_strategy_swaps_momentum():
    paddle.seed(0)
    model = nn.Linear(4, 2)
    st = DistributedStrategy()
    st.dgc = True
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=model.parameters()), st)
    assert isinstance(opt.inner_opt, DGCMomentum)
    # non-Momentum inner optimizer: loud failure, reference constraint
    with pytest.raises(NotImplementedError):
        fleet.distributed_optimizer(
            paddle.optimizer.Adam(parameters=model.parameters()), st)


def _simulate_localsgd_two_ranks():
    """Replicate the 2-rank LocalSGD payload on one process."""
    ws = []
    for rank in range(2):
        paddle.seed(0)
        model = nn.Linear(4, 2, bias_attr=False)
        ws.append({"model": model,
                   "opt": paddle.optimizer.SGD(
                       learning_rate=0.1,
                       parameters=model.parameters()),
                   "rng": np.random.RandomState(100 + rank)})
    for step in range(1, 6):
        for wkr in ws:
            x = paddle.to_tensor(
                wkr["rng"].randn(8, 4).astype(np.float32))
            loss = wkr["model"](x).sum()
            loss.backward()
            wkr["opt"].step()
            wkr["opt"].clear_grad()
        if step >= 1 and (step - 1) % 2 == 0:
            avg = (np.asarray(ws[0]["model"].weight.data) +
                   np.asarray(ws[1]["model"].weight.data)) / 2
            for wkr in ws:
                wkr["model"].weight._data = paddle.to_tensor(avg).data
    return float(np.abs(np.asarray(ws[0]["model"].weight.data)).sum())


@pytest.mark.slow
def test_meta_opts_two_process(tmp_path):
    """REAL 2-process localhost run of LocalSGD, DGC, and the bucketed
    reducer (launch + coordinator rendezvous)."""
    log_dir = str(tmp_path / "logs")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir,
         os.path.join(REPO, "tests", "dist_payload_meta_opts.py")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    logs = ""
    for rank in range(2):
        p = os.path.join(log_dir, f"workerlog.{rank}")
        if os.path.exists(p):
            logs += open(p).read()
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout={proc.stdout}\n" \
        f"stderr={proc.stderr}\nlogs={logs}"

    # LocalSGD: ranks end in sync (last step is a sync step) and match
    # the single-process simulation of the same schedule
    ls = {int(m.group(1)): float(m.group(2)) for m in
          re.finditer(r"LOCALSGD (\d) (-?\d+\.\d+)", logs)}
    assert set(ls) == {0, 1}, logs
    assert ls[0] == pytest.approx(ls[1], abs=1e-4)
    assert ls[0] == pytest.approx(_simulate_localsgd_two_ranks(),
                                  rel=1e-4)

    # DGC: the gathered top-k union is identical on both ranks, so the
    # params must agree exactly, and training must have reduced the loss
    dgc = {int(m.group(1)): tuple(map(float, m.group(2, 3, 4))) for m in
           re.finditer(r"DGC (\d) (-?\d+\.\d+) (\d+\.\d+) (\d+\.\d+)",
                       logs)}
    assert set(dgc) == {0, 1}, logs
    assert dgc[0][0] == pytest.approx(dgc[1][0], abs=1e-4)
    # descent on the SUMMED objective: the cross-rank average loss drops
    avg_first = (dgc[0][1] + dgc[1][1]) / 2
    avg_last = (dgc[0][2] + dgc[1][2]) / 2
    assert avg_last < avg_first, f"avg loss did not decrease: {dgc}"

    # bucketed DDP: both ranks see identical (summed) dense + sparse
    ddp = {int(m.group(1)): (float(m.group(2)), float(m.group(3)))
           for m in re.finditer(r"DDP (\d) (-?\d+\.\d+) (-?\d+\.\d+)",
                                logs)}
    assert set(ddp) == {0, 1}, logs
    assert ddp[0][0] == pytest.approx(ddp[1][0], abs=1e-3)
    assert ddp[0][1] == pytest.approx(ddp[1][1], abs=1e-3)
