"""Expert-parallel MoE serving (ISSUE 19).

Every test runs on the suite's virtual 8-device CPU mesh.  The
contracts:

- ep=2 serving is TOKEN-IDENTICAL to the replicated (ep=1) engine —
  the capacity-bucketed a2a dispatch reorders WHERE each token's
  expert FFN runs, never its math (greedy) — with ZERO XLA compiles
  after warmup, because the dispatch is ONE fixed-shape chunked
  all_to_all whose token dim is padded to capacity.
- expert FFN weights shard over 'ep': per-device expert bytes drop
  ~ep×, the exec registry records the ep degree per executable, and
  the comm_stats fold attributes the dispatch/combine a2a to the 'ep'
  axis.
- capacity overflow is ACCOUNTED, not hidden: dropped = assigned −
  kept at every layer, identical between ep=1 and ep=2, and the
  'expert-imbalance' doctor rule turns the stats into a knob.

Tier-1 covers the corners (dense fp full observability, paged int8
churn, tp×ep, disjoint disagg groups); the exhaustive layout × dtype
× spec matrix rides the slow lane.
"""
import os

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import create_mesh
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import compile_counter

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs a multi-device (CPU) mesh")

MOE = dict(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
           max_seq_len=64, use_flash_attention=False,
           moe_num_experts=4, moe_top_k=2)


def moe_model(seed=0, **over):
    paddle.seed(seed)
    m = GPTForCausalLM(GPTConfig(**{**MOE, **over}))
    m.eval()
    return m


def _ep_mesh(ep, tp=1):
    if ep == 1 and tp == 1:
        return None
    axes = {"dp": 1, "tp": tp}
    if ep > 1:
        axes["ep"] = ep
    return create_mesh(axes)


def _mk(model, ep, tp=1, **kw):
    return InferenceEngine(model, batch_slots=2, prefill_buckets=[16],
                           mesh=_ep_mesh(ep, tp), **kw)


def _run(eng, prompts, gen=5):
    rids = [eng.add_request(p, max_new_tokens=gen) for p in prompts]
    out = eng.run()
    return [list(map(int, out[r])) for r in rids]


def _prompts(seed=0, lens=(5, 9)):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 96, (n,)).astype(np.int32) for n in lens]


@pytest.fixture(scope="module")
def model():
    return moe_model(0)


def test_ep_dense_parity_and_observability(model):
    """The dense leg carries the full contract in one pair of engines:
    ep=2 tokens ≡ ep=1, ZERO compiles after warmup, identical expert
    LOAD histograms (the dispatch moves work, not assignments),
    per-device expert bytes halved, registry entries name ep and the
    submesh, and the analysis folds ep-attributed a2a collectives."""
    from paddle_tpu.observability import exec_registry

    prompts = _prompts(0)
    base_eng = _mk(model, 1)
    base = _run(base_eng, prompts)
    eng = _mk(model, 2)
    eng.warmup(buckets=[16])
    with compile_counter.assert_no_recompiles("dense ep=2 post-warmup"):
        toks = _run(eng, prompts)
    assert toks == base

    s1, s2 = base_eng.stats, eng.stats
    assert s2["ep"] == 2 and s2["tp"] == 1
    assert s2["serving_mesh"] == {"dp": 1, "tp": 1, "ep": 2}
    assert s2["moe_num_experts"] == 4
    # routing is replicated: same per-expert assignment counts no
    # matter where the expert FFNs physically ran
    assert s2["moe_expert_load"] == s1["moe_expert_load"]
    assert s2["moe_dropped_rate"] == s1["moe_dropped_rate"]
    # the point of ep: each device holds 1/ep of the expert weights
    b1 = base_eng._moe_expert_bytes_per_device()
    b2 = eng._moe_expert_bytes_per_device()
    assert b2 * 2 == b1
    assert s2["decode_hbm_bytes_per_tok"] < s1["decode_hbm_bytes_per_tok"]

    reg = exec_registry.registry()
    reg.analyze_all(eng._exec_component)
    rows = [r for r in reg.snapshot(eng._exec_component)["executables"]
            if (r.get("meta") or {}).get("submesh")]
    assert rows, "no submesh-tagged entries for the ep engine"
    for r in rows:
        assert r["meta"]["ep"] == 2
        assert r["meta"]["submesh"]["shape"].get("ep") == 2
    decode_rows = [r for r in rows
                   if r["kind"] == "decode" and r["analyzed"]]
    assert decode_rows
    for r in decode_rows:
        coll = r.get("collectives")
        assert coll and coll["count"] > 0
        # the expert dispatch/combine must actually COMMUNICATE,
        # attributed to 'ep' by the comm_stats axis fold
        assert coll.get("by_axis", {}).get("ep", {}).get("count", 0) > 0


def test_ep_paged_int8_churn_recompile_free(model):
    """The paged leg doubles as the int8-KV (satellite: kv_dtype is
    ORTHOGONAL to MoE — only quantized COMPUTE is gated) and
    slot-churn corner: more requests than slots through a warmed ep=2
    paged int8 engine — tokens ≡ ep=1, ZERO new compiles, pool
    leak-free at drain."""
    kw = dict(kv_layout="paged", kv_block_size=8, kv_dtype="int8")
    churn = _prompts(1, lens=(4, 7, 11, 6))
    base = _run(_mk(model, 1, **kw), churn)
    eng = _mk(model, 2, **kw)
    eng.warmup(buckets=[16])
    with compile_counter.assert_no_recompiles("paged int8 ep churn"):
        toks = _run(eng, churn)
    assert toks == base
    eng.check_leak_free()


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_tp_ep_composition(model):
    """tp=2 × ep=2 on one mesh: attention/dense FFN shard over 'tp',
    expert FFNs over 'ep', and the tokens still match the unsharded
    engine."""
    prompts = _prompts(2)
    base = _run(_mk(model, 1), prompts)
    eng = _mk(model, 2, tp=2)
    toks = _run(eng, prompts)
    assert toks == base
    s = eng.stats
    assert s["tp"] == 2 and s["ep"] == 2
    assert s["serving_mesh"] == {"dp": 1, "tp": 2, "ep": 2}


@pytest.mark.slow
def test_serve_ep_env(model, monkeypatch):
    """PADDLE_TPU_SERVE_EP=2 builds the {'dp','tp','ep'} mesh without
    an explicit mesh argument — one env knob for the whole fleet."""
    monkeypatch.setenv("PADDLE_TPU_SERVE_EP", "2")
    eng = InferenceEngine(model, batch_slots=2, prefill_buckets=[16])
    prompts = _prompts(3, lens=(5,))
    toks = _run(eng, prompts, gen=4)
    monkeypatch.delenv("PADDLE_TPU_SERVE_EP")
    base = _run(_mk(model, 1), prompts, gen=4)
    assert toks == base
    assert eng.stats["ep"] == 2


def test_capacity_overflow_accounting(model):
    """Dropped tokens are exact accounting, not an estimate.  Unit
    half: a host reference over a hand-routed gating — every token
    beyond an expert's capacity loses its dispatch slot.  Engine half:
    a starved capacity factor drops tokens, and ep=2 reports the SAME
    drop ledger as ep=1 (the a2a dispatch pads to capacity; it never
    drops on its own)."""
    from paddle_tpu.distributed.moe import moe_capacity, top_k_gating

    # -- unit: all tokens prefer expert 0, capacity keeps only `cap`
    s, e, k = 8, 4, 1
    logits = np.zeros((1, s, e), np.float32)
    logits[..., 0] = 5.0                       # expert 0 wins every token
    cap = moe_capacity(s, e, k, capacity_factor=0.5)   # = 1
    dispatch, combine, _, _ = top_k_gating(
        jax.numpy.asarray(logits), k, cap)
    load = np.asarray(jax.numpy.sum(dispatch, axis=(0, 1, 3)))
    assert load.tolist() == [float(cap)] + [0.0] * (e - 1)
    assert float(np.asarray(combine).sum()) > 0

    # -- engine: starved capacity → drops, identical across ep
    starved = moe_model(4, moe_capacity_factor=0.25)
    prompts = _prompts(4, lens=(9, 6))
    e1 = _mk(starved, 1)
    t1 = _run(e1, prompts, gen=4)
    e2 = _mk(starved, 2)
    t2 = _run(e2, prompts, gen=4)
    assert t2 == t1
    s1, s2 = e1.stats, e2.stats
    assert s1["moe_dropped_rate"] > 0
    assert s2["moe_dropped_rate"] == s1["moe_dropped_rate"]
    assert s2["moe_expert_load"] == s1["moe_expert_load"]


def test_quantize_moe_guard():
    """Satellite: quantized COMPUTE with MoE raises (the expert
    einsums have no quantized path), but int8 KV CACHE is orthogonal —
    the config must accept it (the churn test above runs it)."""
    with pytest.raises(NotImplementedError,
                       match="quantize='int8' COMPUTE"):
        GPTConfig(**MOE, quantize="int8")
    GPTConfig(**MOE)                         # no quantize: fine


def test_a2a_chunks_divisor_error():
    """Satellite: an explicit a2a_chunks that doesn't divide the
    capacity slice names the NEAREST VALID divisors instead of a bare
    refusal — the knob is meant for A/B sweeps, and a sweep script
    needs the legal neighbours."""
    from paddle_tpu.distributed.moe import (MoELayer,
                                            nearest_chunk_divisors)

    assert nearest_chunk_divisors(12, 5) == (4, 6)
    assert nearest_chunk_divisors(12, 1) == (1, 1)
    assert nearest_chunk_divisors(12, 100) == (12, 12)

    layer = MoELayer(hidden_size=8, ffn_size=16, num_experts=4,
                     a2a_chunks=5)
    with pytest.raises(ValueError) as ei:
        layer._serve_chunks(12)
    msg = str(ei.value)
    assert "4 (below)" in msg and "6 (above)" in msg
    # None auto-clamps down to a divisor instead of raising
    layer.a2a_chunks = None
    assert 12 % layer._serve_chunks(12) == 0


def test_doctor_expert_imbalance():
    """The 'expert-imbalance' rule: silent on balanced traffic, fires
    on capacity overflow (→ raise moe_capacity_factor), fires on pure
    skew under spec decode (→ lower spec_k first: a rejected draft
    burst is the usual skew source), and stays silent below the
    minimum evidence window."""
    from paddle_tpu.observability import doctor

    base = {"moe_num_experts": 4, "moe_assigned_tokens": 1000.0,
            "moe_dropped_rate": 0.0, "moe_load_skew": 1.1,
            "moe_expert_load": [250.0, 240.0, 260.0, 250.0], "ep": 2}

    def verdicts(s):
        return [v for v in doctor.diagnose(s, kind="serve")
                if v["bottleneck"] == "expert-imbalance"]

    assert verdicts(base) == []

    over = dict(base, moe_dropped_rate=0.2,
                moe_expert_load=[700.0, 40.0, 30.0, 30.0],
                moe_load_skew=3.5)
    (v,) = verdicts(over)
    assert v["evidence"]["moe_dropped_rate"] == 0.2
    assert v["evidence"]["hottest_expert"] == 0
    assert v["action"]["param"] == "moe_capacity_factor"

    skew = dict(base, moe_load_skew=3.0, spec_k=4)
    (v,) = verdicts(skew)
    assert v["action"]["param"] == "spec_k"
    assert v["action"]["candidates"] == [2, 1]

    assert verdicts(dict(over, moe_assigned_tokens=8.0)) == []


def test_tier1_budget_unit(tmp_path):
    """The wall-budget guard bench --smoke runs: pure decision fn +
    record/load round trip, exemptions by basename."""
    from paddle_tpu.testing import tier1_budget as tb

    assert tb.files_over_budget({"a.py": 10.0, "b.py": 70.0},
                                budget_s=60, exempt=[]) == [("b.py", 70.0)]
    assert tb.files_over_budget({"t/b.py": 70.0}, budget_s=60,
                                exempt=["b.py"]) == []

    p = str(tmp_path / ".tier1_durations.json")
    assert tb.check_recorded_durations(p) is None
    tb.record_durations({"x.py": 12.0, "y.py": 99.9}, p)
    v = tb.check_recorded_durations(p)
    assert v is not None and v["files"] == 2
    assert [f for f, _ in v["over_budget"]] == ["y.py"]


@pytest.mark.slow
def test_loadgen_moe_columns(model):
    """Loadgen reports grow the expert-balance window columns: the
    histogram, dropped rate, and skew are WINDOW-scoped (snapshot and
    subtract), so a reused engine reports this run's balance."""
    from paddle_tpu.inference.loadgen import (SharedPrefixWorkload,
                                              run_loadtest)

    eng = _mk(model, 2)
    wl = SharedPrefixWorkload(96, prefix_len=4, tail_len=(3, 6),
                              max_new=(3, 5), seed=0)
    report = run_loadtest(eng, num_requests=3, rate_rps=1000.0,
                          workload=wl)
    assert report["moe_num_experts"] == 4 and report["ep"] == 2
    assert report["moe_assigned_tokens"] > 0
    assert report["moe_dropped_rate"] >= 0.0
    assert len(report["moe_expert_load"]) == 4
    assert sum(report["moe_expert_load"]) > 0
    assert report["moe_load_skew"] is not None


# ---- disaggregated prefill with expert parallelism --------------------
def test_disagg_disjoint_ep(model):
    """Disjoint prefill/decode groups, each with its own
    {'dp','tp','ep'} mesh: the prefill worker's executables must trace
    under the PREFILL mesh (a shared trace would bake the decode
    group's devices into the serve-ep shard_map), the KV handoff
    crosses the boundary, and tokens match the plain engine."""
    from paddle_tpu.inference.disagg import DisaggServingEngine

    prompts = _prompts(5, lens=(7, 12))
    ref = InferenceEngine(model, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, seed=3)
    rids = [ref.add_request(p, max_new_tokens=5) for p in prompts]
    ref_out = ref.run()

    eng = DisaggServingEngine(model, prefill_devices=4, seed=3,
                              batch_slots=2, kv_block_size=8,
                              prefill_ep=2, decode_ep=2)
    rids2 = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    out = eng.run()
    for a, b in zip(rids, rids2):
        np.testing.assert_array_equal(ref_out[a], out[b])

    s = eng.stats
    assert s["disjoint_groups"] is True
    assert s["ep"] == 2
    assert s["prefill_mesh"] == {"dp": 1, "tp": 2, "ep": 2}
    assert s["serving_mesh"] == {"dp": 1, "tp": 2, "ep": 2}
    assert s["handoff_transfers"] >= len(prompts)
    assert s["moe_dropped_rate"] == ref.stats["moe_dropped_rate"]

    # a non-dividing group is a config error, named per group
    with pytest.raises(ValueError, match="prefill_ep=2"):
        DisaggServingEngine(model, prefill_devices=3, prefill_ep=2,
                            batch_slots=2, kv_block_size=8)

    eng.decode.drain()
    eng.check_leak_free()


@pytest.mark.slow
@pytest.mark.parametrize("layout,kv_dtype,spec", [
    ("dense", "int8", False), ("paged", None, False),
    ("dense", None, True), ("paged", "int8", True),
])
def test_ep_parity_matrix_full(model, layout, kv_dtype, spec):
    """The exhaustive matrix (slow lane): every remaining layout ×
    KV-dtype × spec-decode combination, ep=2 ≡ ep=1 (the spec VERIFY
    path routes through the same fixed-shape expert dispatch)."""
    kw = dict(kv_layout=layout, kv_dtype=kv_dtype)
    if layout == "paged":
        kw.update(kv_block_size=8)
    if spec:
        draft = moe_model(1, num_layers=1, moe_num_experts=0)
        kw.update(spec_k=2, draft_model=draft)
    prompts = _prompts(6, lens=(5, 9, 3))
    base = _run(_mk(model, 1, **kw), prompts, gen=8)
    eng = _mk(model, 2, **kw)
    toks = _run(eng, prompts, gen=8)
    assert toks == base
    if spec:
        assert eng.stats["spec_ticks"] > 0
    if layout == "paged":
        eng.check_leak_free()


@pytest.mark.slow
def test_disagg_shared_pool_ep(model):
    """Shared-pool disagg (no device carve) on one ep=2 mesh: the
    prefill worker reuses the decode engine's executables — parity and
    a combined expert-load histogram."""
    from paddle_tpu.inference.disagg import DisaggServingEngine

    prompts = _prompts(7, lens=(6, 10))
    ref = InferenceEngine(model, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, seed=3)
    rids = [ref.add_request(p, max_new_tokens=5) for p in prompts]
    ref_out = ref.run()

    eng = DisaggServingEngine(model, seed=3, batch_slots=2,
                              kv_block_size=8, mesh=_ep_mesh(2))
    rids2 = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    out = eng.run()
    for a, b in zip(rids, rids2):
        np.testing.assert_array_equal(ref_out[a], out[b])
    s = eng.stats
    assert s["ep"] == 2 and s["moe_num_experts"] == 4
    # ONE combined histogram: worker prefills accumulate into the
    # decode engine's counters.  The disagg drive loop ticks decode
    # once more than the monolithic engine (the handoff poll), so
    # compare per-expert load within that one-tick slack rather than
    # exactly — token identity above is the strong check.
    ref_load = ref.stats["moe_expert_load"]
    assert len(s["moe_expert_load"]) == 4
    for got, want in zip(s["moe_expert_load"], ref_load):
        assert want <= got <= want + 2 * len(prompts)
