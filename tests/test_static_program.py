"""Static-graph surface: Program/Variable/Executor/program_guard.

Reference: fluid/framework.py Program:4127 + executor.py:475 — the
classic enable_static workflow: declare data, build layers, minimize,
then Executor.run(feed, fetch_list) in a loop.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import static


@pytest.fixture(autouse=True)
def static_mode_guard():
    """Each test gets fresh default programs and leaves eager mode on."""
    from paddle_tpu.static import program as prog
    prog._state.mode = False
    prog._state.main = static.Program()
    prog._state.startup = static.Program()
    yield
    prog._state.mode = False
    prog._state.main = static.Program()
    prog._state.startup = static.Program()


def test_data_records_inputs_and_ops():
    paddle.enable_static()
    x = static.data("x", [None, 4])
    assert isinstance(x, static.Variable)
    y = paddle.add(x, x)
    assert isinstance(y, static.Variable)
    main = static.default_main_program()
    assert "x" in main.inputs
    assert len(main.ops) == 1
    paddle.disable_static()
    # eager mode restored: data() yields InputSpec again
    assert not isinstance(static.data("z", [2]), static.Variable)


def test_executor_runs_forward_graph():
    paddle.enable_static()
    x = static.data("x", [None, 3])
    y = (x * 2.0 + 1.0).sum(axis=1)
    exe = static.Executor()
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    (out,) = exe.run(feed={"x": a}, fetch_list=[y])
    np.testing.assert_allclose(out, (a * 2 + 1).sum(1))
    # a different feed shape re-traces transparently
    b = np.ones((5, 3), np.float32)
    (out2,) = exe.run(feed={"x": b}, fetch_list=[y])
    np.testing.assert_allclose(out2, np.full(5, 9.0))


def test_executor_missing_feed_raises():
    paddle.enable_static()
    x = static.data("x", [None, 2])
    y = x + 1.0
    with pytest.raises(ValueError, match="missing graph inputs"):
        static.Executor().run(feed={}, fetch_list=[y])


def test_layers_capture_parameters_not_constants():
    """Captured Parameters are read at run time: mutating the weight
    between runs changes the output (the reference's scope semantics)."""
    paddle.enable_static()
    lin = nn.Linear(2, 1, bias_attr=False)
    x = static.data("x", [None, 2])
    y = lin(x)
    exe = static.Executor()
    a = np.ones((1, 2), np.float32)
    (o1,) = exe.run(feed={"x": a}, fetch_list=[y])
    lin.weight._data = lin.weight.data * 2
    (o2,) = exe.run(feed={"x": a}, fetch_list=[y])
    np.testing.assert_allclose(o2, 2 * o1, rtol=1e-6)


def test_program_guard_isolation():
    paddle.enable_static()
    main2 = static.Program()
    with static.program_guard(main2):
        x = static.data("x", [None, 2])
        _ = x + 1.0
    assert len(main2.ops) == 1
    assert len(static.default_main_program().ops) == 0


def test_static_training_converges_like_dygraph():
    """The headline parity: build net + loss under static mode, SGD
    minimize, Executor.run loop — and match the dygraph run exactly."""
    lr, steps = 0.1, 10
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = rng.randn(16, 2).astype(np.float32)

    # dygraph reference
    paddle.seed(0)
    dy_net = nn.Linear(4, 2)
    dy_opt = paddle.optimizer.SGD(learning_rate=lr,
                                  parameters=dy_net.parameters())
    dy_losses = []
    for _ in range(steps):
        loss = F.mse_loss(dy_net(paddle.to_tensor(xs)),
                          paddle.to_tensor(ys))
        loss.backward()
        dy_opt.step()
        dy_opt.clear_grad()
        dy_losses.append(float(loss))

    # static twin
    paddle.enable_static()
    paddle.seed(0)
    st_net = nn.Linear(4, 2)
    x = static.data("x", [None, 4])
    y = static.data("y", [None, 2])
    loss = F.mse_loss(st_net(x), y)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=st_net.parameters())
    opt.minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    st_losses = []
    for _ in range(steps):
        (l,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        st_losses.append(float(l))
    paddle.disable_static()

    np.testing.assert_allclose(st_losses, dy_losses, rtol=1e-5,
                               atol=1e-6)
    for p_dy, p_st in zip(dy_net.parameters(), st_net.parameters()):
        np.testing.assert_allclose(np.asarray(p_dy.data),
                                   np.asarray(p_st.data),
                                   rtol=1e-5, atol=1e-6)


def test_static_adam_training_decreases_loss():
    paddle.enable_static()
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    x = static.data("x", [None, 4])
    y = static.data("y", [None, 1])
    loss = F.mse_loss(net(x), y)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    opt.minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 4).astype(np.float32)
    ys = rng.randn(32, 1).astype(np.float32)
    losses = [float(exe.run(feed={"x": xs, "y": ys},
                            fetch_list=[loss])[0])
              for _ in range(30)]
    paddle.disable_static()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_clone_for_test_drops_train_hook():
    paddle.enable_static()
    net = nn.Linear(2, 1)
    x = static.data("x", [None, 2])
    loss = net(x).sum()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    opt.minimize(loss)
    main = static.default_main_program()
    assert main._train is not None
    test_prog = main.clone(for_test=True)
    assert test_prog._train is None
    # inference on the clone still works
    (out,) = static.Executor().run(
        test_prog, feed={"x": np.ones((2, 2), np.float32)},
        fetch_list=[loss])
    assert np.isfinite(out).all()


def test_minimize_after_guard_exit_lands_on_owning_program():
    """Review fix: the loss Variable carries its Program, so minimize
    outside the recording guard still installs the train hook there."""
    paddle.enable_static()
    prog = static.Program()
    with static.program_guard(prog):
        net = nn.Linear(2, 1)
        x = static.data("x", [None, 2])
        loss = net(x).sum()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    opt.minimize(loss)  # guard has exited; default program is different
    assert prog._train is not None
    assert static.default_main_program()._train is None
    w0 = np.asarray(net.weight.data).copy()
    static.Executor().run(prog, feed={"x": np.ones((2, 2), np.float32)},
                          fetch_list=[loss])
    assert not np.allclose(np.asarray(net.weight.data), w0)


def test_static_adam_bias_correction_advances():
    """Review fix: lr/step enter the jitted train step as arguments —
    static Adam must match dygraph Adam exactly over many steps (a
    frozen step counter would diverge from step 2 on)."""
    rng = np.random.RandomState(3)
    xs = rng.randn(8, 3).astype(np.float32)
    ys = rng.randn(8, 2).astype(np.float32)

    paddle.seed(0)
    dy = nn.Linear(3, 2)
    dopt = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=dy.parameters())
    dy_losses = []
    for _ in range(6):
        loss = F.mse_loss(dy(paddle.to_tensor(xs)), paddle.to_tensor(ys))
        loss.backward()
        dopt.step()
        dopt.clear_grad()
        dy_losses.append(float(loss))

    paddle.enable_static()
    paddle.seed(0)
    st = nn.Linear(3, 2)
    x = static.data("x", [None, 3])
    y = static.data("y", [None, 2])
    loss = F.mse_loss(st(x), y)
    sopt = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=st.parameters())
    sopt.minimize(loss)
    exe = static.Executor()
    st_losses = [float(exe.run(feed={"x": xs, "y": ys},
                               fetch_list=[loss])[0])
                 for _ in range(6)]
    paddle.disable_static()
    np.testing.assert_allclose(st_losses, dy_losses, rtol=1e-5,
                               atol=1e-6)


def test_save_load_inference_model(tmp_path):
    """static.save_inference_model exports the pruned graph with frozen
    params; load_inference_model returns a program Executor.run serves —
    across batch sizes (symbolic dims)."""
    paddle.enable_static()
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = static.data("x", [None, 4])
    y = net(x)
    exe = static.Executor()
    a = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    (want,) = exe.run(feed={"x": a}, fetch_list=[y])

    prefix = str(tmp_path / "infer" / "net")
    static.save_inference_model(prefix, [x], [y], exe)
    paddle.disable_static()

    prog, feed_names, n_out = static.load_inference_model(prefix)
    assert feed_names == ["x"]
    (got,) = static.Executor().run(prog, feed={"x": a})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # params are FROZEN at save time: later weight changes don't leak in
    # and a different batch size serves through the symbolic dim
    b = np.ones((7, 4), np.float32)
    (got7,) = static.Executor().run(prog, feed={"x": b})
    assert got7.shape == (7, 2)


def test_save_inference_model_validates_feeds(tmp_path):
    paddle.enable_static()
    x = static.data("x", [None, 2])
    z = static.data("z", [None, 2])
    out = x + z
    with pytest.raises(ValueError, match="not in feed_vars"):
        static.save_inference_model(str(tmp_path / "m"), [x], [out])
