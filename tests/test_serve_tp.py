"""Pod-scale tensor-parallel serving (ISSUE 18).

Every test runs on the suite's virtual 8-device CPU mesh (conftest sets
``--xla_force_host_platform_device_count=8`` before jax initializes).
The contracts:

- tp=2 serving is TOKEN-IDENTICAL to the unsharded engine — the
  NamedSharding commit changes layout, never numerics (greedy) — with
  ZERO XLA compiles after warmup (committed weights/cache/rng key must
  not add sharding-keyed cache misses, even under slot churn).
- disaggregated prefill/decode runs on provably DISJOINT device
  groups, with device-to-device KV-block handoff, and still matches
  the plain engine token for token.
- comm_stats attributes collectives to mesh axes; exec-registry
  entries compiled against a submesh carry it and fold the per-axis
  collective breakdown into their analysis.

Tier-1 covers the matrix corners on SHARED engines (dense fp with the
full observability sweep, paged int8 under slot churn, GQA on the
paged fp pool); the exhaustive layout × dtype × spec matrix rides the
slow lane.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import create_mesh
from paddle_tpu.inference import InferenceEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import compile_counter

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs a multi-device (CPU) mesh")

TINY = dict(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=64, use_flash_attention=False)


def tiny_model(seed=0, **over):
    paddle.seed(seed)
    m = GPTForCausalLM(GPTConfig(**{**TINY, **over}))
    m.eval()
    return m


def _tp_mesh(tp):
    return create_mesh({"dp": 1, "tp": tp}) if tp > 1 else None


def _mk(model, tp, **kw):
    return InferenceEngine(model, batch_slots=2, prefill_buckets=[16],
                           mesh=_tp_mesh(tp), **kw)


def _run(eng, prompts, gen=5):
    rids = [eng.add_request(p, max_new_tokens=gen) for p in prompts]
    out = eng.run()
    return [list(map(int, out[r])) for r in rids]


def _prompts(seed=0, lens=(5, 9)):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 96, (n,)).astype(np.int32) for n in lens]


@pytest.fixture(scope="module")
def model():
    return tiny_model(0)


def test_tp_dense_parity_and_observability(model):
    """The dense leg carries the full contract in one pair of engines:
    tp=2 tokens ≡ tp=1, ZERO compiles after warmup, stats carry
    tp/serving_mesh, the megakernel stands down, registry entries name
    the submesh, and the deferred analysis folds tp-attributed
    collectives into the snapshot row."""
    from paddle_tpu.observability import exec_registry

    prompts = _prompts(0)
    base = _run(_mk(model, 1), prompts)
    eng = _mk(model, 2)
    eng.warmup(buckets=[16])
    with compile_counter.assert_no_recompiles("dense tp=2 post-warmup"):
        toks = _run(eng, prompts)
    assert toks == base
    s = eng.stats
    assert s["tp"] == 2 and s["serving_mesh"] == {"dp": 1, "tp": 2}
    assert s["decode_megakernel"] is False  # stands down under tp>1

    reg = exec_registry.registry()
    reg.analyze_all(eng._exec_component)
    rows = [r for r in reg.snapshot(eng._exec_component)["executables"]
            if (r.get("meta") or {}).get("submesh")]
    assert rows, "no submesh-tagged entries for the tp engine"
    for r in rows:
        assert r["meta"]["tp"] == 2
        assert r["meta"]["submesh"]["shape"].get("tp") == 2
        assert len(r["meta"]["submesh"]["devices"]) == 2
    decode_rows = [r for r in rows
                   if r["kind"] == "decode" and r["analyzed"]]
    assert decode_rows
    for r in decode_rows:
        coll = r.get("collectives")
        assert coll and coll["count"] > 0, \
            f"no collective fold on {r['name']}"
        # a tp-sharded decode step must actually COMMUNICATE (the
        # row-parallel partial-sum reduce), attributed to 'tp'
        assert coll.get("by_axis", {}).get("tp", {}).get("count", 0) > 0


def test_tp_paged_int8_churn_recompile_free(model):
    """The paged leg doubles as the int8-KV and slot-churn corner:
    more requests than slots through a warmed tp=2 paged int8 engine —
    tokens ≡ tp=1, ZERO new compiles across admit/retire/scale
    round-trips, pool leak-free at drain."""
    kw = dict(kv_layout="paged", kv_block_size=8, kv_dtype="int8")
    churn = _prompts(1, lens=(4, 7, 11, 6))
    base = _run(_mk(model, 1, **kw), churn)
    eng = _mk(model, 2, **kw)
    eng.warmup(buckets=[16])
    with compile_counter.assert_no_recompiles("paged int8 tp churn"):
        toks = _run(eng, churn)
    assert toks == base
    eng.check_leak_free()


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_tp_paged_gqa_parity():
    """GQA on the paged fp pool: 2 KV heads over tp=2 means ONE kv
    head per shard — the sharpest head-sharding corner."""
    model = tiny_model(2, num_kv_heads=2)
    kw = dict(kv_layout="paged", kv_block_size=8)
    prompts = _prompts(2)
    base = _run(_mk(model, 1, **kw), prompts)
    eng = _mk(model, 2, **kw)
    toks = _run(eng, prompts)
    assert toks == base
    eng.check_leak_free()


@pytest.mark.slow
@pytest.mark.parametrize("layout,kv_dtype,spec", [
    ("dense", "int8", False), ("paged", None, False),
    ("paged", "int8", False), ("dense", None, True),
    ("paged", None, True),
])
def test_tp_parity_matrix_full(model, layout, kv_dtype, spec):
    """The exhaustive matrix (slow lane): every remaining layout ×
    KV-dtype × spec-decode combination, tp=2 ≡ tp=1."""
    kw = dict(kv_layout=layout, kv_dtype=kv_dtype)
    if layout == "paged":
        kw.update(kv_block_size=8)
    if spec:
        kw.update(spec_k=2, draft_model=tiny_model(1, num_layers=1))
    prompts = _prompts(3, lens=(5, 9, 3))
    base = _run(_mk(model, 1, **kw), prompts, gen=8)
    eng = _mk(model, 2, **kw)
    toks = _run(eng, prompts, gen=8)
    assert toks == base
    if spec:
        assert eng.stats["spec_ticks"] > 0
    if layout == "paged":
        eng.check_leak_free()


# ---- disaggregated prefill on disjoint device groups ------------------
@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_disagg_disjoint_groups(model):
    """DistServe-style split: prefill compiles against devices [0:4],
    decode against [4:8], the KV handoff crosses the group boundary,
    and tokens still match the plain single-group engine."""
    from paddle_tpu.inference.disagg import DisaggServingEngine
    from paddle_tpu.observability import exec_registry

    prompts = _prompts(4, lens=(7, 13))
    ref = InferenceEngine(model, batch_slots=2, kv_layout="paged",
                          kv_block_size=8, seed=3)
    rids = [ref.add_request(p, max_new_tokens=5) for p in prompts]
    ref_out = ref.run()

    eng = DisaggServingEngine(model, prefill_devices=4, seed=3,
                              batch_slots=2, kv_block_size=8)
    rids2 = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    out = eng.run()
    for a, b in zip(rids, rids2):
        np.testing.assert_array_equal(ref_out[a], out[b])

    s = eng.stats
    assert s["disjoint_groups"] is True
    assert s["handoff_transfers"] >= len(prompts)
    p_devs, d_devs = set(s["prefill_devices"]), set(s["decode_devices"])
    assert p_devs and d_devs and not (p_devs & d_devs)

    # the observatory records WHICH submesh each half compiled
    # against: the handoff gather runs on the prefill group, the
    # scatter on the decode group — disjoint by construction
    by_key = {e.key: e for e in exec_registry.registry().entries(
        eng.decode._exec_component)}
    gather = by_key.get(("handoff_gather", 0))
    scatter = by_key.get(("handoff_scatter", 0))
    assert gather is not None and scatter is not None
    g_devs = set(gather.meta["submesh"]["devices"])
    s_devs = set(scatter.meta["submesh"]["devices"])
    assert g_devs == p_devs and s_devs == d_devs

    eng.decode.drain()
    eng.check_leak_free()


# ---- collective axis attribution (pure units) -------------------------
def test_comm_stats_axis_groups():
    """axis_groups_from_shape partitions logical device ids per axis
    in mesh-major order; _match_axis names the axis whose partition a
    collective's replica groups equal (global groups on a multi-axis
    mesh → "all", anything else → "other")."""
    from paddle_tpu.utils import comm_stats as cs

    ag = cs.axis_groups_from_shape({"dp": 2, "tp": 4})
    assert [sorted(g) for g in ag["tp"]] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert [sorted(g) for g in ag["dp"]] == [[0, 4], [1, 5], [2, 6],
                                             [3, 7]]
    # extent-1 axes are dropped (nothing to attribute)
    assert "dp" not in cs.axis_groups_from_shape({"dp": 1, "tp": 2})

    axis_sets = {ax: set(gs) for ax, gs in ag.items()}
    assert cs._match_axis([[0, 1, 2, 3], [4, 5, 6, 7]], axis_sets,
                          8) == "tp"
    assert cs._match_axis([[0, 4], [1, 5], [2, 6], [3, 7]], axis_sets,
                          8) == "dp"
    assert cs._match_axis(None, axis_sets, 8) == "all"
    assert cs._match_axis([[0, 1], [2, 3], [4, 5], [6, 7]], axis_sets,
                          8) == "other"

    # by_axis lands in parse output when axis_groups is passed
    hlo = ('%ar = f32[16]{0} all-reduce(%x), '
           'replica_groups={{0,1,2,3},{4,5,6,7}}')
    out = cs.parse_hlo_collectives(hlo, axis_groups=ag)
    assert out["by_axis"] == {"tp": {"count": 1, "bytes": 64}}
