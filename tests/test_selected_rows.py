"""SelectedRows sparse embedding gradients.

Reference: /root/reference/paddle/fluid/framework/selected_rows.h,
operators/math/selected_rows_functor.cc (MergeAdd), adam_op.h
SparseAdamFunctor (lazy vs non-lazy), lookup_table_v2_op.cc is_sparse.
"""
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.selected_rows import SelectedRows


def test_selected_rows_merge_and_to_dense():
    rows = np.array([3, 1, 3, 0], np.int32)
    vals = np.arange(8, dtype=np.float32).reshape(4, 2)
    sr = SelectedRows(rows, jnp.asarray(vals), (5, 2))
    dense = np.zeros((5, 2), np.float32)
    for r, v in zip(rows, vals):
        dense[r] += v
    np.testing.assert_array_equal(sr.numpy(), dense)
    merged = sr.merge()
    np.testing.assert_array_equal(np.asarray(merged.to_dense()), dense)
    # merged rows are unique (padding slots point past the vocab)
    real = np.asarray(merged.rows)[np.asarray(merged.rows) < 5]
    assert len(real) == len(set(real.tolist()))


def test_selected_rows_add_sparse_and_dense():
    a = SelectedRows(np.array([0, 2], np.int32),
                     jnp.ones((2, 3), jnp.float32), (4, 3))
    b = SelectedRows(np.array([2], np.int32),
                     2 * jnp.ones((1, 3), jnp.float32), (4, 3))
    both = a + b
    assert isinstance(both, SelectedRows)
    expect = np.zeros((4, 3), np.float32)
    expect[0] += 1
    expect[2] += 3
    np.testing.assert_array_equal(both.numpy(), expect)
    densified = a + jnp.full((4, 3), 5.0)
    assert not isinstance(densified, SelectedRows)
    np.testing.assert_array_equal(
        np.asarray(densified), a.numpy() + 5.0)


def test_sparse_embedding_grad_is_selected_rows():
    paddle.seed(0)
    emb = nn.Embedding(50, 4, sparse=True)
    ids = paddle.to_tensor(np.array([[1, 3], [3, 7]], np.int64))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    assert sorted(np.asarray(g.rows).tolist()) == [1, 3, 3, 7]
    # dense equivalent: ones scattered at the looked-up rows
    expect = np.zeros((50, 4), np.float32)
    for r in [1, 3, 3, 7]:
        expect[r] += 1.0
    np.testing.assert_array_equal(g.numpy(), expect)


def test_sparse_updates_match_dense_sgd_and_adam():
    for opt_name in ("sgd", "adam", "adam_lazy", "adamw_lazy"):
        paddle.seed(7)
        emb_s = nn.Embedding(30, 8, sparse=True)
        paddle.seed(7)
        emb_d = nn.Embedding(30, 8, sparse=False)

        def make_opt(params, lazy):
            if opt_name == "sgd":
                return paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=params)
            if opt_name.startswith("adamw"):
                return paddle.optimizer.AdamW(
                    learning_rate=0.05, parameters=params,
                    weight_decay=0.01, lazy_mode=lazy)
            return paddle.optimizer.Adam(learning_rate=0.05,
                                         parameters=params,
                                         lazy_mode=lazy)

        lazy = opt_name.endswith("lazy")
        opt_s = make_opt(emb_s.parameters(), lazy)
        opt_d = make_opt(emb_d.parameters(), lazy)
        ids = paddle.to_tensor(np.array([[2, 9, 2], [14, 9, 5]], np.int64))
        tgt = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 8).astype(np.float32))
        for _ in range(3):
            for emb, opt in ((emb_s, opt_s), (emb_d, opt_d)):
                loss = F.mse_loss(emb(ids), tgt)
                loss.backward()
                opt.step()
                opt.clear_grad()
        w_s = np.asarray(emb_s.weight.data)
        w_d = np.asarray(emb_d.weight.data)
        if lazy:
            # lazy equals dense on the TOUCHED rows; untouched rows are
            # frozen in lazy mode — for plain Adam dense also leaves them
            # alone (zero grad + zero moments => zero update), but dense
            # AdamW decays EVERY row, the documented lazy deviation
            touched = [2, 5, 9, 14]
            np.testing.assert_allclose(w_s[touched], w_d[touched],
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=opt_name)
            untouched = [i for i in range(30) if i not in touched]
            if opt_name == "adam_lazy":
                np.testing.assert_allclose(w_s[untouched], w_d[untouched],
                                           rtol=1e-5, atol=1e-6,
                                           err_msg=opt_name)
            else:  # adamw: lazy froze them, dense decayed them
                assert not np.allclose(w_s[untouched], w_d[untouched])
        else:
            np.testing.assert_allclose(w_s, w_d, rtol=1e-5, atol=1e-6,
                                       err_msg=opt_name)


def test_sparse_with_unsupported_optimizer_raises():
    paddle.seed(0)
    emb = nn.Embedding(10, 4, sparse=True)
    opt = paddle.optimizer.Lamb(learning_rate=0.01,
                                parameters=emb.parameters())
    out = emb(paddle.to_tensor(np.array([1, 2], np.int64)))
    out.sum().backward()
    with pytest.raises(NotImplementedError):
        opt.step()


def test_sparse_padding_idx_rows_zeroed():
    paddle.seed(0)
    emb = nn.Embedding(20, 4, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.array([0, 1, 0, 2], np.int64))
    emb(ids).sum().backward()
    g = emb.weight.grad.numpy()
    np.testing.assert_array_equal(g[0], np.zeros(4))
    np.testing.assert_array_equal(g[1], np.ones(4))


@pytest.mark.slow
def test_sparse_update_faster_on_million_row_vocab():
    """The point of SelectedRows: a 1M x 64 embedding update must not
    touch the full table. Compare wall time of 5 sparse lazy-Adam steps
    vs 5 dense ones (grad densification dominates the dense path).
    Wall-clock soak over a 1M-row table (~30s) — slow-marked; the
    correctness of sparse updates is covered by the fast tests above."""
    vocab, dim, bs = 1_000_000, 64, 256
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, vocab, (bs,)).astype(np.int64)
    tgt = paddle.to_tensor(rng.randn(bs, dim).astype(np.float32))

    def run(sparse):
        paddle.seed(0)
        emb = nn.Embedding(vocab, dim, sparse=sparse)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=emb.parameters(),
                                    lazy_mode=True)
        ids = paddle.to_tensor(ids_np)
        # warmup (compile/alloc)
        loss = F.mse_loss(emb(ids), tgt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        t0 = time.perf_counter()
        for _ in range(5):
            loss = F.mse_loss(emb(ids), tgt)
            loss.backward()
            opt.step()
            opt.clear_grad()
        float(loss)  # sync
        return time.perf_counter() - t0

    t_sparse = run(True)
    t_dense = run(False)
    # demand a clear win, not statistical noise
    assert t_sparse < t_dense * 0.7, \
        f"sparse {t_sparse:.3f}s vs dense {t_dense:.3f}s"
