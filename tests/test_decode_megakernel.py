"""Decode megakernel (ISSUE 11): the fused per-layer decode step must be
tolerance-equal (1e-5) to the composed kernels path across fp/int8 ×
dense/paged × GQA, keep the zero-recompile decode contract, and the
sweep/tuning satellites must behave (bench resume, nearest-shape tuning
fallbacks, remat-policy table, decode HBM byte accounting)."""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.ops import decode_megakernel as mk
from paddle_tpu.ops.quantized_matmul import quantize_kv
from paddle_tpu.utils import compile_counter
from paddle_tpu.utils import tuning as _tuning

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TOL = 1e-5


def _weights(rng, h, hkv, d, f):
    kvd = hkv * d

    def r(*s):
        return jnp.asarray(rng.randn(*s).astype(np.float32) * 0.05)

    return (r(h) + 1.0, r(h), r(h, h + 2 * kvd), r(h + 2 * kvd),
            r(h, h), r(h), r(h) + 1.0, r(h), r(h, f), r(f), r(f, h),
            r(h))


# ---------------------------------------------------------------------------
# op level: interpret-mode Pallas kernel ≡ XLA composite
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("hkv", [2, 1])  # MHA and GQA (2 heads)
def test_kernel_matches_composite(paged, quantized, hkv):
    """Pallas megakernel (interpret) vs the XLA composite across the
    fp/int8 × dense/paged × GQA matrix, lengths pinned at the prefix
    boundaries (0, 1, block edge, block edge - 1, cap - 1)."""
    rng = np.random.RandomState(0)
    B, heads, d, f = 5, 2, 64, 256
    h = heads * d
    cap = 256
    w = _weights(rng, h, hkv, d, f)
    x = jnp.asarray(rng.randn(B, h).astype(np.float32) * 0.1)
    lengths = jnp.asarray([0, 1, 127, 128, 255], jnp.int32)
    if paged:
        bs = 128
        mb = cap // bs
        nb = B * mb + 1
        kp = jnp.asarray(rng.randn(nb, bs, hkv, d).astype(np.float32)
                         * 0.1)
        vp = jnp.asarray(rng.randn(nb, bs, hkv, d).astype(np.float32)
                         * 0.1)
        tables = jnp.asarray(
            np.arange(1, B * mb + 1).reshape(B, mb), jnp.int32)
        if quantized:
            kq, ks = quantize_kv(kp)
            vq, vs = quantize_kv(vp)
            args = (x, w, kq, vq, tables, lengths, ks, vs)
        else:
            args = (x, w, kp, vp, tables, lengths)
        fn = mk.decode_layer_step_paged
    else:
        k = jnp.asarray(rng.randn(B, cap, hkv, d).astype(np.float32)
                        * 0.1)
        v = jnp.asarray(rng.randn(B, cap, hkv, d).astype(np.float32)
                        * 0.1)
        if quantized:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            args = (x, w, kq, vq, lengths, ks, vs)
        else:
            args = (x, w, k, v, lengths)
        fn = mk.decode_layer_step

    mk.set_interpret_mode(False)       # CPU: forces the composite
    try:
        xc, kc, vc = jax.jit(lambda *a: fn(*a))(*args)
        mk.set_interpret_mode(True)
        assert mk.decode_megakernel_available()
        xk, kk, vk = jax.jit(lambda *a: fn(*a))(*args)
    finally:
        mk.set_interpret_mode(None)
    np.testing.assert_allclose(np.asarray(xk), np.asarray(xc), atol=TOL,
                               rtol=0)
    np.testing.assert_allclose(np.asarray(kk), np.asarray(kc), atol=TOL,
                               rtol=0)
    np.testing.assert_allclose(np.asarray(vk), np.asarray(vc), atol=TOL,
                               rtol=0)


def test_kernel_gate_falls_back_not_crashes():
    """Unfriendly shapes (h % 128 != 0) must route the composite, not
    raise — the gate is what keeps tiny test configs working."""
    rng = np.random.RandomState(1)
    B, hkv, d, f = 2, 1, 16, 64   # h=16: kernel-unsupported
    h = 16
    w = _weights(rng, h, hkv, d, f)
    x = jnp.asarray(rng.randn(B, h).astype(np.float32))
    k = jnp.asarray(rng.randn(B, 32, hkv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, 32, hkv, d).astype(np.float32))
    mk.set_interpret_mode(True)
    try:
        xo, kn, vn = mk.decode_layer_step(
            x, w, k, v, jnp.asarray([3, 7], jnp.int32))
    finally:
        mk.set_interpret_mode(None)
    assert xo.shape == (B, h) and kn.shape == (B, hkv, d)


def test_vmem_gate_admits_350m_class_config():
    """The ISSUE-12 gate-widening satellite: with the qkv/out-proj
    weight fetches TILED (streamed per phase instead of resident), a
    gpt3-350m-shaped layer (h=1024, f=4096, 16 heads x 64, cap 2048,
    bf16, 8 slots) fits the VMEM budget and runs fused — fp AND int8
    KV — where the resident-qkv estimate used to fall back."""
    h, hkv, d, f, cap, B = 1024, 16, 64, 4096, 2048, 8
    kvd = hkv * d
    shapes = [(h,), (h,), (h, h + 2 * kvd), (h + 2 * kvd,), (h, h),
              (h,), (h,), (h,), (h, f), (f,), (f, h), (h,)]
    w = [jnp.zeros(s, jnp.bfloat16) for s in shapes]
    x = jnp.zeros((B, h), jnp.bfloat16)
    block_s = mk._pick_blocks(cap, f)[0]
    assert mk._fused_supported(x, w, hkv, d, block_s, None,
                               jnp.bfloat16, 2, False)
    assert mk._fused_supported(x, w, hkv, d, block_s, None,
                               jnp.int8, 1, True)
    # the estimate itself sits under the budget with real headroom
    bs2, bf2, bq, bo = mk._pick_blocks(cap, f, h + 2 * kvd, h)
    est = mk._vmem_estimate(h, kvd, f, bs2, bf2, bq, bo, hkv, d, 2, 2,
                            False, B)
    assert est < mk._VMEM_BUDGET
    # a resident qkv+out accounting would NOT have fit: adding those
    # matrices back on top of the streamed tiles blows the budget
    resident_extra = (h * (h + 2 * kvd) + h * h) * 2
    assert est + resident_extra > mk._VMEM_BUDGET


# ---------------------------------------------------------------------------
# model level: fused path ≡ composed path
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def model():
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, num_kv_heads=2, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_megakernel_matches_composed_dense(model, kv_dtype):
    """Fused decode steps (CPU composite) track the composed path's
    logits AND cache contents over several steps, mixed slot lengths,
    GQA model."""
    m = model
    rng = np.random.RandomState(0)
    p0 = rng.randint(0, 97, 9).astype(np.int32)
    p1 = rng.randint(0, 97, 5).astype(np.int32)
    act = jnp.ones((2,), jnp.int32)

    def rollout(fused):
        m.enable_decode_megakernel(fused)
        try:
            c = m.init_kv_cache(2, kv_dtype=kv_dtype)
            ids0 = np.zeros((1, 16), np.int32)
            ids0[0, :9] = p0
            _, c = m.prefill(jnp.asarray(ids0), c, 0, 9)
            ids1 = np.zeros((1, 16), np.int32)
            ids1[0, :5] = p1
            _, c = m.prefill(jnp.asarray(ids1), c, 1, 5)
            toks = jnp.asarray([p0[-1], p1[-1]], jnp.int32)
            outs = []
            for _ in range(3):
                logits, c = m.decode_step(toks, c, act)
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                outs.append(np.asarray(logits))
            return outs, c
        finally:
            m.enable_decode_megakernel(False)

    outs_c, cache_c = rollout(False)
    outs_f, cache_f = rollout(True)
    for lc, lf in zip(outs_c, outs_f):
        np.testing.assert_allclose(lf, lc, atol=TOL, rtol=0)
    np.testing.assert_allclose(
        np.asarray(cache_f.k, np.float32),
        np.asarray(cache_c.k, np.float32), atol=TOL, rtol=0)
    if kv_dtype:
        np.testing.assert_allclose(np.asarray(cache_f.k_scale),
                                   np.asarray(cache_c.k_scale),
                                   atol=TOL, rtol=0)


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_megakernel_matches_composed_paged_engine(model, kv_dtype):
    """Paged engines with the megakernel off/on generate IDENTICAL
    greedy tokens (CPU lowers both to the same XLA ops)."""
    from paddle_tpu.inference import InferenceEngine
    m = model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 97, n).astype(np.int32)
               for n in (9, 5, 12)]

    def run(fused):
        m.enable_decode_megakernel(fused)
        try:
            eng = InferenceEngine(m, batch_slots=2, kv_layout="paged",
                                  kv_block_size=8,
                                  prefill_buckets=[16],
                                  kv_dtype=kv_dtype)
            rids = [eng.add_request(p, max_new_tokens=6)
                    for p in prompts]
            out = eng.run()
            return [out[r].tolist() for r in rids]
        finally:
            m.enable_decode_megakernel(False)

    assert run(False) == run(True)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_megakernel_matches_composed_quantized_compute(model):
    """With int8 COMPUTE (cfg.quantize) the fused op routes its
    composite, whose projections run ops.quantized_matmul — logits must
    match the composed quantized path."""
    m = model
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 97, (1, 9)).astype(np.int32)
    tok = jnp.asarray([ids[0, -1]], jnp.int32)
    act = jnp.ones((1,), jnp.int32)
    m.enable_quantize("int8")
    try:
        c = m.init_kv_cache(1)
        _, c = m.prefill(jnp.asarray(ids[:, :-1]), c, 0, 8)
        lc, _ = m.decode_step(tok, c, act)
        m.enable_decode_megakernel(True)
        c2 = m.init_kv_cache(1)
        _, c2 = m.prefill(jnp.asarray(ids[:, :-1]), c2, 0, 8)
        lf, _ = m.decode_step(tok, c2, act)
    finally:
        m.enable_decode_megakernel(False)
        m.enable_quantize(None)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lc), atol=TOL,
                               rtol=0)


@pytest.mark.slow  # tier-1 wall budget: heaviest in file
def test_megakernel_interpret_kernel_in_model():
    """The REAL Pallas kernel (interpret mode) inside the model decode
    step matches the composed path — kernel-compatible shapes (h=128,
    cap=128)."""
    cfg = GPTConfig(vocab_size=97, hidden_size=128, num_layers=2,
                    num_heads=2, max_seq_len=128,
                    use_flash_attention=False)
    paddle.seed(1)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 97, (1, 9)).astype(np.int32)
    tok = jnp.asarray([ids[0, -1]], jnp.int32)
    act = jnp.ones((1,), jnp.int32)
    c = m.init_kv_cache(1)
    _, c = m.prefill(jnp.asarray(ids[:, :-1]), c, 0, 8)
    lc, _ = m.decode_step(tok, c, act)
    m.enable_decode_megakernel(True)
    mk.set_interpret_mode(True)
    try:
        c2 = m.init_kv_cache(1)
        _, c2 = m.prefill(jnp.asarray(ids[:, :-1]), c2, 0, 8)
        lk, _ = m.decode_step(tok, c2, act)
    finally:
        mk.set_interpret_mode(None)
        m.enable_decode_megakernel(False)
    np.testing.assert_allclose(np.asarray(lk), np.asarray(lc), atol=TOL,
                               rtol=0)


# ---------------------------------------------------------------------------
# zero-recompile churn with the megakernel on
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_zero_recompile_churn_megakernel(model, layout):
    """A warmed megakernel engine admits/retires/decodes with ZERO new
    XLA compiles — the fused op is shape-stable inside the decode
    executable exactly like the composed kernels."""
    from paddle_tpu.inference import InferenceEngine
    m = model
    m.enable_decode_megakernel(True)
    try:
        kw = dict(kv_layout="paged", kv_block_size=8) \
            if layout == "paged" else {}
        eng = InferenceEngine(m, batch_slots=2, prefill_buckets=[16],
                              **kw)
        eng.warmup(buckets=[16])
        assert eng.stats["decode_megakernel"]
        rng = np.random.RandomState(3)
        with compile_counter.assert_no_recompiles(
                f"megakernel churn {layout}"):
            rids = [eng.add_request(rng.randint(1, 97, n)
                                    .astype(np.int32),
                                    max_new_tokens=5)
                    for n in (4, 9, 6)]
            out = eng.run()
        assert all(len(out[r]) == 5 for r in rids)
    finally:
        m.enable_decode_megakernel(False)


# ---------------------------------------------------------------------------
# decode HBM byte accounting
# ---------------------------------------------------------------------------
def test_decode_hbm_bytes_per_tok_int8_smaller(model):
    from paddle_tpu.inference import InferenceEngine
    fp = InferenceEngine(model, batch_slots=2, prefill_buckets=[16])
    q8 = InferenceEngine(model, batch_slots=2, prefill_buckets=[16],
                         kv_dtype="int8")
    b_fp = fp.stats["decode_hbm_bytes_per_tok"]
    b_q8 = q8.stats["decode_hbm_bytes_per_tok"]
    assert b_fp > 0 and b_q8 > 0
    # int8 halves the KV values but adds f32 scale planes; with d=32
    # heads the scales cost 4/32 of fp — still a clear net win
    assert b_q8 < b_fp
    cfg = model.cfg
    kv_fp = 2 * cfg.num_layers * fp.max_seq_len * cfg.num_kv_heads * \
        cfg.head_dim * 4            # f32 cache on CPU
    assert b_fp >= kv_fp            # params amortized on top


# ---------------------------------------------------------------------------
# bench sweep resume (satellite)
# ---------------------------------------------------------------------------
def _bench_module():
    import importlib
    import bench
    return importlib.reload(bench)


def test_bench_resume_matches_persisted_rows(tmp_path, monkeypatch):
    """_persist_row tags rows with the run id and _measured_rows only
    returns rows whose (run, candidate identity) matches — the rerun
    after a late transient failure re-measures only the tail."""
    rows = tmp_path / "rows.jsonl"
    monkeypatch.setenv("BENCH_ROWS_FILE", str(rows))
    monkeypatch.setenv("BENCH_RUN", "r06")
    monkeypatch.delenv("BENCH_RECOMPUTE", raising=False)
    monkeypatch.delenv("BENCH_QUANTIZE", raising=False)
    monkeypatch.delenv("BENCH_SCAN_LAYERS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
    bench = _bench_module()
    row = {"config": "gpt3-125m", "batch": 8, "seq": 2048,
           "use_flash": True, "remat": False, "remat_policy": "off",
           "scan_layers": True, "overlap": True, "quantize": "int8",
           "mfu": 0.40, "step_ms": 10.0, "pathological": False}
    bench._persist_row(row, kind="train")
    measured = bench._measured_rows("train")
    spec = dict(config="gpt3-125m", batch=8, seq=2048, flash=True,
                remat=False, quantize="int8")
    assert bench._candidate_key(spec) in measured
    assert measured[bench._candidate_key(spec)]["mfu"] == 0.40
    # a different candidate (fp) must NOT match
    other = dict(spec, quantize="off")
    assert bench._candidate_key(other) not in measured
    # rows from another run are invisible
    monkeypatch.setenv("BENCH_RUN", "r07")
    assert bench._measured_rows("train") == {}
    # no run id => resume disabled entirely
    monkeypatch.setenv("BENCH_RUN", "")
    assert bench._measured_rows("train") == {}


def test_bench_resume_serve_rows(tmp_path, monkeypatch):
    rows = tmp_path / "rows.jsonl"
    monkeypatch.setenv("BENCH_ROWS_FILE", str(rows))
    monkeypatch.setenv("BENCH_RUN", "r06")
    bench = _bench_module()
    row = {"config": "gpt3-125m", "batch_slots": 8, "kv_dtype": "dense",
           "decode_megakernel": True, "prompt_len": 128,
           "gen_tokens": 64, "value": 900.0}
    bench._persist_row(row, kind="serve")
    measured = bench._measured_rows("serve")
    # tp (ISSUE 18), ep (ISSUE 19) and prefill_chunk (ISSUE 20) joined
    # the candidate key: a row without the columns resumes as the
    # tp=1/ep=1/monolithic candidate; a tp=2, ep=2 or chunked row is a
    # DIFFERENT point
    key = ("serve", "gpt3-125m", 8, "dense", True, 128, 64, 1, 1, 0)
    assert key in measured and measured[key]["value"] == 900.0
    assert ("serve", "gpt3-125m", 8, "dense", False, 128, 64, 1, 1, 0) \
        not in measured
    assert ("serve", "gpt3-125m", 8, "dense", True, 128, 64, 2, 1, 0) \
        not in measured
    assert ("serve", "gpt3-125m", 8, "dense", True, 128, 64, 1, 2, 0) \
        not in measured
    assert ("serve", "gpt3-125m", 8, "dense", True, 128, 64, 1, 1, 64) \
        not in measured


# ---------------------------------------------------------------------------
# tuning-table nearest-shape fallbacks (satellite)
# ---------------------------------------------------------------------------
@pytest.fixture
def tuning_tmp(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TUNING_CACHE",
                       str(tmp_path / "tuning.json"))
    monkeypatch.delenv("PADDLE_TPU_TUNING", raising=False)
    _tuning.reset_for_tests()
    yield
    _tuning.reset_for_tests()


def test_qmm_tiles_nearest_shape_fallback(tuning_tmp):
    from paddle_tpu.ops.quantized_matmul import get_qmm_tiles
    kind = _tuning.device_kind()
    _tuning.record("qmm_tiles", (kind, 1024, 512, 256, "int8"),
                   [64, 128, 128])
    # exact hit
    assert get_qmm_tiles(1024, 512, 256) == (64, 128, 128)
    # near miss (m bucket 2048, same n/k): nearest entry serves,
    # clamped — NOT the (256, 256, 256) hard defaults
    assert get_qmm_tiles(2048, 512, 256) == (64, 128, 128)
    # different n/k within log-distance still beats hard defaults
    assert get_qmm_tiles(1024, 256, 256) == (64, 128, 128)


def test_flash_blocks_nearest_seq_from_unified_table(tuning_tmp,
                                                     monkeypatch):
    import importlib
    fa = importlib.import_module("paddle_tpu.ops.flash_attention")
    monkeypatch.delenv("PADDLE_TPU_FLASH_AUTOTUNE_CACHE", raising=False)
    monkeypatch.setenv("PADDLE_TPU_FLASH_AUTOTUNE", "1")
    kind = _tuning.device_kind()
    saved = dict(fa._SWEEP_CACHE)
    fa._SWEEP_CACHE.clear()
    fa._SWEEP_STORE_STATE["loaded"] = False
    try:
        _tuning.record("flash_blocks", (kind, 1024, 64, True),
                       [256, 256])
        # seq 512 has no exact entry anywhere on CPU: the swept 1024
        # entry is the nearest and must serve (defaults are 512/512)
        assert fa.get_block_sizes(512, 64, True) == (256, 256)
    finally:
        fa._SWEEP_CACHE.clear()
        fa._SWEEP_CACHE.update(saved)
        fa._SWEEP_STORE_STATE["loaded"] = False


def test_tuned_remat_policy_consumed(tuning_tmp):
    from paddle_tpu.distributed.spmd import tuned_remat_policy

    class _Cfg:
        hidden_size, num_layers, max_seq_len = 128, 2, 64

    class _Model:
        cfg = _Cfg()

    kind = _tuning.device_kind()
    assert tuned_remat_policy(_Model()) is None
    _tuning.record("remat_policy", (kind, 128, 2, 64), "dots_no_batch")
    assert tuned_remat_policy(_Model()) == "dots_no_batch"
    # nearest shape serves a near-miss model
    _Cfg.hidden_size = 256
    assert tuned_remat_policy(_Model()) == "dots_no_batch"
    # 'off' entries mean "winner ran without remat": ignored
    _tuning.record("remat_policy", (kind, 256, 2, 64), "off")
    assert tuned_remat_policy(_Model()) is None


@pytest.mark.slow
def test_megakernel_long_churn_soak(model):
    """Longer mixed-admission soak with the fused path on (slow tier)."""
    from paddle_tpu.inference import InferenceEngine
    m = model
    m.enable_decode_megakernel(True)
    try:
        eng = InferenceEngine(m, batch_slots=3, prefill_buckets=[16])
        eng.warmup(buckets=[16])
        rng = np.random.RandomState(7)
        with compile_counter.assert_no_recompiles("megakernel soak"):
            for wave in range(4):
                rids = [eng.add_request(
                    rng.randint(1, 97, int(rng.randint(3, 14)))
                    .astype(np.int32),
                    max_new_tokens=int(rng.randint(3, 9)))
                    for _ in range(4)]
                out = eng.run()
                assert all(r in out for r in rids)
    finally:
        m.enable_decode_megakernel(False)
