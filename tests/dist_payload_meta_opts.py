"""2-rank payload for the eager meta-optimizers (reference
test_dist_base.py:668 separate-script pattern): LocalSGD periodic
averaging, DGC top-k compressed training, and the bucketed DDP reducer
(multiple buckets + a sparse embedding grad). Each rank prints values
the parent test compares."""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu.core.selected_rows import SelectedRows  # noqa: E402
from paddle_tpu.distributed import DataParallel, env  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.distributed.fleet import (  # noqa: E402
    DGCMomentum, DistributedStrategy)


def run_localsgd(rank):
    paddle.seed(0)
    model = nn.Linear(4, 2, bias_attr=False)
    st = DistributedStrategy()
    st.localsgd = True
    st.localsgd_configs = {"k_steps": 2, "begin_step": 1}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=model.parameters()), st)
    rng = np.random.RandomState(100 + rank)
    # 5 steps with k=2, begin=1: syncs at steps 1, 3, 5 — the LAST step
    # is a sync, so both ranks must print identical weights
    for _ in range(5):
        x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        loss = model(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w = np.asarray(model.weight.data)
    print(f"LOCALSGD {rank} {float(np.abs(w).sum()):.6f}", flush=True)


def run_dgc(rank):
    paddle.seed(0)
    model = nn.Linear(8, 4, bias_attr=False)   # 32 elems
    opt = DGCMomentum(learning_rate=0.02, momentum=0.9,
                      parameters=model.parameters(),
                      sparsity=[0.5], min_dgc_size=1)
    # fixed per-rank batch, SHARED target: descent on the summed
    # quadratic objective drives the average loss down deterministically
    rng = np.random.RandomState(200 + rank)
    x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    tgt = paddle.to_tensor(
        np.random.RandomState(999).randn(8, 4).astype(np.float32))
    losses = []
    for _ in range(6):
        loss = ((model(x) - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    w = np.asarray(model.weight.data)
    print(f"DGC {rank} {float(np.abs(w).sum()):.6f} "
          f"{losses[0]:.4f} {losses[-1]:.4f}", flush=True)


def run_bucketed_ddp(rank):
    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(20, 4, sparse=True)
            self.fc1 = nn.Linear(4, 16)
            self.fc2 = nn.Linear(16, 2)

        def forward(self, ids):
            h = paddle.mean(self.emb(ids), axis=1)
            return self.fc2(self.fc1(h))

    model = Net()
    # tiny buffer: every dense grad lands in its own bucket
    dp = DataParallel(model, comm_buffer_size=1e-6)
    rng = np.random.RandomState(300 + rank)
    ids = paddle.to_tensor(rng.randint(0, 20, (4, 3)).astype(np.int64))
    loss = dp(ids).sum()
    loss.backward()
    dp.apply_collective_grads()
    dense_sum = sum(float(np.asarray(p.grad.data).sum())
                    for n, p in model.named_parameters()
                    if not isinstance(p.grad, SelectedRows))
    emb_g = model.emb.weight.grad
    assert isinstance(emb_g, SelectedRows), type(emb_g)
    sparse_sum = float(emb_g.numpy().sum())
    print(f"DDP {rank} {dense_sum:.6f} {sparse_sum:.6f}", flush=True)


def main():
    env.init_parallel_env()
    rank, world = env.get_rank(), env.get_world_size()
    assert world == 2, f"expected 2 ranks, got {world}"
    run_localsgd(rank)
    run_dgc(rank)
    run_bucketed_ddp(rank)


if __name__ == "__main__":
    main()
