"""Custom-op extension API — register your own kernels as framework ops.

Reference: /root/reference/paddle/fluid/extension/ (ext_op_meta_info.h
PD_BUILD_OP / PD_BUILD_GRAD_OP macros, framework/custom_operator.cc
registration) + python/paddle/utils/cpp_extension.  There a user writes
a C++ kernel, compiles it, and the loader registers forward/backward ops.

TPU-native shape: a "kernel" is any jax-traceable function — jnp code or
a Pallas TPU kernel — so registration needs no compiler toolchain.
`register_op(name, forward, backward=...)` produces an op that:
- participates in the eager autograd tape (custom backward honored),
- traces into jit/to_static/SpmdTrainer steps like any built-in op,
- is discoverable via get_op(name) / list_ops().

The backward contract mirrors PD_BUILD_GRAD_OP: it receives the saved
forward inputs, the forward outputs, and the output cotangents, and
returns one gradient per forward input (None for non-differentiable
inputs).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax

from .core.autograd import apply

__all__ = ["register_op", "get_op", "list_ops", "CustomOp",
           "py_func"]

_REGISTRY: Dict[str, "CustomOp"] = {}


class CustomOp:
    """A registered custom operator (callable)."""

    def __init__(self, name: str, forward: Callable,
                 backward: Optional[Callable] = None):
        self.name = name
        self._forward = forward
        self._backward = backward
        if backward is not None:
            fwd = jax.custom_vjp(forward)

            def _fwd(*args):
                out = forward(*args)
                return out, (args, out)

            def _bwd(res, cots):
                args, out = res
                grads = backward(args, out, cots)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                if len(grads) != len(args):
                    raise ValueError(
                        f"custom op {name!r}: backward returned "
                        f"{len(grads)} grads for {len(args)} inputs")
                # None -> zero cotangent; integer/bool primals need the
                # float0 convention (an int-dtype zeros array would make
                # jax.vjp reject the rule)
                import numpy as np
                import jax.numpy as jnp

                def zero_for(a):
                    if jnp.issubdtype(jnp.result_type(a), jnp.inexact):
                        return jnp.zeros_like(a)
                    return np.zeros(jnp.shape(a), jax.dtypes.float0)

                return tuple(
                    zero_for(a) if g is None else g
                    for a, g in zip(args, grads))

            fwd.defvjp(_fwd, _bwd)
            self._traced = fwd
        else:
            self._traced = forward

    def __call__(self, *args, **kwargs):
        if kwargs:
            # static config args bind by closure, like attrs in the
            # reference's op attrs
            import functools
            fn = functools.partial(self._traced, **kwargs)
        else:
            fn = self._traced
        return apply(fn, *args, name=self.name)

    @property
    def raw(self) -> Callable:
        """The jax-level function (for use inside other jax code)."""
        return self._traced


def register_op(name: str, forward: Optional[Callable] = None,
                backward: Optional[Callable] = None):
    """Register a custom op. Usable directly or as a decorator:

        @register_op("fused_swiglu")
        def fused_swiglu(x, w): ...

        def gelu_grad(inputs, outputs, cotangents): ...
        op = register_op("my_gelu", my_gelu, backward=gelu_grad)
    """
    def _register(fn):
        if name in _REGISTRY:
            raise ValueError(f"custom op {name!r} already registered")
        op = CustomOp(name, fn, backward)
        _REGISTRY[name] = op
        return op

    if forward is None:
        return _register
    return _register(forward)


def get_op(name: str) -> CustomOp:
    if name not in _REGISTRY:
        raise KeyError(f"no custom op named {name!r}; registered: "
                       f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_ops():
    return sorted(_REGISTRY)


def py_func(func, x, out, backward_func=None, name="py_func"):
    """Run an arbitrary host-Python (numpy) function as a framework op —
    reference fluid.layers.py_func (py_func_op.cc): the escape hatch for
    logic with no device kernel.

    x: input Tensor or list; out: output template(s) — (shape, dtype)
    tuples or Tensors whose shape/dtype declare the result;
    backward_func(inputs, outputs, out_grads) -> per-input grads (numpy),
    optional.  The callback runs on the HOST even inside jit/to_static
    (jax.pure_callback), so it must be pure and shape-stable.
    """
    import numpy as np

    import jax.numpy as jnp

    from .core.tensor import Tensor

    xs = list(x) if isinstance(x, (list, tuple)) else [x]

    def is_template(o):
        if isinstance(o, Tensor):
            return True
        return (isinstance(o, (tuple, list)) and len(o) == 2 and
                isinstance(o[0], (tuple, list)) and
                (isinstance(o[1], str) or hasattr(o[1], "name")))

    # `out` is a LIST of templates only when it isn't itself one
    # ((shape, dtype) is a tuple too)
    multi = isinstance(out, (list, tuple)) and not is_template(out)
    outs = list(out) if multi else [out]

    def tmpl(o):
        if isinstance(o, Tensor):
            return jax.ShapeDtypeStruct(tuple(o.data.shape), o.data.dtype)
        shape, dtype = o
        return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))

    result_sdt = tuple(tmpl(o) for o in outs)

    def host_fwd(*arrs):
        res = func(*[np.asarray(a) for a in arrs])
        res = res if isinstance(res, (list, tuple)) else (res,)
        return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(res, result_sdt))

    def fwd_fn(*arrs):
        res = jax.pure_callback(host_fwd, result_sdt, *arrs)
        return tuple(res) if multi else res[0]

    if backward_func is None:
        return apply(fwd_fn, *xs, name=name)

    wrapped = jax.custom_vjp(fwd_fn)

    def _f(*arrs):
        o = wrapped(*arrs)
        return o, (arrs, tuple(o) if multi else (o,))

    def _b(res, cots):
        arrs, fwd_out = res
        cot_t = tuple(cots) if multi else (cots,)
        in_sdt = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                       for a in arrs)

        def host_bwd(*flat):
            n_in = len(arrs)
            n_out = len(fwd_out)
            ins = [np.asarray(v) for v in flat[:n_in]]
            outs_ = [np.asarray(v) for v in flat[n_in:n_in + n_out]]
            gs_ = [np.asarray(v) for v in flat[n_in + n_out:]]
            g = backward_func(ins, outs_, gs_)
            g = g if isinstance(g, (list, tuple)) else (g,)
            return tuple(
                np.zeros(s.shape, s.dtype) if gi is None
                else np.asarray(gi, dtype=s.dtype).reshape(s.shape)
                for gi, s in zip(g, in_sdt))

        return jax.pure_callback(host_bwd, in_sdt, *arrs, *fwd_out,
                                 *cot_t)

    wrapped.defvjp(_f, _b)
    return apply(wrapped, *xs, name=name)
