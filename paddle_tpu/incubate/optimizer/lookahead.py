"""LookAhead optimizer (k steps forward, 1 step back).

Reference: /root/reference/python/paddle/incubate/optimizer/lookahead.py
(LookAhead(inner_optimizer, alpha=0.5, k=5): every k inner-optimizer
steps the slow weights catch up, slow += alpha * (fast - slow), and the
fast weights restart from the slow ones).

TPU-native shape: the whole rule is part of the pure `_update`, so it
runs identically in the eager tape path and INSIDE a compiled SpmdTrainer
step — the slow copy is just one more optimizer-state leaf that shards
like the parameter (ZeRO-friendly by construction).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead"]


class LookAhead(Optimizer):
    def __init__(self, inner_optimizer: Optimizer, alpha: float = 0.5,
                 k: int = 5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError(
                "inner_optimizer must be a paddle_tpu Optimizer, got "
                f"{type(inner_optimizer).__name__}")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        # take over the inner optimizer's learning rate / clip / decay at
        # THIS level (the base class applies clip + coupled decay before
        # _update; the inner _update is called raw, so nothing doubles up)
        super().__init__(learning_rate=inner_optimizer._lr,
                         parameters=inner_optimizer._parameters,
                         weight_decay=None,
                         grad_clip=inner_optimizer._grad_clip,
                         name=name)
        self._weight_decay = inner_optimizer._weight_decay
        self._lr_scheduler = inner_optimizer._lr_scheduler

    @property
    def _decoupled_wd(self):
        return self.inner_optimizer._decoupled_wd

    def _init_accumulators(self, param):
        accs = self.inner_optimizer._init_accumulators(param)
        if "slow" in accs:
            raise RuntimeError(
                "inner optimizer already has a 'slow' accumulator")
        # slow weights start at the initial params; materialize a COPY —
        # aliasing the param buffer breaks compiled trainers that donate
        # both params and optimizer state to the step executable
        accs["slow"] = jnp.array(param, copy=True)
        return accs

    def _update(self, p, g, state, lr, step):
        inner_state = {n: a for n, a in state.items() if n != "slow"}
        # per-param hooks (AdamW apply_decay_param_fun etc.) must see the
        # same context in the inner rule
        self.inner_optimizer._cur_param_name = self._cur_param_name
        self.inner_optimizer._cur_param = self._cur_param
        fast, new_inner = self.inner_optimizer._update(
            p, g, inner_state, lr, step)
        slow = state["slow"]
        sync = (step % self.k) == 0
        caught_up = slow + self.alpha * (fast.astype(slow.dtype) - slow)
        new_slow = jnp.where(sync, caught_up, slow)
        new_p = jnp.where(sync, caught_up.astype(fast.dtype), fast)
        new_inner["slow"] = new_slow
        return new_p, new_inner

    def get_lr(self):
        return self.inner_optimizer.get_lr()
