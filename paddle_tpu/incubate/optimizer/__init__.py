from .lookahead import LookAhead  # noqa: F401
from .modelaverage import ModelAverage  # noqa: F401

__all__ = ["LookAhead", "ModelAverage"]
