"""ModelAverage — sliding-window parameter averaging for evaluation.

Reference: /root/reference/python/paddle/incubate/optimizer/modelaverage.py
(window rule) + paddle/fluid/operators/average_accumulates_op.h:80-106
(the exact accumulator shift rule, reproduced here as pure jnp):

- every step: sum_1 += param; num_updates += 1; num_accumulates += 1
- every 16384 updates, fold sum_1 into sum_2 (precision: keep any single
  running sum short)
- when num_accumulates >= min_average_window and
  num_accumulates >= min(max_average_window,
                         num_updates * average_window_rate):
  discard the old window: sum_3 = sum_1 + sum_2; sum_1 = sum_2 = 0;
  old_num_accumulates = num_accumulates; num_accumulates = 0
- apply(): param <- (sum_1 + sum_2 + sum_3) /
                    max(num_accumulates + old_num_accumulates, 1)

The rule is a pure `_update` (jnp.where on traced ints), so it runs in
eager `step()` and inside compiled steps alike.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...optimizer.optimizer import Optimizer

__all__ = ["ModelAverage"]

_FOLD_EVERY = 16384  # kMaxNumAccumulates in average_accumulates_op.h


class ModelAverage(Optimizer):
    def __init__(self, average_window_rate: float, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        super().__init__(learning_rate=0.0, parameters=parameters,
                         name=name)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._restore_values = None

    def _init_accumulators(self, param):
        f32 = jnp.float32
        return {
            "sum_1": jnp.zeros(param.shape, f32),
            "sum_2": jnp.zeros(param.shape, f32),
            "sum_3": jnp.zeros(param.shape, f32),
            "num_accumulates": jnp.zeros((), jnp.int32),
            "old_num_accumulates": jnp.zeros((), jnp.int32),
            "num_updates": jnp.zeros((), jnp.int32),
        }

    def _update(self, p, g, state, lr, step):
        nu = state["num_updates"] + 1
        na = state["num_accumulates"] + 1
        ona = state["old_num_accumulates"]
        s1 = state["sum_1"] + p.astype(jnp.float32)
        s2, s3 = state["sum_2"], state["sum_3"]

        fold = (nu % _FOLD_EVERY) == 0
        s2 = jnp.where(fold, s2 + s1, s2)
        s1 = jnp.where(fold, jnp.zeros_like(s1), s1)

        window = jnp.minimum(
            jnp.asarray(self.max_average_window, jnp.int32),
            (nu.astype(jnp.float32) * self.average_window)
            .astype(jnp.int32))
        shift = (na >= self.min_average_window) & (na >= window)
        s3 = jnp.where(shift, s1 + s2, s3)
        s1 = jnp.where(shift, jnp.zeros_like(s1), s1)
        s2 = jnp.where(shift, jnp.zeros_like(s2), s2)
        ona = jnp.where(shift, na, ona)
        na = jnp.where(shift, jnp.zeros_like(na), na)

        new_state = {"sum_1": s1, "sum_2": s2, "sum_3": s3,
                     "num_accumulates": na.astype(jnp.int32),
                     "old_num_accumulates": ona.astype(jnp.int32),
                     "num_updates": nu.astype(jnp.int32)}
        return p, new_state  # accumulation never moves the live params

    # ModelAverage accumulates from the params themselves, so (unlike a
    # real optimizer) it must run even after grads were cleared
    def step(self):
        for p in self._parameters or []:
            if not p.trainable:
                continue
            key = p.name
            if key not in self._accumulators:
                self._accumulators[key] = self._init_accumulators(p.data)
            _, self._accumulators[key] = self._update(
                p.data, None, self._accumulators[key], 0.0,
                self._step_count + 1)
        self._step_count += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, []

    def _averaged(self, p, accs):
        total = accs["sum_1"] + accs["sum_2"] + accs["sum_3"]
        count = jnp.maximum(
            accs["num_accumulates"] + accs["old_num_accumulates"], 1)
        return (total / count.astype(jnp.float32)).astype(p.data.dtype)

    @contextmanager
    def apply(self, need_restore: bool = True):
        """Swap the averaged values into the live parameters (reference
        ModelAverage.apply context manager)."""
        if self._restore_values is not None:
            raise RuntimeError("ModelAverage.apply() calls cannot nest")
        self._restore_values = {}
        for p in self._parameters or []:
            accs = self._accumulators.get(p.name)
            if accs is None:
                continue
            self._restore_values[p.name] = p.data
            p._data = self._averaged(p, accs)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        """Put the pre-apply() parameter values back."""
        if self._restore_values is None:
            return
        for p in self._parameters or []:
            if p.name in self._restore_values:
                p._data = self._restore_values[p.name]
        self._restore_values = None

    def state_dict(self):
        sd = {}
        for pname, accs in self._accumulators.items():
            for aname, arr in accs.items():
                sd[f"{pname}@{aname}"] = Tensor(arr)
        sd["@step_count"] = self._step_count
        return sd
