"""paddle.incubate — experimental API surface.

Reference: /root/reference/python/paddle/incubate/__init__.py (exposes
LookAhead + ModelAverage from incubate.optimizer).
"""
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["optimizer", "LookAhead", "ModelAverage"]
