"""GPT model family — the flagship decoder-only transformer.

Reference parity target: the GPT configs the driver benchmarks
(/root/repo/BASELINE.json config #4: GPT-3 1.3B/13B under Fleet hybrid
parallel; the reference repo itself ships the transformer building blocks
at python/paddle/nn/layer/transformer.py — no in-tree GPT — so the
architecture here is the standard GPT-3 decoder written TPU-first).

TPU-first design decisions:
- weights live in tensor-parallel layers (ColumnParallelLinear /
  RowParallelLinear / VocabParallelEmbedding) whose PartitionSpecs the
  compiled trainer (distributed.spmd.SpmdTrainer) hands to GSPMD: the
  attention qkv + mlp-up projections shard over 'tp' columns, the output
  projections shard over 'tp' rows — Megatron placement, one all-reduce
  per block half, riding ICI;
- attention routes through the Pallas flash-attention kernel when shapes
  allow (paddle_tpu.ops.flash_attention), XLA composite otherwise;
- `enable_recompute()` wraps every block in jax.checkpoint (remat), the
  strategy.recompute hook the trainer calls;
- static shapes everywhere; position ids are an iota baked at trace time.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Dropout, Embedding
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..tensor.manipulation import concat, repeat_interleave
from ..tensor.math import matmul
from ..distributed.parallel_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    mark_sharding)
from ..distributed.mesh import PartitionSpec
from ..distributed.recompute import RecomputeWrapper

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_configs"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None -> MHA
    ffn_hidden_size: Optional[int] = None  # None -> 4*hidden
    max_seq_len: int = 1024
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    # fused LM loss: during training the model returns (hidden, wte) so
    # the criterion can run the blocked cross-entropy over vocab chunks
    # (ops.fused_cross_entropy) — the [B, S, V] logits tensor is never
    # materialized. Requires tie_word_embeddings.
    fused_ce: bool = False
    tp_axis: str = "tp"
    # MoE (0 experts = dense; BASELINE.json config #5 switch-transformer)
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_every_n_layers: int = 1   # every Nth block is MoE
    moe_aux_loss_coeff: float = 0.01
    moe_z_loss_coeff: float = 0.0
    ep_axis: str = "ep"
    # sequence/context parallelism: ring attention over the 'sp' axis
    sequence_parallel: bool = False
    sp_axis: str = "sp"

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads

    def is_moe_layer(self, layer_idx: int) -> bool:
        return (self.moe_num_experts > 0 and
                (layer_idx + 1) % self.moe_every_n_layers == 0)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    def num_params(self, include_embeddings=True):
        h, l, v = self.hidden_size, self.num_layers, self.vocab_size
        # qkv (h*(h+2*kv)) + out (h*h) + mlp (2*h*ffn) + biases/norms
        kv_dim = self.num_kv_heads * self.head_dim
        per_block = h * (h + 2 * kv_dim) + h * h + \
            2 * h * self.ffn_hidden_size + 13 * h
        total = l * per_block + 2 * h  # final norm
        if include_embeddings:
            total += v * h + self.max_seq_len * h
        return int(total)

    def flops_per_token(self, seq_len=None):
        """Model FLOPs per token (fwd+bwd, 6N + attention quadratic term)
        — the MFU formula used by bench.py."""
        s = seq_len or self.max_seq_len
        n = self.num_params(include_embeddings=False)
        return 6 * n + 12 * self.num_layers * self.hidden_size * s


def gpt_configs():
    """Named configs; 1.3b/13b are the BASELINE.json targets."""
    return {
        "gpt3-tiny": GPTConfig(vocab_size=512, hidden_size=128,
                               num_layers=2, num_heads=4, max_seq_len=256),
        "gpt3-125m": GPTConfig(hidden_size=768, num_layers=12,
                               num_heads=12, max_seq_len=2048),
        "gpt3-350m": GPTConfig(hidden_size=1024, num_layers=24,
                               num_heads=16, max_seq_len=2048),
        "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24,
                               num_heads=16, max_seq_len=2048),
        "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32,
                               num_heads=32, max_seq_len=2048),
        "gpt3-13b": GPTConfig(hidden_size=5120, num_layers=40,
                              num_heads=40, max_seq_len=2048),
    }


class GPTAttention(Layer):
    """Causal self-attention, Megatron-sharded: fused qkv column-parallel
    (heads shard over tp), output row-parallel (one all-reduce)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        kv_dim = config.num_kv_heads * config.head_dim
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.qkv_proj = ColumnParallelLinear(
            h, h + 2 * kv_dim, weight_attr=init, has_bias=True,
            gather_output=False, axis_name=config.tp_axis)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=init, has_bias=True, input_is_parallel=True,
            axis_name=config.tp_axis)
        self.dropout = Dropout(config.dropout)

    def _sp_active(self, b, s) -> bool:
        """True when an ambient mesh (bound by the compiled trainer while
        tracing) carries a real 'sp' axis AND the shapes divide evenly —
        ragged batches fall back to dense attention instead of crashing
        the shard_map."""
        from ..distributed.mesh import get_mesh
        m = get_mesh()
        if (m is None or self.cfg.sp_axis not in m.axis_names or
                m.shape[self.cfg.sp_axis] <= 1):
            return False
        if s % m.shape[self.cfg.sp_axis]:
            return False
        dp = m.shape.get("dp", 1) if "dp" in m.axis_names else 1
        return b % dp == 0

    def forward(self, x, attn_mask=None, cache=None):
        cfg = self.cfg
        b = x.shape[0]
        s = x.shape[1]
        qkv = self.qkv_proj(x)
        h_dim = cfg.hidden_size
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        q = qkv[:, :, :h_dim].reshape(
            [b, s, cfg.num_heads, cfg.head_dim])
        k = qkv[:, :, h_dim:h_dim + kv_dim].reshape(
            [b, s, cfg.num_kv_heads, cfg.head_dim])
        v = qkv[:, :, h_dim + kv_dim:].reshape(
            [b, s, cfg.num_kv_heads, cfg.head_dim])

        new_cache = None
        if cache is not None:
            # decode: append to the kv cache (generation path)
            pk, pv = cache
            k = concat([pk, k], axis=1) if pk is not None else k
            v = concat([pv, v], axis=1) if pv is not None else v
            new_cache = (k, v)

        # Any multi-token call is causal — including prefill with a cache
        # (the composite's bottom-right-aligned mask lets query i see keys
        # <= past + i). Only single-token decode attends unmasked.
        causal = s > 1
        empty_cache = cache is None or cache[0] is None
        if (cfg.sequence_parallel and attn_mask is None and empty_cache
                and self._sp_active(b, s)):
            # ring attention: seq dim sharded over 'sp', KV blocks rotate
            # around the ICI ring (distributed/ring_attention.py). K/V go
            # in UN-expanded (GQA): the ring rotates Hkv heads, not H.
            from ..distributed.ring_attention import \
                sequence_parallel_attention
            if cfg.attn_dropout and self.training:
                raise NotImplementedError(
                    "attn_dropout inside ring attention is not supported")
            out = sequence_parallel_attention(
                q, k, v, sp_axis=cfg.sp_axis, causal=causal)
            out = out.reshape([b, s, -1])
            out = self.out_proj(out)
            out = self.dropout(out)
            return (out, new_cache) if cache is not None else out

        if cfg.use_flash_attention and attn_mask is None and empty_cache:
            # GQA goes in un-expanded: the Pallas kernel walks kv-head
            # groups on its grid, never materializing repeated K/V
            out = F.flash_attention(q, k, v, dropout=cfg.attn_dropout,
                                    causal=causal,
                                    training=self.training)
        else:
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                k = repeat_interleave(k, rep, axis=2)
                v = repeat_interleave(v, rep, axis=2)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=cfg.attn_dropout, is_causal=causal,
                training=self.training)
        out = out.reshape([b, s, -1])
        out = self.out_proj(out)
        out = self.dropout(out)
        return (out, new_cache) if cache is not None else out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        out_init = ParamAttr(initializer=I.Normal(
            0.0, config.initializer_range / math.sqrt(
                2.0 * config.num_layers)))
        self.up_proj = ColumnParallelLinear(
            config.hidden_size, config.ffn_hidden_size, weight_attr=init,
            gather_output=False, axis_name=config.tp_axis)
        self.down_proj = RowParallelLinear(
            config.ffn_hidden_size, config.hidden_size,
            weight_attr=out_init, input_is_parallel=True,
            axis_name=config.tp_axis)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.down_proj(F.gelu(self.up_proj(x),
                                                  approximate=True)))


class GPTBlock(Layer):
    """Pre-LN decoder block (GPT-2/3 style). When the config marks this
    layer index as MoE the dense MLP is replaced by an expert-parallel
    MoELayer (switch-transformer block; BASELINE.json config #5)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        if config.is_moe_layer(layer_idx):
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(
                config.hidden_size, config.ffn_hidden_size,
                num_experts=config.moe_num_experts,
                top_k=config.moe_top_k,
                capacity_factor=config.moe_capacity_factor,
                aux_loss_coeff=config.moe_aux_loss_coeff,
                z_loss_coeff=config.moe_z_loss_coeff,
                ep_axis=config.ep_axis,
                weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range)),
                # depth-scaled residual-out init, same as GPTMLP.down_proj
                down_weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range / math.sqrt(
                        2.0 * config.num_layers))))
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None):
        x = x + self.attn(self.ln_1(x), attn_mask=attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(Layer):
    """Embeddings + N blocks + final norm. Returns hidden states."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        self.wte = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)),
            axis_name=config.tp_axis)
        self.wpe = Embedding(config.max_seq_len, config.hidden_size,
                             weight_attr=ParamAttr(initializer=I.Normal(
                                 0.0, config.initializer_range)))
        self.drop = Dropout(config.dropout)
        self.blocks = LayerList([GPTBlock(config, layer_idx=i)
                                 for i in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self._recompute = False
        self._scan_layers = False

    def enable_recompute(self, policy=None):
        """strategy.recompute hook: remat every block. Applied in
        forward() (not by re-wrapping sublayers) so parameter names —
        and therefore state dicts/checkpoints — are unchanged.

        policy: jax.checkpoint_policies name ('dots', 'dots_no_batch',
        ...) — selective save policies keep matmul outputs and only
        recompute the cheap elementwise ops, recovering most of the remat
        FLOPs vs full recompute (None)."""
        self._recompute = True
        self._recompute_policy = policy
        return self

    def enable_scan_layers(self, flag: bool = True):
        """Run the block stack as ONE jax.lax.scan over per-layer
        stacked parameters instead of a Python loop: the transformer
        body is traced (and XLA-compiled) once regardless of depth, so
        compile time drops from O(layers) to O(1) traced bodies, and
        per-iteration jax.checkpoint gives per-layer remat under the
        active recompute policy. Parameters stay per-layer Tensors
        (state dicts/checkpoints unchanged); the stacking happens at
        trace time. Falls back to the unrolled loop when the stack is
        not scannable (MoE blocks, live dropout, attention masks)."""
        self._scan_layers = bool(flag)
        return self

    def _scan_ok(self, attn_mask) -> bool:
        cfg = self.cfg
        if (not self._scan_layers or attn_mask is not None
                or len(self.blocks) < 2):
            return False
        if cfg.moe_num_experts > 0 or cfg.sequence_parallel:
            return False  # heterogeneous blocks / shard_map inside scan
        if self.training and (cfg.dropout > 0 or cfg.attn_dropout > 0):
            return False  # one traced body would share dropout masks
        if any(b is not None for _, b in self.blocks[0].named_buffers()):
            return False
        return True

    def _forward_blocks_scanned(self, x):
        from ..distributed.recompute import checkpoint_policy
        from ..func import functional_call
        blk0 = self.blocks[0]
        names = [n for n, _ in blk0.named_parameters()]
        n_names = len(names)
        n_layers = len(self.blocks)
        flat = [dict(blk.named_parameters())[n]
                for blk in self.blocks for n in names]
        use_remat = self._recompute and self.training
        pol = checkpoint_policy(getattr(self, "_recompute_policy", None)) \
            if use_remat else None

        def scan_fn(h, *flat_arrs):
            stacked = {
                name: jnp.stack([flat_arrs[b * n_names + j]
                                 for b in range(n_layers)])
                for j, name in enumerate(names)}

            def body(carry, layer_params):
                out, _ = functional_call(blk0, layer_params, {}, carry)
                return out, None

            if use_remat:
                # prevent_cse=False: scan bodies don't need the CSE
                # barrier, and it costs performance
                body = jax.checkpoint(body, policy=pol,
                                      prevent_cse=False)
            out, _ = jax.lax.scan(body, h, stacked)
            return out

        from ..core.autograd import apply
        return apply(scan_fn, x, *flat, name="gpt_scan_layers")

    def forward(self, input_ids, attn_mask=None):
        from ..distributed.recompute import recompute as _rc
        s = input_ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if self._scan_ok(attn_mask):
            return self.ln_f(self._forward_blocks_scanned(x))
        for blk in self.blocks:
            if self._recompute and self.training:
                # mask passed positionally so the checkpointed region
                # treats it as a traced input
                pol = getattr(self, "_recompute_policy", None)
                x = _rc(blk, x, policy=pol) if attn_mask is None else \
                    _rc(blk, x, attn_mask, policy=pol)
            else:
                x = blk(x) if attn_mask is None else blk(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head on top; logits share the (vocab-sharded) embedding matrix
    when tie_word_embeddings (GPT-3 convention)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range)),
                has_bias=False, gather_output=True,
                axis_name=config.tp_axis)

    def enable_recompute(self, policy=None):
        self.gpt.enable_recompute(policy=policy)
        return self

    def enable_scan_layers(self, flag: bool = True):
        self.gpt.enable_scan_layers(flag)
        return self

    def _tp_size(self) -> int:
        from ..distributed.mesh import get_mesh
        m = get_mesh()
        if m is None or self.cfg.tp_axis not in m.axis_names:
            return 1
        return m.shape[self.cfg.tp_axis]

    def forward(self, input_ids, attn_mask=None):
        x = self.gpt(input_ids, attn_mask=attn_mask)
        if (self.cfg.fused_ce and self.training
                and self.cfg.tie_word_embeddings
                and self._tp_size() == 1):
            # blocked-CE training path: hand (hidden, lm weight) to the
            # criterion instead of projecting to [B, S, V] logits — the
            # projection happens inside the fused loss, vocab chunk by
            # vocab chunk (eval/generation still produce full logits).
            # Skipped on tp>1 meshes: the blocked loop's dynamic vocab
            # slices would force GSPMD to all-gather the vocab-sharded
            # LM head every step, costing more than the logits save
            return x, self.gpt.wte.weight
        if self.cfg.tie_word_embeddings:
            w = self.gpt.wte.weight  # [V, H], vocab-sharded over tp
            logits = matmul(x, w, transpose_y=True)
        else:
            logits = self.lm_head(x)
        return logits


class GPTEmbeddingStage(Layer):
    """Pipeline 'pre' stage: token + position embedding (shares the
    underlying parameters with the source model)."""

    def __init__(self, wte, wpe, drop):
        super().__init__()
        self.wte, self.wpe, self.drop = wte, wpe, drop

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class GPTHeadStage(Layer):
    """Pipeline 'post' stage: final norm + untied LM head."""

    def __init__(self, ln_f, lm_head):
        super().__init__()
        self.ln_f, self.lm_head = ln_f, lm_head

    def forward(self, h):
        return self.lm_head(self.ln_f(h))


def gpt_pipeline_parts(model: "GPTForCausalLM"):
    """Split a GPTForCausalLM into (pre, blocks, post) stage views for
    GPipeTrainer — the analogue of the reference PipelineOptimizer's
    program split by op_device (fluid/optimizer.py:3718), but the split
    is BY CONSTRUCTION (embedding / N identical blocks / head) instead
    of by annotation. Requires tie_word_embeddings=False: tied weights
    would put one parameter on two pipeline stages."""
    if model.cfg.tie_word_embeddings:
        raise ValueError(
            "pipeline parallelism needs tie_word_embeddings=False (tied "
            "embedding+head would live on both the first and last stage)")
    pre = GPTEmbeddingStage(model.gpt.wte, model.gpt.wpe, model.gpt.drop)
    post = GPTHeadStage(model.gpt.ln_f, model.lm_head)
    return pre, list(model.gpt.blocks), post


class GPTPretrainingCriterion(Layer):
    """Shifted-token cross entropy with optional loss mask (the reference
    trains GPT with a masked LM loss over ignored pad positions)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels, loss_mask=None):
        # logits: [B, S, V]; labels: [B, S] already shifted by the data
        # pipeline (labels[t] = input_ids[t+1]). With config.fused_ce
        # the model hands over (hidden [B, S, H], lm weight [V, H])
        # instead and the loss runs blockwise over the vocab without
        # ever materializing the logits tensor.
        flat_labels = labels.reshape([-1])
        if isinstance(logits, (tuple, list)) and len(logits) == 2:
            hidden, w = logits
            h = hidden.shape[-1]
            losses = F.fused_linear_cross_entropy(
                hidden.reshape([-1, h]), w, flat_labels,
                reduction="none", ignore_index=self.ignore_index)
        else:
            v = logits.shape[-1]
            losses = F.cross_entropy(logits.reshape([-1, v]), flat_labels,
                                     reduction="none",
                                     ignore_index=self.ignore_index)
        if loss_mask is not None:
            m = loss_mask.reshape([-1]).astype("float32")
            return (losses.reshape([-1]) * m).sum() / m.sum()
        return losses.mean()
