"""GPT model family — the flagship decoder-only transformer.

Reference parity target: the GPT configs the driver benchmarks
(/root/repo/BASELINE.json config #4: GPT-3 1.3B/13B under Fleet hybrid
parallel; the reference repo itself ships the transformer building blocks
at python/paddle/nn/layer/transformer.py — no in-tree GPT — so the
architecture here is the standard GPT-3 decoder written TPU-first).

TPU-first design decisions:
- weights live in tensor-parallel layers (ColumnParallelLinear /
  RowParallelLinear / VocabParallelEmbedding) whose PartitionSpecs the
  compiled trainer (distributed.spmd.SpmdTrainer) hands to GSPMD: the
  attention qkv + mlp-up projections shard over 'tp' columns, the output
  projections shard over 'tp' rows — Megatron placement, one all-reduce
  per block half, riding ICI;
- attention routes through the Pallas flash-attention kernel when shapes
  allow (paddle_tpu.ops.flash_attention), XLA composite otherwise;
- `enable_recompute()` wraps every block in jax.checkpoint (remat), the
  strategy.recompute hook the trainer calls;
- static shapes everywhere; position ids are an iota baked at trace time.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Dropout, Embedding
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..tensor.manipulation import repeat_interleave
from ..tensor.math import matmul
from ..distributed.parallel_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    mark_sharding)
from ..distributed.mesh import PartitionSpec
from ..distributed.recompute import RecomputeWrapper

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_configs", "StaticKVCache"]


class StaticKVCache:
    """Preallocated serving KV cache: ``k``/``v`` are
    ``[layers, batch_slots, max_seq, kv_heads, head_dim]`` and
    ``lengths`` is ``[batch_slots]`` int32 — valid tokens per slot.

    Statically shaped on purpose (Pope et al., *Efficiently Scaling
    Transformer Inference*): every prefill/decode executable sees the
    same cache shape, so generating N tokens never changes a shape and
    never recompiles.  All updates are functional (`lax.dynamic_update_
    slice` / scatter); under jit with donated cache operands XLA turns
    them into true in-place writes.  Registered as a pytree so it rides
    through jit/scan/while_loop carries.

    Quantized form (``kv_dtype='int8'``/``'fp8'`` in init_kv_cache):
    ``k``/``v`` hold 8-bit values and ``k_scale``/``v_scale`` the
    per-(position, head) f32 scales
    ``[layers, batch_slots, max_seq, kv_heads]`` — decode streams half
    the bytes and dequantizes inside the fused attention kernel.  The
    fp cache (``k_scale is None``) stays the default and the parity
    oracle; shapes are static either way, so the zero-recompile
    contract is unchanged.
    """

    __slots__ = ("k", "v", "lengths", "k_scale", "v_scale")

    def __init__(self, k, v, lengths, k_scale=None, v_scale=None):
        self.k, self.v, self.lengths = k, v, lengths
        self.k_scale, self.v_scale = k_scale, v_scale

    @property
    def num_layers(self):
        return self.k.shape[0]

    @property
    def batch_slots(self):
        return self.k.shape[1]

    @property
    def capacity(self):
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def __repr__(self):
        return (f"StaticKVCache(layers={self.k.shape[0]}, "
                f"slots={self.k.shape[1]}, capacity={self.k.shape[2]}, "
                f"kv_heads={self.k.shape[3]}, dtype={self.k.dtype}"
                f"{', quantized' if self.quantized else ''})")


jax.tree_util.register_pytree_node(
    StaticKVCache,
    lambda c: ((c.k, c.v, c.lengths, c.k_scale, c.v_scale), None),
    lambda aux, ch: StaticKVCache(*ch))


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None -> MHA
    ffn_hidden_size: Optional[int] = None  # None -> 4*hidden
    max_seq_len: int = 1024
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    # fused LM loss: during training the model returns (hidden, wte) so
    # the criterion can run the blocked cross-entropy over vocab chunks
    # (ops.fused_cross_entropy) — the [B, S, V] logits tensor is never
    # materialized. Requires tie_word_embeddings.
    fused_ce: bool = False
    # AQT-style quantized compute: 'int8' (or 'fp8' where this jax has
    # float8) routes every block linear (qkv/out/up/down projections)
    # through ops.fake_quant_matmul — quantized forward, straight-
    # through backward — so training sees (and adapts to) quantization
    # noise while optimizer/params stay fp32/bf16.  Embeddings and the
    # LM head stay full precision (the standard sensitivity split).
    # None (default) keeps every path bitwise-identical to unquantized.
    quantize: Optional[str] = None
    # serving: fuse each layer's WHOLE decode step (attention over the
    # KV cache + new-token fold + out proj + residual + LayerNorm + MLP)
    # into one Pallas kernel (ops.decode_megakernel) — intermediates
    # stay in VMEM, no HBM round-trips between sub-ops.  Off (default)
    # keeps the composed kernels path, which remains the parity oracle;
    # PADDLE_TPU_DECODE_MEGAKERNEL overrides at trace time.  On CPU the
    # fused op lowers to an XLA composite that matches the composed
    # path op for op, so the flag is safe everywhere.
    decode_megakernel: bool = False
    tp_axis: str = "tp"
    # MoE (0 experts = dense; BASELINE.json config #5 switch-transformer)
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_every_n_layers: int = 1   # every Nth block is MoE
    moe_aux_loss_coeff: float = 0.01
    moe_z_loss_coeff: float = 0.0
    ep_axis: str = "ep"
    # sequence/context parallelism: ring attention over the 'sp' axis
    sequence_parallel: bool = False
    sp_axis: str = "sp"

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.quantize is not None:
            from ..ops.quantized_matmul import _check_mode
            _check_mode(self.quantize)
            if self.moe_num_experts > 0:
                # the expert FFNs are raw einsums (distributed.moe), not
                # parallel linears — they would silently stay full
                # precision while bench reported quantize='int8'
                raise NotImplementedError(
                    f"quantize={self.quantize!r} COMPUTE with MoE is "
                    f"not supported: expert FFN matmuls (the dominant "
                    f"MoE FLOPs) have no quantized path yet, and "
                    f"quantizing only attention would misattribute the "
                    f"measured MFU. Quantized KV CACHES are orthogonal "
                    f"and do work with MoE engines — pass "
                    f"kv_dtype='int8' to InferenceEngine/init_kv_cache "
                    f"instead")

    def is_moe_layer(self, layer_idx: int) -> bool:
        return (self.moe_num_experts > 0 and
                (layer_idx + 1) % self.moe_every_n_layers == 0)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    def num_params(self, include_embeddings=True):
        h, l, v = self.hidden_size, self.num_layers, self.vocab_size
        # qkv (h*(h+2*kv)) + out (h*h) + mlp (2*h*ffn) + biases/norms
        kv_dim = self.num_kv_heads * self.head_dim
        per_block = h * (h + 2 * kv_dim) + h * h + \
            2 * h * self.ffn_hidden_size + 13 * h
        total = l * per_block + 2 * h  # final norm
        if include_embeddings:
            total += v * h + self.max_seq_len * h
        return int(total)

    def flops_per_token(self, seq_len=None):
        """Model FLOPs per token (fwd+bwd, 6N + attention quadratic term)
        — the MFU formula used by bench.py."""
        s = seq_len or self.max_seq_len
        n = self.num_params(include_embeddings=False)
        return 6 * n + 12 * self.num_layers * self.hidden_size * s


def gpt_configs():
    """Named configs; 1.3b/13b are the BASELINE.json targets."""
    return {
        "gpt3-tiny": GPTConfig(vocab_size=512, hidden_size=128,
                               num_layers=2, num_heads=4, max_seq_len=256),
        "gpt3-125m": GPTConfig(hidden_size=768, num_layers=12,
                               num_heads=12, max_seq_len=2048),
        "gpt3-350m": GPTConfig(hidden_size=1024, num_layers=24,
                               num_heads=16, max_seq_len=2048),
        "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24,
                               num_heads=16, max_seq_len=2048),
        "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32,
                               num_heads=32, max_seq_len=2048),
        "gpt3-13b": GPTConfig(hidden_size=5120, num_layers=40,
                              num_heads=40, max_seq_len=2048),
    }


class GPTAttention(Layer):
    """Causal self-attention, Megatron-sharded: fused qkv column-parallel
    (heads shard over tp), output row-parallel (one all-reduce)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        kv_dim = config.num_kv_heads * config.head_dim
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.qkv_proj = ColumnParallelLinear(
            h, h + 2 * kv_dim, weight_attr=init, has_bias=True,
            gather_output=False, axis_name=config.tp_axis,
            quantize=config.quantize)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=init, has_bias=True, input_is_parallel=True,
            axis_name=config.tp_axis, quantize=config.quantize)
        self.dropout = Dropout(config.dropout)

    def _sp_active(self, b, s) -> bool:
        """True when an ambient mesh (bound by the compiled trainer while
        tracing) carries a real 'sp' axis AND the shapes divide evenly —
        ragged batches fall back to dense attention instead of crashing
        the shard_map."""
        from ..distributed.mesh import get_mesh
        m = get_mesh()
        if (m is None or self.cfg.sp_axis not in m.axis_names or
                m.shape[self.cfg.sp_axis] <= 1):
            return False
        if s % m.shape[self.cfg.sp_axis]:
            return False
        dp = m.shape.get("dp", 1) if "dp" in m.axis_names else 1
        return b % dp == 0

    def _qkv_arrays(self, x):
        """qkv projection split into raw arrays q [B,S,H,D],
        k/v [B,S,Hkv,D].  Inference-path helper: reading ``.data``
        detaches from the eager autograd tape, which is why
        ``forward`` keeps its own Tensor-level split (training grads
        flow through the tape there)."""
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x if isinstance(x, Tensor) else Tensor(x))
        arr = qkv.data
        h_dim = cfg.hidden_size
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        q = arr[:, :, :h_dim].reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = arr[:, :, h_dim:h_dim + kv_dim].reshape(
            b, s, cfg.num_kv_heads, cfg.head_dim)
        v = arr[:, :, h_dim + kv_dim:].reshape(
            b, s, cfg.num_kv_heads, cfg.head_dim)
        return q, k, v

    def _proj_out(self, out_arr, b, s):
        out = Tensor(out_arr.reshape(b, s, -1))
        return self.dropout(self.out_proj(out))

    @staticmethod
    def _upgrade_cache(cache, b, hkv, d, cap, dtype):
        """Adopt any accepted cache form into the fixed-capacity triple
        ``(k_buf [B, cap, Hkv, D], v_buf, length)``.

        Accepted: the triple itself; the legacy 2-tuple ``(pk, pv)`` of
        dense past keys/values (padded into a fresh buffer — its static
        `past` length stays static, so adopting is compile-stable); and
        ``(None, None)`` / empty to start a fresh buffer.  The fixed
        capacity is what kills the per-token recompile: the old concat
        path changed the cache shape every generated token, forcing XLA
        to recompile each step and copy O(n²) bytes.
        """
        if len(cache) == 3:
            k_buf, v_buf, length = cache
            k_buf = k_buf.data if isinstance(k_buf, Tensor) else k_buf
            v_buf = v_buf.data if isinstance(v_buf, Tensor) else v_buf
            return k_buf, v_buf, length
        pk, pv = cache
        k_buf = jnp.zeros((b, cap, hkv, d), dtype)
        v_buf = jnp.zeros((b, cap, hkv, d), dtype)
        if pk is None:
            return k_buf, v_buf, 0
        pk = pk.data if isinstance(pk, Tensor) else jnp.asarray(pk)
        pv = pv.data if isinstance(pv, Tensor) else jnp.asarray(pv)
        k_buf = jax.lax.dynamic_update_slice(
            k_buf, pk.astype(dtype), (0, 0, 0, 0))
        v_buf = jax.lax.dynamic_update_slice(
            v_buf, pv.astype(dtype), (0, 0, 0, 0))
        return k_buf, v_buf, int(pk.shape[1])

    def _attend_fresh(self, q, k, v, b, s):
        """No-past causal attention on raw arrays — the same
        ring/flash/composite routing as the no-cache forward, shared by
        forward_prefill and the fresh-cache legacy path.  Returns raw
        [b, s, H, D]."""
        cfg = self.cfg
        causal = s > 1
        if cfg.sequence_parallel and self._sp_active(b, s):
            from ..distributed.ring_attention import \
                sequence_parallel_attention
            out = sequence_parallel_attention(
                Tensor(q), Tensor(k), Tensor(v), sp_axis=cfg.sp_axis,
                causal=causal)
            return out.data if isinstance(out, Tensor) else out
        if cfg.use_flash_attention:
            return F.flash_attention(Tensor(q), Tensor(k), Tensor(v),
                                     causal=causal, training=False).data
        kf, vf = k, v
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            kf = jnp.repeat(kf, rep, axis=2)
            vf = jnp.repeat(vf, rep, axis=2)
        return F.scaled_dot_product_attention(
            Tensor(q), Tensor(kf), Tensor(vf), is_causal=causal,
            training=False).data

    def _forward_with_cache(self, x, cache):
        """Fixed-capacity cached attention (the legacy ``cache=`` path,
        now recompile-free): write the s new tokens at ``length``, attend
        query i (absolute position length+i) against buffer keys
        ``j <= length + i``.  Single-token calls run the fused decode
        kernel (ops.decode_attention); a fresh cache's multi-token
        prefill keeps the ring/flash fast path.  Returns
        ``(out, (k_buf, v_buf, new_length))``.

        The buffer capacity is ``cfg.max_seq_len``; exceeding it raises
        in eager use (concrete length).  Under jit the length is traced
        and cannot be checked — writes past capacity clamp to the last
        position (callers must bound generation, as the engine does)."""
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        cap = cfg.max_seq_len
        q, k, v = self._qkv_arrays(x)
        k_buf, v_buf, length = self._upgrade_cache(
            cache, b, cfg.num_kv_heads, cfg.head_dim, cap, q.dtype)
        try:
            concrete_len = int(length)
        except Exception:  # traced inside jit/scan: unverifiable
            concrete_len = None
        if concrete_len is not None and concrete_len + s > cap:
            raise ValueError(
                f"kv cache overflow: {concrete_len} cached + {s} new "
                f"tokens > capacity {cap} (cfg.max_seq_len) — the old "
                f"concat cache grew past this silently; the static "
                f"cache cannot")
        # same offset for every row (the legacy API is uniform-length;
        # per-slot offsets live in StaticKVCache/forward_decode)
        k_buf = jax.lax.dynamic_update_slice(
            k_buf, k.astype(k_buf.dtype), (0, length, 0, 0))
        v_buf = jax.lax.dynamic_update_slice(
            v_buf, v.astype(v_buf.dtype), (0, length, 0, 0))
        new_len = length + s
        if s == 1:
            from .. import ops as _ops
            lens = jnp.broadcast_to(
                jnp.asarray(new_len, jnp.int32), (b,))
            out = _ops.decode_attention(
                q[:, 0].astype(k_buf.dtype), k_buf, v_buf, lens)
            out = out[:, None].astype(q.dtype)          # [b, 1, H, D]
        elif concrete_len == 0:
            # fresh-cache prefill: nothing valid in the buffer yet, so
            # this IS plain causal attention — keep the ring/flash path
            # instead of a [s, cap] masked composite
            out = self._attend_fresh(q, k, v, b, s)
        else:
            kf, vf = k_buf, v_buf
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                kf = jnp.repeat(kf, rep, axis=2)
                vf = jnp.repeat(vf, rep, axis=2)
            # bool mask [1, 1, s, cap]: query i sees keys j <= length+i
            mask = (jnp.arange(cap)[None, :] <=
                    (jnp.asarray(length) + jnp.arange(s))[:, None])
            out = F.scaled_dot_product_attention(
                Tensor(q), Tensor(kf.astype(q.dtype)),
                Tensor(vf.astype(q.dtype)),
                attn_mask=mask[None, None], training=False).data
        out_t = self._proj_out(out, b, s)
        return out_t, (k_buf, v_buf, new_len)

    def forward_prefill(self, x):
        """Causal attention over a fresh prompt, also returning the
        per-token k/v arrays so the caller can write them into a
        StaticKVCache slot.  Returns ``(out, k [B,S,Hkv,D], v)``."""
        b, s = x.shape[0], x.shape[1]
        q, k, v = self._qkv_arrays(x)
        out = self._attend_fresh(q, k, v, b, s)
        return self._proj_out(out, b, s), k, v

    def forward_decode(self, x, k_layer, v_layer, lengths,
                       k_scale=None, v_scale=None):
        """One decode step over a StaticKVCache layer: write each slot's
        new k/v at its own ``lengths[b]`` (scatter), then run the fused
        single-token attention masked to ``j <= lengths[b]``.  x is
        [B, 1, hidden]; k_layer/v_layer [B, cap, Hkv, D]; lengths [B]
        int32 (tokens already in the cache, EXCLUDING this one).
        Returns ``(out, k_layer, v_layer)``.

        Quantized cache layer: ``k_scale``/``v_scale`` [B, cap, Hkv]
        f32 — the new token's k/v are quantized per head on write and
        the fused kernel dequantizes while streaming; returns
        ``(out, k_layer, v_layer, k_scale, v_scale)``."""
        b = x.shape[0]
        cap = k_layer.shape[1]
        q, k, v = self._qkv_arrays(x)
        idx = jnp.minimum(lengths.astype(jnp.int32), cap - 1)
        rows = jnp.arange(b)
        from .. import ops as _ops
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_layer.dtype)
            kq, ks = quantize_kv(k[:, 0], mode)         # [b,Hkv,D],[b,Hkv]
            vq, vs = quantize_kv(v[:, 0], mode)
            k_layer = k_layer.at[rows, idx].set(kq)
            v_layer = v_layer.at[rows, idx].set(vq)
            k_scale = k_scale.at[rows, idx].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[rows, idx].set(vs.astype(v_scale.dtype))
            out = _ops.decode_attention(q[:, 0], k_layer, v_layer,
                                        idx + 1, k_scale, v_scale)
            out = out[:, None].astype(q.dtype)           # [b, 1, H, D]
            return (self._proj_out(out, b, 1), k_layer, v_layer,
                    k_scale, v_scale)
        k_layer = k_layer.at[rows, idx].set(k[:, 0].astype(k_layer.dtype))
        v_layer = v_layer.at[rows, idx].set(v[:, 0].astype(v_layer.dtype))
        out = _ops.decode_attention(
            q[:, 0].astype(k_layer.dtype), k_layer, v_layer, idx + 1)
        out = out[:, None].astype(q.dtype)               # [b, 1, H, D]
        return self._proj_out(out, b, 1), k_layer, v_layer

    def forward_verify(self, x, k_layer, v_layer, lengths,
                       k_scale=None, v_scale=None):
        """Windowed multi-token step over one StaticKVCache layer — the
        spec-decode verify/catch-up primitive: write the W new tokens'
        k/v at positions ``lengths[b]..lengths[b]+W-1`` (scatter), then
        run the fused window attention where query i sees
        ``j <= lengths[b]+i``.  x is [B, W, hidden]; lengths [B] int32
        EXCLUDING the window.  Returns ``(out, k_layer, v_layer)`` (+
        scale planes when quantized).  W=1 is numerically the
        forward_decode step."""
        b, w = x.shape[0], x.shape[1]
        cap = k_layer.shape[1]
        q, k, v = self._qkv_arrays(x)
        lens = lengths.astype(jnp.int32)
        idx = jnp.minimum(
            lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :],
            cap - 1)                                     # [B, W]
        rows = jnp.arange(b)[:, None]
        from .. import ops as _ops
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_layer.dtype)
            kq, ks = quantize_kv(k, mode)           # [b,w,Hkv,D],[b,w,Hkv]
            vq, vs = quantize_kv(v, mode)
            k_layer = k_layer.at[rows, idx].set(kq)
            v_layer = v_layer.at[rows, idx].set(vq)
            k_scale = k_scale.at[rows, idx].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[rows, idx].set(vs.astype(v_scale.dtype))
            out = _ops.decode_attention_window(q, k_layer, v_layer, lens,
                                               k_scale, v_scale)
            out = out.astype(q.dtype)               # [b, w, H, D]
            return (self._proj_out(out, b, w), k_layer, v_layer,
                    k_scale, v_scale)
        k_layer = k_layer.at[rows, idx].set(k.astype(k_layer.dtype))
        v_layer = v_layer.at[rows, idx].set(v.astype(v_layer.dtype))
        out = _ops.decode_attention_window(
            q.astype(k_layer.dtype), k_layer, v_layer, lens)
        out = out.astype(q.dtype)                    # [b, w, H, D]
        return self._proj_out(out, b, w), k_layer, v_layer

    def forward_verify_paged(self, x, k_pool, v_pool, tables, lengths,
                             k_scale=None, v_scale=None):
        """Paged twin of forward_verify: scatter the W new tokens' k/v
        through each slot's block table at positions
        ``lengths[b]+i``, then run the paged window attention.  x
        [B, W, hidden]; tables [B, MB] int32; lengths [B] int32
        EXCLUDING the window."""
        b, w = x.shape[0], x.shape[1]
        bs = k_pool.shape[1]
        mb = tables.shape[1]
        q, k, v = self._qkv_arrays(x)
        lens = lengths.astype(jnp.int32)
        pos = lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
        blk_pos = jnp.minimum(pos // bs, mb - 1)         # [B, W]
        off = pos % bs
        rows = jnp.arange(b)[:, None]
        blk = tables[rows, blk_pos]                      # [B, W]
        from .. import ops as _ops
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_pool.dtype)
            kq, ks = quantize_kv(k, mode)
            vq, vs = quantize_kv(v, mode)
            k_pool = k_pool.at[blk, off].set(kq)
            v_pool = v_pool.at[blk, off].set(vq)
            k_scale = k_scale.at[blk, off].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[blk, off].set(vs.astype(v_scale.dtype))
            out = _ops.paged_decode_attention_window(
                q, k_pool, v_pool, tables, lens, k_scale, v_scale)
            out = out.astype(q.dtype)
            return (self._proj_out(out, b, w), k_pool, v_pool,
                    k_scale, v_scale)
        k_pool = k_pool.at[blk, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[blk, off].set(v.astype(v_pool.dtype))
        out = _ops.paged_decode_attention_window(
            q.astype(k_pool.dtype), k_pool, v_pool, tables, lens)
        out = out.astype(q.dtype)
        return self._proj_out(out, b, w), k_pool, v_pool

    def forward_prefill_paged(self, x, k_buf, v_buf, prefix_len):
        """Prefill attention over ONE slot's gathered block buffer:
        ``k_buf``/``v_buf`` are the slot's blocks laid out contiguously
        ``[cap_row, Hkv, D]`` (cap_row = max_blocks·block_size) with
        ``prefix_len`` tokens already valid (a radix-cache hit; 0 =
        cold).  Writes the s new k/v at ``prefix_len`` and attends
        suffix query i (absolute position prefix_len+i) against buffer
        keys ``j <= prefix_len + i``.

        ``prefix_len`` may be a PYTHON INT 0 — the engine compiles that
        as its own executable so the cold path keeps the exact
        ring/flash/composite attention of the dense prefill (bitwise
        parity with the dense engine); a traced prefix_len takes the
        masked composite over the whole buffer.  Returns
        ``(out, k_buf, v_buf)``."""
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        q, k, v = self._qkv_arrays(x)
        static_cold = isinstance(prefix_len, int) and prefix_len == 0
        off = jnp.asarray(prefix_len, jnp.int32)
        zero = jnp.asarray(0, jnp.int32)
        k_buf = jax.lax.dynamic_update_slice(
            k_buf, k[0].astype(k_buf.dtype), (off, zero, zero))
        v_buf = jax.lax.dynamic_update_slice(
            v_buf, v[0].astype(v_buf.dtype), (off, zero, zero))
        if static_cold:
            out = self._attend_fresh(q, k, v, b, s)
        else:
            cap = k_buf.shape[0]
            kf, vf = k_buf[None], v_buf[None]       # [1, cap, Hkv, D]
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                kf = jnp.repeat(kf, rep, axis=2)
                vf = jnp.repeat(vf, rep, axis=2)
            # query i sees buffer keys j <= prefix_len + i
            mask = (jnp.arange(cap)[None, :] <=
                    (off + jnp.arange(s))[:, None])
            out = F.scaled_dot_product_attention(
                Tensor(q), Tensor(kf.astype(q.dtype)),
                Tensor(vf.astype(q.dtype)),
                attn_mask=mask[None, None], training=False).data
        return self._proj_out(out, b, s), k_buf, v_buf

    def forward_decode_paged(self, x, k_pool, v_pool, tables, lengths,
                             k_scale=None, v_scale=None):
        """One decode step over a PagedKVCache layer: write each slot's
        new k/v at pool position ``(tables[b, lengths[b]//bs],
        lengths[b]%bs)`` (scatter), then run the paged fused attention
        streaming the slot's blocks through its table.  x [B, 1, H];
        k_pool/v_pool [num_blocks, bs, Hkv, D]; tables [B, MB] int32;
        lengths [B] int32 EXCLUDING the new token.  Inactive slots write
        into the reserved null block (their table rows are all-zero) —
        masked garbage by construction.  Returns
        ``(out, k_pool, v_pool)``.

        Quantized pools: ``k_scale``/``v_scale`` [num_blocks, bs, Hkv]
        f32 — new k/v quantized per head on write, scales streamed and
        dequantized inside the paged kernel; returns
        ``(out, k_pool, v_pool, k_scale, v_scale)``."""
        b = x.shape[0]
        bs = k_pool.shape[1]
        mb = tables.shape[1]
        q, k, v = self._qkv_arrays(x)
        lens = lengths.astype(jnp.int32)
        blk_pos = jnp.minimum(lens // bs, mb - 1)
        off = lens % bs
        rows = jnp.arange(b)
        blk = tables[rows, blk_pos]
        from .. import ops as _ops
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_pool.dtype)
            kq, ks = quantize_kv(k[:, 0], mode)         # [b,Hkv,D],[b,Hkv]
            vq, vs = quantize_kv(v[:, 0], mode)
            k_pool = k_pool.at[blk, off].set(kq)
            v_pool = v_pool.at[blk, off].set(vq)
            k_scale = k_scale.at[blk, off].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[blk, off].set(vs.astype(v_scale.dtype))
            out = _ops.paged_decode_attention(
                q[:, 0], k_pool, v_pool, tables, lens + 1,
                k_scale, v_scale)
            out = out[:, None].astype(q.dtype)           # [b, 1, H, D]
            return (self._proj_out(out, b, 1), k_pool, v_pool,
                    k_scale, v_scale)
        k_pool = k_pool.at[blk, off].set(k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[blk, off].set(v[:, 0].astype(v_pool.dtype))
        out = _ops.paged_decode_attention(
            q[:, 0].astype(k_pool.dtype), k_pool, v_pool, tables,
            lens + 1)
        out = out[:, None].astype(q.dtype)               # [b, 1, H, D]
        return self._proj_out(out, b, 1), k_pool, v_pool

    def forward(self, x, attn_mask=None, cache=None):
        cfg = self.cfg
        b = x.shape[0]
        s = x.shape[1]
        if cache is not None:
            # generation path: fixed-capacity cache, static shapes (the
            # old concat-grown cache recompiled every generated token)
            if attn_mask is not None:
                raise NotImplementedError(
                    "attn_mask with a kv cache is not supported; pad "
                    "tokens are masked by the cache length instead")
            return self._forward_with_cache(x, cache)
        qkv = self.qkv_proj(x)
        h_dim = cfg.hidden_size
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        q = qkv[:, :, :h_dim].reshape(
            [b, s, cfg.num_heads, cfg.head_dim])
        k = qkv[:, :, h_dim:h_dim + kv_dim].reshape(
            [b, s, cfg.num_kv_heads, cfg.head_dim])
        v = qkv[:, :, h_dim + kv_dim:].reshape(
            [b, s, cfg.num_kv_heads, cfg.head_dim])

        causal = s > 1
        if (cfg.sequence_parallel and attn_mask is None
                and self._sp_active(b, s)):
            # ring attention: seq dim sharded over 'sp', KV blocks rotate
            # around the ICI ring (distributed/ring_attention.py). K/V go
            # in UN-expanded (GQA): the ring rotates Hkv heads, not H.
            from ..distributed.ring_attention import \
                sequence_parallel_attention
            if cfg.attn_dropout and self.training:
                raise NotImplementedError(
                    "attn_dropout inside ring attention is not supported")
            out = sequence_parallel_attention(
                q, k, v, sp_axis=cfg.sp_axis, causal=causal)
            out = out.reshape([b, s, -1])
            out = self.out_proj(out)
            return self.dropout(out)

        if cfg.use_flash_attention and attn_mask is None:
            # GQA goes in un-expanded: the Pallas kernel walks kv-head
            # groups on its grid, never materializing repeated K/V
            out = F.flash_attention(q, k, v, dropout=cfg.attn_dropout,
                                    causal=causal,
                                    training=self.training)
        else:
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                k = repeat_interleave(k, rep, axis=2)
                v = repeat_interleave(v, rep, axis=2)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=cfg.attn_dropout, is_causal=causal,
                training=self.training)
        out = out.reshape([b, s, -1])
        out = self.out_proj(out)
        return self.dropout(out)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        out_init = ParamAttr(initializer=I.Normal(
            0.0, config.initializer_range / math.sqrt(
                2.0 * config.num_layers)))
        self.up_proj = ColumnParallelLinear(
            config.hidden_size, config.ffn_hidden_size, weight_attr=init,
            gather_output=False, axis_name=config.tp_axis,
            quantize=config.quantize)
        self.down_proj = RowParallelLinear(
            config.ffn_hidden_size, config.hidden_size,
            weight_attr=out_init, input_is_parallel=True,
            axis_name=config.tp_axis, quantize=config.quantize)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.down_proj(F.gelu(self.up_proj(x),
                                                  approximate=True)))


class GPTBlock(Layer):
    """Pre-LN decoder block (GPT-2/3 style). When the config marks this
    layer index as MoE the dense MLP is replaced by an expert-parallel
    MoELayer (switch-transformer block; BASELINE.json config #5)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        if config.is_moe_layer(layer_idx):
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(
                config.hidden_size, config.ffn_hidden_size,
                num_experts=config.moe_num_experts,
                top_k=config.moe_top_k,
                capacity_factor=config.moe_capacity_factor,
                aux_loss_coeff=config.moe_aux_loss_coeff,
                z_loss_coeff=config.moe_z_loss_coeff,
                ep_axis=config.ep_axis,
                weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range)),
                # depth-scaled residual-out init, same as GPTMLP.down_proj
                down_weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range / math.sqrt(
                        2.0 * config.num_layers))))
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None):
        x = x + self.attn(self.ln_1(x), attn_mask=attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x

    def forward_prefill(self, x):
        """Block forward that also surfaces this layer's k/v for the
        StaticKVCache write. Returns (x, k [B,S,Hkv,D], v)."""
        a, k, v = self.attn.forward_prefill(self.ln_1(x))
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k, v

    def forward_decode(self, x, k_layer, v_layer, lengths,
                       k_scale=None, v_scale=None):
        """Single-token block step over one StaticKVCache layer
        (quantized layers thread their scale planes through)."""
        if k_scale is not None:
            a, k_layer, v_layer, k_scale, v_scale = \
                self.attn.forward_decode(self.ln_1(x), k_layer, v_layer,
                                         lengths, k_scale, v_scale)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, k_layer, v_layer, k_scale, v_scale
        a, k_layer, v_layer = self.attn.forward_decode(
            self.ln_1(x), k_layer, v_layer, lengths)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_layer, v_layer

    def forward_verify(self, x, k_layer, v_layer, lengths,
                       k_scale=None, v_scale=None):
        """Windowed multi-token block step over one StaticKVCache layer
        (LN/MLP are position-wise, so only attention needs the window
        machinery)."""
        if k_scale is not None:
            a, k_layer, v_layer, k_scale, v_scale = \
                self.attn.forward_verify(self.ln_1(x), k_layer, v_layer,
                                         lengths, k_scale, v_scale)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, k_layer, v_layer, k_scale, v_scale
        a, k_layer, v_layer = self.attn.forward_verify(
            self.ln_1(x), k_layer, v_layer, lengths)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_layer, v_layer

    def forward_verify_paged(self, x, k_pool, v_pool, tables, lengths,
                             k_scale=None, v_scale=None):
        """Windowed multi-token block step over one PagedKVCache
        layer."""
        if k_scale is not None:
            a, k_pool, v_pool, k_scale, v_scale = \
                self.attn.forward_verify_paged(
                    self.ln_1(x), k_pool, v_pool, tables, lengths,
                    k_scale, v_scale)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, k_pool, v_pool, k_scale, v_scale
        a, k_pool, v_pool = self.attn.forward_verify_paged(
            self.ln_1(x), k_pool, v_pool, tables, lengths)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_pool, v_pool

    # ---- fused (megakernel) decode step --------------------------------
    def _megakernel_weights(self):
        """The 12 per-layer arrays the fused decode step consumes, in
        ops.decode_megakernel.LAYER_WEIGHTS order."""
        a, m = self.attn, self.mlp
        return tuple(t.data for t in (
            self.ln_1.weight, self.ln_1.bias,
            a.qkv_proj.weight, a.qkv_proj.bias,
            a.out_proj.weight, a.out_proj.bias,
            self.ln_2.weight, self.ln_2.bias,
            m.up_proj.weight, m.up_proj.bias,
            m.down_proj.weight, m.down_proj.bias))

    def _megakernel_ok(self) -> bool:
        """This block can run the fused decode step: a dense (non-MoE)
        MLP and every projection carrying its bias."""
        a = self.attn
        m = self.mlp
        if not hasattr(m, "up_proj") or not hasattr(m, "down_proj"):
            return False
        return not any(p is None for p in (
            a.qkv_proj.bias, a.out_proj.bias, m.up_proj.bias,
            m.down_proj.bias, self.ln_1.bias, self.ln_2.bias))

    def forward_decode_fused(self, x, k_layer, v_layer, lengths,
                             k_scale=None, v_scale=None):
        """Single-token block step as ONE fused op (megakernel when the
        backend/shape allow, the mirrored XLA composite otherwise) —
        same signature and cache-write semantics as forward_decode, so
        the two paths are drop-in interchangeable per layer."""
        from ..ops import decode_megakernel as _mk
        arr = x.data if isinstance(x, Tensor) else x      # [B, 1, H]
        b = arr.shape[0]
        xo, k_new, v_new = _mk.decode_layer_step(
            arr[:, 0], self._megakernel_weights(), k_layer, v_layer,
            lengths, k_scale, v_scale,
            # the LIVE projection attribute, not attn.cfg: it's what
            # enable_quantize() flips after construction
            quantize=self.attn.qkv_proj.quantize,
            eps=self.ln_1._epsilon)
        cap = k_layer.shape[1]
        idx = jnp.minimum(lengths.astype(jnp.int32), cap - 1)
        rows = jnp.arange(b)
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_layer.dtype)
            kq, ks = quantize_kv(k_new, mode)
            vq, vs = quantize_kv(v_new, mode)
            k_layer = k_layer.at[rows, idx].set(kq)
            v_layer = v_layer.at[rows, idx].set(vq)
            k_scale = k_scale.at[rows, idx].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[rows, idx].set(vs.astype(v_scale.dtype))
            return (Tensor(xo[:, None]), k_layer, v_layer, k_scale,
                    v_scale)
        k_layer = k_layer.at[rows, idx].set(k_new.astype(k_layer.dtype))
        v_layer = v_layer.at[rows, idx].set(v_new.astype(v_layer.dtype))
        return Tensor(xo[:, None]), k_layer, v_layer

    def forward_decode_paged_fused(self, x, k_pool, v_pool, tables,
                                   lengths, k_scale=None, v_scale=None):
        """Paged twin of forward_decode_fused: one fused op per layer
        step, then the same scatter-through-the-block-table write as
        forward_decode_paged."""
        from ..ops import decode_megakernel as _mk
        arr = x.data if isinstance(x, Tensor) else x      # [B, 1, H]
        b = arr.shape[0]
        bs = k_pool.shape[1]
        mb = tables.shape[1]
        xo, k_new, v_new = _mk.decode_layer_step_paged(
            arr[:, 0], self._megakernel_weights(), k_pool, v_pool,
            tables, lengths, k_scale, v_scale,
            quantize=self.attn.qkv_proj.quantize,
            eps=self.ln_1._epsilon)
        lens = lengths.astype(jnp.int32)
        blk_pos = jnp.minimum(lens // bs, mb - 1)
        off = lens % bs
        rows = jnp.arange(b)
        blk = tables[rows, blk_pos]
        if k_scale is not None:
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(k_pool.dtype)
            kq, ks = quantize_kv(k_new, mode)
            vq, vs = quantize_kv(v_new, mode)
            k_pool = k_pool.at[blk, off].set(kq)
            v_pool = v_pool.at[blk, off].set(vq)
            k_scale = k_scale.at[blk, off].set(ks.astype(k_scale.dtype))
            v_scale = v_scale.at[blk, off].set(vs.astype(v_scale.dtype))
            return (Tensor(xo[:, None]), k_pool, v_pool, k_scale,
                    v_scale)
        k_pool = k_pool.at[blk, off].set(k_new.astype(k_pool.dtype))
        v_pool = v_pool.at[blk, off].set(v_new.astype(v_pool.dtype))
        return Tensor(xo[:, None]), k_pool, v_pool

    def forward_prefill_paged(self, x, k_buf, v_buf, prefix_len):
        """Block prefill over one slot's gathered block buffer."""
        a, k_buf, v_buf = self.attn.forward_prefill_paged(
            self.ln_1(x), k_buf, v_buf, prefix_len)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_buf, v_buf

    def forward_decode_paged(self, x, k_pool, v_pool, tables, lengths,
                             k_scale=None, v_scale=None):
        """Single-token block step over one PagedKVCache layer
        (quantized pools thread their scale pools through)."""
        if k_scale is not None:
            a, k_pool, v_pool, k_scale, v_scale = \
                self.attn.forward_decode_paged(
                    self.ln_1(x), k_pool, v_pool, tables, lengths,
                    k_scale, v_scale)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, k_pool, v_pool, k_scale, v_scale
        a, k_pool, v_pool = self.attn.forward_decode_paged(
            self.ln_1(x), k_pool, v_pool, tables, lengths)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_pool, v_pool


class GPTModel(Layer):
    """Embeddings + N blocks + final norm. Returns hidden states."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        self.wte = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)),
            axis_name=config.tp_axis)
        self.wpe = Embedding(config.max_seq_len, config.hidden_size,
                             weight_attr=ParamAttr(initializer=I.Normal(
                                 0.0, config.initializer_range)))
        self.drop = Dropout(config.dropout)
        self.blocks = LayerList([GPTBlock(config, layer_idx=i)
                                 for i in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self._recompute = False
        self._scan_layers = False
        self._zero3_axis = None

    def enable_recompute(self, policy=None):
        """strategy.recompute hook: remat every block. Applied in
        forward() (not by re-wrapping sublayers) so parameter names —
        and therefore state dicts/checkpoints — are unchanged.

        policy: jax.checkpoint_policies name ('dots', 'dots_no_batch',
        ...) — selective save policies keep matmul outputs and only
        recompute the cheap elementwise ops, recovering most of the remat
        FLOPs vs full recompute (None)."""
        self._recompute = True
        self._recompute_policy = policy
        return self

    def enable_scan_layers(self, flag: bool = True):
        """Run the block stack as ONE jax.lax.scan over per-layer
        stacked parameters instead of a Python loop: the transformer
        body is traced (and XLA-compiled) once regardless of depth, so
        compile time drops from O(layers) to O(1) traced bodies, and
        per-iteration jax.checkpoint gives per-layer remat under the
        active recompute policy. Parameters stay per-layer Tensors
        (state dicts/checkpoints unchanged); the stacking happens at
        trace time. Falls back to the unrolled loop when the stack is
        not scannable (MoE blocks, live dropout, attention masks)."""
        self._scan_layers = bool(flag)
        return self

    def enable_zero3_overlap(self, axis: str = "dp"):
        """ZeRO-3 latency-hiding hook (SpmdTrainer sharding stage 3 +
        scan_layers): the layer scan runs under shard_map over `axis`
        with layer i+1's params all-gathered while layer i computes, and
        block grads leave the backward reduce-scattered (see
        distributed.zero3).  Per-trace preconditions (a dp>1 compile
        mesh, dp-divisible batch, no tensor-parallel specs on block
        params) are re-checked at trace time; when they fail the plain
        scan runs and GSPMD places the stage-3 gathers itself."""
        self._zero3_axis = axis
        return self

    def enable_quantize(self, mode: Optional[str] = "int8"):
        """strategy.qat hook: flip every block linear (qkv/out/up/down)
        onto the fake-quant AQT path (ops.fake_quant_matmul — quantized
        forward, straight-through backward) after construction.  ``None``
        restores the exact unquantized lowering.  Parameter names,
        dtypes and state dicts are untouched — only the forward matmul
        routing changes, so the optimizer never notices."""
        if mode is not None:
            from ..ops.quantized_matmul import _check_mode
            _check_mode(mode)
            if self.cfg.moe_num_experts > 0:
                raise NotImplementedError(
                    "enable_quantize on a MoE model is not supported: "
                    "expert FFN matmuls have no quantized COMPUTE path "
                    "yet (see GPTConfig.quantize). Quantized KV caches "
                    "are orthogonal and do work — pass kv_dtype='int8' "
                    "to InferenceEngine/init_kv_cache instead")
        self.cfg = replace(self.cfg, quantize=mode)
        for blk in self.blocks:
            for lin in (blk.attn.qkv_proj, blk.attn.out_proj):
                lin.quantize = mode
            for name in ("up_proj", "down_proj"):
                lin = getattr(blk.mlp, name, None)
                if lin is not None:
                    lin.quantize = mode
        return self

    def enable_decode_megakernel(self, flag: bool = True):
        """Route every serving decode step through the fused per-layer
        megakernel (ops.decode_megakernel).  Parameters and cache
        layouts are untouched — only the decode lowering changes — so
        the composed path stays available as the parity oracle by
        flipping the flag back."""
        self.cfg = replace(self.cfg, decode_megakernel=bool(flag))
        # blocks read their attention's cfg for quantize/epsilon only;
        # the routing decision lives here, at the model
        return self

    def _megakernel_active(self) -> bool:
        """The fused decode path runs for this trace: knob armed
        (config or PADDLE_TPU_DECODE_MEGAKERNEL), homogeneous dense
        blocks with biases, and no live tensor-parallel sharding (tp>1
        block weights keep the composed GSPMD path)."""
        from ..ops.decode_megakernel import megakernel_enabled
        cfg = self.cfg
        if not megakernel_enabled(cfg):
            return False
        if cfg.moe_num_experts > 0:
            return False
        if self.training and (cfg.dropout > 0 or cfg.attn_dropout > 0):
            return False
        from ..distributed.mesh import get_mesh
        m = get_mesh()
        if (m is not None and cfg.tp_axis in m.axis_names
                and m.shape[cfg.tp_axis] > 1):
            return False
        return all(blk._megakernel_ok() for blk in self.blocks)

    def _zero3_mesh(self, x):
        """The compile mesh when the overlapped ZeRO-3 scan can run for
        this trace, else None."""
        if self._zero3_axis is None:
            return None
        from ..distributed.mesh import get_compile_mesh
        from ..distributed.zero3 import zero3_scan_available
        mesh = get_compile_mesh()
        arr = x.data if isinstance(x, Tensor) else x
        if not zero3_scan_available(mesh, self._zero3_axis, arr.shape[0]):
            return None
        # tensor-parallel block params keep the GSPMD path: their tp
        # placement and the manual dp gather would fight over layout
        for _, p in self.blocks[0].named_parameters():
            spec = getattr(p, "pspec", None)
            if spec and any(a in mesh.axis_names and mesh.shape[a] > 1
                            for a in tuple(spec) if a is not None):
                return None
        return mesh

    def _scan_ok(self, attn_mask) -> bool:
        cfg = self.cfg
        if (not self._scan_layers or attn_mask is not None
                or len(self.blocks) < 2):
            return False
        if cfg.moe_num_experts > 0 or cfg.sequence_parallel:
            return False  # heterogeneous blocks / shard_map inside scan
        if self.training and (cfg.dropout > 0 or cfg.attn_dropout > 0):
            return False  # one traced body would share dropout masks
        if any(b is not None for _, b in self.blocks[0].named_buffers()):
            return False
        return True

    def _forward_blocks_scanned(self, x):
        from ..distributed.recompute import checkpoint_policy
        from ..func import functional_call
        blk0 = self.blocks[0]
        names = [n for n, _ in blk0.named_parameters()]
        n_names = len(names)
        n_layers = len(self.blocks)
        flat = [dict(blk.named_parameters())[n]
                for blk in self.blocks for n in names]
        use_remat = self._recompute and self.training
        pol = checkpoint_policy(getattr(self, "_recompute_policy", None)) \
            if use_remat else None
        z3_mesh = self._zero3_mesh(x)

        def scan_fn(h, *flat_arrs):
            stacked = {
                name: jnp.stack([flat_arrs[b * n_names + j]
                                 for b in range(n_layers)])
                for j, name in enumerate(names)}

            if z3_mesh is not None:
                # ZeRO-3 overlapped gather: shard_map over dp with the
                # next layer's all-gather riding under this layer's
                # compute (distributed.zero3)
                from ..distributed.zero3 import scan_layers_zero3

                def call_block(layer_params, carry):
                    out, _ = functional_call(blk0, layer_params, {},
                                             carry)
                    return out

                return scan_layers_zero3(
                    call_block, stacked, h, z3_mesh, self._zero3_axis,
                    use_remat=use_remat, policy=pol)

            def body(carry, layer_params):
                out, _ = functional_call(blk0, layer_params, {}, carry)
                return out, None

            if use_remat:
                # prevent_cse=False: scan bodies don't need the CSE
                # barrier, and it costs performance
                body = jax.checkpoint(body, policy=pol,
                                      prevent_cse=False)
            out, _ = jax.lax.scan(body, h, stacked)
            return out

        from ..core.autograd import apply
        return apply(scan_fn, x, *flat,
                     name="gpt_scan_layers_zero3" if z3_mesh is not None
                     else "gpt_scan_layers")

    # ---- serving path: static KV cache --------------------------------
    def init_kv_cache(self, batch_slots: int, capacity: Optional[int] = None,
                      dtype=None, kv_dtype=None) -> StaticKVCache:
        """Allocate the fixed-shape serving cache
        ``[layers, batch_slots, capacity, kv_heads, head_dim]`` (zeros;
        per-slot lengths 0). ``capacity`` defaults to max_seq_len;
        ``dtype`` defaults to the embedding dtype.  ``kv_dtype='int8'``
        (or ``'fp8'``; default from ``PADDLE_TPU_KV_DTYPE``) stores
        8-bit values plus per-(position, head) f32 scale planes — half
        the decode HBM traffic, dequantized inside the fused kernel."""
        from ..ops.quantized_matmul import (kv_storage_dtype,
                                            resolve_kv_quant)
        cfg = self.cfg
        cap = int(capacity or cfg.max_seq_len)
        mode = resolve_kv_quant(kv_dtype)
        dt = kv_storage_dtype(mode) if mode else \
            (dtype or self.wte.weight.dtype)
        shape = (cfg.num_layers, int(batch_slots), cap,
                 cfg.num_kv_heads, cfg.head_dim)
        scales = (jnp.zeros(shape[:-1], jnp.float32),
                  jnp.zeros(shape[:-1], jnp.float32)) if mode \
            else (None, None)
        return StaticKVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt),
                             jnp.zeros((int(batch_slots),), jnp.int32),
                             *scales)

    def forward_prefill(self, input_ids, cache: StaticKVCache, slot,
                        prompt_len):
        """Prefill ONE slot: run the causal forward over a (possibly
        padded) prompt ``input_ids [1, s_bucket]``, write every layer's
        k/v into ``cache`` at ``(layer, slot, 0)``, and set
        ``lengths[slot] = prompt_len``.  Tokens past ``prompt_len`` are
        bucket padding: their k/v land beyond the recorded length and
        are masked out of every later decode step.  Returns
        ``(hidden [1, s, H], cache)``."""
        ids = input_ids.data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        s = ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        x = self.wte(Tensor(ids)) + self.wpe(pos)
        x = self.drop(x)
        ks, vs = [], []
        for blk in self.blocks:
            x, k, v = blk.forward_prefill(x)
            ks.append(k[0])
            vs.append(v[0])
        k_new = jnp.stack(ks)[:, None]        # [L, 1, s, Hkv, D]
        v_new = jnp.stack(vs)[:, None]
        slot = jnp.asarray(slot, jnp.int32)
        zero = jnp.asarray(0, jnp.int32)
        k_scale = v_scale = None
        if cache.quantized:
            # attention ran on the full-precision k/v above (bitwise
            # the dense prefill); only the STORED copy is quantized
            from ..ops.quantized_matmul import kv_quant_mode, quantize_kv
            mode = kv_quant_mode(cache.k.dtype)
            k_new, k_s = quantize_kv(k_new, mode)   # [L,1,s,Hkv]
            v_new, v_s = quantize_kv(v_new, mode)
            k_scale = jax.lax.dynamic_update_slice(
                cache.k_scale, k_s.astype(cache.k_scale.dtype),
                (zero, slot, zero, zero))
            v_scale = jax.lax.dynamic_update_slice(
                cache.v_scale, v_s.astype(cache.v_scale.dtype),
                (zero, slot, zero, zero))
        cache_k = jax.lax.dynamic_update_slice(
            cache.k, k_new.astype(cache.k.dtype),
            (zero, slot, zero, zero, zero))
        cache_v = jax.lax.dynamic_update_slice(
            cache.v, v_new.astype(cache.v.dtype),
            (zero, slot, zero, zero, zero))
        lengths = cache.lengths.at[slot].set(
            jnp.asarray(prompt_len, jnp.int32))
        return self.ln_f(x), StaticKVCache(cache_k, cache_v, lengths,
                                           k_scale, v_scale)

    def forward_decode(self, tokens, cache: StaticKVCache, active):
        """One decode step for every slot: append ``tokens [B]`` at each
        slot's current length, run the fused single-token attention per
        layer, and advance ``lengths`` by ``active [B]`` (0/1 — retired
        or empty slots keep their length; their writes land at a masked
        position and their outputs are ignored by the scheduler).
        Returns ``(hidden [B, 1, H], cache)``."""
        cfg = self.cfg
        b = cache.batch_slots
        toks = tokens.data if isinstance(tokens, Tensor) \
            else jnp.asarray(tokens)
        pos = jnp.minimum(cache.lengths, cfg.max_seq_len - 1)
        x = self.wte(Tensor(toks.reshape(b, 1))) + \
            self.wpe(Tensor(pos.reshape(b, 1)))
        x = self.drop(x)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        fused = self._megakernel_active()
        for i, blk in enumerate(self.blocks):
            step = blk.forward_decode_fused if fused else \
                blk.forward_decode
            if k_sc is not None:
                x, k_layer, v_layer, ks_l, vs_l = step(
                    x, cache_k[i], cache_v[i], cache.lengths,
                    k_sc[i], v_sc[i])
                k_sc = k_sc.at[i].set(ks_l)
                v_sc = v_sc.at[i].set(vs_l)
            else:
                x, k_layer, v_layer = step(
                    x, cache_k[i], cache_v[i], cache.lengths)
            cache_k = cache_k.at[i].set(k_layer)
            cache_v = cache_v.at[i].set(v_layer)
        lengths = jnp.minimum(
            cache.lengths + jnp.asarray(active, jnp.int32),
            cache.capacity)
        return self.ln_f(x), StaticKVCache(cache_k, cache_v, lengths,
                                           k_sc, v_sc)

    def forward_verify(self, tokens, cache: StaticKVCache):
        """Windowed multi-token step for every slot — the spec-decode
        verify (and draft catch-up) primitive: process ``tokens
        [B, W]`` as W consecutive new tokens per slot starting at each
        slot's current length, writing their k/v into the cache and
        attending each window query i against positions
        ``j <= lengths[b]+i``.  Returns ``(hidden [B, W, H], cache)``
        with lengths UNCHANGED — the caller (the spec tick) advances
        them by the count it actually commits, which it only knows
        after the acceptance rule runs on these logits.  Positions
        beyond the committed count hold garbage above the advanced
        length, exactly the masked-garbage convention of
        forward_decode."""
        cfg = self.cfg
        toks = tokens.data if isinstance(tokens, Tensor) \
            else jnp.asarray(tokens)
        b, w = toks.shape
        lens = cache.lengths.astype(jnp.int32)
        pos = jnp.minimum(
            lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :],
            cfg.max_seq_len - 1)
        x = self.wte(Tensor(toks)) + self.wpe(Tensor(pos))
        x = self.drop(x)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        for i, blk in enumerate(self.blocks):
            if k_sc is not None:
                x, k_layer, v_layer, ks_l, vs_l = blk.forward_verify(
                    x, cache_k[i], cache_v[i], lens, k_sc[i], v_sc[i])
                k_sc = k_sc.at[i].set(ks_l)
                v_sc = v_sc.at[i].set(vs_l)
            else:
                x, k_layer, v_layer = blk.forward_verify(
                    x, cache_k[i], cache_v[i], lens)
            cache_k = cache_k.at[i].set(k_layer)
            cache_v = cache_v.at[i].set(v_layer)
        return self.ln_f(x), StaticKVCache(cache_k, cache_v,
                                           cache.lengths, k_sc, v_sc)

    def forward_verify_paged(self, tokens, cache, tables, lengths):
        """Paged twin of forward_verify: W consecutive tokens per slot
        scattered through the block tables.  Lengths are HOST state
        (the scheduler owns block accounting) and ride in as an
        operand, EXCLUDING the window.  Returns
        ``(hidden [B, W, H], cache)``."""
        cfg = self.cfg
        tables = jnp.asarray(tables, jnp.int32)
        toks = tokens.data if isinstance(tokens, Tensor) \
            else jnp.asarray(tokens)
        b, w = toks.shape
        lens = jnp.asarray(lengths, jnp.int32)
        pos = jnp.minimum(
            lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :],
            cfg.max_seq_len - 1)
        x = self.wte(Tensor(toks)) + self.wpe(Tensor(pos))
        x = self.drop(x)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        for i, blk in enumerate(self.blocks):
            if k_sc is not None:
                x, k_pool, v_pool, ks_p, vs_p = blk.forward_verify_paged(
                    x, cache_k[i], cache_v[i], tables, lens,
                    k_sc[i], v_sc[i])
                k_sc = k_sc.at[i].set(ks_p)
                v_sc = v_sc.at[i].set(vs_p)
            else:
                x, k_pool, v_pool = blk.forward_verify_paged(
                    x, cache_k[i], cache_v[i], tables, lens)
            cache_k = cache_k.at[i].set(k_pool)
            cache_v = cache_v.at[i].set(v_pool)
        return self.ln_f(x), type(cache)(cache_k, cache_v, k_sc, v_sc)

    def forward_prefill_chunk(self, tokens, cache: StaticKVCache,
                              lengths, advance):
        """Chunked-prefill step for every slot over the DENSE cache —
        the Sarathi-style stall-free admission primitive: ``tokens
        [B, C]`` carries the next (up to) C prompt tokens per
        still-prefilling slot, written and attended with the same
        window machinery as forward_verify (query i sees positions
        ``j <= lengths[b]+i``).  ``lengths`` rides in as a HOST
        operand — the scheduler's per-slot mirror, not
        ``cache.lengths`` — so a slot retired between chunks can't
        leave a stale in-graph length behind; ``advance [B]`` (0 for
        decode/empty slots, the real chunk token count otherwise)
        advances lengths in-graph so subsequent decode ticks see the
        grown prefix.  Rows with ``advance[b] < C`` write padded
        positions above their new length — masked garbage, overwritten
        by the next chunk or decode, the forward_decode convention.
        Returns ``(hidden [B, C, H], cache)``."""
        cfg = self.cfg
        toks = tokens.data if isinstance(tokens, Tensor) \
            else jnp.asarray(tokens)
        b, w = toks.shape
        lens = jnp.asarray(lengths, jnp.int32)
        pos = jnp.minimum(
            lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :],
            cfg.max_seq_len - 1)
        x = self.wte(Tensor(toks)) + self.wpe(Tensor(pos))
        x = self.drop(x)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        for i, blk in enumerate(self.blocks):
            if k_sc is not None:
                x, k_layer, v_layer, ks_l, vs_l = blk.forward_verify(
                    x, cache_k[i], cache_v[i], lens, k_sc[i], v_sc[i])
                k_sc = k_sc.at[i].set(ks_l)
                v_sc = v_sc.at[i].set(vs_l)
            else:
                x, k_layer, v_layer = blk.forward_verify(
                    x, cache_k[i], cache_v[i], lens)
            cache_k = cache_k.at[i].set(k_layer)
            cache_v = cache_v.at[i].set(v_layer)
        new_len = jnp.minimum(lens + jnp.asarray(advance, jnp.int32),
                              cache.capacity)
        return self.ln_f(x), StaticKVCache(cache_k, cache_v, new_len,
                                           k_sc, v_sc)

    def forward_prefill_chunk_paged(self, tokens, cache, tables,
                                    lengths, advance):
        """Paged twin of forward_prefill_chunk.  The paged layout
        already keeps lengths on the host (the scheduler owns block
        accounting), so the chunk step IS the paged verify window —
        scatter C tokens per slot through the block tables at
        ``lengths[b]+i`` and attend the staircase; out-of-extent rows
        (decode slots, padding above ``advance[b]``) write into the
        reserved null block.  ``advance`` only documents the contract
        here; the scheduler advances its host lengths itself.  Returns
        ``(hidden [B, C, H], cache)``."""
        del advance  # host-side bookkeeping with the paged layout
        return self.forward_verify_paged(tokens, cache, tables, lengths)

    # ---- serving path: paged KV cache ---------------------------------
    def forward_prefill_paged(self, input_ids, cache, table_row,
                              prefix_len):
        """Prefill ONE slot over a PAGED cache: ``input_ids [1, s]`` is
        the (bucket-padded) DIVERGENT SUFFIX — tokens ``prefix_len`` of
        the prompt onward; ``table_row [max_blocks]`` int32 maps the
        slot's positions to pool blocks (shared radix-cache blocks for
        the prefix, fresh blocks for the suffix, null block 0 beyond).
        Per layer: gather the slot's blocks contiguous, write the suffix
        k/v at ``prefix_len``, attend, scatter the blocks back.  Pool
        shapes never change, so one executable serves any prefix length
        (``prefix_len`` rides in as a traced scalar; the engine compiles
        the common cold case — a static Python 0 — separately to keep
        the dense prefill's exact attention path).  Returns
        ``(hidden [1, s, H], cache)``."""
        ids = input_ids.data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        cfg = self.cfg
        s = ids.shape[1]
        mb = table_row.shape[0]
        bs = cache.block_size
        hkv, dh = cfg.num_kv_heads, cfg.head_dim
        off = jnp.asarray(prefix_len, jnp.int32)
        pos = jnp.minimum(off + jnp.arange(s, dtype=jnp.int32),
                          cfg.max_seq_len - 1)
        x = self.wte(Tensor(ids)) + self.wpe(Tensor(pos[None, :]))
        x = self.drop(x)
        table_row = jnp.asarray(table_row, jnp.int32)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        quantized = k_sc is not None
        if quantized:
            from ..ops.quantized_matmul import (dequantize_kv,
                                                kv_quant_mode,
                                                quantize_kv)
            mode = kv_quant_mode(cache_k.dtype)
        for i, blk in enumerate(self.blocks):
            if quantized:
                # gather int8 blocks + scale planes, DEQUANTIZE into an
                # f32 working buffer, then requantize on the scatter
                # back.  The buffer must stay f32 end to end: in f32,
                # requantization of untouched prefix positions is exact
                # (amax positions quantize to ±127, so round(q·s/s')
                # reproduces q bit for bit) — a bf16 buffer would round
                # q·s first and drift the shared prefix codes on every
                # radix-cache hit.  Attention dtype is unaffected: the
                # block casts the buffer to q.dtype before attending.
                k_buf = dequantize_kv(
                    cache_k[i][table_row], k_sc[i][table_row],
                    jnp.float32).reshape(mb * bs, hkv, dh)
                v_buf = dequantize_kv(
                    cache_v[i][table_row], v_sc[i][table_row],
                    jnp.float32).reshape(mb * bs, hkv, dh)
            else:
                k_buf = cache_k[i][table_row].reshape(mb * bs, hkv, dh)
                v_buf = cache_v[i][table_row].reshape(mb * bs, hkv, dh)
            x, k_buf, v_buf = blk.forward_prefill_paged(
                x, k_buf, v_buf, prefix_len)
            # duplicate table entries (trailing null-block slots) scatter
            # identical gathered-back values — benign by construction
            if quantized:
                kq, ks = quantize_kv(k_buf, mode)
                vq, vs = quantize_kv(v_buf, mode)
                cache_k = cache_k.at[i, table_row].set(
                    kq.reshape(mb, bs, hkv, dh))
                cache_v = cache_v.at[i, table_row].set(
                    vq.reshape(mb, bs, hkv, dh))
                k_sc = k_sc.at[i, table_row].set(
                    ks.reshape(mb, bs, hkv).astype(k_sc.dtype))
                v_sc = v_sc.at[i, table_row].set(
                    vs.reshape(mb, bs, hkv).astype(v_sc.dtype))
            else:
                cache_k = cache_k.at[i, table_row].set(
                    k_buf.reshape(mb, bs, hkv, dh))
                cache_v = cache_v.at[i, table_row].set(
                    v_buf.reshape(mb, bs, hkv, dh))
        return self.ln_f(x), type(cache)(cache_k, cache_v, k_sc, v_sc)

    def forward_decode_paged(self, tokens, cache, tables, lengths):
        """One decode step for every slot over the PAGED cache: append
        ``tokens [B]`` at each slot's ``lengths[b]`` through its block
        table, run the paged fused attention per layer.  Lengths are
        HOST state with the paged layout (the scheduler owns block
        accounting), so they ride in as an operand and are not advanced
        in-graph.  Returns ``(hidden [B, 1, H], cache)``."""
        cfg = self.cfg
        tables = jnp.asarray(tables, jnp.int32)
        b = tables.shape[0]
        toks = tokens.data if isinstance(tokens, Tensor) \
            else jnp.asarray(tokens)
        lens = jnp.asarray(lengths, jnp.int32)
        pos = jnp.minimum(lens, cfg.max_seq_len - 1)
        x = self.wte(Tensor(toks.reshape(b, 1))) + \
            self.wpe(Tensor(pos.reshape(b, 1)))
        x = self.drop(x)
        cache_k, cache_v = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        fused = self._megakernel_active()
        for i, blk in enumerate(self.blocks):
            step = blk.forward_decode_paged_fused if fused else \
                blk.forward_decode_paged
            if k_sc is not None:
                x, k_pool, v_pool, ks_p, vs_p = step(
                    x, cache_k[i], cache_v[i], tables, lens,
                    k_sc[i], v_sc[i])
                k_sc = k_sc.at[i].set(ks_p)
                v_sc = v_sc.at[i].set(vs_p)
            else:
                x, k_pool, v_pool = step(
                    x, cache_k[i], cache_v[i], tables, lens)
            cache_k = cache_k.at[i].set(k_pool)
            cache_v = cache_v.at[i].set(v_pool)
        return self.ln_f(x), type(cache)(cache_k, cache_v, k_sc, v_sc)

    def forward(self, input_ids, attn_mask=None):
        from ..distributed.recompute import recompute as _rc
        s = input_ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if self._scan_ok(attn_mask):
            return self.ln_f(self._forward_blocks_scanned(x))
        for blk in self.blocks:
            if self._recompute and self.training:
                # mask passed positionally so the checkpointed region
                # treats it as a traced input
                pol = getattr(self, "_recompute_policy", None)
                x = _rc(blk, x, policy=pol) if attn_mask is None else \
                    _rc(blk, x, attn_mask, policy=pol)
            else:
                x = blk(x) if attn_mask is None else blk(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head on top; logits share the (vocab-sharded) embedding matrix
    when tie_word_embeddings (GPT-3 convention)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=ParamAttr(initializer=I.Normal(
                    0.0, config.initializer_range)),
                has_bias=False, gather_output=True,
                axis_name=config.tp_axis)

    def enable_recompute(self, policy=None):
        self.gpt.enable_recompute(policy=policy)
        return self

    def enable_scan_layers(self, flag: bool = True):
        self.gpt.enable_scan_layers(flag)
        return self

    def enable_zero3_overlap(self, axis: str = "dp"):
        self.gpt.enable_zero3_overlap(axis)
        return self

    def enable_quantize(self, mode: Optional[str] = "int8"):
        self.gpt.enable_quantize(mode)
        self.cfg = self.gpt.cfg
        return self

    def enable_decode_megakernel(self, flag: bool = True):
        self.gpt.enable_decode_megakernel(flag)
        self.cfg = self.gpt.cfg
        return self

    def _tp_size(self) -> int:
        from ..distributed.mesh import get_mesh
        m = get_mesh()
        if m is None or self.cfg.tp_axis not in m.axis_names:
            return 1
        return m.shape[self.cfg.tp_axis]

    def forward(self, input_ids, attn_mask=None):
        x = self.gpt(input_ids, attn_mask=attn_mask)
        if (self.cfg.fused_ce and self.training
                and self.cfg.tie_word_embeddings
                and self._tp_size() == 1):
            # blocked-CE training path: hand (hidden, lm weight) to the
            # criterion instead of projecting to [B, S, V] logits — the
            # projection happens inside the fused loss, vocab chunk by
            # vocab chunk (eval/generation still produce full logits).
            # Skipped on tp>1 meshes: the blocked loop's dynamic vocab
            # slices would force GSPMD to all-gather the vocab-sharded
            # LM head every step, costing more than the logits save
            return x, self.gpt.wte.weight
        if self.cfg.tie_word_embeddings:
            w = self.gpt.wte.weight  # [V, H], vocab-sharded over tp
            logits = matmul(x, w, transpose_y=True)
        else:
            logits = self.lm_head(x)
        return logits

    # ---- serving path -------------------------------------------------
    def init_kv_cache(self, batch_slots: int, capacity: Optional[int] = None,
                      dtype=None, kv_dtype=None) -> StaticKVCache:
        return self.gpt.init_kv_cache(batch_slots, capacity, dtype,
                                      kv_dtype)

    def _head_logits(self, hidden):
        """hidden Tensor [..., H] -> logits Tensor [..., V]."""
        if self.cfg.tie_word_embeddings:
            return matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(hidden)

    def prefill(self, input_ids, cache: StaticKVCache, slot, prompt_len):
        """Prefill one slot; returns ``(logits [1, V], cache)`` — the
        logits of the LAST real prompt token (position prompt_len-1),
        i.e. the distribution of the first generated token."""
        h, cache = self.gpt.forward_prefill(input_ids, cache, slot,
                                            prompt_len)
        harr = h.data                                     # [1, s, H]
        last = jax.lax.dynamic_slice(
            harr, (jnp.asarray(0, jnp.int32),
                   jnp.asarray(prompt_len, jnp.int32) - 1,
                   jnp.asarray(0, jnp.int32)),
            (1, 1, harr.shape[-1]))[:, 0]                 # [1, H]
        logits = self._head_logits(Tensor(last))
        return logits.data, cache

    def decode_step(self, tokens, cache: StaticKVCache, active):
        """One decode step for all slots; returns
        ``(logits [B, V], cache)``."""
        h, cache = self.gpt.forward_decode(tokens, cache, active)
        logits = self._head_logits(h)                     # [B, 1, V]
        return logits.data[:, 0], cache

    def verify_step(self, tokens, cache: StaticKVCache):
        """Windowed multi-token step for all slots (spec-decode verify /
        draft catch-up); returns ``(logits [B, W, V], cache)`` — the
        logits at every window position, i.e. logits[:, i] is the
        next-token distribution after consuming tokens[:, :i+1].
        Lengths are NOT advanced (see GPTModel.forward_verify)."""
        h, cache = self.gpt.forward_verify(tokens, cache)
        logits = self._head_logits(h)                     # [B, W, V]
        return logits.data, cache

    def verify_step_paged(self, tokens, cache, tables, lengths):
        """Paged windowed multi-token step for all slots; returns
        ``(logits [B, W, V], cache)``."""
        h, cache = self.gpt.forward_verify_paged(tokens, cache, tables,
                                                 lengths)
        logits = self._head_logits(h)
        return logits.data, cache

    def _chunk_last_logits(self, h, advance):
        """Gather each slot's LAST-real-chunk-token hidden state
        (position ``advance[b]-1`` in the window; clamped to 0 for
        non-participating rows, whose logits the scheduler ignores)
        and project to logits [B, V] — one head matmul per tick
        instead of [B, C, V]."""
        harr = h.data                                     # [B, C, H]
        idx = jnp.clip(jnp.asarray(advance, jnp.int32) - 1, 0,
                       harr.shape[1] - 1)
        last = jnp.take_along_axis(harr, idx[:, None, None],
                                   axis=1)[:, 0]          # [B, H]
        logits = self._head_logits(Tensor(last))
        return logits.data

    def prefill_chunk(self, tokens, cache: StaticKVCache, lengths,
                      advance):
        """Chunked-prefill step for all slots (dense cache); returns
        ``(logits [B, V], cache)`` — logits after each slot's last
        real chunk token, i.e. the first-generated-token distribution
        for slots whose chunk completes their prompt."""
        h, cache = self.gpt.forward_prefill_chunk(tokens, cache,
                                                  lengths, advance)
        return self._chunk_last_logits(h, advance), cache

    def prefill_chunk_paged(self, tokens, cache, tables, lengths,
                            advance):
        """Paged chunked-prefill step for all slots; returns
        ``(logits [B, V], cache)``."""
        h, cache = self.gpt.forward_prefill_chunk_paged(
            tokens, cache, tables, lengths, advance)
        return self._chunk_last_logits(h, advance), cache

    def prefill_paged(self, input_ids, cache, table_row, prefix_len,
                      suffix_len):
        """Paged prefill of one slot; ``input_ids`` is the bucket-padded
        divergent suffix and ``suffix_len`` its real token count.
        Returns ``(logits [1, V], cache)`` — the logits of the last real
        suffix token (= the first generated token's distribution)."""
        h, cache = self.gpt.forward_prefill_paged(
            input_ids, cache, table_row, prefix_len)
        harr = h.data                                     # [1, s, H]
        last = jax.lax.dynamic_slice(
            harr, (jnp.asarray(0, jnp.int32),
                   jnp.asarray(suffix_len, jnp.int32) - 1,
                   jnp.asarray(0, jnp.int32)),
            (1, 1, harr.shape[-1]))[:, 0]                 # [1, H]
        logits = self._head_logits(Tensor(last))
        return logits.data, cache

    def decode_step_paged(self, tokens, cache, tables, lengths):
        """One paged decode step for all slots; returns
        ``(logits [B, V], cache)``."""
        h, cache = self.gpt.forward_decode_paged(tokens, cache, tables,
                                                 lengths)
        logits = self._head_logits(h)                     # [B, 1, V]
        return logits.data[:, 0], cache

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 include_prompt: bool = False):
        """Single-request convenience wrapper over the serving engine
        (inference.engine.InferenceEngine): prefill the prompt, decode
        greedily (temperature=0) or by temperature/top-k/top-p sampling,
        stop at ``eos_id``/``max_new_tokens``.  Returns a 1-D numpy
        array of generated token ids.

        Builds a 1-slot engine per call (compiles on first use; the
        persistent compile cache makes repeat processes cheap).  For
        throughput serving use InferenceEngine directly.
        """
        from ..inference.engine import InferenceEngine
        ids = np.asarray(
            input_ids.numpy() if isinstance(input_ids, Tensor)
            else input_ids).reshape(-1).astype(np.int32)
        eng = InferenceEngine(self, batch_slots=1,
                              top_k=top_k, seed=seed)
        # engine.generate routes through the admission queue: on a busy
        # engine the call BLOCKS until a slot frees instead of raising
        gen = np.asarray(eng.generate(
            ids, max_new_tokens=max_new_tokens, eos_id=eos_id,
            temperature=temperature, top_p=top_p), np.int32)
        if include_prompt:
            return np.concatenate([ids, gen])
        return gen


class GPTEmbeddingStage(Layer):
    """Pipeline 'pre' stage: token + position embedding (shares the
    underlying parameters with the source model)."""

    def __init__(self, wte, wpe, drop):
        super().__init__()
        self.wte, self.wpe, self.drop = wte, wpe, drop

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class GPTHeadStage(Layer):
    """Pipeline 'post' stage: final norm + untied LM head."""

    def __init__(self, ln_f, lm_head):
        super().__init__()
        self.ln_f, self.lm_head = ln_f, lm_head

    def forward(self, h):
        return self.lm_head(self.ln_f(h))


def gpt_pipeline_parts(model: "GPTForCausalLM"):
    """Split a GPTForCausalLM into (pre, blocks, post) stage views for
    GPipeTrainer — the analogue of the reference PipelineOptimizer's
    program split by op_device (fluid/optimizer.py:3718), but the split
    is BY CONSTRUCTION (embedding / N identical blocks / head) instead
    of by annotation. Requires tie_word_embeddings=False: tied weights
    would put one parameter on two pipeline stages."""
    if model.cfg.tie_word_embeddings:
        raise ValueError(
            "pipeline parallelism needs tie_word_embeddings=False (tied "
            "embedding+head would live on both the first and last stage)")
    pre = GPTEmbeddingStage(model.gpt.wte, model.gpt.wpe, model.gpt.drop)
    post = GPTHeadStage(model.gpt.ln_f, model.lm_head)
    return pre, list(model.gpt.blocks), post


class GPTPretrainingCriterion(Layer):
    """Shifted-token cross entropy with optional loss mask (the reference
    trains GPT with a masked LM loss over ignored pad positions)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels, loss_mask=None):
        # logits: [B, S, V]; labels: [B, S] already shifted by the data
        # pipeline (labels[t] = input_ids[t+1]). With config.fused_ce
        # the model hands over (hidden [B, S, H], lm weight [V, H])
        # instead and the loss runs blockwise over the vocab without
        # ever materializing the logits tensor.
        flat_labels = labels.reshape([-1])
        if isinstance(logits, (tuple, list)) and len(logits) == 2:
            hidden, w = logits
            h = hidden.shape[-1]
            losses = F.fused_linear_cross_entropy(
                hidden.reshape([-1, h]), w, flat_labels,
                reduction="none", ignore_index=self.ignore_index)
        else:
            v = logits.shape[-1]
            losses = F.cross_entropy(logits.reshape([-1, v]), flat_labels,
                                     reduction="none",
                                     ignore_index=self.ignore_index)
        if loss_mask is not None:
            m = loss_mask.reshape([-1]).astype("float32")
            return (losses.reshape([-1]) * m).sum() / m.sum()
        return losses.mean()
