"""Model zoo beyond vision: the flagship transformer family used by the
benchmarks (BASELINE.json configs #3-#5)."""
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    StaticKVCache, gpt_configs)
