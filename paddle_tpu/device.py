"""Device/place management.

Reference parity: paddle/fluid/platform/place.h:26-62 (CPUPlace/CUDAPlace
variants) and python paddle.device. On TPU the 'place' maps to a jax.Device;
CUDAPlace is accepted as an alias for the n-th accelerator so reference
scripts keep working.
"""
from __future__ import annotations

import jax


class Place:
    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and (self.device_type, self.device_id)
                == (other.device_type, other.device_id))

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = jax.devices() if self.device_type != "cpu" else jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(Place):
    """Alias for the n-th accelerator (compat with reference scripts)."""

    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class XPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


_current_device = None


def set_device(device: str):
    """paddle.device.set_device parity ('cpu', 'tpu', 'tpu:0', 'gpu:0'...)."""
    global _current_device
    dev = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if dev in ("gpu", "cuda", "tpu", "xpu"):
        _current_device = TPUPlace(idx)
    else:
        _current_device = CPUPlace()
    return _current_device


def get_device() -> str:
    if _current_device is None:
        plat = jax.default_backend()
        return "cpu" if plat == "cpu" else f"{plat}:0"
    p = _current_device
    return p.device_type if p.device_type == "cpu" else f"{p.device_type}:{p.device_id}"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return jax.device_count()


def cuda_device_count() -> int:
    return 0
