"""Live retune tier: PADDLE_TPU_AUTOTUNE=live.

A fleet flagged by the SLO monitor should re-tune itself instead of
paging someone at 3am — but a LIVE replica is not a bench harness, so
the live tier is deliberately narrower than the offline controller:

- **edge-triggered, one episode per signal**: the SLO monitor's
  regression verdict SCHEDULES an episode; a still-regressed monitor on
  the next scrape does not schedule another (the latch resets only
  after a healthy verdict), and a cooldown bounds episode frequency
  even across distinct signals.  No retrigger storm.
- **quiesced-replica measurement**: the pending episode runs from the
  engine's tick hook only when the replica has NO active slots and an
  empty queue — trials never steal decode-step time from real traffic.
- **hot-apply, table-only knobs**: the episode re-measures the
  per-bucket prefill cost on the ALREADY-WARMED executables and
  re-merges the engine's prefill bucket list (the same pad-up rule as
  bench.py's offline sweep).  The bucket list is host-side state
  (``engine.buckets`` feeds ``_bucket_for``), and the merged list is a
  SUBSET of the warmed one — applying it is a plain attribute write:
  no restart, no retrace, no recompile.  Winners persist to the tuning
  table (op ``prefill_buckets``) with autotune provenance so the next
  process boots tuned.
- **rails**: the episode runs under the flight recorder; any failure
  inside it keeps the incumbent bucket list and dumps an
  ``autotune-rollback`` bundle.

The trainer-side sibling (:class:`TrainerRetuner`) is ADVISORY: train
knobs that matter (remat policy, quantize) retrace by nature, so a live
trainer never mutates them mid-run — on a sustained step-time
regression it runs the doctor once over the host-side timing surfaces
and ships the ranked verdicts (structured actions included) as a
flight-recorder event for the offline controller to act on.
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..observability import flightrec as _flightrec
from ..utils import tuning as _tuning

__all__ = ["LiveRetuner", "TrainerRetuner", "arm_engine", "arm_trainer"]

# offline sweep's merge rule (bench.py _sweep_prefill_buckets): keep a
# bucket only when using it beats padding up to the next kept bucket by
# this factor
PAD_UP_FACTOR = 1.25
# a merged list must cut the average measured prefill cost by more than
# this fraction to be applied — the live noise floor
LIVE_NOISE_FLOOR = 0.02


class LiveRetuner:
    """SLO-triggered, quiesce-gated prefill-bucket retuner for a
    serving engine (see module docstring for the contract)."""

    def __init__(self, engine, *, cooldown_s: float = 300.0,
                 noise_floor: float = LIVE_NOISE_FLOOR,
                 repeats: int = 3):
        self.engine = engine
        self.cooldown_s = float(cooldown_s)
        self.noise_floor = float(noise_floor)
        self.repeats = max(1, int(repeats))
        self.episodes = 0
        self.applied: List[dict] = []
        self._pending = False
        self._latched = False           # signal seen, not yet healthy
        self._last_episode_t: Optional[float] = None

    # -- signal side ----------------------------------------------------
    def notify_slo(self, verdict: dict) -> bool:
        """Feed one SLOMonitor.check() verdict; returns True when this
        call scheduled an episode.  Edge-triggered with a healthy-reset
        latch + wall-clock cooldown: a regressed monitor re-checked
        every scrape schedules exactly ONE episode."""
        bad = bool(verdict.get("regressed") or verdict.get("breached"))
        if not bad:
            self._latched = False
            return False
        if self._latched:
            return False
        self._latched = True
        now = time.monotonic()
        if self._last_episode_t is not None and \
                now - self._last_episode_t < self.cooldown_s:
            return False
        self._pending = True
        _flightrec.note_event("autotune_live_scheduled",
                              p99_ms=verdict.get("p99_ms"),
                              regressed=bool(verdict.get("regressed")),
                              breached=bool(verdict.get("breached")))
        return True

    # -- engine side ----------------------------------------------------
    def on_tick(self) -> bool:
        """Engine.step() hook: O(1) when nothing is pending; runs the
        scheduled episode only on a quiesced replica (no active slots,
        empty queue — trials never displace traffic)."""
        if not self._pending:
            return False
        eng = self.engine
        if eng.num_active or len(getattr(eng, "_queue", ())):
            return False
        self._pending = False
        self._last_episode_t = time.monotonic()
        try:
            self._episode()
        except Exception as e:          # a retune must NEVER kill serving
            _flightrec.dump("autotune-rollback",
                            extra={"autotune": {
                                "tier": "live",
                                "reason": "episode-error",
                                "error": f"{type(e).__name__}: {e}"}})
        return True

    def _episode(self) -> None:
        """One retune episode: time warmed prefill buckets, re-merge,
        hot-apply an improved subset, persist with provenance."""
        self.episodes += 1
        eng = self.engine
        old = list(eng.buckets)
        _flightrec.note_event("autotune_live_episode",
                              episode=self.episodes, buckets=old)
        times = self._time_buckets(old)
        kept = self._merge(old, times)
        old_cost = self._mean_cost(old, times)
        new_cost = self._mean_cost(kept, times)
        improvement = 0.0 if old_cost <= 0 else \
            (old_cost - new_cost) / old_cost
        if kept != old and improvement > self.noise_floor:
            # subset of warmed buckets -> pure host-side table write:
            # this is the hot-apply (no restart, no recompile)
            eng.buckets = kept
            rec = {"old": old, "new": kept,
                   "improvement": round(improvement, 6),
                   "times_ms": {str(b): round(t, 3)
                                for b, t in times.items()}}
            self.applied.append(rec)
            _flightrec.note_event("autotune_live_applied", **rec)
            try:
                _tuning.record(
                    "prefill_buckets",
                    (_tuning.device_kind(), eng.max_seq_len), kept,
                    source="autotune", run=f"live-{self.episodes}",
                    improvement=improvement)
            except Exception:
                pass                    # persistence is best-effort
        else:
            _flightrec.note_event("autotune_live_noop",
                                  episode=self.episodes,
                                  improvement=round(improvement, 6))

    # -- measurement ----------------------------------------------------
    def _time_buckets(self, buckets) -> dict:
        """Median wall time of each warmed bucket's prefill executable
        (mirrors bench.py's offline sweep, but on the LIVE engine's
        already-compiled functions — zero compiles by construction)."""
        import jax.numpy as jnp
        eng = self.engine
        out = {}
        for b in buckets:
            ids = jnp.zeros((1, b), jnp.int32)
            samples = []
            for _ in range(self.repeats):
                t0 = time.perf_counter()
                if eng.kv_layout == "paged":
                    from ..inference.paged_kv import blocks_for
                    n = blocks_for(b, eng.block_size)
                    blocks = eng._alloc.alloc(n)
                    if blocks is None:  # pool busier than quiesce said
                        raise RuntimeError("no free blocks for trial")
                    row = np.zeros(eng.blocks_per_slot, np.int32)
                    row[:n] = blocks
                    try:
                        logits, cache = eng._prefill_paged_cold_jit(
                            eng.params, eng.cache, ids,
                            jnp.asarray(row), np.int32(1))
                        eng.cache = cache
                    finally:
                        eng._alloc.decref(blocks)
                else:
                    logits, cache = eng._prefill_jit(
                        eng.params, eng.cache, ids, np.int32(0),
                        np.int32(1))
                    eng.cache = cache
                logits.block_until_ready()
                samples.append((time.perf_counter() - t0) * 1e3)
            out[b] = float(np.median(samples))
        if eng.kv_layout != "paged":
            # drop the trial garbage exactly like engine.warmup(): zero
            # every slot length so the junk written at slot 0 stays
            # masked (host-side constant, no new executable)
            c = eng.cache
            eng.cache = type(c)(c.k, c.v,
                                jnp.zeros((eng.batch_slots,), jnp.int32),
                                c.k_scale, c.v_scale)
        return out

    @staticmethod
    def _merge(buckets, times) -> list:
        """bench.py's _sweep_prefill_buckets rule: walk small→large,
        keep a bucket only when the previously-kept (smaller) bucket is
        more than PAD_UP_FACTOR cheaper — i.e. drop buckets whose
        marginal win doesn't pay for their executable."""
        order = sorted(buckets)
        kept = [order[-1]]              # the largest must stay (capacity)
        for b in reversed(order[:-1]):
            nxt = kept[0]
            if times[b] * PAD_UP_FACTOR < times[nxt]:
                kept.insert(0, b)
        return kept

    @staticmethod
    def _mean_cost(kept, times) -> float:
        """Expected prefill cost under uniform prompt lengths: each
        length pays the cheapest kept bucket that fits it, weighted by
        the fraction of lengths that land in it."""
        ks = sorted(kept)
        total, lo = 0.0, 0
        top = ks[-1]
        for b in ks:
            total += times[b] * (b - lo) / top
            lo = b
        return total


class TrainerRetuner:
    """Advisory live tier for SpmdTrainer: detect a sustained step-time
    regression from the host-side step timer (no device sync), run the
    doctor ONCE over the trainer's timing surfaces, and ship the ranked
    verdicts — structured actions included — as a flightrec event.  One
    episode per regression signal (healthy-reset latch), cooldown in
    steps."""

    def __init__(self, trainer, *, window: int = 32,
                 factor: float = 1.5, cooldown_steps: int = 256):
        self.trainer = trainer
        self.window = int(window)
        self.factor = float(factor)
        self.cooldown_steps = int(cooldown_steps)
        self.episodes = 0
        self.last_advice: Optional[list] = None
        self._recent: List[float] = []
        self._baseline_ms: Optional[float] = None
        self._steps = 0
        self._latched = False
        self._last_episode_step: Optional[int] = None

    def on_step(self, step_ms: Optional[float]) -> bool:
        """Per-step hook (host arithmetic only). Returns True when this
        step fired an advisory episode."""
        self._steps += 1
        if step_ms is None:
            return False
        self._recent.append(float(step_ms))
        if len(self._recent) > self.window:
            self._recent.pop(0)
        if len(self._recent) < self.window:
            return False
        med = float(np.median(self._recent))
        if self._baseline_ms is None:
            self._baseline_ms = med     # first full window is the record
            return False
        self._baseline_ms = min(self._baseline_ms, med)
        if med <= self._baseline_ms * self.factor:
            self._latched = False
            return False
        if self._latched:
            return False
        self._latched = True
        if self._last_episode_step is not None and \
                self._steps - self._last_episode_step < \
                self.cooldown_steps:
            return False
        self._last_episode_step = self._steps
        self._episode(med)
        return True

    def _episode(self, median_ms: float) -> None:
        self.episodes += 1
        t = dict(getattr(self.trainer, "_timings", {}) or {})
        stats = {k: t.get(k) for k in
                 ("dispatch_ms", "sync_ms", "data_wait_ms", "h2d_ms",
                  "steps_timed") if t.get(k) is not None}
        from ..observability import doctor as _doctor
        try:
            self.last_advice = _doctor.diagnose(stats, "train")
        except Exception:
            self.last_advice = []
        _flightrec.note_event(
            "autotune_train_advice", episode=self.episodes,
            median_step_ms=round(median_ms, 3),
            baseline_step_ms=round(self._baseline_ms or 0.0, 3),
            advice=self.last_advice[:3])


def arm_engine(engine) -> Optional[LiveRetuner]:
    """Construct + attach a LiveRetuner when PADDLE_TPU_AUTOTUNE=live
    (engine ctor calls this; returns the retuner or None)."""
    from . import autotune_mode
    if autotune_mode() != "live":
        return None
    return LiveRetuner(engine)


def arm_trainer(trainer) -> Optional[TrainerRetuner]:
    """Trainer-side arming under the same env tier."""
    from . import autotune_mode
    if autotune_mode() != "live":
        return None
    return TrainerRetuner(trainer)
